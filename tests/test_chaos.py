"""Chaos harness: seeded, deterministic faults against a live serving
stack, reconciled EXACTLY against the injected plan.

Every scenario asserts the self-healing contract end to end:

* **zero lost records** — every enqueued uri is eventually answered with
  a prediction or an addressable error (never a hang),
* **bounded recovery** — the serve loop restarts at most the configured
  bound; a down backend trips the breaker instead of a poll/crash storm,
* **exact metric reconciliation** — restart / breaker / deadline /
  dead-letter counters match ``plan.fired`` one for one,
* **zero orphaned traces** — every traced record ends in a terminal
  ``publish`` or ``failed`` phase event.

All waits are sub-50ms (tiny backoffs, tiny breaker windows); the query
timeouts are safety nets, not sleeps.
"""

import time
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common.context import init_zoo_context
from analytics_zoo_tpu.common.faults import FaultPlan
from analytics_zoo_tpu.common.reliability import CircuitBreaker, RetryPolicy
from analytics_zoo_tpu.observability import MetricsRegistry, read_events
from analytics_zoo_tpu.pipeline.api.keras.engine import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.pipeline.inference import InferenceModel
from analytics_zoo_tpu.serving import (ClusterServing, InputQueue,
                                       LocalBackend, OutputQueue,
                                       ServingError)


def _toy_model():
    init_zoo_context(faults_enabled=True)
    m = Sequential()
    m.add(Dense(4, input_shape=(6,), activation="relu"))
    m.add(Dense(3, activation="softmax"))
    m.init_weights()
    return m


def _serving(model, backend, reg, **kw):
    """A server with chaos-friendly (tiny, seeded) recovery knobs."""
    kw.setdefault("batch_size", 4)
    kw.setdefault("block_ms", 20)
    kw.setdefault("max_loop_restarts", 3)
    kw.setdefault("restart_backoff", RetryPolicy(
        max_attempts=4, base_delay=0.005, max_delay=0.02, seed=7))
    kw.setdefault("breaker", CircuitBreaker(
        "serving.backend", failure_threshold=2, reset_timeout=0.05,
        registry=reg))
    return ClusterServing(model, backend=backend, registry=reg, **kw)


def _enqueue(backend, n, prefix="c"):
    inq = InputQueue(backend)
    rng = np.random.default_rng(11)
    xs = {f"{prefix}-{i}": rng.normal(size=(6,)).astype(np.float32)
          for i in range(n)}
    for uri, x in xs.items():
        inq.enqueue(uri, x)
    return xs


def test_mid_serve_disconnect_recovers_via_breaker(tmp_path):
    """Kill the stream connection twice mid-serve: the loop absorbs the
    first failure, the second opens the breaker, the probe read closes
    it, and every record is still answered — no loop restart, no lost
    records, breaker metrics reconciled exactly against the plan."""
    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    xs = _enqueue(backend, 12)           # pre-enqueued: read order is fixed
    plan = FaultPlan(seed=3).add("backend.xread", "disconnect", at=(1, 2))
    serving = _serving(im, backend, reg)
    serving.set_json_events(str(tmp_path / "events.jsonl"))
    outq = OutputQueue(backend)
    with faults.activate(plan):
        serving.start()
        try:
            results = {uri: outq.query(uri, timeout=30.0) for uri in xs}
        finally:
            serving.stop(drain=False)
    direct = np.asarray(im.predict(np.stack(list(xs.values()))))
    for i, uri in enumerate(xs):
        assert results[uri] is not None, f"lost record {uri}"
        np.testing.assert_allclose(results[uri], direct[i],
                                   rtol=1e-5, atol=1e-6)
    # exact reconciliation against the plan
    assert plan.fired == [("backend.xread", "disconnect", 1),
                          ("backend.xread", "disconnect", 2)]
    snap = reg.snapshot()
    b = 'zoo_breaker_transitions_total{breaker="serving.backend",state="%s"}'
    assert snap[b % "open"]["value"] == 1          # exactly one trip
    assert snap[b % "half_open"]["value"] == 1     # one probe window
    assert snap[b % "closed"]["value"] == 1        # probe succeeded
    assert snap['zoo_breaker_state{breaker="serving.backend"}']["value"] == 0
    # transient transport blips are absorbed in-loop: NOT a crash/restart
    assert snap['zoo_serving_loop_restarts_total{loop="serve"}']["value"] == 0
    assert snap["zoo_serving_failures_total"]["value"] == 0
    assert snap["zoo_serving_records_total"]["value"] == 12
    # zero orphaned traces: every record's trace ends in a publish event
    events = read_events(str(tmp_path / "events.jsonl"), kind="request")
    by_trace = {}
    for e in events:
        by_trace.setdefault(e["trace"], []).append(e["phase"])
    assert len(by_trace) == 12
    for trace, phases in by_trace.items():
        assert phases.count("publish") == 1, (trace, phases)
        assert set(phases) == {"enqueue", "dequeue", "dispatch", "publish"}


def test_loop_crash_restarts_under_supervisor():
    """An escaped exception in the serve loop (a bug, not a transport
    blip) restarts it with backoff; records enqueued before AND after the
    crash are all answered; the restart counter matches the plan."""
    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    xs = _enqueue(backend, 8, prefix="r")
    plan = FaultPlan(seed=5).add("serving.loop", "error", at=(1,))
    serving = _serving(im, backend, reg)
    outq = OutputQueue(backend)
    with faults.activate(plan):
        serving.start()
        try:
            results = {uri: outq.query(uri, timeout=30.0) for uri in xs}
        finally:
            serving.stop(drain=False)
    assert all(v is not None and v.shape == (3,) for v in results.values())
    assert plan.fired == [("serving.loop", "error", 1)]
    snap = reg.snapshot()
    assert snap['zoo_serving_loop_restarts_total{loop="serve"}']["value"] == 1
    assert snap["zoo_serving_records_total"]["value"] == 8
    assert snap["zoo_serving_failures_total"]["value"] == 0


def test_supervisor_gives_up_and_healthz_reads_down():
    """A crash-looping serve loop stops flapping after max_loop_restarts:
    /healthz flips to down and /statusz carries the last traceback — the
    operator pages instead of the loop thrashing forever."""
    import json
    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    # crash EVERY iteration: initial run + the single allowed restart
    plan = FaultPlan(seed=9).add("serving.loop", "error",
                                 at=tuple(range(16)))
    serving = _serving(im, backend, reg, max_loop_restarts=1)
    scrape = serving.serve_metrics(port=0)
    with faults.activate(plan):
        serving.start()
        try:
            # the supervisor gives up quickly (two tiny backoffs)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if serving._thread is not None \
                        and not serving._thread.is_alive():
                    break
                time.sleep(0.005)
            base = f"http://{scrape.host}:{scrape.port}"
            with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
                health = json.loads(r.read())
            with urllib.request.urlopen(base + "/statusz", timeout=10) as r:
                status = json.loads(r.read())
        finally:
            serving.stop(drain=False)
    assert health["status"] == "down"
    assert health["serving"]["running"] is False
    assert "serve" in health["serving"]["loops_down"]
    assert "FaultError" in status["serving"]["last_crash"]["serve"]
    snap = reg.snapshot()
    # exactly the configured bound, then give-up — no restart storm
    assert snap['zoo_serving_loop_restarts_total{loop="serve"}']["value"] == 1
    assert len(plan.fired) == 2          # initial crash + the one restart


def test_dispatch_crash_retries_records_solo_with_no_loss():
    """A batch whose dispatch crashes is re-dispatched one record at a
    time: a transient crash costs one retry round and zero records."""
    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    xs = _enqueue(backend, 4, prefix="d")       # exactly one batch
    plan = FaultPlan(seed=1).add("serving.dispatch", "error", at=(0,))
    serving = _serving(im, backend, reg)
    outq = OutputQueue(backend)
    with faults.activate(plan):
        serving.start()
        try:
            results = {uri: outq.query(uri, timeout=30.0) for uri in xs}
        finally:
            serving.stop(drain=False)
    direct = np.asarray(im.predict(np.stack(list(xs.values()))))
    for i, uri in enumerate(xs):
        assert results[uri] is not None
        np.testing.assert_allclose(results[uri], direct[i],
                                   rtol=1e-5, atol=1e-6)
    assert plan.fired == [("serving.dispatch", "error", 0)]
    snap = reg.snapshot()
    assert snap['zoo_retry_attempts_total{op="serving.dispatch"}'][
        "value"] == 4                            # one solo retry per record
    assert snap["zoo_serving_failures_total"]["value"] == 0
    assert snap["zoo_serving_dead_letter_total"]["value"] == 0
    assert snap["zoo_serving_records_total"]["value"] == 4


def test_poison_records_dead_letter_instead_of_retrying_forever():
    """Records that crash EVERY dispatch attempt are answered with the
    distinct dead-letter error after the bounded retries — reconciled
    against the plan's fired log, never an infinite retry loop."""
    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    xs = _enqueue(backend, 2, prefix="p")
    plan = FaultPlan(seed=2).add("serving.dispatch", "error",
                                 at=tuple(range(32)))
    serving = _serving(im, backend, reg)
    outq = OutputQueue(backend)
    with faults.activate(plan):
        serving.start()
        try:
            errors = {}
            for uri in xs:
                with pytest.raises(ServingError) as ei:
                    outq.query(uri, timeout=30.0)
                errors[uri] = str(ei.value)
        finally:
            serving.stop(drain=False)
    assert all("dead-lettered" in e for e in errors.values())
    # batch attempt + one solo attempt per record, nothing more
    assert [f[:2] for f in plan.fired] == \
        [("serving.dispatch", "error")] * 3
    snap = reg.snapshot()
    assert snap["zoo_serving_dead_letter_total"]["value"] == 2
    assert snap["zoo_serving_failures_total"]["value"] == 2
    assert snap['zoo_serving_failure_errors_total{error="dead-lettered: '
                'dispatch crashed repeatedly"}']["value"] == 2
    assert snap['zoo_retry_attempts_total{op="serving.dispatch"}'][
        "value"] == 2


def test_expired_deadline_answered_before_dispatch():
    """A record whose producer-stamped deadline_ms has passed is answered
    with the distinct `deadline exceeded` error without spending dispatch
    on it; in-budget records in the same read still serve."""
    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(4)
    x = rng.normal(size=(6,)).astype(np.float32)
    now_ms = int(time.time() * 1000)
    inq.enqueue("late", x, deadline_ms=now_ms - 1)        # already expired
    inq.enqueue("ok", x, deadline_ms=now_ms + 60_000)     # plenty of budget
    inq.enqueue("no-deadline", x)                         # old contract
    serving = _serving(im, backend, reg)
    serving.start()
    try:
        with pytest.raises(ServingError, match="deadline exceeded"):
            outq.query("late", timeout=30.0)
        assert outq.query("ok", timeout=30.0) is not None
        assert outq.query("no-deadline", timeout=30.0) is not None
    finally:
        serving.stop(drain=False)
    snap = reg.snapshot()
    assert snap["zoo_serving_deadline_exceeded_total"]["value"] == 1
    assert snap['zoo_serving_failure_errors_total{error="deadline '
                'exceeded"}']["value"] == 1
    assert snap["zoo_serving_records_total"]["value"] == 2


def test_partial_result_write_leaves_no_silent_loss(tmp_path):
    """A result-store write that dies mid-batch (half applied, then the
    connection drops) must leave every record answered — value or
    addressable error — and every trace terminated."""
    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    xs = _enqueue(backend, 4, prefix="w")
    plan = FaultPlan(seed=6).add("backend.set_results", "partial_write",
                                 at=(0,), fraction=0.5)
    serving = _serving(im, backend, reg)
    serving.set_json_events(str(tmp_path / "events.jsonl"))
    outq = OutputQueue(backend)
    with faults.activate(plan):
        serving.start()
        try:
            answered = {}
            for uri in xs:
                try:
                    answered[uri] = ("value", outq.query(uri, timeout=30.0))
                except ServingError as e:
                    answered[uri] = ("error", str(e))
        finally:
            serving.stop(drain=False)
    assert plan.fired == [("backend.set_results", "partial_write", 0)]
    # every record addressably answered (publish failure overwrites the
    # half-written values with the distinct publish-failure error)
    assert set(answered) == set(xs)
    assert all(v is not None for _, v in answered.values())
    assert any(kind == "error" and "result publish failed" in v
               for kind, v in answered.values())
    snap = reg.snapshot()
    assert snap['zoo_serving_failure_errors_total{error="result publish '
                'failed"}']["value"] == 4
    # zero orphaned traces: each of the 4 ends in a terminal phase event
    events = read_events(str(tmp_path / "events.jsonl"), kind="request")
    by_trace = {}
    for e in events:
        by_trace.setdefault(e["trace"], []).append(e["phase"])
    assert len(by_trace) == 4
    for trace, phases in by_trace.items():
        assert sum(p in ("publish", "failed") for p in phases) == 1, \
            (trace, phases)


def test_stop_drain_survives_dead_backend():
    """stop(drain=True) against a backend that died mid-flight logs and
    skips the drain instead of raising out of the stream_len poll —
    workers still join, sinks still close."""

    class DyingBackend(LocalBackend):
        def __init__(self):
            super().__init__()
            self.dead = False

        def stream_len(self, stream):
            if self.dead:
                raise ConnectionError("backend is gone")
            return super().stream_len(stream)

    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = DyingBackend()
    xs = _enqueue(backend, 4, prefix="s")
    serving = _serving(im, backend, reg)
    outq = OutputQueue(backend)
    serving.start()
    results = {uri: outq.query(uri, timeout=30.0) for uri in xs}
    assert all(v is not None for v in results.values())
    backend.dead = True
    serving.stop(drain=True, timeout=10.0)      # must not raise
    assert serving._thread is None and serving._pub_thread is None
    # and the server is restartable against a recovered backend
    backend.dead = False
    serving.start()
    serving.stop(drain=False)


def test_probe_crash_does_not_wedge_the_breaker():
    """Regression: a NON-transport exception during the admitted
    half-open probe read escaped to the supervisor without resolving the
    probe slot — the restarted loop then found allow() refusing forever
    and never read the stream again. The probe now records a failure
    before escaping: the breaker re-opens cleanly, the next window's
    probe succeeds, and every record still serves."""
    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    xs = _enqueue(backend, 8, prefix="pb")
    plan = (FaultPlan(seed=8)
            .add("backend.xread", "disconnect", at=(1, 2))  # trip it open
            .add("backend.xread", "error", at=(3,)))        # crash the probe
    serving = _serving(im, backend, reg)
    outq = OutputQueue(backend)
    with faults.activate(plan):
        serving.start()
        try:
            results = {uri: outq.query(uri, timeout=30.0) for uri in xs}
        finally:
            serving.stop(drain=False)
    assert all(v is not None and v.shape == (3,) for v in results.values())
    assert [f[:2] for f in plan.fired] == [
        ("backend.xread", "disconnect"), ("backend.xread", "disconnect"),
        ("backend.xread", "error")]
    snap = reg.snapshot()
    b = 'zoo_breaker_transitions_total{breaker="serving.backend",state="%s"}'
    assert snap[b % "open"]["value"] == 2       # trip + probe-crash re-open
    assert snap['zoo_breaker_state{breaker="serving.backend"}']["value"] == 0
    # the probe crash is non-transport: it restarts the loop (once)
    assert snap['zoo_serving_loop_restarts_total{loop="serve"}']["value"] == 1
    assert snap["zoo_serving_records_total"]["value"] == 8


def test_retry_budget_caps_solo_redispatches_fleet_wide():
    """A shared RetryBudget bounds TOTAL solo re-dispatches during a
    correlated outage: with one token, the first crashed record gets its
    solo retry, later ones dead-letter immediately — the exhausted
    counter and the plan's fired log reconcile exactly."""
    from analytics_zoo_tpu.common.reliability import RetryBudget

    reg = MetricsRegistry()
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    xs = _enqueue(backend, 2, prefix="b")
    # every dispatch crashes: batch attempt + whatever solo retries run
    plan = FaultPlan(seed=10).add("serving.dispatch", "error",
                                  at=tuple(range(32)))
    budget = RetryBudget(capacity=1, deposit=0.1, name="fleet",
                         registry=reg)
    serving = _serving(im, backend, reg, retry_budget=budget)
    outq = OutputQueue(backend)
    with faults.activate(plan):
        serving.start()
        try:
            for uri in xs:
                with pytest.raises(ServingError, match="dead-lettered"):
                    outq.query(uri, timeout=30.0)
        finally:
            serving.stop(drain=False)
    # fired: the batch attempt + exactly ONE budgeted solo retry — the
    # second record's retry was refused by the drained bucket
    assert [f[:2] for f in plan.fired] == \
        [("serving.dispatch", "error")] * 2
    snap = reg.snapshot()
    assert snap['zoo_retry_budget_exhausted_total{budget="fleet"}'][
        "value"] == 1
    assert snap["zoo_serving_dead_letter_total"]["value"] == 2
    assert snap['zoo_retry_attempts_total{op="serving.dispatch"}'][
        "value"] == 1


def test_stream_len_fault_site_fires_and_reconciles():
    """Deterministic coverage of the `backend.stream_len` site: the
    depth-probe path surfaces an injected disconnect as the builtin
    ConnectionError (what the serve loop's breaker classifies), exactly
    once, exactly at the planned call index."""
    init_zoo_context(faults_enabled=True)
    backend = LocalBackend()
    _enqueue(backend, 2, prefix="sl")
    plan = FaultPlan(seed=11).add("backend.stream_len", "disconnect",
                                  at=(1,))
    with faults.activate(plan):
        assert backend.stream_len("tensor_stream") == 2   # call 0: clean
        with pytest.raises(ConnectionError):               # call 1: planned
            backend.stream_len("tensor_stream")
        assert backend.stream_len("tensor_stream") == 2   # call 2: clean
    assert plan.fired == [("backend.stream_len", "disconnect", 1)]


def test_set_result_fault_site_fires_and_reconciles():
    """Deterministic coverage of the `backend.set_result` site (the
    per-record error/shed answer path, distinct from the batched
    `backend.set_results`): a planned error fires once and a retried
    write lands — the addressable-answer path stays recoverable."""
    init_zoo_context(faults_enabled=True)
    backend = LocalBackend()
    plan = FaultPlan(seed=12).add("backend.set_result", "error", at=(0,))
    with faults.activate(plan):
        with pytest.raises(Exception):
            backend.set_result("sr-0", {"error": "shed: overloaded"})
        backend.set_result("sr-0", {"error": "shed: overloaded"})
    assert plan.fired == [("backend.set_result", "error", 0)]
    outq = OutputQueue(backend)
    with pytest.raises(ServingError, match="shed"):
        outq.query("sr-0", timeout=5.0)
