"""BERT numerical oracle (VERDICT weak #5): the native BERT layer under
imported HuggingFace weights must reproduce transformers' BertModel outputs
— catches gate-order / LN-placement / gelu-form divergences shape checks
can't. Plus the BERTClassifier fine-tune path (config #4 surface)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from analytics_zoo_tpu.common.context import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.layers import BERT
from analytics_zoo_tpu.tfpark import BERTClassifier, bert_params_from_torch

VOCAB, HIDDEN, BLOCKS, HEADS, SEQ, INTER = 99, 32, 2, 4, 16, 64


def _tiny_hf_bert():
    cfg = transformers.BertConfig(
        vocab_size=VOCAB, hidden_size=HIDDEN, num_hidden_layers=BLOCKS,
        num_attention_heads=HEADS, intermediate_size=INTER,
        max_position_embeddings=SEQ, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        hidden_act="gelu")
    torch.manual_seed(0)
    return transformers.BertModel(cfg).eval()


def _inputs(b=3, t=SEQ, pad_from=None):
    rng = np.random.default_rng(0)
    ids = rng.integers(1, VOCAB, (b, t)).astype(np.int32)
    tt = rng.integers(0, 2, (b, t)).astype(np.int32)
    mask = np.ones((b, t), np.float32)
    if pad_from is not None:
        ids[:, pad_from:] = 0
        mask[:, pad_from:] = 0.0
    pos = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    return ids, tt, pos, mask


@pytest.mark.parametrize("pad_from", [None, 10])
def test_bert_matches_transformers(pad_from):
    init_zoo_context()
    hf = _tiny_hf_bert()
    ids, tt, pos, mask = _inputs(pad_from=pad_from)

    ours = BERT(vocab=VOCAB, hidden_size=HIDDEN, n_block=BLOCKS,
                n_head=HEADS, seq_len=SEQ, intermediate_size=INTER,
                hidden_drop=0.0, attn_drop=0.0)
    import jax
    params = ours.build(jax.random.key(0), [(None, SEQ)] * 4)
    imported = bert_params_from_torch(hf.state_dict(), BLOCKS)
    # same tree structure → install by matching keys
    params = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params),
        jax.tree_util.tree_leaves(
            jax.tree.map(lambda x: np.asarray(x, np.float32), imported)))
    seq_out, pooled = ours.call(params, [ids, tt, pos, mask])

    with torch.no_grad():
        out = hf(input_ids=torch.tensor(ids.astype(np.int64)),
                 token_type_ids=torch.tensor(tt.astype(np.int64)),
                 attention_mask=torch.tensor(mask.astype(np.int64)))
    np.testing.assert_allclose(np.asarray(seq_out),
                               out.last_hidden_state.numpy(),
                               rtol=1e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(pooled),
                               out.pooler_output.numpy(),
                               rtol=1e-4, atol=2e-4)


def test_bert_classifier_finetunes_from_pretrained():
    init_zoo_context()
    hf = _tiny_hf_bert()
    clf = BERTClassifier(num_classes=2, vocab=VOCAB, hidden_size=HIDDEN,
                         n_block=BLOCKS, n_head=HEADS, seq_len=SEQ,
                         intermediate_size=INTER, hidden_drop=0.0,
                         attn_drop=0.0)
    clf.load_pretrained(hf.state_dict())

    # trivial task: class = whether token 7 appears
    rng = np.random.default_rng(1)
    n = 96
    ids = rng.integers(1, VOCAB, (n, SEQ)).astype(np.int32)
    y = (ids == 7).any(axis=1).astype(np.int32)
    x = clf.make_inputs(ids)
    clf.compile(optimizer="adam", loss="scce", metrics=["accuracy"], lr=3e-3)
    h = clf.fit(x, y, batch_size=16, nb_epoch=6)
    assert h["loss"][-1] < h["loss"][0]
    assert clf.evaluate(x, y, batch_size=16)["accuracy"] > 0.75


def test_import_rejects_wrong_shapes():
    init_zoo_context()
    hf = _tiny_hf_bert()
    clf = BERTClassifier(num_classes=2, vocab=VOCAB, hidden_size=HIDDEN + 32,
                         n_block=BLOCKS, n_head=HEADS, seq_len=SEQ,
                         intermediate_size=INTER)
    with pytest.raises(ValueError):
        clf.load_pretrained(hf.state_dict())