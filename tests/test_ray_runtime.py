"""Ray-equivalent runtime: task pool, stateful actors, error propagation,
and the parent-death guard."""

import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from analytics_zoo_tpu.ray import ObjectRef, RayContext, RayTaskError


def _square(x):
    return x * x


def _boom():
    raise ValueError("intentional")


class Counter:
    def __init__(self, start=0):
        self.v = start

    def add(self, k):
        self.v += k
        return self.v

    def value(self):
        return self.v


class BadActor:
    """Raises during construction (must be module-level: payloads cross
    process boundaries by pickle, same contract as ray)."""

    def __init__(self):
        raise RuntimeError("no")


@pytest.fixture
def ctx():
    c = RayContext(num_workers=2).init()
    yield c
    c.stop()


def test_remote_tasks_parallel_map(ctx):
    refs = [ctx.remote(_square, i) for i in range(20)]
    assert all(isinstance(r, ObjectRef) for r in refs)
    assert ctx.get(refs) == [i * i for i in range(20)]
    # out-of-order get works
    a, b = ctx.remote(_square, 7), ctx.remote(_square, 8)
    assert ctx.get(b) == 64 and ctx.get(a) == 49


def test_task_error_propagates(ctx):
    with pytest.raises(RayTaskError, match="intentional"):
        ctx.get(ctx.remote(_boom))
    # pool survives a failed task
    assert ctx.get(ctx.remote(_square, 3)) == 9


def test_actor_keeps_state(ctx):
    c = ctx.actor(Counter, 10)
    refs = [c.add.remote(1) for _ in range(5)]
    assert ctx.get(refs) == [11, 12, 13, 14, 15]
    assert ctx.get(c.value.remote()) == 15


def test_actor_construction_failure_is_loud(ctx):
    with pytest.raises(RayTaskError, match="construction failed"):
        ctx.actor(BadActor)


def test_uninitialized_context_raises():
    c = RayContext(2)
    with pytest.raises(RuntimeError, match="init"):
        c.remote(_square, 1)


def test_workers_die_with_parent(tmp_path):
    """JVMGuard parity: kill -9 the driver → workers must exit."""
    script = textwrap.dedent("""
        import os, sys, time
        sys.path.insert(0, %r)
        from analytics_zoo_tpu.ray import RayContext
        ctx = RayContext(2).init()
        pids = [p.pid for p in ctx._procs]
        print(" ".join(map(str, pids)), flush=True)
        time.sleep(60)
    """) % (os.getcwd(),)
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True)
    pids = [int(p) for p in proc.stdout.readline().split()]
    assert pids
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    deadline = time.time() + 10
    while time.time() < deadline:
        alive = []
        for p in pids:
            try:
                os.kill(p, 0)
                alive.append(p)
            except OSError:
                pass
        if not alive:
            break
        time.sleep(0.3)
    assert not alive, f"orphaned workers survived driver kill: {alive}"


def test_get_twice_returns_cached_result(ctx):
    ref = ctx.remote(_square, 6)
    assert ctx.get(ref) == 36
    assert ctx.get(ref) == 36  # must not hang (ray.get semantics)


def test_unpicklable_task_fails_at_submission(ctx):
    with pytest.raises(RayTaskError, match="picklable"):
        ctx.remote(lambda: 1)


def test_crashed_worker_raises_instead_of_hanging(ctx):
    ref = ctx.remote(os._exit, 0)  # worker dies before replying
    with pytest.raises(RayTaskError, match="died"):
        ctx.get(ref)


def test_timeout_raises_timeout_error_and_is_global(ctx):
    refs = [ctx.remote(time.sleep, 5) for _ in range(4)]
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        ctx.get(refs, timeout=0.5)
    assert time.monotonic() - t0 < 2.0  # one deadline for the whole list


def test_numpy_payloads(ctx):
    a = np.arange(6).reshape(2, 3)
    ref = ctx.remote(np.dot, a, a.T)
    np.testing.assert_array_equal(ctx.get(ref), a @ a.T)


class _BoomInit:
    def __init__(self):
        raise RuntimeError("boom at init")


class _Counter2:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n


def test_second_actor_init_failure_not_masked():
    """Actor construction acks use unique ids — a second actor's failed
    __init__ must raise immediately, not be masked by the first actor's
    cached ack (code-review regression)."""
    import pytest

    from analytics_zoo_tpu.ray import RayContext
    from analytics_zoo_tpu.ray.raycontext import RayTaskError

    ctx = RayContext(num_workers=1).init()
    try:
        ok = ctx.actor(_Counter2)
        assert ctx.get(ok.bump.remote()) == 1
        with pytest.raises(RayTaskError, match="boom at init"):
            ctx.actor(_BoomInit)
        # first actor still healthy afterwards
        assert ctx.get(ok.bump.remote()) == 2
    finally:
        ctx.stop()


class _ExitInit:
    def __init__(self):
        import os
        os._exit(7)  # dies WITHOUT sending a construction ack


def test_actor_dying_without_ack_raises_not_hangs():
    """A child that exits before acking (segfault/os._exit) must raise
    RayTaskError promptly instead of spinning forever (code-review
    regression)."""
    import time

    import pytest

    from analytics_zoo_tpu.ray import RayContext
    from analytics_zoo_tpu.ray.raycontext import RayTaskError

    ctx = RayContext(num_workers=1).init()
    try:
        t0 = time.monotonic()
        with pytest.raises(RayTaskError, match="died"):
            ctx.actor(_ExitInit)
        assert time.monotonic() - t0 < 30
    finally:
        ctx.stop()
