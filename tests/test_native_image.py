"""Native image-ops library (``native/zoo_image.cc`` via
``analytics_zoo_tpu/native/image.py``) — the host-side C++ component of the
image pipeline (reference role: OpenCV through JNI,
``feature/image/OpenCVMethod.scala``). Parity oracles: PIL's BILINEAR
resampling (same triangle-filter family) and the numpy normalize path."""

import os

import numpy as np
import pytest

from analytics_zoo_tpu.native import image as native_image


pytestmark = pytest.mark.skipif(
    not native_image.available(), reason="native image lib unavailable")


def _pil_resize(im, oh, ow):
    from PIL import Image
    return np.asarray(Image.fromarray(im).resize((ow, oh), Image.BILINEAR))


@pytest.mark.parametrize("shape,out_hw", [
    ((40, 50, 3), (32, 36)),    # downscale
    ((16, 16, 3), (32, 48)),    # upscale
    ((33, 47, 3), (16, 16)),    # odd sizes
    ((24, 24, 1), (12, 12)),    # single channel
])
def test_resize_matches_pil_uint8(shape, out_hw):
    rng = np.random.default_rng(0)
    im = rng.integers(0, 256, shape).astype(np.uint8)
    got = native_image.resize_bilinear(im, *out_hw)
    assert got.shape == (*out_hw, shape[-1]) and got.dtype == np.uint8
    if shape[-1] == 1:
        want = _pil_resize(im[..., 0], *out_hw)[..., None]
    else:
        want = _pil_resize(im, *out_hw)
    # same filter family; implementations differ by fixed-point vs float
    # rounding — at most one grey level, no structural drift
    diff = np.abs(got.astype(int) - want.astype(int))
    assert diff.max() <= 1, f"max diff {diff.max()}"
    assert (diff > 0).mean() < 0.35


def test_resize_batch_matches_per_image():
    rng = np.random.default_rng(1)
    batch = rng.integers(0, 256, (7, 21, 17, 3)).astype(np.uint8)
    got = native_image.resize_bilinear(batch, 11, 13)
    assert got.shape == (7, 11, 13, 3)
    for i in range(7):
        np.testing.assert_array_equal(
            got[i], native_image.resize_bilinear(batch[i], 11, 13))


def test_resize_float32_identity_and_interp():
    # identity resize returns the (float) input exactly: the window
    # degenerates to weight 1 on the source pixel
    rng = np.random.default_rng(2)
    im = rng.normal(size=(9, 9, 3)).astype(np.float32)
    same = native_image.resize_bilinear(im, 9, 9)
    np.testing.assert_allclose(same, im, rtol=1e-6, atol=1e-6)
    # 2x upscale of a constant image stays constant
    const = np.full((8, 8, 3), 3.25, np.float32)
    up = native_image.resize_bilinear(const, 16, 16)
    np.testing.assert_allclose(up, 3.25, rtol=1e-6)


def test_resize_threading_is_deterministic():
    rng = np.random.default_rng(3)
    batch = rng.integers(0, 256, (33, 28, 28, 3)).astype(np.uint8)
    a = native_image.resize_bilinear(batch, 14, 14, nthreads=1)
    b = native_image.resize_bilinear(batch, 14, 14, nthreads=8)
    np.testing.assert_array_equal(a, b)


def test_normalize_matches_numpy():
    rng = np.random.default_rng(4)
    mean, std = (100.0, 50.0, 25.0), (2.0, 4.0, 8.0)
    for dtype in (np.uint8, np.float32):
        batch = (rng.integers(0, 256, (5, 12, 10, 3))
                 if dtype == np.uint8
                 else rng.normal(0, 100, (5, 12, 10, 3))).astype(dtype)
        got = native_image.normalize(batch, mean, std)
        want = (batch.astype(np.float32) - np.asarray(mean, np.float32)) \
            / np.asarray(std, np.float32)
        assert got.dtype == np.float32
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-5)


def test_unsupported_inputs_return_none():
    assert native_image.resize_bilinear(
        np.zeros((4, 4, 3), np.float64), 2, 2) is None
    assert native_image.normalize(
        np.zeros((4, 4, 3), np.uint8), (0.0,), (1.0,)) is None   # c mismatch
    assert native_image.normalize(
        np.zeros((4, 4, 3), np.uint8), (0.0,) * 3, (0.0,) * 3) is None


def test_transform_classes_use_native_path():
    """Resize/ChannelNormalize produce correct results whichever path
    runs — and the batched outputs match the per-image fallback loop."""
    from analytics_zoo_tpu.feature.image.transforms import (ChannelNormalize,
                                                            Resize)
    rng = np.random.default_rng(5)
    batch = rng.integers(0, 256, (4, 30, 26, 3)).astype(np.uint8)
    out = Resize(15, 13)(batch)
    assert out.shape == (4, 15, 13, 3) and out.dtype == np.uint8
    norm = ChannelNormalize((127.5,) * 3, (127.5,) * 3)(out)
    assert norm.dtype == np.float32
    want = (out.astype(np.float32) - 127.5) / 127.5
    np.testing.assert_allclose(norm, want, rtol=1e-6, atol=1e-6)


def test_loader_builds_atomically(tmp_path, monkeypatch):
    """build_and_load compiles to a temp path then os.replace()s into
    place: a missing .so is rebuilt, no *.tmp stragglers survive, and a
    failed compile leaves nothing behind (concurrent first-use builds can
    never publish a half-written library)."""
    import shutil

    from analytics_zoo_tpu.native import _loader

    work = tmp_path / "native"
    work.mkdir()
    shutil.copy(os.path.join(_loader.NATIVE_DIR, "zoo_image.cc"),
                work / "zoo_image.cc")
    monkeypatch.setattr(_loader, "NATIVE_DIR", str(work))
    lib = _loader.build_and_load("libzoo_image.so", "zoo_image.cc")
    assert lib is not None and (work / "libzoo_image.so").exists()
    assert not list(work.glob("*.tmp"))
    # broken source: build fails, returns None, leaves no artifacts
    (work / "broken.cc").write_text("int main( {")
    assert _loader.build_and_load("libbroken.so", "broken.cc") is None
    assert not (work / "libbroken.so").exists()
    assert not list(work.glob("*.tmp"))
