"""Serving host path: wire-format v2 codec + v1 interop, the async
publisher (drain-on-stop, backlog, batched writes), concurrent-publish
trace reconciliation, worker-thread lifecycle, and the status CLI's p99
SLO gate."""

import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common.context import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.engine import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.pipeline.inference import InferenceModel
from analytics_zoo_tpu.serving import (ClusterServing, InputQueue,
                                       LocalBackend, OutputQueue)
from analytics_zoo_tpu.serving.client import (INPUT_STREAM, decode_array,
                                              decode_payload, encode_array,
                                              encode_tensor, is_v2)


def _toy_model():
    init_zoo_context()
    m = Sequential()
    m.add(Dense(4, input_shape=(6,), activation="relu"))
    m.add(Dense(3, activation="softmax"))
    m.init_weights()
    return m


# ---------------------------------------------------------------------------
# v2 codec
# ---------------------------------------------------------------------------

def test_v2_codec_roundtrip_dtypes_and_shapes():
    rng = np.random.default_rng(0)
    for arr in (rng.normal(size=(3, 4)).astype(np.float32),
                np.array([1, -2, 3], np.int64),
                np.array(7.5, np.float64),              # 0-d scalar
                np.array([True, False]),
                rng.normal(size=(2, 5, 5)).astype(np.float16),
                np.empty((0, 4), np.float32)):          # empty batch axis
        fields = encode_tensor(arr)
        assert is_v2(fields) and isinstance(fields["data"], bytes)
        out = decode_payload(fields)
        assert out.dtype == arr.dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)


def test_v2_codec_big_endian_normalized_and_text_transport():
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    fields = encode_tensor(arr.astype(">f4"))
    # wire bytes are always little-endian, whatever the producer held
    assert np.dtype(fields["dtype"]).byteorder in ("<", "=", "|")
    np.testing.assert_array_equal(decode_payload(fields), arr)
    # a text-only transport degrades the payload to latin-1 str: decode
    # still recovers the exact bytes
    fields["data"] = fields["data"].decode("latin-1")
    np.testing.assert_array_equal(decode_payload(fields), arr)


def test_v2_codec_rejects_length_mismatch_and_objects():
    fields = encode_tensor(np.zeros((2, 2), np.float32))
    fields["data"] = fields["data"][:-1]
    with pytest.raises(ValueError):
        decode_payload(fields)
    with pytest.raises(ValueError):
        encode_tensor(np.array([object()]))
    # dtypes with no raw byte representation are rejected at VALIDATION,
    # not by a frombuffer failure mid-copy
    with pytest.raises(ValueError):
        decode_payload({"data": b"\x00" * 8, "dtype": "|O8", "shape": "1"})
    with pytest.raises(ValueError):
        decode_payload({"data": b"", "dtype": "<U0", "shape": "1"})


def test_v2_codec_rejects_hostile_shape_headers():
    """Headers are untrusted strings: negative dims, int64-wrapping
    products, and absurd dimensions must all fail VALIDATION — the
    server allocates a batch arena from a validated header, so any of
    these reaching np.empty would raise (or allocate gigabytes) on the
    unguarded serve loop."""
    from analytics_zoo_tpu.serving.client import (MAX_PAYLOAD_BYTES,
                                                  validate_v2)
    hostile = [
        "0,-1",                      # negative dim; product 0 matches b""
        "-4",                        # plainly negative
        "4294967296,4294967296",     # 2^64 wraps int64 prod to 0
        "0,99999999999999999999",    # 0 bytes but a >ssize_t dim
        str(MAX_PAYLOAD_BYTES + 1),  # over the single-tensor byte cap
    ]
    for shape in hostile:
        with pytest.raises(ValueError):
            validate_v2({"data": b"", "dtype": "|u1", "shape": shape})
        with pytest.raises(ValueError):
            decode_payload({"data": b"", "dtype": "|u1", "shape": shape})
    # the cap is on bytes, not elements: a big-itemsize dtype at the
    # element count that would pass as |u1 must still be rejected
    with pytest.raises(ValueError):
        validate_v2({"data": b"", "dtype": "<f8",
                     "shape": str(MAX_PAYLOAD_BYTES // 2)})
    # dimension COUNT is bounded too: 100 ones with a length-correct
    # 1-byte payload passes every per-dim/byte check, but np.empty caps
    # ndim at 64 (and the batch arena prepends a dim) — must fail at
    # validation, not at a loop-killing allocation
    with pytest.raises(ValueError):
        validate_v2({"data": b"\x00", "dtype": "|u1",
                     "shape": ",".join("1" * 100)})
    # subarray dtypes smuggle dims past every shape check: frombuffer
    # expands "(2,2)<f4" and the reshape/arena paths blow up mid-copy
    with pytest.raises(ValueError):
        validate_v2({"data": b"\x00" * 48, "dtype": "(2,2)<f4",
                     "shape": "3"})


def test_v1_npy_header_bounded_before_allocation():
    """The v1 fallback's .npy header is attacker-controlled too, and
    np.load preallocates the whole array from it before reading any
    payload — a ~100-byte record claiming a multi-GiB shape must be
    rejected at header validation, not at allocation."""
    import base64
    import io

    from analytics_zoo_tpu.serving.client import decode_array
    buf = io.BytesIO()
    np.lib.format.write_array_header_1_0(
        buf, {"descr": "<f8", "fortran_order": False,
              "shape": (2 ** 34,)})
    hostile = base64.b64encode(buf.getvalue()).decode("ascii")
    with pytest.raises(ValueError):
        decode_array(hostile)
    with pytest.raises(ValueError):
        decode_payload({"data": hostile})    # the v1 fallback path
    # a claimed size UNDER the byte cap but absent from the payload must
    # also fail before np.load preallocates the claimed gigabyte
    buf2 = io.BytesIO()
    np.lib.format.write_array_header_1_0(
        buf2, {"descr": "<f8", "fortran_order": False,
               "shape": (2 ** 27,)})
    with pytest.raises(ValueError):
        decode_array(base64.b64encode(buf2.getvalue()).decode("ascii"))
    # legit v1 payloads still round-trip through the bounded decode
    arr = np.arange(4, dtype=np.float32)
    np.testing.assert_array_equal(
        decode_payload({"data": encode_array(arr)}), arr)


def test_v1_fallback_decode():
    arr = np.arange(4, dtype=np.float32)
    # no dtype/shape fields => the base64 .npy path, str or bytes payload
    np.testing.assert_array_equal(
        decode_payload({"data": encode_array(arr)}), arr)
    np.testing.assert_array_equal(
        decode_payload({"data": encode_array(arr).encode("ascii")}), arr)


# ---------------------------------------------------------------------------
# v1 <-> v2 interop through the live server (version echo)
# ---------------------------------------------------------------------------

def test_v1_producer_served_and_answered_in_v1():
    """An OLD producer (base64 .npy, no dtype/shape fields) must be served
    by the new server AND answered in v1, so an old consumer's
    ``decode_array(res["value"])`` keeps working."""
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    rng = np.random.default_rng(1)
    x = rng.normal(size=(6,)).astype(np.float32)
    try:
        backend.xadd(INPUT_STREAM, {"uri": "old-1", "data": encode_array(x)})
        res = backend.pop_result("old-1", timeout=30.0)
    finally:
        serving.stop(drain=False)
    assert res is not None and set(res) == {"value"}, "v1 echo: bare value"
    assert isinstance(res["value"], str)
    np.testing.assert_allclose(decode_array(res["value"]),
                               im.predict(x[None])[0], rtol=1e-5, atol=1e-6)


def test_v2_producer_answered_in_v2():
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    inq = InputQueue(backend)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(6,)).astype(np.float32)
    try:
        inq.enqueue("new-1", x)
        res = backend.pop_result("new-1", timeout=30.0)
    finally:
        serving.stop(drain=False)
    assert res is not None and is_v2(res)
    assert isinstance(res["value"], bytes)
    np.testing.assert_allclose(decode_payload(res, "value"),
                               im.predict(x[None])[0], rtol=1e-5, atol=1e-6)


def test_mixed_v1_v2_batch_interop():
    """One read containing BOTH wire versions: all records served with the
    right predictions, each answered in its own request's format (the
    mixed read exercises the legacy decode fallback, not the arena)."""
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    rng = np.random.default_rng(3)
    xs = {f"i-{i}": rng.normal(size=(6,)).astype(np.float32)
          for i in range(6)}
    inq = InputQueue(backend)
    for i, (uri, x) in enumerate(xs.items()):
        if i % 2 == 0:
            backend.xadd(INPUT_STREAM, {"uri": uri,
                                        "data": encode_array(x)})   # v1
        else:
            inq.enqueue(uri, x)                                     # v2
    serving = ClusterServing(im, backend=backend, batch_size=8).start()
    outq = OutputQueue(backend)
    try:
        got = {uri: outq.query(uri, timeout=30.0) for uri in xs}
    finally:
        serving.stop(drain=False)
    direct = np.asarray(im.predict(np.stack(list(xs.values()))))
    for i, uri in enumerate(xs):
        np.testing.assert_allclose(got[uri], direct[i], rtol=1e-5,
                                   atol=1e-6)


def test_malformed_v2_header_cannot_kill_serve_loop():
    """A v2 record whose header passes shape/length arithmetic but names
    an unrepresentable dtype (object, zero-itemsize) must become an
    addressable undecodable error — and the loop must keep serving."""
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    outq = OutputQueue(backend)
    try:
        backend.xadd(INPUT_STREAM, {"uri": "obj", "data": b"\x00" * 8,
                                    "dtype": "|O8", "shape": "1", "v": "2"})
        from analytics_zoo_tpu.serving import ServingError
        with pytest.raises(ServingError):
            outq.query("obj", timeout=10.0)
        # the loop survived: a well-formed record still serves
        InputQueue(backend).enqueue("ok", np.zeros(6, np.float32))
        assert outq.query("ok", timeout=30.0) is not None
    finally:
        serving.stop(drain=False)


def test_hostile_v2_shape_header_dropped_not_loop_killing():
    """The review repro: a v2 header whose length arithmetic passes
    (negative-dim / wrapped product = 0 against an empty payload) used
    to reach ``np.empty`` in the arena pool and kill the serve loop.
    It must be dropped as an addressable undecodable error, and the
    loop must keep serving."""
    from analytics_zoo_tpu.serving import ServingError
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    outq = OutputQueue(backend)
    try:
        for uri, payload, dtype, shape in (
                ("neg", b"", "<f4", "0,-1"),
                ("wrap", b"", "<f4", "4294967296,4294967296"),
                ("ndim", b"\x00" * 4, "<f4", ",".join("1" * 100)),
                ("subarr", b"\x00" * 48, "(2,2)<f4", "3")):
            backend.xadd(INPUT_STREAM, {"uri": uri, "data": payload,
                                        "dtype": dtype, "shape": shape,
                                        "v": "2"})
            with pytest.raises(ServingError):
                outq.query(uri, timeout=10.0)
        InputQueue(backend).enqueue("ok", np.zeros(6, np.float32))
        assert outq.query("ok", timeout=30.0) is not None
    finally:
        serving.stop(drain=False)


def test_arena_pool_total_bytes_bounded_lru():
    """Shape-rotating traffic must not pin one pool entry per shape
    forever: the pool bounds TOTAL free bytes, evicting least-recently-
    used shapes first while the hot shape keeps its buffer."""
    from analytics_zoo_tpu.serving.server import _ArenaPool
    pool = _ArenaPool(batch_size=4, cap=4, max_bytes=192)  # a tight budget
    arenas = {}
    for n in (3, 5, 7, 9, 11):           # five distinct row shapes
        a = pool.acquire((n,), np.float32)
        arenas[n] = a
        pool.release(a)
    assert pool._bytes <= pool.max_bytes
    assert sum(len(v) for v in pool._free.values()) <= 2
    # the most recently released shape survived and is reused
    assert pool.acquire((11,), np.float32) is arenas[11]


def test_serve_loop_survives_error_record_write_failure():
    """An undecodable record while the result store refuses writes: the
    failure handler's own set_result raising must not kill the serve
    loop (the error record is lost, the loop keeps serving)."""
    class NoErrorWrites(LocalBackend):
        def set_result(self, uri, fields):
            raise RuntimeError("result store down")
        # set_results (the publisher's batched write) still works

    im = InferenceModel().from_keras(_toy_model())
    backend = NoErrorWrites()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    try:
        backend.xadd(INPUT_STREAM, {"uri": "bad", "data": b"",
                                    "dtype": "<f4", "shape": "0,-1",
                                    "v": "2"})
        InputQueue(backend).enqueue("good", np.zeros(6, np.float32))
        assert OutputQueue(backend).query("good", timeout=30.0) is not None
        assert serving._thread.is_alive()
    finally:
        serving.stop(drain=False)


def test_oversized_rows_fall_back_to_stack_not_giant_arena(monkeypatch):
    """The arena preallocates ``batch_size`` rows from ONE header, so a
    large validated row must not drive a batch_size-times-larger
    np.empty — reads over ``_MAX_ARENA_BYTES`` must assemble via the
    stack fallback and still serve correctly."""
    from analytics_zoo_tpu.serving import server as server_mod
    monkeypatch.setattr(server_mod, "_MAX_ARENA_BYTES", 64)
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(7)
    xs = {f"big-{i}": rng.normal(size=(6,)).astype(np.float32)  # 24 B rows:
          for i in range(8)}                                    # 4x24 > 64
    try:
        for uri, x in xs.items():
            inq.enqueue(uri, x)
        got = {uri: outq.query(uri, timeout=30.0) for uri in xs}
    finally:
        serving.stop(drain=False)
    assert not serving._arena_pool._free, "no arena may have been pooled"
    direct = np.asarray(im.predict(np.stack(list(xs.values()))))
    for i, uri in enumerate(xs):
        np.testing.assert_allclose(got[uri], direct[i], rtol=1e-5,
                                   atol=1e-6)


def test_sync_passthrough_model_view_results_not_corrupted():
    """The server accepts any ``.predict``; one answering with a VIEW of
    its input must not publish bytes that a recycled arena has since
    overwritten (the publisher encodes after the arena returns to the
    pool)."""

    class Passthrough:
        def predict(self, batch):
            return batch       # a view of the arena rows

    backend = LocalBackend()
    serving = ClusterServing(Passthrough(), backend=backend,
                             batch_size=4).start()
    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(9)
    xs = {f"v-{i}": rng.normal(size=(6,)).astype(np.float32)
          for i in range(20)}      # several batches through the same pool
    try:
        for uri, x in xs.items():
            inq.enqueue(uri, x)
        for uri, x in xs.items():
            np.testing.assert_array_equal(outq.query(uri, timeout=30.0), x)
    finally:
        serving.stop(drain=False)


def test_arena_reuse_across_batches_keeps_results_correct():
    """Consecutive uniform-v2 batches reuse pooled arena buffers; a stale
    row must never leak into a later batch's prediction."""
    im = InferenceModel(concurrent_num=2).from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4,
                             decode_workers=2).start()
    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(4)
    try:
        for round_i in range(5):        # many batches through the pool
            xs = {f"b{round_i}-{i}": rng.normal(size=(6,)).astype(np.float32)
                  for i in range(4)}
            for uri, x in xs.items():
                inq.enqueue(uri, x)
            for uri, x in xs.items():
                got = outq.query(uri, timeout=30.0)
                want = im.predict(x[None])[0]
                np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    finally:
        serving.stop(drain=False)


# ---------------------------------------------------------------------------
# async publisher
# ---------------------------------------------------------------------------

class _SlowResultBackend(LocalBackend):
    """LocalBackend whose batched result writes stall — builds a real
    publisher backlog so drain-on-stop is actually exercised."""

    def __init__(self, delay_s: float = 0.05, **kw):
        super().__init__(**kw)
        self.delay_s = delay_s
        self.batched_writes = 0

    def set_results(self, results):
        time.sleep(self.delay_s)
        self.batched_writes += 1
        super().set_results(results)


def test_publisher_drains_backlog_on_stop():
    """Every batch the serve loop handed the publisher must be published
    before stop() returns, even when the result backend is slow enough
    that a backlog exists at stop time — and the results must have gone
    through the BATCHED write path."""
    im = InferenceModel(concurrent_num=2).from_keras(_toy_model())
    backend = _SlowResultBackend(delay_s=0.05)
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    inq = InputQueue(backend)
    rng = np.random.default_rng(5)
    n = 24
    for i in range(n):
        inq.enqueue(f"d-{i}", rng.normal(size=(6,)).astype(np.float32))
    serving.stop(drain=True)
    # after stop: publisher thread gone, every record answered
    assert serving.served == n
    assert backend.batched_writes >= 1
    outq = OutputQueue(backend)
    got = outq.dequeue()
    assert set(got) == {f"d-{i}" for i in range(n)}
    assert not outq.last_errors


class _BrokenResultBackend(LocalBackend):
    """Batched result writes always fail; single-record error writes
    still work — models a result store rejecting the bulk op."""

    def set_results(self, results):
        raise RuntimeError("bulk write refused")


def test_publish_failure_answers_with_distinct_error():
    """When inference succeeded but the result write failed, producers
    must see a PUBLISH error, not 'inference failed' — the two need
    different operator responses (backend vs model)."""
    from analytics_zoo_tpu import observability as obs
    from analytics_zoo_tpu.serving import ServingError
    reg = obs.MetricsRegistry()
    im = InferenceModel(registry=reg).from_keras(_toy_model())
    backend = _BrokenResultBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4,
                             registry=reg).start()
    inq, outq = InputQueue(backend), OutputQueue(backend)
    try:
        inq.enqueue("pub-fail", np.zeros(6, np.float32))
        with pytest.raises(ServingError, match="result publish failed"):
            outq.query("pub-fail", timeout=10.0)
    finally:
        serving.stop(drain=False)
    # the scrape separates publish failures from model failures, in a
    # family of its own so sum() over zoo_serving_failures_total stays 1
    text = obs.render_prometheus(reg)
    assert ('zoo_serving_failure_errors_total'
            '{error="result publish failed"} 1') in text
    assert 'zoo_serving_failures_total 1' in text


def test_stop_times_out_instead_of_hanging_when_publisher_wedged():
    """Publisher wedged mid-write on a stalled backend with the publish
    queue full: stop() must raise its TimeoutError (the stop sentinel
    put is bounded), not block forever — and a second stop() after the
    backend recovers must drain everything cleanly."""
    gate = threading.Event()

    class Wedged(LocalBackend):
        def set_results(self, results):
            gate.wait()      # a dead-but-open connection
            super().set_results(results)

    im = InferenceModel().from_keras(_toy_model())
    backend = Wedged()
    serving = ClusterServing(im, backend=backend, batch_size=1,
                             publish_queue=2).start()
    inq = InputQueue(backend)
    for i in range(3):       # 1 wedged in the publisher + 2 filling the queue
        inq.enqueue(f"w-{i}", np.zeros(6, np.float32))
    deadline = time.monotonic() + 10
    while serving._pub_queue.qsize() < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(TimeoutError):
        serving.stop(drain=True, timeout=0.5)
    gate.set()
    serving.stop(timeout=30.0)
    assert serving.served == 3
    got = OutputQueue(backend).dequeue()
    assert set(got) == {f"w-{i}" for i in range(3)}


def test_concurrent_publish_trace_reconciliation(tmp_path):
    """Producers enqueue concurrently while the publisher emits publish
    events from its own thread: the event log must still show EXACTLY
    four parent-linked phase events per record, one trace per record,
    zero orphans."""
    from analytics_zoo_tpu import observability as obs

    reg = obs.MetricsRegistry()
    im = InferenceModel(concurrent_num=2, registry=reg).from_keras(
        _toy_model())
    backend = LocalBackend()
    events_path = str(tmp_path / "events.jsonl")
    serving = (ClusterServing(im, backend=backend, batch_size=8,
                              registry=reg, decode_workers=2)
               .set_json_events(events_path).start())
    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(6)
    data = {f"c{t}-{i}": rng.normal(size=(6,)).astype(np.float32)
            for t in range(4) for i in range(12)}

    def produce(t):
        for i in range(12):
            inq.enqueue(f"c{t}-{i}", data[f"c{t}-{i}"])

    threads = [threading.Thread(target=produce, args=(t,)) for t in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    for uri in data:
        assert outq.query(uri, timeout=30.0) is not None
    serving.stop()          # joins the publisher: all events flushed

    events = obs.read_events(events_path, kind="request")
    n = len(data)
    assert len(events) == 4 * n, "exactly 4 events per record"
    by_trace = {}
    for e in events:
        by_trace.setdefault(e["trace"], {})[e["phase"]] = e
    assert len(by_trace) == n, "one trace per record, zero orphans"
    expected_parent = {"enqueue": None, "dequeue": "enqueue",
                       "dispatch": "dequeue", "publish": "dispatch"}
    uris = set()
    for trace, phases in by_trace.items():
        assert set(phases) == set(expected_parent), trace
        for phase, e in phases.items():
            assert e["parent"] == expected_parent[phase]
        assert len({e["uri"] for e in phases.values()}) == 1
        uris.add(phases["publish"]["uri"])
    assert uris == set(data)
    # registry agrees with the log
    snap = reg.snapshot()
    assert snap["zoo_serving_records_total"]["value"] == n
    assert snap["zoo_serving_failures_total"]["value"] == 0
    assert snap["zoo_serving_undecodable_total"]["value"] == 0
    # the codec histograms saw every read/publish
    assert snap["zoo_serving_decode_seconds"]["count"] >= 1
    assert snap["zoo_serving_encode_seconds"]["count"] == \
        snap["zoo_serving_batches_total"]["value"]


def test_no_leaked_threads_after_stop():
    """The serve loop, decode workers, publisher, and scrape endpoint must
    all be joined by stop() — a restartable server cannot shed threads."""
    im = InferenceModel().from_keras(_toy_model())
    x = np.zeros((1, 6), np.float32)
    im.predict(x)           # warm the backend's own lazy thread pools
    before = set(threading.enumerate())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4,
                             decode_workers=2)
    serving.serve_metrics(port=0)
    serving.start()
    inq, outq = InputQueue(backend), OutputQueue(backend)
    inq.enqueue("t-0", x[0])
    assert outq.query("t-0", timeout=30.0) is not None
    serving.stop()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"threads survived stop(): {leaked}"
    # belt and braces: none of OUR named workers linger even if some
    # unrelated library thread appeared mid-test
    names = [t.name for t in threading.enumerate()]
    for prefix in ("cluster-serving", "serving-decode", "zoo-metrics"):
        assert not any(n.startswith(prefix) for n in names), names


def test_restart_after_stop_serves_again():
    """start() after a full stop() rebuilds the publisher + decode pool."""
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4)
    inq, outq = InputQueue(backend), OutputQueue(backend)
    for round_i in range(2):
        serving.start()
        inq.enqueue(f"r-{round_i}", np.zeros(6, np.float32))
        assert outq.query(f"r-{round_i}", timeout=30.0) is not None
        serving.stop()


# ---------------------------------------------------------------------------
# bounded in-flight chunks (ADVICE r5)
# ---------------------------------------------------------------------------

def test_predict_async_many_chunks_matches_unchunked():
    """A many-chunk predict (outputs read back incrementally to bound
    HBM) must equal the single-chunk result, ragged final chunk
    included."""
    model = _toy_model()
    chunked = InferenceModel(max_batch_size=4).from_keras(model)
    whole = InferenceModel().from_keras(model)
    rng = np.random.default_rng(7)
    for n in (3, 8, 19):     # 1 chunk, 2 chunks, 5 chunks (ragged tail)
        x = rng.normal(size=(n, 6)).astype(np.float32)
        np.testing.assert_allclose(chunked.predict(x), whole.predict(x),
                                   rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# status CLI: p99 SLO thresholds
# ---------------------------------------------------------------------------

def test_status_cli_slo_threshold_flags():
    """--slo-p99-ms: generous thresholds pass (exit 0); a sub-microsecond
    e2e threshold and a threshold on an absent family both breach (exit
    2, breaching rows flagged)."""
    import os
    import subprocess
    import sys

    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(scripts) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    env["JAX_PLATFORMS"] = "cpu"

    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4)
    scrape = serving.serve_metrics(port=0)
    serving.start()
    try:
        inq, outq = InputQueue(backend), OutputQueue(backend)
        rng = np.random.default_rng(8)
        for i in range(8):
            inq.enqueue(f"s-{i}", rng.normal(size=(6,)).astype(np.float32))
        for i in range(8):
            assert outq.query(f"s-{i}", timeout=30.0) is not None
        cli = [sys.executable, os.path.join(scripts,
                                            "cluster-serving-status"),
              f"{scrape.host}:{scrape.port}"]
        # generous thresholds on every family: healthy exit
        r = subprocess.run(
            cli + ["--slo-p99-ms", "1e9", "--slo-p99-ms", "queue_wait=1e9",
                   "--slo-p99-ms", "dispatch=1e9"],
            capture_output=True, text=True, env=env, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "BREACH" not in r.stdout
        # an impossible e2e threshold breaches; so does a threshold on a
        # family with no samples
        r = subprocess.run(
            cli + ["--slo-p99-ms", "e2e=0.000001",
                   "--slo-p99-ms", "zoo_absent_quantiles_seconds=5"],
            capture_output=True, text=True, env=env, timeout=120)
        assert r.returncode == 2, (r.stdout[-2000:], r.stderr[-2000:])
        assert "BREACH" in r.stdout
        assert "no samples" in r.stderr
    finally:
        serving.stop(drain=False)
