"""Serving host path: wire-format v2 codec + v1 interop, the async
publisher (drain-on-stop, backlog, batched writes), concurrent-publish
trace reconciliation, worker-thread lifecycle, and the status CLI's p99
SLO gate."""

import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common.context import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.engine import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.pipeline.inference import InferenceModel
from analytics_zoo_tpu.serving import (ClusterServing, InputQueue,
                                       LocalBackend, OutputQueue)
from analytics_zoo_tpu.serving.client import (INPUT_STREAM, decode_array,
                                              decode_payload, encode_array,
                                              encode_tensor, is_v2)


def _toy_model():
    init_zoo_context()
    m = Sequential()
    m.add(Dense(4, input_shape=(6,), activation="relu"))
    m.add(Dense(3, activation="softmax"))
    m.init_weights()
    return m


# ---------------------------------------------------------------------------
# v2 codec
# ---------------------------------------------------------------------------

def test_v2_codec_roundtrip_dtypes_and_shapes():
    rng = np.random.default_rng(0)
    for arr in (rng.normal(size=(3, 4)).astype(np.float32),
                np.array([1, -2, 3], np.int64),
                np.array(7.5, np.float64),              # 0-d scalar
                np.array([True, False]),
                rng.normal(size=(2, 5, 5)).astype(np.float16),
                np.empty((0, 4), np.float32)):          # empty batch axis
        fields = encode_tensor(arr)
        assert is_v2(fields) and isinstance(fields["data"], bytes)
        out = decode_payload(fields)
        assert out.dtype == arr.dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)


def test_v2_codec_big_endian_normalized_and_text_transport():
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    fields = encode_tensor(arr.astype(">f4"))
    # wire bytes are always little-endian, whatever the producer held
    assert np.dtype(fields["dtype"]).byteorder in ("<", "=", "|")
    np.testing.assert_array_equal(decode_payload(fields), arr)
    # a text-only transport degrades the payload to latin-1 str: decode
    # still recovers the exact bytes
    fields["data"] = fields["data"].decode("latin-1")
    np.testing.assert_array_equal(decode_payload(fields), arr)


def test_v2_codec_rejects_length_mismatch_and_objects():
    fields = encode_tensor(np.zeros((2, 2), np.float32))
    fields["data"] = fields["data"][:-1]
    with pytest.raises(ValueError):
        decode_payload(fields)
    with pytest.raises(ValueError):
        encode_tensor(np.array([object()]))
    # dtypes with no raw byte representation are rejected at VALIDATION,
    # not by a frombuffer failure mid-copy
    with pytest.raises(ValueError):
        decode_payload({"data": b"\x00" * 8, "dtype": "|O8", "shape": "1"})
    with pytest.raises(ValueError):
        decode_payload({"data": b"", "dtype": "<U0", "shape": "1"})


def test_v1_fallback_decode():
    arr = np.arange(4, dtype=np.float32)
    # no dtype/shape fields => the base64 .npy path, str or bytes payload
    np.testing.assert_array_equal(
        decode_payload({"data": encode_array(arr)}), arr)
    np.testing.assert_array_equal(
        decode_payload({"data": encode_array(arr).encode("ascii")}), arr)


# ---------------------------------------------------------------------------
# v1 <-> v2 interop through the live server (version echo)
# ---------------------------------------------------------------------------

def test_v1_producer_served_and_answered_in_v1():
    """An OLD producer (base64 .npy, no dtype/shape fields) must be served
    by the new server AND answered in v1, so an old consumer's
    ``decode_array(res["value"])`` keeps working."""
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    rng = np.random.default_rng(1)
    x = rng.normal(size=(6,)).astype(np.float32)
    try:
        backend.xadd(INPUT_STREAM, {"uri": "old-1", "data": encode_array(x)})
        res = backend.pop_result("old-1", timeout=30.0)
    finally:
        serving.stop(drain=False)
    assert res is not None and set(res) == {"value"}, "v1 echo: bare value"
    assert isinstance(res["value"], str)
    np.testing.assert_allclose(decode_array(res["value"]),
                               im.predict(x[None])[0], rtol=1e-5, atol=1e-6)


def test_v2_producer_answered_in_v2():
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    inq = InputQueue(backend)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(6,)).astype(np.float32)
    try:
        inq.enqueue("new-1", x)
        res = backend.pop_result("new-1", timeout=30.0)
    finally:
        serving.stop(drain=False)
    assert res is not None and is_v2(res)
    assert isinstance(res["value"], bytes)
    np.testing.assert_allclose(decode_payload(res, "value"),
                               im.predict(x[None])[0], rtol=1e-5, atol=1e-6)


def test_mixed_v1_v2_batch_interop():
    """One read containing BOTH wire versions: all records served with the
    right predictions, each answered in its own request's format (the
    mixed read exercises the legacy decode fallback, not the arena)."""
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    rng = np.random.default_rng(3)
    xs = {f"i-{i}": rng.normal(size=(6,)).astype(np.float32)
          for i in range(6)}
    inq = InputQueue(backend)
    for i, (uri, x) in enumerate(xs.items()):
        if i % 2 == 0:
            backend.xadd(INPUT_STREAM, {"uri": uri,
                                        "data": encode_array(x)})   # v1
        else:
            inq.enqueue(uri, x)                                     # v2
    serving = ClusterServing(im, backend=backend, batch_size=8).start()
    outq = OutputQueue(backend)
    try:
        got = {uri: outq.query(uri, timeout=30.0) for uri in xs}
    finally:
        serving.stop(drain=False)
    direct = np.asarray(im.predict(np.stack(list(xs.values()))))
    for i, uri in enumerate(xs):
        np.testing.assert_allclose(got[uri], direct[i], rtol=1e-5,
                                   atol=1e-6)


def test_malformed_v2_header_cannot_kill_serve_loop():
    """A v2 record whose header passes shape/length arithmetic but names
    an unrepresentable dtype (object, zero-itemsize) must become an
    addressable undecodable error — and the loop must keep serving."""
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    outq = OutputQueue(backend)
    try:
        backend.xadd(INPUT_STREAM, {"uri": "obj", "data": b"\x00" * 8,
                                    "dtype": "|O8", "shape": "1", "v": "2"})
        from analytics_zoo_tpu.serving import ServingError
        with pytest.raises(ServingError):
            outq.query("obj", timeout=10.0)
        # the loop survived: a well-formed record still serves
        InputQueue(backend).enqueue("ok", np.zeros(6, np.float32))
        assert outq.query("ok", timeout=30.0) is not None
    finally:
        serving.stop(drain=False)


def test_sync_passthrough_model_view_results_not_corrupted():
    """The server accepts any ``.predict``; one answering with a VIEW of
    its input must not publish bytes that a recycled arena has since
    overwritten (the publisher encodes after the arena returns to the
    pool)."""

    class Passthrough:
        def predict(self, batch):
            return batch       # a view of the arena rows

    backend = LocalBackend()
    serving = ClusterServing(Passthrough(), backend=backend,
                             batch_size=4).start()
    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(9)
    xs = {f"v-{i}": rng.normal(size=(6,)).astype(np.float32)
          for i in range(20)}      # several batches through the same pool
    try:
        for uri, x in xs.items():
            inq.enqueue(uri, x)
        for uri, x in xs.items():
            np.testing.assert_array_equal(outq.query(uri, timeout=30.0), x)
    finally:
        serving.stop(drain=False)


def test_arena_reuse_across_batches_keeps_results_correct():
    """Consecutive uniform-v2 batches reuse pooled arena buffers; a stale
    row must never leak into a later batch's prediction."""
    im = InferenceModel(concurrent_num=2).from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4,
                             decode_workers=2).start()
    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(4)
    try:
        for round_i in range(5):        # many batches through the pool
            xs = {f"b{round_i}-{i}": rng.normal(size=(6,)).astype(np.float32)
                  for i in range(4)}
            for uri, x in xs.items():
                inq.enqueue(uri, x)
            for uri, x in xs.items():
                got = outq.query(uri, timeout=30.0)
                want = im.predict(x[None])[0]
                np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    finally:
        serving.stop(drain=False)


# ---------------------------------------------------------------------------
# async publisher
# ---------------------------------------------------------------------------

class _SlowResultBackend(LocalBackend):
    """LocalBackend whose batched result writes stall — builds a real
    publisher backlog so drain-on-stop is actually exercised."""

    def __init__(self, delay_s: float = 0.05, **kw):
        super().__init__(**kw)
        self.delay_s = delay_s
        self.batched_writes = 0

    def set_results(self, results):
        time.sleep(self.delay_s)
        self.batched_writes += 1
        super().set_results(results)


def test_publisher_drains_backlog_on_stop():
    """Every batch the serve loop handed the publisher must be published
    before stop() returns, even when the result backend is slow enough
    that a backlog exists at stop time — and the results must have gone
    through the BATCHED write path."""
    im = InferenceModel(concurrent_num=2).from_keras(_toy_model())
    backend = _SlowResultBackend(delay_s=0.05)
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    inq = InputQueue(backend)
    rng = np.random.default_rng(5)
    n = 24
    for i in range(n):
        inq.enqueue(f"d-{i}", rng.normal(size=(6,)).astype(np.float32))
    serving.stop(drain=True)
    # after stop: publisher thread gone, every record answered
    assert serving.served == n
    assert backend.batched_writes >= 1
    outq = OutputQueue(backend)
    got = outq.dequeue()
    assert set(got) == {f"d-{i}" for i in range(n)}
    assert not outq.last_errors


def test_concurrent_publish_trace_reconciliation(tmp_path):
    """Producers enqueue concurrently while the publisher emits publish
    events from its own thread: the event log must still show EXACTLY
    four parent-linked phase events per record, one trace per record,
    zero orphans."""
    from analytics_zoo_tpu import observability as obs

    reg = obs.MetricsRegistry()
    im = InferenceModel(concurrent_num=2, registry=reg).from_keras(
        _toy_model())
    backend = LocalBackend()
    events_path = str(tmp_path / "events.jsonl")
    serving = (ClusterServing(im, backend=backend, batch_size=8,
                              registry=reg, decode_workers=2)
               .set_json_events(events_path).start())
    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(6)
    data = {f"c{t}-{i}": rng.normal(size=(6,)).astype(np.float32)
            for t in range(4) for i in range(12)}

    def produce(t):
        for i in range(12):
            inq.enqueue(f"c{t}-{i}", data[f"c{t}-{i}"])

    threads = [threading.Thread(target=produce, args=(t,)) for t in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    for uri in data:
        assert outq.query(uri, timeout=30.0) is not None
    serving.stop()          # joins the publisher: all events flushed

    events = obs.read_events(events_path, kind="request")
    n = len(data)
    assert len(events) == 4 * n, "exactly 4 events per record"
    by_trace = {}
    for e in events:
        by_trace.setdefault(e["trace"], {})[e["phase"]] = e
    assert len(by_trace) == n, "one trace per record, zero orphans"
    expected_parent = {"enqueue": None, "dequeue": "enqueue",
                       "dispatch": "dequeue", "publish": "dispatch"}
    uris = set()
    for trace, phases in by_trace.items():
        assert set(phases) == set(expected_parent), trace
        for phase, e in phases.items():
            assert e["parent"] == expected_parent[phase]
        assert len({e["uri"] for e in phases.values()}) == 1
        uris.add(phases["publish"]["uri"])
    assert uris == set(data)
    # registry agrees with the log
    snap = reg.snapshot()
    assert snap["zoo_serving_records_total"]["value"] == n
    assert snap["zoo_serving_failures_total"]["value"] == 0
    assert snap["zoo_serving_undecodable_total"]["value"] == 0
    # the codec histograms saw every read/publish
    assert snap["zoo_serving_decode_seconds"]["count"] >= 1
    assert snap["zoo_serving_encode_seconds"]["count"] == \
        snap["zoo_serving_batches_total"]["value"]


def test_no_leaked_threads_after_stop():
    """The serve loop, decode workers, publisher, and scrape endpoint must
    all be joined by stop() — a restartable server cannot shed threads."""
    im = InferenceModel().from_keras(_toy_model())
    x = np.zeros((1, 6), np.float32)
    im.predict(x)           # warm the backend's own lazy thread pools
    before = set(threading.enumerate())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4,
                             decode_workers=2)
    serving.serve_metrics(port=0)
    serving.start()
    inq, outq = InputQueue(backend), OutputQueue(backend)
    inq.enqueue("t-0", x[0])
    assert outq.query("t-0", timeout=30.0) is not None
    serving.stop()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"threads survived stop(): {leaked}"
    # belt and braces: none of OUR named workers linger even if some
    # unrelated library thread appeared mid-test
    names = [t.name for t in threading.enumerate()]
    for prefix in ("cluster-serving", "serving-decode", "zoo-metrics"):
        assert not any(n.startswith(prefix) for n in names), names


def test_restart_after_stop_serves_again():
    """start() after a full stop() rebuilds the publisher + decode pool."""
    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4)
    inq, outq = InputQueue(backend), OutputQueue(backend)
    for round_i in range(2):
        serving.start()
        inq.enqueue(f"r-{round_i}", np.zeros(6, np.float32))
        assert outq.query(f"r-{round_i}", timeout=30.0) is not None
        serving.stop()


# ---------------------------------------------------------------------------
# bounded in-flight chunks (ADVICE r5)
# ---------------------------------------------------------------------------

def test_predict_async_many_chunks_matches_unchunked():
    """A many-chunk predict (outputs read back incrementally to bound
    HBM) must equal the single-chunk result, ragged final chunk
    included."""
    model = _toy_model()
    chunked = InferenceModel(max_batch_size=4).from_keras(model)
    whole = InferenceModel().from_keras(model)
    rng = np.random.default_rng(7)
    for n in (3, 8, 19):     # 1 chunk, 2 chunks, 5 chunks (ragged tail)
        x = rng.normal(size=(n, 6)).astype(np.float32)
        np.testing.assert_allclose(chunked.predict(x), whole.predict(x),
                                   rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# status CLI: p99 SLO thresholds
# ---------------------------------------------------------------------------

def test_status_cli_slo_threshold_flags():
    """--slo-p99-ms: generous thresholds pass (exit 0); a sub-microsecond
    e2e threshold and a threshold on an absent family both breach (exit
    2, breaching rows flagged)."""
    import os
    import subprocess
    import sys

    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(scripts) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    env["JAX_PLATFORMS"] = "cpu"

    im = InferenceModel().from_keras(_toy_model())
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4)
    scrape = serving.serve_metrics(port=0)
    serving.start()
    try:
        inq, outq = InputQueue(backend), OutputQueue(backend)
        rng = np.random.default_rng(8)
        for i in range(8):
            inq.enqueue(f"s-{i}", rng.normal(size=(6,)).astype(np.float32))
        for i in range(8):
            assert outq.query(f"s-{i}", timeout=30.0) is not None
        cli = [sys.executable, os.path.join(scripts,
                                            "cluster-serving-status"),
              f"{scrape.host}:{scrape.port}"]
        # generous thresholds on every family: healthy exit
        r = subprocess.run(
            cli + ["--slo-p99-ms", "1e9", "--slo-p99-ms", "queue_wait=1e9",
                   "--slo-p99-ms", "dispatch=1e9"],
            capture_output=True, text=True, env=env, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "BREACH" not in r.stdout
        # an impossible e2e threshold breaches; so does a threshold on a
        # family with no samples
        r = subprocess.run(
            cli + ["--slo-p99-ms", "e2e=0.000001",
                   "--slo-p99-ms", "zoo_absent_quantiles_seconds=5"],
            capture_output=True, text=True, env=env, timeout=120)
        assert r.returncode == 2, (r.stdout[-2000:], r.stderr[-2000:])
        assert "BREACH" in r.stdout
        assert "no samples" in r.stderr
    finally:
        serving.stop(drain=False)
