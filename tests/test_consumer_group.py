"""Backend-level consumer-group semantics (``serving/backend.py``) and
the fleet registry primitives (``serving/fleet.py``) — the contracts the
fleet chaos harness (``tests/test_fleet_chaos.py``) builds on:

* exactly-one-consumer delivery, PEL tracking until ack, idempotent
  acks,
* idle-gated reclaim with atomic per-entry ownership transfer and
  delivery counting,
* heartbeat freshness (TTL), cached producer-side reads, the
  all-live-members saturation rule, and the mixed-mode conflict check.
"""

import threading
import time

import pytest

from analytics_zoo_tpu.serving.backend import LocalBackend
from analytics_zoo_tpu.serving.fleet import (FleetView, check_mode_conflict,
                                             live_members, publish_member,
                                             remove_member)


def _seed(backend, stream, n):
    backend.xgroup_create(stream, "g")
    return [backend.xadd(stream, {"uri": f"u{i}"}) for i in range(n)]


def test_group_delivers_each_entry_to_exactly_one_consumer():
    b = LocalBackend()
    _seed(b, "s", 6)
    e1 = b.xreadgroup("s", "g", "c1", 4, block_ms=10)
    e2 = b.xreadgroup("s", "g", "c2", 4, block_ms=10)
    assert [f["uri"] for _, f in e1] == ["u0", "u1", "u2", "u3"]
    assert [f["uri"] for _, f in e2] == ["u4", "u5"]
    # delivered entries left the undelivered backlog but are pending
    assert b.stream_len("s") == 0
    assert b.backlog_len("s", "g") == 0
    assert b.pending_len("s", "g") == 6
    assert b.xpending("s", "g") == {"c1": 4, "c2": 2}
    # an empty group read blocks out its window, it does not re-deliver
    assert b.xreadgroup("s", "g", "c3", 4, block_ms=10) == []


def test_ack_settles_and_is_idempotent():
    b = LocalBackend()
    _seed(b, "s", 3)
    entries = b.xreadgroup("s", "g", "c1", 3, block_ms=10)
    ids = [eid for eid, _ in entries]
    assert b.xack("s", "g", *ids[:2]) == 2
    assert b.pending_len("s", "g") == 1
    # re-ack counts zero — the double-ack after a DLQ spill must never
    # double-count in zoo_serving_acks_total
    assert b.xack("s", "g", *ids[:2]) == 0
    assert b.xack("s", "g", ids[2]) == 1
    assert b.pending_len("s", "g") == 0


def test_autoclaim_respects_idle_threshold_and_count():
    b = LocalBackend()
    _seed(b, "s", 5)
    b.xreadgroup("s", "g", "dead", 5, block_ms=10)
    # nothing is idle enough yet
    assert b.xautoclaim("s", "g", "new", 10_000, count=10) == []
    time.sleep(0.03)
    first = b.xautoclaim("s", "g", "new", 20.0, count=2)
    assert len(first) == 2      # the count cap holds
    assert all(prev == "dead" and times == 2
               for _e, _f, prev, times in first)
    # the claim reset their idle clocks: a second sweep sees only the
    # remaining three
    rest = b.xautoclaim("s", "g", "other", 20.0, count=10)
    assert len(rest) == 3
    assert b.xpending("s", "g") == {"new": 2, "other": 3}
    # a reclaim of one's OWN entries works too (lost-reply recovery)
    time.sleep(0.03)
    own = b.xautoclaim("s", "g", "new", 20.0, count=10)
    assert len(own) == 5
    assert all(times == 3 for _e, _f, _p, times in own)


def test_autoclaim_is_atomic_under_concurrent_sweeps():
    b = LocalBackend()
    _seed(b, "s", 40)
    delivered = b.xreadgroup("s", "g", "dead", 40, block_ms=10)
    time.sleep(0.03)
    out = {}
    barrier = threading.Barrier(2)

    def sweep(name):
        barrier.wait()
        out[name] = {e for e, *_ in b.xautoclaim("s", "g", name, 20.0,
                                                 count=40)}

    ts = [threading.Thread(target=sweep, args=(n,)) for n in ("a", "b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert out["a"] | out["b"] == {e for e, _ in delivered}
    assert out["a"] & out["b"] == set()


def test_group_create_is_idempotent_and_scoped():
    b = LocalBackend()
    b.xgroup_create("s", "g")
    b.xgroup_create("s", "g")           # no raise
    _seed(b, "s", 2)
    b.xreadgroup("s", "g", "c", 2, block_ms=10)
    # a different (stream, group) key holds its own PEL
    assert b.pending_len("s", "other") == 0
    assert b.pending_len("other", "g") == 0


# ---------------------------------------------------------------------------
# fleet registry
# ---------------------------------------------------------------------------

def test_fleet_membership_ttl_and_clean_removal():
    b = LocalBackend()
    publish_member(b, "s", "r1", {"mode": "group:g", "saturated": False})
    publish_member(b, "s", "r2", {"mode": "group:g", "saturated": True})
    members = live_members(b, "s", ttl_s=5.0)
    assert set(members) == {"r1", "r2"}
    # a stale heartbeat is a dead replica
    assert live_members(b, "s", ttl_s=0.0) in ({}, {})
    remove_member(b, "s", "r1")
    assert set(live_members(b, "s", ttl_s=5.0)) == {"r2"}
    # malformed payloads (a half-written heartbeat) are skipped
    b.fleet_set("s", "broken", "{not json")
    assert "broken" not in live_members(b, "s", ttl_s=5.0)


def test_fleet_view_saturation_rule_and_cache():
    b = LocalBackend()
    view = FleetView(b, "s", cache_s=10.0, ttl_s=5.0)
    # zero live members: the fleet is OPEN (pre-fleet deployments and
    # producers racing server start must not be refused)
    assert view.saturated() is False
    publish_member(b, "s", "r1", {"saturated": True})
    publish_member(b, "s", "r2", {"saturated": False})
    view = FleetView(b, "s", cache_s=10.0, ttl_s=5.0)
    # one replica with headroom keeps the fleet open
    assert view.saturated() is False
    publish_member(b, "s", "r2", {"saturated": True})
    # the cached view holds its bounded-staleness answer...
    assert view.saturated() is False
    # ...and a fresh view (or an expired cache) sees the saturation
    assert FleetView(b, "s", cache_s=0.0, ttl_s=5.0).saturated() is True


def test_mode_conflict_detection():
    b = LocalBackend()
    publish_member(b, "s", "old", {"mode": "single"})
    with pytest.raises(RuntimeError, match="mode conflict"):
        check_mode_conflict(b, "s", "new", "group:serving")
    # same mode: no conflict; own registration: never a conflict
    check_mode_conflict(b, "s", "peer", "single")
    check_mode_conflict(b, "s", "old", "group:serving")
    # two DIFFERENT group names also conflict (each would assume it owns
    # a complete delivery accounting of the stream)
    publish_member(b, "s2", "a", {"mode": "group:g1"})
    with pytest.raises(RuntimeError, match="mode conflict"):
        check_mode_conflict(b, "s2", "b", "group:g2")
    # a stale peer cannot veto
    check_mode_conflict(b, "s", "new", "group:serving", ttl_s=0.0)


def test_foreign_backend_without_fleet_surface_opts_out():
    class Minimal:
        pass

    publish_member(Minimal(), "s", "r", {"mode": "single"})     # no raise
    assert live_members(Minimal(), "s") == {}
    check_mode_conflict(Minimal(), "s", "r", "group:g")         # no raise
    assert FleetView(Minimal(), "s").saturated() is False
