"""Autograd op library + CustomLoss (math.scala:32-365, CustomLoss.scala)."""

import jax
import numpy as np
import pytest

import analytics_zoo_tpu.pipeline.api.autograd as A
from analytics_zoo_tpu.common.context import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.engine import (Input, Model,
                                                         Sequential)
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense


def _run(expr_fn, *arrays):
    """Build a graph from Input shapes of the arrays, run it on them."""
    init_zoo_context()
    import jax.numpy as jnp
    ins = [Input(shape=a.shape[1:]) for a in arrays]
    out = expr_fn(*ins)
    m = Model(ins if len(ins) > 1 else ins[0], out)
    p = m.build(jax.random.key(0), None)
    xs = [jnp.asarray(a) for a in arrays]
    return np.asarray(m.call(p, xs if len(xs) > 1 else xs[0]))


def test_unary_ops_match_numpy():
    x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    cases = [
        (lambda v: A.abs(v), np.abs(x)),
        (lambda v: A.square(v), np.square(x)),
        (lambda v: A.exp(v), np.exp(x)),
        (lambda v: A.clip(v, -0.5, 0.5), np.clip(x, -0.5, 0.5)),
        (lambda v: A.sum(v, axis=1), x.sum(axis=1)),
        (lambda v: A.mean(v, axis=1, keep_dims=True), x.mean(1, keepdims=True)),
        (lambda v: A.softsign(v), x / (1 + np.abs(x))),
        (lambda v: A.softplus(v), np.log1p(np.exp(x))),
        (lambda v: A.pow(v, 3.0), np.power(x, 3.0)),
        (lambda v: A.expand_dims(v, 1), x[:, None, :]),
    ]
    for fn, want in cases:
        np.testing.assert_allclose(_run(fn, x), want, rtol=1e-5, atol=1e-5)


def test_erf_and_sqrt():
    from scipy.special import erf as np_erf  # scipy ships with the env
    x = np.random.default_rng(1).uniform(0.1, 2.0, (3, 4)).astype(np.float32)
    np.testing.assert_allclose(_run(A.sqrt, x), np.sqrt(x), rtol=1e-5)
    np.testing.assert_allclose(_run(A.erf, x), np_erf(x), rtol=1e-4, atol=1e-5)


def test_binary_and_operator_composition():
    r = np.random.default_rng(2)
    a = r.normal(size=(3, 4)).astype(np.float32)
    b = r.normal(size=(3, 4)).astype(np.float32)
    got = _run(lambda x, y: A.maximum(x, y) + x * 2.0 - y / 2.0, a, b)
    np.testing.assert_allclose(got, np.maximum(a, b) + a * 2 - b / 2,
                               rtol=1e-5, atol=1e-5)
    got = _run(lambda x: A.maximum(x, 0.0), a)  # const arm
    np.testing.assert_allclose(got, np.maximum(a, 0), rtol=1e-5)


def test_mm_batch_dot_l2_normalize():
    r = np.random.default_rng(3)
    q = r.normal(size=(2, 4, 5)).astype(np.float32)
    d = r.normal(size=(2, 6, 5)).astype(np.float32)
    got = _run(lambda x, y: A.batch_dot(x, y, axes=(2, 2)), q, d)
    np.testing.assert_allclose(got, np.einsum("bqe,bde->bqd", q, d),
                               rtol=1e-4, atol=1e-4)
    qa = q / np.linalg.norm(q, axis=2, keepdims=True)
    da = d / np.linalg.norm(d, axis=2, keepdims=True)
    got = _run(lambda x, y: A.batch_dot(x, y, axes=(2, 2), normalize=True),
               q, d)
    np.testing.assert_allclose(got, np.einsum("bqe,bde->bqd", qa, da),
                               rtol=1e-4, atol=1e-4)
    got = _run(lambda x: A.l2_normalize(x, axis=2), q)
    np.testing.assert_allclose(got, qa, rtol=1e-5, atol=1e-5)
    m1 = r.normal(size=(2, 3, 4)).astype(np.float32)
    m2 = r.normal(size=(2, 4, 5)).astype(np.float32)
    np.testing.assert_allclose(_run(A.mm, m1, m2), m1 @ m2,
                               rtol=1e-4, atol=1e-4)


def test_stack():
    r = np.random.default_rng(4)
    a = r.normal(size=(2, 3)).astype(np.float32)
    b = r.normal(size=(2, 3)).astype(np.float32)
    got = _run(lambda x, y: A.stack([x, y], axis=1), a, b)
    np.testing.assert_allclose(got, np.stack([a, b], axis=1), rtol=1e-6)


def test_custom_loss_values():
    loss = A.CustomLoss(
        lambda yt, yp: A.sqrt(A.mean(A.square(yt - yp), axis=1)), (3,))
    import jax.numpy as jnp
    yt = jnp.asarray(np.zeros((2, 3), np.float32))
    yp = jnp.asarray(np.array([[1, 2, 3], [4, 5, 6]], np.float32))
    want = np.sqrt((np.array([[1, 2, 3], [4, 5, 6.]]) ** 2).mean(1)).mean()
    np.testing.assert_allclose(float(loss(yt, yp)), want, rtol=1e-5)


def test_custom_loss_trains_a_model():
    """compile(loss=CustomLoss(...)) goes through the whole jitted stack."""
    init_zoo_context()
    r = np.random.default_rng(5)
    x = r.normal(size=(256, 6)).astype(np.float32)
    w = r.normal(size=(6, 1)).astype(np.float32)
    y = x @ w

    m = Sequential()
    m.add(Dense(1, input_shape=(6,)))
    mae = A.CustomLoss(lambda yt, yp: A.mean(A.abs(yt - yp), axis=1), (1,))
    m.compile(optimizer="adam", loss=mae, lr=0.05)
    h = m.fit(x, y, batch_size=64, nb_epoch=15)
    assert h["loss"][-1] < 0.25 * h["loss"][0], h["loss"]
    # evaluate routes the custom callable through the fallback loss path
    stats = m.evaluate(x, y, batch_size=64)
    assert np.isfinite(stats["loss"])


def test_custom_loss_rejects_non_variable():
    with pytest.raises(TypeError):
        A.CustomLoss(lambda yt, yp: 3.0, (1,))
