"""Goodput accounting + alert-triggered profiler capture
(``observability/goodput.py`` / ``observability/profiler.py``).

The contract under test (docs/guides/OBSERVABILITY.md "Goodput &
performance attribution"):

* **exclusive, exhaustive attribution** — every second between
  ``open()`` and the last ``note()`` lands in exactly one category, so
  ``goodput + Σ badput == wall time`` reconciles exactly, including
  under an injected fault plan that forces a rollback, a supervised
  restart, replay skips, and checkpoint latency in ONE fit,
* **registry surfaces agree** — the ledger object, the exported
  counter/gauge families, and ``registry_snapshot`` tell one story,
* **alert → capture** — a rule entering ``firing`` arms exactly one
  bounded capture (at most one in flight; trace dirs reconcile 1:1
  with ``zoo_profile_captures_total``; retention evicts the oldest),
* **capture failure is contained** — the ``profiler.capture`` fault
  site degrades to a counter bump + event, never an exception into the
  hosting loop,
* **operator surfaces** — ``/statusz`` carries the ``performance``
  block, ``POST /profilez`` arms over HTTP, and the goodput column
  rolls up through ``zoo-fleet check`` / ``cluster-serving-status``.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common.context import init_zoo_context
from analytics_zoo_tpu.common.faults import FaultPlan
from analytics_zoo_tpu.observability import (AlertEngine, AlertRule,
                                             GoodputLedger, MetricsRegistry,
                                             ProfilerTrigger, ScrapeServer,
                                             StoreSignals, TimeSeriesStore,
                                             default_registry,
                                             default_ruleset)
from analytics_zoo_tpu.observability.goodput import registry_snapshot
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

BATCH = 32


# ---------------------------------------------------------------------------
# ledger exactness (injected clock — deterministic to the float)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_ledger_exclusive_attribution_reconciles_exactly():
    """Interval attribution with a hand-driven clock: every category
    gets exactly the seconds the sequence says, the invariant holds to
    the float, and the exported families mirror the ledger."""
    reg = MetricsRegistry()
    clk = _Clock()
    led = GoodputLedger("train", registry=reg, clock=clk)
    led.open()
    for dt, cat in ((2.0, "compile"), (0.5, "data_wait"),
                    (4.0, "device_step"), (0.25, "ckpt_stall"),
                    (1.0, "device_step"), (0.25, "idle")):
        clk.t += dt
        assert led.note(cat) == dt
    sec = led.seconds()
    assert sec == {"device_step": 5.0, "data_wait": 0.5, "compile": 2.0,
                   "ckpt_stall": 0.25, "rollback_replay": 0.0,
                   "restart": 0.0, "anomaly_skip": 0.0, "idle": 0.25}
    assert led.wall() == 8.0
    assert led.goodput_seconds() == 5.0
    assert led.goodput_seconds() + sum(led.badput_seconds().values()) \
        == led.wall()
    assert led.ratio() == 5.0 / 8.0
    # the registry tells the same story, family by family
    snap = reg.snapshot(compact=True)
    assert snap["zoo_goodput_ratio"]["value"] == 5.0 / 8.0
    assert snap["zoo_goodput_seconds_total"]["value"] == 5.0
    assert snap['zoo_badput_seconds_total{category="compile"}']["value"] \
        == 2.0
    assert snap['zoo_badput_seconds_total{category="data_wait"}']["value"] \
        == 0.5
    rs = registry_snapshot(reg)
    assert rs["ratio"] == 5.0 / 8.0 and rs["goodput_s"] == 5.0
    assert rs["badput_s"]["ckpt_stall"] == 0.25
    assert sum(rs["badput_s"].values()) + rs["goodput_s"] == led.wall()


def test_ledger_edges():
    """Unknown categories refuse loudly; the first note of an unopened
    ledger only arms the mark (no phantom interval); reopen keeps the
    accumulated seconds (a retry continues the same run's ledger); a
    fresh registry reads back as ratio=None, not a fake 0."""
    reg = MetricsRegistry()
    clk = _Clock()
    led = GoodputLedger("serve", registry=reg, clock=clk)
    with pytest.raises(ValueError, match="unknown category"):
        led.note("device_step")     # a TRAIN category, wrong role
    clk.t = 5.0
    assert led.note("idle") == 0.0  # unopened: arms the mark only
    clk.t = 6.0
    assert led.note("device_dispatch") == 1.0
    led.open()                      # re-arm across a gap
    clk.t = 10.0                    # open() read t=6.0 … make the gap real
    led.open(now=9.0)
    clk.t = 10.0
    assert led.note("publish") == 1.0
    assert led.wall() == 2.0        # the 6.0→9.0 gap was never attributed
    assert registry_snapshot(MetricsRegistry()) \
        == {"ratio": None, "goodput_s": 0.0, "badput_s": {}}


# ---------------------------------------------------------------------------
# hbm_high_watermark — the new default-ruleset page
# ---------------------------------------------------------------------------

def test_hbm_high_watermark_rule_fires_on_fraction_of_limit():
    """in_use/limit above 0.92 pages; below it, or with no HBM gauges
    at all (CPU host), the rule reads no-data/healthy and stays quiet."""
    rule = next(r for r in default_ruleset(for_s=0.0)
                if r.name == "hbm_high_watermark")
    assert rule.severity == "page"
    store = TimeSeriesStore(retention_s=60.0, sample_interval_s=1.0)
    sig = StoreSignals(store, clock=lambda: 10.0)
    eng = AlertEngine([rule], registry=MetricsRegistry(),
                      clock=lambda: 10.0)
    eng.evaluate(sig, now=10.0)     # no gauges: no data, no page
    assert eng.state("hbm_high_watermark") == "inactive"
    lim = 16.0e9
    for dev in ("0", "1"):
        store.record(f'zoo_device_hbm_bytes{{device="{dev}",kind="limit"}}',
                     "gauge", 10.0, lim)
        store.record(f'zoo_device_hbm_bytes{{device="{dev}",kind="in_use"}}',
                     "gauge", 10.0, 0.5 * lim)
    eng.evaluate(sig, now=10.0)
    assert eng.state("hbm_high_watermark") == "inactive"   # 50%: fine
    store.record('zoo_device_hbm_bytes{device="0",kind="in_use"}',
                 "gauge", 11.0, 0.99 * lim)
    store.record('zoo_device_hbm_bytes{device="1",kind="in_use"}',
                 "gauge", 11.0, 0.93 * lim)
    eng.evaluate(sig, now=11.0)     # fleet fraction 96% > 92%
    assert eng.state("hbm_high_watermark") == "firing"


# ---------------------------------------------------------------------------
# chaos fit: the full badput taxonomy in one run, reconciled
# ---------------------------------------------------------------------------

def _data(n=256, d=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, 1)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def _model(lr=0.05):
    m = Sequential([Dense(8, activation="relu", input_shape=(8,)),
                    Dense(1)])
    m.compile(optimizer="adam", loss="mse", lr=lr)
    return m


def _family_totals(*names):
    """Default-registry per-family totals (labeled series summed into
    ``name{k="v"}`` keys), absent -> 0.0 — tests diff before/after."""
    snap = default_registry().snapshot(compact=True)
    out = {}
    for n in names:
        for key, entry in snap.items():
            if key == n or key.startswith(n + "{"):
                out[key] = entry.get("value", entry.get("count", 0.0))
        out.setdefault(n, 0.0)
    return out


def test_chaos_fit_goodput_reconciles_to_wall_time(tmp_path):
    """One fit through the whole failure taxonomy — 3 poisoned steps
    (> skip budget 2 ⇒ one rollback + replay skips), a checkpoint save
    killed mid-write (⇒ one supervised restart), and injected manifest
    latency (⇒ checkpoint stall) — and the ledger still attributes
    every second exclusively: goodput + Σ badput == wall, the exported
    families' deltas match the ledger per category, and every expected
    badput category is charged."""
    init_zoo_context(faults_enabled=True, train_sentinel="recover",
                     train_max_skips_per_epoch=2)
    x, y = _data()
    m = _model()
    m.set_checkpoint(str(tmp_path / "ckpt"))
    m.fit(x, y, batch_size=BATCH, nb_epoch=1, shuffle=False)  # clean ckpt-8

    fams = ("zoo_goodput_seconds_total", "zoo_badput_seconds_total",
            "zoo_train_rollback_total")
    before = _family_totals(*fams)
    # epoch 2's dispatches are site calls 0..7: batches 2,3,4 poisoned →
    # 3 skips > budget 2 ⇒ rollback to ckpt-8 + replay with skips; the
    # replayed epoch's save then dies at its first tree file ⇒ the
    # failure surfaces at the next save and the retry loop restarts;
    # every manifest commit pays injected latency ⇒ visible ckpt_stall
    plan = (FaultPlan(seed=11)
            .add("train.grads", "nan_loss", at=(2, 3, 4))
            .add("ckpt.write", "error", at=(0,))
            .add("ckpt.manifest", "latency", delay_s=0.02,
                 at=(0, 1, 2, 3, 4, 5)))
    with faults.activate(plan):
        m.fit(x, y, batch_size=BATCH, nb_epoch=2, shuffle=False)
    after = _family_totals(*fams)

    assert len(plan.fired_at("train.grads")) == 3
    assert len(plan.fired_at("ckpt.write")) == 1
    assert after["zoo_train_rollback_total"] \
        - before["zoo_train_rollback_total"] == 1

    led = m._loop._goodput
    sec = led.seconds()
    # the invariant: exclusive and exhaustive, no unaccounted bucket
    assert led.goodput_seconds() + sum(led.badput_seconds().values()) \
        == pytest.approx(led.wall(), rel=1e-12)
    assert sum(sec.values()) == pytest.approx(led.wall(), rel=1e-12)
    assert led.ratio() == pytest.approx(
        sec["device_step"] / led.wall(), rel=1e-12)
    # every failure mode the plan forced left its wall-time fingerprint
    for cat in ("device_step", "data_wait", "ckpt_stall",
                "rollback_replay", "restart", "anomaly_skip", "idle"):
        assert sec[cat] > 0.0, f"category {cat} never charged"
    # the manifest latency DID fire — but on the background writer
    # thread, so the ledger charges only the synchronous join window:
    # async-hidden save time is by design not badput
    assert len(plan.fired_at("ckpt.manifest")) >= 1
    # the exported families moved by exactly this fit's ledger
    assert after["zoo_goodput_seconds_total"] \
        - before["zoo_goodput_seconds_total"] \
        == pytest.approx(sec["device_step"], rel=1e-9)
    for cat, s in led.badput_seconds().items():
        key = f'zoo_badput_seconds_total{{category="{cat}"}}'
        assert after.get(key, 0.0) - before.get(key, 0.0) \
            == pytest.approx(s, rel=1e-9, abs=1e-12), cat
    # the registry roll-up recomputes its ratio from the SUMMED seconds
    # (several ledgers — the clean fit above and this one — exported into
    # the default registry; the last-writer gauge would misstate that)
    snap = registry_snapshot()
    wall_all = snap["goodput_s"] + sum(snap["badput_s"].values())
    assert snap["ratio"] == pytest.approx(snap["goodput_s"] / wall_all,
                                          rel=1e-12)


def test_goodput_disabled_leaves_no_ledger(tmp_path):
    init_zoo_context(goodput_enabled=False)
    try:
        x, y = _data(n=64)
        m = _model()
        m.fit(x, y, batch_size=BATCH, nb_epoch=1, shuffle=False)
        assert m._loop._goodput is None
    finally:
        init_zoo_context()


# ---------------------------------------------------------------------------
# alert → capture lifecycle
# ---------------------------------------------------------------------------

class _FakeProfiler:
    def __init__(self):
        self.started, self.stopped = [], 0

    def start(self, d):
        self.started.append(d)

    def stop(self):
        self.stopped += 1


def test_alert_transition_arms_exactly_one_capture(tmp_path):
    """A rule crossing into firing arms ONE capture through the
    transition hook; while it is in flight further transitions and
    manual arms are refused; the counter, the trace dirs, and the fake
    profiler's start calls reconcile 1:1."""
    reg = MetricsRegistry()
    events = []
    reg.add_event_sink(type("S", (), {
        "write": lambda self, r: events.append(r),
        "close": lambda self: None})())
    fake = _FakeProfiler()
    trig = ProfilerTrigger(str(tmp_path / "prof"), registry=reg, keep=10,
                           duration_s=0.0, steps=0,
                           start_fn=fake.start, stop_fn=fake.stop)
    rule = AlertRule("depth_high", lambda s: s.v, threshold=10.0,
                     for_s=5.0, severity="page", summary="backlog")
    eng = AlertEngine([rule], registry=reg, clock=lambda: 0.0)
    eng.add_transition_hook(trig.on_alert)
    sig = type("V", (), {"v": 50.0})()
    eng.evaluate(sig, now=0.0)                 # pending — no capture
    assert fake.started == [] and trig.in_flight() is None
    eng.evaluate(sig, now=6.0)                 # firing — one capture
    flight = trig.in_flight()
    assert flight is not None and flight["trigger"] == "alert"
    assert fake.started == [flight["dir"]]
    assert os.path.isdir(flight["dir"])
    # a second arm (any source) is refused while one is in flight
    assert trig.arm("manual") is None
    assert fake.started == [flight["dir"]]
    snap = reg.snapshot(compact=True)
    assert snap['zoo_profile_captures_total{trigger="alert"}']["value"] == 1
    assert snap['zoo_profile_captures_total{trigger="manual"}']["value"] == 0
    assert trig.stop() == flight["dir"] and fake.stopped == 1
    assert trig.stop() is None and fake.stopped == 1   # idempotent
    phases = [e.get("phase") for e in events
              if e.get("kind") == "profile.capture"]
    assert phases == ["start", "skipped", "stop"]
    # resolve → re-fire arms a SECOND capture (new episode, new trace)
    sig.v = 1.0
    eng.evaluate(sig, now=7.0)
    sig.v = 50.0
    eng.evaluate(sig, now=8.0)
    eng.evaluate(sig, now=20.0)
    assert len(fake.started) == 2
    snap = reg.snapshot(compact=True)
    assert snap['zoo_profile_captures_total{trigger="alert"}']["value"] == 2


def test_step_bound_and_retention_eviction(tmp_path):
    """A steps-bounded capture stops itself after N step() calls;
    retention keeps only the newest ``keep`` capture dirs and never the
    in-flight one."""
    reg = MetricsRegistry()
    fake = _FakeProfiler()
    trig = ProfilerTrigger(str(tmp_path / "prof"), registry=reg, keep=2,
                           duration_s=0.0, steps=3,
                           start_fn=fake.start, stop_fn=fake.stop)
    d1 = trig.arm("manual")
    assert d1 is not None
    trig.step(); trig.step()
    assert trig.in_flight() is not None        # budget not yet spent
    trig.step()
    assert trig.in_flight() is None and fake.stopped == 1
    d2 = trig.arm("http")
    trig.step(); trig.step(); trig.step()
    d3 = trig.arm("manual")
    for _ in range(3):
        trig.step()
    d4 = trig.arm("manual")                    # eviction runs on each arm
    names = sorted(os.listdir(str(tmp_path / "prof")))
    assert names == [os.path.basename(d) for d in (d3, d4)]
    assert d1 is not None and d2 is not None and d4 is not None
    trig.close()


def test_profiler_capture_fault_degrades_gracefully(tmp_path):
    """The ``profiler.capture`` chaos site: an injected error makes
    ``arm()`` return None, bump the failure counter, and emit a
    ``phase="failed"`` event — nothing escapes into the caller, and the
    next arm (plan exhausted) succeeds. Reconciled exactly against
    ``plan.fired``."""
    init_zoo_context(faults_enabled=True)
    reg = MetricsRegistry()
    events = []
    reg.add_event_sink(type("S", (), {
        "write": lambda self, r: events.append(r),
        "close": lambda self: None})())
    fake = _FakeProfiler()
    trig = ProfilerTrigger(str(tmp_path / "prof"), registry=reg,
                           duration_s=0.0, steps=0,
                           start_fn=fake.start, stop_fn=fake.stop)
    plan = FaultPlan(seed=5).add("profiler.capture", "error", at=(0,))
    with faults.activate(plan):
        assert trig.arm("alert", reason="chaos") is None   # injected fail
        d = trig.arm("alert")                              # recovers
    assert plan.fired == [("profiler.capture", "error", 0)]
    assert d is not None and fake.started == [d]
    snap = reg.snapshot(compact=True)
    assert snap["zoo_profile_capture_failures_total"]["value"] == 1
    assert snap['zoo_profile_captures_total{trigger="alert"}']["value"] == 1
    failed = [e for e in events if e.get("phase") == "failed"]
    assert len(failed) == 1 and "FaultError" in failed[0]["error"]
    trig.close()


# ---------------------------------------------------------------------------
# HTTP surfaces: /statusz performance block + POST /profilez
# ---------------------------------------------------------------------------

def test_statusz_performance_and_profilez_http(tmp_path):
    """Live HTTP: ``/statusz`` carries the goodput roll-up + profiler
    state; ``POST /profilez`` arms (200), refuses a second in-flight
    capture (409), and 404s with no profiler mounted."""
    reg = MetricsRegistry()
    clk = _Clock()
    led = GoodputLedger("serve", registry=reg, clock=clk)
    led.open()
    clk.t = 3.0
    led.note("device_dispatch")
    clk.t = 4.0
    led.note("idle")
    fake = _FakeProfiler()
    trig = ProfilerTrigger(str(tmp_path / "prof"), registry=reg,
                           duration_s=0.0, steps=0,
                           start_fn=fake.start, stop_fn=fake.stop)
    srv = ScrapeServer(reg, port=0, profiler=trig)
    base = f"http://{srv.host}:{srv.port}"
    try:
        with urllib.request.urlopen(base + "/statusz", timeout=10.0) as r:
            status = json.loads(r.read())
        perf = status["performance"]
        assert perf["ratio"] == 0.75
        assert perf["goodput_s"] == 3.0 and perf["badput_s"]["idle"] == 1.0
        assert perf["profiler"] == {"in_flight": None,
                                    "trace_dir": trig.trace_dir}
        req = urllib.request.Request(base + "/profilez", data=b"",
                                     method="POST")
        with urllib.request.urlopen(req, timeout=10.0) as r:
            armed = json.loads(r.read())
        assert armed["armed"] is True and fake.started == [armed["dir"]]
        assert armed["in_flight"]["trigger"] == "http"
        with pytest.raises(urllib.error.HTTPError) as e2:
            urllib.request.urlopen(
                urllib.request.Request(base + "/profilez", data=b"",
                                       method="POST"), timeout=10.0)
        assert e2.value.code == 409               # already in flight
        assert json.loads(e2.value.read())["armed"] is False
        with urllib.request.urlopen(base + "/statusz", timeout=10.0) as r:
            flight = json.loads(r.read())["performance"]["profiler"]
        assert flight["in_flight"]["dir"] == armed["dir"]
    finally:
        trig.close()
        srv.close()
    # no profiler mounted → /profilez is a clean 404, not a crash
    srv2 = ScrapeServer(reg, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as e3:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{srv2.host}:{srv2.port}/profilez",
                    data=b"", method="POST"), timeout=10.0)
        assert e3.value.code == 404
    finally:
        srv2.close()


# ---------------------------------------------------------------------------
# CLI roll-up: the goodput column
# ---------------------------------------------------------------------------

def _cli_env():
    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(scripts) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    env["JAX_PLATFORMS"] = "cpu"
    return scripts, env


def test_cli_goodput_column_rolls_up(tmp_path):
    """Subprocess truth: a replica whose scaling block reports goodput
    shows it in ``zoo-fleet check``'s table and in
    ``cluster-serving-status``'s scaling + performance lines."""
    scripts, env = _cli_env()
    reg = MetricsRegistry()
    reg.counter("zoo_serving_records_total", "t").inc(5)
    clk = _Clock()
    led = GoodputLedger("serve", registry=reg, clock=clk)
    led.open()
    clk.t = 17.0
    led.note("device_dispatch")
    clk.t = 20.0
    led.note("publish")                        # ratio 0.85
    scaling = {"consumer": "c-1", "stream_depth": 0, "pending_entries": 0,
               "utilization": 0.5, "batch_size_target": 4,
               "goodput": round(led.ratio(), 4)}
    srv = ScrapeServer(reg, port=0,
                       health_fn=lambda: {"serving": {"running": True,
                                                      "scaling": scaling}})
    try:
        live = f"{srv.host}:{srv.port}"
        r = subprocess.run(
            [sys.executable, os.path.join(scripts, "zoo-fleet"),
             "check", live],
            capture_output=True, text=True, env=env, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "goodput" in r.stdout            # the column header
        row = next(l for l in r.stdout.splitlines() if live in l)
        assert "85%" in row
        r = subprocess.run(
            [sys.executable,
             os.path.join(scripts, "cluster-serving-status"), live],
            capture_output=True, text=True, env=env, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "goodput 85%" in r.stdout        # the scaling line
        perf = next(l for l in r.stdout.splitlines()
                    if l.startswith("performance"))
        assert "goodput 85%" in perf and "publish 3.0s" in perf
    finally:
        srv.close()
