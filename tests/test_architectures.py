"""End-to-end architecture tests on the 8-device CPU mesh: a conv net, an
LSTM classifier, and a BERT-small classifier train and learn — the round-2
milestones from the build plan (SURVEY §7 step 6)."""

import numpy as np

from analytics_zoo_tpu.common import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras import Input, Model, Sequential
from analytics_zoo_tpu.pipeline.api.keras.engine import Lambda
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    BERT, GRU, LSTM, Convolution2D, Dense, Flatten, GlobalAveragePooling1D,
    MaxPooling2D, TransformerLayer)


def test_convnet_trains():
    init_zoo_context()
    rng = np.random.default_rng(0)
    n = 256
    # class = which quadrant holds the bright blob
    y = rng.integers(0, 4, n).astype(np.int32)
    x = rng.normal(0, 0.1, (n, 8, 8, 1)).astype(np.float32)
    for i in range(n):
        r, c = divmod(int(y[i]), 2)
        x[i, r * 4:(r + 1) * 4, c * 4:(c + 1) * 4, 0] += 1.0
    m = Sequential([
        Convolution2D(8, 3, 3, activation="relu", border_mode="same",
                      input_shape=(8, 8, 1)),
        MaxPooling2D((2, 2)),
        Flatten(),
        Dense(4, activation="softmax"),
    ])
    m.compile(optimizer="adam", loss="scce", metrics=["accuracy"], lr=0.01)
    m.fit(x, y, batch_size=32, nb_epoch=10)
    assert m.evaluate(x, y, batch_size=32)["accuracy"] > 0.9


def test_lstm_classifier_trains():
    init_zoo_context()
    rng = np.random.default_rng(1)
    n, t, d = 256, 10, 4
    x = rng.normal(size=(n, t, d)).astype(np.float32)
    # label depends on the sign of the sum of the LAST timestep
    y = (x[:, -1, :].sum(axis=1) > 0).astype(np.float32)[:, None]
    m = Sequential([
        LSTM(16, input_shape=(t, d)),
        Dense(1, activation="sigmoid"),
    ])
    m.compile(optimizer="adam", loss="bce", metrics=["accuracy"], lr=0.01)
    m.fit(x, y, batch_size=32, nb_epoch=15)
    assert m.evaluate(x, y, batch_size=32)["accuracy"] > 0.9


def test_gru_sequence_output_feeds_pooling():
    init_zoo_context()
    rng = np.random.default_rng(2)
    x = rng.normal(size=(64, 6, 3)).astype(np.float32)
    y = (x.mean(axis=(1, 2)) > 0).astype(np.float32)[:, None]
    m = Sequential([
        GRU(8, return_sequences=True, input_shape=(6, 3)),
        GlobalAveragePooling1D(),
        Dense(1, activation="sigmoid"),
    ])
    m.compile(optimizer="adam", loss="bce", lr=0.02)
    h = m.fit(x, y, batch_size=32, nb_epoch=10)
    assert h["loss"][-1] < h["loss"][0]


def _bert_inputs(n, t, vocab, seed=3):
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, vocab, (n, t)).astype(np.int32)
    token_type = np.zeros((n, t), np.int32)
    pos = np.broadcast_to(np.arange(t, dtype=np.int32), (n, t)).copy()
    mask = np.ones((n, 1, 1, t), np.float32)
    return ids, token_type, pos, mask


def test_bert_small_classifier_trains():
    init_zoo_context()
    n, t, vocab = 128, 12, 50
    ids, token_type, pos, mask = _bert_inputs(n, t, vocab)
    # learnable: label = parity of the first token id
    y = (ids[:, 0] % 2).astype(np.int32)

    i1, i2, i3 = Input(shape=(t,)), Input(shape=(t,)), Input(shape=(t,))
    i4 = Input(shape=(1, 1, t))
    bert = BERT(vocab=vocab, hidden_size=32, n_block=2, n_head=2, seq_len=t,
                intermediate_size=64, hidden_drop=0.0, attn_drop=0.0)
    seq_and_pooled = bert([i1, i2, i3, i4])
    pooled = Lambda(lambda seq, pooled: pooled, name="take_pooled")(seq_and_pooled)
    out = Dense(2, activation="softmax")(pooled)
    m = Model(input=[i1, i2, i3, i4], output=out)
    m.compile(optimizer="adam", loss="scce", metrics=["accuracy"], lr=3e-3)
    h = m.fit([ids, token_type, pos, mask], y, batch_size=32, nb_epoch=12)
    assert h["loss"][-1] < 0.7 * h["loss"][0]
    res = m.evaluate([ids, token_type, pos, mask], y, batch_size=32)
    assert res["accuracy"] > 0.8


def test_transformer_layer_in_graph():
    init_zoo_context()
    n, t, vocab = 96, 8, 40
    rng = np.random.default_rng(4)
    ids = rng.integers(0, vocab, (n, t)).astype(np.int32)
    y = (ids[:, 0] >= vocab // 2).astype(np.float32)[:, None]
    m = Sequential([
        TransformerLayer(vocab=vocab, seq_len=t, n_block=1, hidden_size=16,
                         n_head=2, hidden_drop=0.0, attn_drop=0.0,
                         embedding_drop=0.0, input_shape=(t,)),
        GlobalAveragePooling1D(),
        Dense(1, activation="sigmoid"),
    ])
    m.compile(optimizer="adam", loss="bce", lr=5e-3)
    h = m.fit(ids, y, batch_size=32, nb_epoch=10)
    assert h["loss"][-1] < h["loss"][0]
