"""The round-over-round regression gate in ``bench.py`` (VERDICT r4 weak #1:
the 41% transfer-learning drop sailed through because nothing compared
against the previous round's record). These tests drive ``check_regressions``
against the committed ``BENCH_r04.json`` so the gate's comparison, tolerance,
and absolute-floor paths are themselves regression-tested."""

import copy
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

@pytest.fixture()
def prev_record():
    # bench's own baseline lookup: the tests track whichever round's
    # record the gate actually compares against, or they would fail the
    # round after any metric improves
    parsed, name = bench.latest_bench_record()
    assert parsed and name, "no BENCH_r*.json record found"
    return parsed


def test_equal_metrics_pass(prev_record):
    bench.check_regressions(copy.deepcopy(prev_record))  # must not exit


def test_within_tolerance_passes(prev_record):
    out = copy.deepcopy(prev_record)
    # -20% is inside the dispatch-RTT-noise override (0.30) for this key
    out["wide_deep_train_samples_per_sec"] *= 0.80
    bench.check_regressions(out)


def test_gated_drop_fails(prev_record):
    out = copy.deepcopy(prev_record)
    out["wide_deep_train_samples_per_sec"] *= 0.65   # -35% > 30% override
    with pytest.raises(SystemExit):
        bench.check_regressions(out)


def test_default_tolerance_is_15pct(prev_record):
    out = copy.deepcopy(prev_record)
    out["bert_train_samples_per_sec"] *= 0.80   # -20% > default 15% gate
    with pytest.raises(SystemExit):
        bench.check_regressions(out)


def test_noisy_metric_uses_wider_tolerance(prev_record):
    out = copy.deepcopy(prev_record)
    out["image_infer_fp32_fps"] *= 0.75   # -25% < its 30% override
    bench.check_regressions(out)
    out["image_infer_fp32_fps"] = prev_record["image_infer_fp32_fps"] * 0.65
    with pytest.raises(SystemExit):
        bench.check_regressions(out)


def test_absolute_floor_is_not_relative(prev_record):
    out = copy.deepcopy(prev_record)
    # 86% agreement is within 15% of r4's 100% but below the 97% floor —
    # the whitepaper's claim is <0.1% accuracy drop (wp-bigdl.md:192)
    out["int8_top1_agreement_pct"] = 86.0
    with pytest.raises(SystemExit):
        bench.check_regressions(out)


def test_absolute_ceiling(prev_record):
    out = copy.deepcopy(prev_record)
    out["int8_top1_delta_pct"] = 5.0     # lower-is-better metric
    with pytest.raises(SystemExit):
        bench.check_regressions(out)


def test_device_step_ceiling_backstops_wall_tolerance(prev_record):
    # the wide wall-clock tolerance on the NCF headline is backstopped by
    # the tunnel-free device-only step time: a real compute regression
    # fails here even if the wall number squeaks past the relative gate
    out = copy.deepcopy(prev_record)
    out["device_step_ms"] = 1.5
    with pytest.raises(SystemExit):
        bench.check_regressions(out)


def test_new_metric_without_history_passes(prev_record):
    out = copy.deepcopy(prev_record)
    fresh = [k for k in bench.GATED_METRICS
             if k not in prev_record and k not in bench.ABSOLUTE_FLOORS]
    if not fresh:
        pytest.skip("every gated metric already has a history record")
    out[fresh[0]] = 1.0                 # no prior record → no relative gate
    bench.check_regressions(out)


def test_latest_bench_record_ignores_non_numbered_files():
    """A stray BENCH_r*.json without a round number (e.g. BENCH_rerun.json)
    must be ignored, not crash the baseline lookup (ADVICE r5)."""
    import re
    stray = os.path.join(os.path.dirname(os.path.abspath(bench.__file__)),
                         "BENCH_rerun.json")
    with open(stray, "w") as f:
        f.write("{}")
    try:
        parsed, name = bench.latest_bench_record()
        assert name is not None and re.match(r"^BENCH_r\d+\.json$", name)
        assert parsed     # still the newest numbered round's record
    finally:
        os.remove(stray)
