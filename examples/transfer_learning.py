"""Transfer learning (parity config #3 shape): fine-tune a pretrained-style
classifier on a new small dataset by re-heading the backbone and training
the head with a per-submodule optimizer split.

Run:  python examples/transfer_learning.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.models.image.imageclassification import ImageClassifier
from analytics_zoo_tpu.pipeline.estimator import Estimator


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)

    # "pretrained" backbone (use e.g. "resnet-50" for real work)
    base = ImageClassifier("simple-cnn", num_classes=7,
                           input_shape=(48, 48, 3))
    xa = rng.normal(size=(64, 48, 48, 3)).astype(np.float32)
    base.init_weights(sample_input=xa[:2])

    # new 2-class task: dogs-vs-cats-shaped synthetic data
    x = rng.normal(0, 0.3, size=(256, 48, 48, 3)).astype(np.float32)
    y = rng.integers(0, 2, 256).astype(np.int32)
    x[y == 1, :, :, 0] += 0.8  # class-1 images are redder

    import optax
    ft = base.new_head(2)  # keep backbone weights, fresh 2-class head
    # freeze-ish backbone: tiny lr for everything, real lr for the head
    est = Estimator(ft, optim_methods={
        "head_dense": optax.adam(3e-3), "__default__": optax.adam(1e-5)})
    est.train(FeatureSet.array(x, y), criterion="scce", batch_size=32,
              nb_epoch=12, validation_set=FeatureSet.array(x, y),
              validation_methods=["accuracy"])
    print("fine-tuned accuracy:",
          est.evaluate(FeatureSet.array(x, y), ["accuracy"],
                       batch_size=32))


if __name__ == "__main__":
    main()
