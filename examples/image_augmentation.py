"""Image augmentation — the reference's ``apps/image-augmentation`` and
``apps/image-augmentation-3d`` notebook roles: build an ImageSet, apply 2D
transformer chains (geometry + color), then run the 3D pipeline on a
volume (reference: ``apps/image-augmentation/image-augmentation.ipynb``,
``apps/image-augmentation-3d/image-augmentation-3d.ipynb``).

Run:  python examples/image_augmentation.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature.image import (Brightness, CenterCrop,
                                             ChannelNormalize, ColorJitter,
                                             HFlip, Hue, ImageSet, Resize,
                                             Saturation)
from analytics_zoo_tpu.feature.image3d import (CenterCrop3D, Rotate3D)


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)

    # ---- 2D: a ragged batch of synthetic "photos" -------------------------
    images = [rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
              for h, w in ((140, 180), (120, 160), (200, 150), (128, 128))]
    labels = np.array([0, 1, 0, 1], np.int32)
    iset = ImageSet(images, labels=labels)

    geometry = Resize(112, 112) >> CenterCrop(96, 96) >> HFlip(p=1.0)
    color = (Brightness(-16, 16) >> Hue(-9.0, 9.0)
             >> Saturation(0.8, 1.2) >> ColorJitter())
    chain = geometry >> color >> ChannelNormalize(
        (127.5, 127.5, 127.5), (127.5, 127.5, 127.5))
    out = iset.transform(chain)
    arr = np.stack(list(out.images))
    print(f"2D: {len(images)} ragged images -> dense {arr.shape} "
          f"(mean {arr.mean():+.3f}, std {arr.std():.3f})")

    # ---- 3D: one CT-like volume through the 3D pipeline -------------------
    volume = rng.normal(size=(32, 64, 64)).astype(np.float32)
    chain3d = Rotate3D((0.0, 0.0, np.pi / 6)) >> CenterCrop3D(24, 48, 48)
    vol_out = chain3d(volume)
    print(f"3D: volume {volume.shape} -> rotated+cropped {vol_out.shape}")


if __name__ == "__main__":
    main()
