"""Text classification end-to-end (the reference's textclassification
example): raw strings -> TextSet tokenize/word2idx/shape -> TextClassifier
(CNN encoder) -> train/evaluate.

Run:  python examples/text_classification.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature.text import TextSet
from analytics_zoo_tpu.models.textclassification import TextClassifier


def make_corpus(rng, n_per_class=96):
    sports = ["the team won the match", "a great goal in the final game",
              "the player scored again", "championship race was close"]
    tech = ["the new chip doubles performance", "software update improves the",
            "machine learning model training", "the device battery lasts"]
    texts, labels = [], []
    for label, pool in enumerate((sports, tech)):
        for _ in range(n_per_class):
            words = []
            for _ in range(3):
                words.extend(rng.choice(pool).split())
            texts.append(" ".join(words))
            labels.append(label)
    return texts, np.asarray(labels, np.int32)


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)
    texts, labels = make_corpus(rng)

    seq_len = 20
    ts = TextSet.from_texts(texts, labels).tokenize().word2idx() \
        .shape_sequence(seq_len)
    x, y = ts.to_arrays()

    model = TextClassifier(class_num=2, token_length=32,
                           sequence_length=seq_len, encoder="cnn",
                           vocab_size=len(ts.word_index) + 2)
    model.compile(optimizer="adam", loss="scce", metrics=["accuracy"],
                  lr=2e-3)
    model.fit(x, y, batch_size=32, nb_epoch=8)
    print("accuracy:", model.evaluate(x, y, batch_size=32))


if __name__ == "__main__":
    main()
