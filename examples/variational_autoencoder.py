"""Variational autoencoder (the reference's ``apps/variational-autoencoder``
notebooks: VAE on digit images with the Keras-1 zoo API + autograd KL loss).

Digits here are synthetic glyph-like 28x28 images (no dataset download in
this environment). The VAE is the standard architecture: encoder → (mu,
log_var) → reparameterized z → decoder; the loss = reconstruction BCE +
KL(q(z|x) || N(0,1)) expressed with the native graph/Lambda machinery, and
the whole thing trains under the ordinary jitted fit loop.

Run:  python examples/variational_autoencoder.py
"""

import numpy as np

import jax
import jax.numpy as jnp

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.engine import (Input, Lambda,
                                                         Model)
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

LATENT = 8


def make_digits(n=2048, seed=0):
    """Glyph-ish 28x28 binary images: random strokes per class template."""
    rng = np.random.default_rng(seed)
    temps = np.zeros((8, 28, 28), np.float32)
    for c in range(8):
        r0, c0 = rng.integers(4, 12, 2)
        r1, c1 = rng.integers(16, 24, 2)
        temps[c, r0:r1, c0] = 1.0
        temps[c, r0, c0:c1] = 1.0
        if c % 2:
            temps[c, r1, c0:c1] = 1.0
    y = rng.integers(0, 8, n)
    x = temps[y] + rng.normal(0, 0.05, (n, 28, 28)).astype(np.float32)
    return np.clip(x, 0, 1).reshape(n, 784).astype(np.float32), y


def build_vae():
    x_in = Input(shape=(784,), name="pixels")
    h = Dense(256, activation="relu", name="enc1")(x_in)
    h = Dense(64, activation="relu", name="enc2")(h)
    mu = Dense(LATENT, name="mu")(h)
    log_var = Dense(LATENT, name="log_var")(h)

    def reparam(m, lv):
        # deterministic per-value noise (hash of mu) keeps the example
        # dependency-free of the training-loop rng plumbing while still
        # exercising the sampling path
        eps = jax.random.normal(jax.random.key(0), m.shape)
        return m + jnp.exp(0.5 * lv) * eps

    z = Lambda(reparam, name="sample_z")([mu, log_var])
    d = Dense(64, activation="relu", name="dec1")(z)
    d = Dense(256, activation="relu", name="dec2")(d)
    recon = Dense(784, activation="sigmoid", name="recon")(d)

    def vae_loss(x, xr, m, lv):
        xr = jnp.clip(xr, 1e-6, 1 - 1e-6)
        bce = -jnp.sum(x * jnp.log(xr) + (1 - x) * jnp.log(1 - xr), axis=-1)
        kl = -0.5 * jnp.sum(1 + lv - m ** 2 - jnp.exp(lv), axis=-1)
        return jnp.mean(bce + kl)

    loss_var = Lambda(vae_loss, name="vae_loss")([x_in, recon, mu, log_var])
    train_model = Model(x_in, loss_var)        # output IS the loss
    recon_model = Model(x_in, recon)
    encoder = Model(x_in, mu)
    return train_model, recon_model, encoder


def main():
    init_zoo_context()
    x, y = make_digits()
    train_model, recon_model, encoder = build_vae()
    train_model.compile(optimizer="adam", lr=1e-3,
                        loss=lambda yt, yp: jnp.mean(yp))
    h = train_model.fit(x, np.zeros(len(x), np.float32), batch_size=128,
                        nb_epoch=15)
    assert h["loss"][-1] < h["loss"][0] * 0.5, h["loss"]

    # share trained weights into the reconstruction/encoder views (same
    # layer objects -> same param keys)
    recon_model.params = {k: v for k, v in train_model.params.items()
                          if k in recon_model.init(
                              jax.random.key(0))[0]}
    rec = np.asarray(recon_model.predict(x[:64]))
    err = float(np.mean((rec - x[:64]) ** 2))
    print(f"loss {h['loss'][0]:.1f} -> {h['loss'][-1]:.1f}; "
          f"recon mse={err:.4f}")
    assert err < 0.05, err

    # the latent space should cluster by glyph class: mean intra-class
    # distance < mean inter-class distance
    encoder.params = {k: v for k, v in train_model.params.items()
                      if k in encoder.init(jax.random.key(0))[0]}
    z = np.asarray(encoder.predict(x[:512]))
    yz = y[:512]
    intra, inter = [], []
    for c in range(8):
        zc = z[yz == c]
        zo = z[yz != c]
        if len(zc) > 1:
            intra.append(np.mean(np.linalg.norm(
                zc[:, None] - zc[None], axis=-1)))
            inter.append(np.mean(np.linalg.norm(
                zc[:, None] - zo[None][:, :100], axis=-1)))
    print(f"latent: intra={np.mean(intra):.3f} inter={np.mean(inter):.3f}")
    assert np.mean(intra) < np.mean(inter)
    print("OK")


if __name__ == "__main__":
    main()
