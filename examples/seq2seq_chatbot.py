"""Sequence-to-sequence learning (the reference's chatbot example surface):
train an LSTM encoder/decoder on a sequence-transduction task (reverse the
input), then generate autoregressively with greedy infer().

Run:  python examples/seq2seq_chatbot.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.seq2seq import Seq2seq


def one_hot(ids, vocab):
    return np.eye(vocab, dtype=np.float32)[ids]


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)
    vocab, t = 12, 6
    n = 768

    src_ids = rng.integers(2, vocab, size=(n, t))         # 0=pad/start, 1=eos
    tgt_ids = src_ids[:, ::-1]                            # task: reverse
    enc = one_hot(src_ids, vocab)
    tgt = one_hot(tgt_ids, vocab)
    # teacher forcing: decoder sees <start> + shifted target
    dec_in = np.concatenate([np.zeros((n, 1, vocab), np.float32),
                             tgt[:, :-1]], axis=1)

    model = Seq2seq(rnn_type="lstm", num_layers=1, hidden_size=128,
                    input_dim=vocab, bridge="dense", generator_dim=vocab,
                    generator_activation="softmax")
    model.compile(optimizer="adam", loss="cce", lr=2e-3)
    model.fit([enc, dec_in], tgt, batch_size=64, nb_epoch=30)

    # greedy generation from the start token
    start = np.zeros((8, vocab), np.float32)
    out = model.infer(enc[:8], start, max_seq_len=t)
    pred_ids = np.asarray(out).argmax(-1)
    acc = (pred_ids == tgt_ids[:8]).mean()
    print(f"greedy-decode token accuracy on the reverse task: {acc:.2f}")
    print("sample:", src_ids[0], "->", pred_ids[0])


if __name__ == "__main__":
    main()
