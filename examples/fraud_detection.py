"""Fraud detection on imbalanced tabular data (the reference's
``apps/fraud-detection`` notebook: creditcard transactions, ~0.2% positive
class, class-rebalancing + an MLP classifier + threshold tuning on
precision/recall).

Data here is creditcard-shaped synthetic: 29 numeric features where fraud
rows follow a shifted distribution, 0.3% positive rate. The flow mirrors
the notebook: stratified split → minority oversampling for the train set →
MLP via the NNFrames NNClassifier columnar path → evaluate precision/
recall/AUC on the UNBALANCED held-out set and pick the F1-best threshold.

Run:  python examples/fraud_detection.py
"""

import numpy as np

import optax

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Dropout


def make_transactions(n=60_000, d=29, fraud_rate=0.003, seed=0):
    rng = np.random.default_rng(seed)
    y = (rng.random(n) < fraud_rate).astype(np.int32)
    x = rng.normal(size=(n, d)).astype(np.float32)
    shift = rng.normal(0.8, 0.3, d).astype(np.float32)  # fraud signature
    x[y == 1] += shift * rng.uniform(0.7, 1.3, (int(y.sum()), 1))
    return x, y


def oversample(x, y, ratio=0.15, seed=1):
    """Upsample the minority class to ``ratio`` of the train set (the
    notebook's rebalancing step)."""
    rng = np.random.default_rng(seed)
    pos = np.flatnonzero(y == 1)
    n_target = int(len(y) * ratio)
    picks = rng.choice(pos, n_target, replace=True)
    xx = np.concatenate([x, x[picks]])
    yy = np.concatenate([y, y[picks]])
    order = rng.permutation(len(yy))
    return xx[order], yy[order]


def main():
    init_zoo_context()
    x, y = make_transactions()
    cut = int(len(x) * 0.8)
    xtr, ytr = oversample(x[:cut], y[:cut])
    xte, yte = x[cut:], y[cut:]

    m = Sequential([Dense(64, activation="relu", input_shape=(29,)),
                    Dropout(0.2),
                    Dense(32, activation="relu"),
                    Dense(2, activation="softmax")])
    m.compile(optimizer=optax.adam(1e-3), loss="scce")
    m.fit(xtr, ytr, batch_size=256, nb_epoch=4)

    probs = np.asarray(m.predict(xte, batch_size=1024))[:, 1]
    # AUC by rank statistic
    order = np.argsort(probs)
    ranks = np.empty(len(probs)); ranks[order] = np.arange(len(probs))
    n_pos, n_neg = int(yte.sum()), int((1 - yte).sum())
    auc = (ranks[yte == 1].sum() - n_pos * (n_pos - 1) / 2) / (n_pos * n_neg)

    best = (0.0, 0.5, 0.0, 0.0)
    for thr in np.linspace(0.05, 0.95, 19):
        pred = (probs > thr).astype(np.int32)
        tp = int(((pred == 1) & (yte == 1)).sum())
        fp = int(((pred == 1) & (yte == 0)).sum())
        fn = int(((pred == 0) & (yte == 1)).sum())
        prec = tp / max(tp + fp, 1)
        rec = tp / max(tp + fn, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-9)
        if f1 > best[0]:
            best = (f1, thr, prec, rec)
    f1, thr, prec, rec = best
    print(f"held-out: auc={auc:.3f} best_f1={f1:.3f} @thr={thr:.2f} "
          f"(precision={prec:.3f} recall={rec:.3f}; "
          f"{n_pos} frauds in {len(yte)})")
    assert auc > 0.95 and f1 > 0.5, (auc, f1)
    print("OK")


if __name__ == "__main__":
    main()
