"""Object detection end-to-end: train a small SSD on synthetic
single-object images, then run decode + NMS detection and VOC mAP.

Run:  python examples/object_detection.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.image.objectdetection import (
    MeanAveragePrecision, ObjectDetector)


def make_squares(n, res, rng):
    images = rng.normal(0, 0.05, size=(n, res, res, 3)).astype(np.float32)
    gt = np.full((n, 3, 5), -1.0, np.float32)
    for i in range(n):
        size = int(rng.integers(14, 26))
        x0 = int(rng.integers(0, res - size))
        y0 = int(rng.integers(0, res - size))
        images[i, y0:y0 + size, x0:x0 + size, :] = 1.0
        gt[i, 0] = [1, x0 / res, y0 / res, (x0 + size) / res,
                    (y0 + size) / res]
    return images, gt


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)
    res = 64
    images, gt = make_squares(96, res, rng)

    det = ObjectDetector("ssd-lite", num_classes=2, resolution=res)
    det.init_weights(sample_input=images[:2])
    det.compile(optimizer="adam", loss=det.multibox_loss(), lr=3e-3)
    det.fit(images, gt, batch_size=16, nb_epoch=30)

    dets = det.detect(images[:32], conf_thresh=0.3)
    metric = MeanAveragePrecision(num_classes=2)
    metric.update(dets, gt[:32])
    mean_ap, per_class = metric.result()
    print(f"mAP@0.5 = {mean_ap:.3f}  per-class = {per_class}")


if __name__ == "__main__":
    main()
