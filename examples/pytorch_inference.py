"""PyTorch model import — the reference's ``apps/pytorch`` notebook role
(TorchNet wraps a torch module for inference and fine-tuning inside the
zoo pipeline; reference: ``apps/pytorch/*.ipynb``,
``pipeline/api/net/torch_net.py``).

A torch MLP is converted weight-for-weight into a native trainable graph
(``Net.load_torch``), its predictions verified against torch, then
fine-tuned with the zoo training loop; the same facade accepts a
TorchScript ``.pt`` file for models shipped without source.

Run:  python examples/pytorch_inference.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.net import Net


def main():
    import torch

    init_zoo_context()
    torch.manual_seed(0)
    module = torch.nn.Sequential(
        torch.nn.Linear(16, 32), torch.nn.ReLU(),
        torch.nn.Linear(32, 3), torch.nn.Softmax(dim=-1))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 16)).astype(np.float32)

    net = Net.load_torch(module, input_shape=(16,))
    with torch.no_grad():
        want = module(torch.from_numpy(x)).numpy()
    got = np.asarray(net.predict(x, batch_size=32))
    # TPU fp32 matmuls run via bf16 passes at default precision -> ~3e-4
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)
    print(f"torch parity OK: max |diff| = {np.abs(got - want).max():.2e}")

    # fine-tune the imported weights with the native loop
    w = rng.normal(size=(16, 3)).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    net.compile(optimizer="adam", loss="scce", metrics=["accuracy"], lr=0.01)
    net.fit(x, y, batch_size=32, nb_epoch=15)
    acc = net.evaluate(x, y, batch_size=32)["accuracy"]
    print(f"fine-tuned imported torch model: accuracy {acc:.3f}")

    # TorchScript file path: models shipped as .pt without python source
    import tempfile
    # script (not trace): tracing drops the attributes the converter reads
    scripted = torch.jit.script(module)
    with tempfile.NamedTemporaryFile(suffix=".pt") as f:
        scripted.save(f.name)
        net2 = Net.load_torch(f.name, input_shape=(16,))
    got2 = np.asarray(net2.predict(x[:8], batch_size=8))
    assert got2.shape == (8, 3)
    print("torchscript file import OK")


if __name__ == "__main__":
    main()
