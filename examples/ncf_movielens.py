"""NCF recommender end-to-end (parity config #1): MovieLens-1M-shaped data
through compile/fit/evaluate, save/load, and top-k recommendation.

Run:  python examples/ncf_movielens.py
(On a machine without a TPU, set
 XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu.)
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.common.zoo_model import load_model
from analytics_zoo_tpu.models.recommendation import NeuralCF

N_USERS, N_ITEMS, N_CLASSES = 600, 370, 5


def synthetic_ratings(n=100_000, seed=0):
    rng = np.random.default_rng(seed)
    dim = 8
    uf = rng.normal(size=(N_USERS + 1, dim))
    vf = rng.normal(size=(N_ITEMS + 1, dim))
    users = rng.integers(1, N_USERS + 1, n).astype(np.int32)
    items = rng.integers(1, N_ITEMS + 1, n).astype(np.int32)
    score = np.einsum("nd,nd->n", uf[users], vf[items]) / np.sqrt(dim)
    edges = np.quantile(score, [0.2, 0.4, 0.6, 0.8])
    y = np.digitize(score, edges).astype(np.int32)
    return np.stack([users, items], axis=1), y


def main():
    init_zoo_context()
    x, y = synthetic_ratings()
    model = NeuralCF(N_USERS, N_ITEMS, N_CLASSES)
    model.compile(optimizer="adam", loss="scce", metrics=["accuracy"],
                  lr=1e-3)
    model.fit(x, y, batch_size=2048, nb_epoch=5, validation_data=(x, y))
    print("eval:", model.evaluate(x, y, batch_size=2048))

    path = model.save("/tmp/ncf_example")
    back = load_model(path)
    recs = back.recommend_for_user(user_id=42,
                                   candidate_items=np.unique(x[:500, 1]),
                                   max_items=5)
    print("top-5 for user 42:", recs)


if __name__ == "__main__":
    main()
