"""ImageNet-style classifier training CLI — the reference's flagship
training example (examples/inception/Train.scala) surface: pick a
published topology, point it at a class-per-subfolder image directory (or
use synthetic data), with checkpointing and TensorBoard.

Run:  python examples/inception_training.py --topology simple-cnn --epochs 3
      python examples/inception_training.py --data /path/to/imagefolders \
             --topology inception-v1 --image-size 224 --batch 256 \
             --checkpoint /tmp/ckpt --tensorboard /tmp/tb
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.common.triggers import EveryEpoch
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.feature.image import ImageSet
from analytics_zoo_tpu.models.image.imageclassification import ImageClassifier


def load_data(args):
    if args.data:
        iset = ImageSet.read(args.data, with_label=True,
                             resize_h=args.image_size,
                             resize_w=args.image_size)
        x = np.asarray(iset.images, np.float32) / 255.0
        y = iset.labels.astype(np.int32)
        n_classes = int(y.max()) + 1
        return x, y, n_classes
    # synthetic fallback: class = dominant color channel
    rng = np.random.default_rng(0)
    n, s = 512, args.image_size
    y = rng.integers(0, 3, n).astype(np.int32)
    x = rng.normal(0.3, 0.1, size=(n, s, s, 3)).astype(np.float32)
    x[np.arange(n), :, :, y] += 0.4
    return x, y, 3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None,
                    help="directory of class subfolders (else synthetic)")
    ap.add_argument("--topology", default="simple-cnn")
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--tensorboard", default=None)
    args = ap.parse_args()

    init_zoo_context()
    x, y, n_classes = load_data(args)
    print(f"dataset: {x.shape[0]} images, {n_classes} classes")

    model = ImageClassifier(args.topology, num_classes=n_classes,
                            input_shape=(args.image_size, args.image_size, 3))
    model.init_weights(sample_input=x[:2])
    model.compile(optimizer="adam", loss="scce", metrics=["accuracy"],
                  lr=args.lr)
    if args.checkpoint:
        model.set_checkpoint(args.checkpoint, trigger=EveryEpoch())
    if args.tensorboard:
        model.set_tensorboard(args.tensorboard, args.topology)

    model.fit(FeatureSet.array(x, y), batch_size=args.batch,
              nb_epoch=args.epochs, validation_data=(x, y))
    print("final:", model.evaluate(x, y, batch_size=args.batch))


if __name__ == "__main__":
    main()
