"""QA ranking (the reference's QARanker example): KNRM kernel-pooling text
matching trained on (question, answer) pairs with rank-hinge loss, scored
with the Ranker NDCG / HitRate metrics.

Run:  python examples/qa_ranker.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.models.textmatching import KNRM


def make_pairs(rng, n_questions=64, vocab=200, q_len=10, a_len=20):
    """Each question has one relevant answer (shares its rare tokens) and
    negatives drawn at random."""
    qs, pos, neg = [], [], []
    for _ in range(n_questions):
        topic = rng.integers(100, vocab, size=4)   # rare topic tokens
        q = np.concatenate([topic, rng.integers(1, 100, q_len - 4)])
        a_good = np.concatenate([topic, rng.integers(1, 100, a_len - 4)])
        a_bad = rng.integers(1, 100, a_len)
        qs.append(q)
        pos.append(a_good)
        neg.append(a_bad)
    return (np.asarray(qs, np.int32), np.asarray(pos, np.int32),
            np.asarray(neg, np.int32))


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)
    q, pos, neg = make_pairs(rng)
    q_len, a_len = q.shape[1], pos.shape[1]

    # rank-hinge training data: (positive, negative) pair rows interleaved
    x = np.concatenate([np.concatenate([q, pos], axis=1),
                        np.concatenate([q, neg], axis=1)])
    order = np.empty(2 * len(q), np.int64)
    order[0::2] = np.arange(len(q))              # pos row
    order[1::2] = np.arange(len(q)) + len(q)     # its neg row
    x = x[order]
    y = np.zeros((len(x), 1), np.float32)        # rank_hinge ignores labels

    model = KNRM(text1_length=q_len, text2_length=a_len, vocab_size=200,
                 embed_size=32, target_mode="ranking")
    model.compile(optimizer="adam", loss="rank_hinge", lr=2e-3)
    # rank_hinge consumes consecutive (positive, negative) rows: train
    # UNSHUFFLED so the pairing survives batching
    model.fit(FeatureSet.array(x, y, shuffle=False), batch_size=32,
              nb_epoch=30)

    # rank each question's candidate set: 1 relevant + 7 distractors
    # (groups of (input rows, relevance) — the Ranker contract)
    groups = []
    for i in range(len(q)):
        cands = [pos[i]] + [neg[(i + j) % len(q)] for j in range(7)]
        rows = np.stack([np.concatenate([q[i], c]) for c in cands])
        truth = np.zeros(8, np.float32)
        truth[0] = 1.0
        groups.append((rows, truth))
    print("NDCG@3 :", round(model.evaluate_ndcg(groups, 3, batch_size=8), 3))
    print("Hit@1  :", round(model.evaluate_hit_rate(groups, 1,
                                                    batch_size=8), 3))


if __name__ == "__main__":
    main()
