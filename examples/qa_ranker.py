"""QA ranking (the reference's QARanker example): raw question/answer texts
+ a relation table -> Relations pair generation -> KNRM kernel-pooling text
matching trained with rank-hinge loss -> list-wise NDCG / MAP / HitRate via
the Ranker metrics. Mirrors the reference flow ``Relations.read`` →
``TextSet.fromRelationPairs`` → train → ``TextSet.fromRelationLists`` →
evaluate (``feature/common/Relations.scala``, ``TextSet.scala:399-533``).

Run:  python examples/qa_ranker.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.feature.text import (Relation, TextSet,
                                            relation_lists_to_groups,
                                            relation_pairs_to_arrays)
from analytics_zoo_tpu.models.textmatching import KNRM

Q_LEN, A_LEN = 10, 20


def make_corpus(rng, n_questions=64, vocab=200):
    """Synthetic corpus: each question shares rare 'topic' words with its
    one relevant answer; negatives are random common words."""
    words = [f"w{i}" for i in range(vocab)]
    questions, answers, relations = {}, {}, []
    for i in range(n_questions):
        topic = rng.integers(100, vocab, size=4)
        q_toks = [words[t] for t in topic] + \
            [words[t] for t in rng.integers(1, 100, Q_LEN - 4)]
        a_toks = [words[t] for t in topic] + \
            [words[t] for t in rng.integers(1, 100, A_LEN - 4)]
        n_toks = [words[t] for t in rng.integers(1, 100, A_LEN)]
        questions[f"q{i}"] = " ".join(q_toks)
        answers[f"a{i}p"] = " ".join(a_toks)
        answers[f"a{i}n"] = " ".join(n_toks)
        relations.append(Relation(f"q{i}", f"a{i}p", 1))
        relations.append(Relation(f"q{i}", f"a{i}n", 0))
    return questions, answers, relations


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)
    questions, answers, relations = make_corpus(rng)

    # text pipeline: one vocabulary over BOTH corpora (answer-only words
    # must not collapse to the 0 padding index), then fixed lengths
    vocab_set = TextSet.from_texts(list(questions.values())
                                   + list(answers.values())).tokenize()
    vocab_set.word2idx()
    word_index = vocab_set.get_word_index()
    c_q = TextSet.from_corpus(questions).tokenize()
    c_q.word2idx(existing_map=word_index)
    c_q.shape_sequence(Q_LEN)
    c_a = TextSet.from_corpus(answers).tokenize()
    c_a.word2idx(existing_map=word_index)
    c_a.shape_sequence(A_LEN)
    vocab_size = len(word_index) + 1

    # pair training data: rows interleaved (positive, negative)
    x, _ = relation_pairs_to_arrays(relations, c_q, c_a)
    y = np.zeros((len(x), 1), np.float32)        # rank_hinge ignores labels

    model = KNRM(text1_length=Q_LEN, text2_length=A_LEN,
                 vocab_size=vocab_size, embed_size=32,
                 target_mode="ranking")
    model.compile(optimizer="adam", loss="rank_hinge", lr=2e-3)
    # rank_hinge consumes consecutive (positive, negative) rows: train
    # UNSHUFFLED so the pairing survives batching
    model.fit(FeatureSet.array(x, y, shuffle=False), batch_size=32,
              nb_epoch=30)

    # list-wise evaluation: every candidate of each question as one group
    groups = relation_lists_to_groups(relations, c_q, c_a)
    print("NDCG@3 :", round(model.evaluate_ndcg(groups, 3, batch_size=8), 3))
    print("MAP    :", round(model.evaluate_map(groups, batch_size=8), 3))
    print("Hit@1  :", round(model.evaluate_hit_rate(groups, 1,
                                                    batch_size=8), 3))


if __name__ == "__main__":
    main()
