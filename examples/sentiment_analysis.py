"""Sentiment analysis (the reference's ``apps/sentiment-analysis`` notebook:
IMDB-style reviews → embedding → recurrent/CNN encoders compared → best
model evaluated).

Flow (matching the notebook): raw texts → ``TextSet`` tokenize/word2idx/
shape_sequence → three encoder variants (CNN via the TextClassifier zoo
model, LSTM and GRU via the Keras-1 layer API) trained on the same split →
held-out accuracy compared, all three must beat chance comfortably.

Run:  python examples/sentiment_analysis.py
"""

import numpy as np

import optax

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature.text import TextSet
from analytics_zoo_tpu.models.textclassification import TextClassifier
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (GRU, LSTM, Dense,
                                                         Embedding)

SEQ_LEN = 24


def make_reviews(n_per_class=400, seed=0):
    rng = np.random.default_rng(seed)
    pos_pool = ["a wonderful heartfelt film", "brilliant acting and pacing",
                "i loved every minute", "an instant classic to rewatch",
                "the plot is moving and sharp"]
    neg_pool = ["a dull lifeless mess", "terrible pacing and flat acting",
                "i regret watching this", "the plot makes no sense at all",
                "boring from start to finish"]
    texts, labels = [], []
    for label, pool in enumerate((neg_pool, pos_pool)):
        for _ in range(n_per_class):
            words = []
            for _ in range(3):
                words.extend(rng.choice(pool).split())
            rng.shuffle(words)
            texts.append(" ".join(words))
            labels.append(label)
    order = rng.permutation(len(texts))
    return [texts[i] for i in order], np.asarray(labels, np.int32)[order]


def encode(texts, labels):
    ts = (TextSet.from_texts(texts, labels)
          .tokenize().word2idx().shape_sequence(SEQ_LEN))
    x = ts.to_arrays()[0]
    vocab = int(x.max()) + 1
    return x.astype(np.int32), vocab


def recurrent_model(kind, vocab):
    rnn = LSTM(32) if kind == "lstm" else GRU(32)
    return Sequential([Embedding(vocab, 32, input_shape=(SEQ_LEN,)),
                       rnn, Dense(2, activation="softmax")])


def main():
    init_zoo_context()
    texts, y = make_reviews()
    x, vocab = encode(texts, y)
    cut = int(len(x) * 0.8)
    results = {}

    # CNN encoder via the zoo model (the notebook's best performer)
    clf = TextClassifier(class_num=2, token_length=32,
                         sequence_length=SEQ_LEN, encoder="cnn",
                         vocab_size=vocab)
    clf.compile(optimizer=optax.adam(1e-3), loss="scce",
                metrics=["accuracy"])
    clf.fit(x[:cut], y[:cut], batch_size=64, nb_epoch=6)
    results["cnn"] = clf.evaluate(x[cut:], y[cut:],
                                  batch_size=128)["accuracy"]

    for kind in ("lstm", "gru"):
        m = recurrent_model(kind, vocab)
        m.compile(optimizer=optax.adam(1e-3), loss="scce",
                  metrics=["accuracy"])
        m.fit(x[:cut], y[:cut], batch_size=64, nb_epoch=6)
        results[kind] = m.evaluate(x[cut:], y[cut:],
                                   batch_size=128)["accuracy"]

    for kind, acc in sorted(results.items(), key=lambda kv: -kv[1]):
        print(f"{kind:5s} held-out accuracy: {acc:.3f}")
    assert all(a > 0.85 for a in results.values()), results
    print("OK")


if __name__ == "__main__":
    main()
