"""Anomaly detection on a univariate time series (the reference's
anomaly-detection app): LSTM forecaster + threshold on prediction error.

Run:  python examples/anomaly_detection.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.anomalydetection import AnomalyDetector
from analytics_zoo_tpu.models.anomalydetection.anomaly_detector import (
    detect_anomalies, unroll)


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)
    t = np.arange(2000, dtype=np.float32)
    series = np.sin(t / 24 * 2 * np.pi) + rng.normal(0, 0.05, t.shape)
    spikes = rng.choice(2000, size=8, replace=False)
    series[spikes] += rng.choice([-2.5, 2.5], size=8)  # injected anomalies

    unroll_len = 24
    x, y, _ = unroll(series[:, None], unroll_len)
    model = AnomalyDetector(feature_shape=(unroll_len, 1))
    model.compile(optimizer="adam", loss="mse", lr=1e-3)
    model.fit(x, y, batch_size=64, nb_epoch=8)

    preds = np.asarray(model.predict(x, batch_size=256)).reshape(-1)
    flagged = detect_anomalies(y.reshape(-1), preds, anomaly_size=8)
    flagged_idx = set(np.flatnonzero(~np.isnan(flagged)))
    spike_idx = {s - unroll_len for s in spikes if s >= unroll_len}
    hit = len(flagged_idx & spike_idx)
    print(f"flagged {len(flagged_idx)} points; "
          f"{hit}/{len(spike_idx)} injected spikes hit")


if __name__ == "__main__":
    main()
