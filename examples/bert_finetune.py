"""BERT fine-tuning (parity config #4 shape): text classification with the
tfpark BERTClassifier, optionally importing HuggingFace/torch weights.

Run:  python examples/bert_finetune.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature.text import TextSet
from analytics_zoo_tpu.tfpark import BERTClassifier


def main():
    init_zoo_context()
    texts = (["great movie loved it", "what a fantastic film"] * 32
             + ["terrible waste of time", "awful plot bad acting"] * 32)
    labels = np.array([1, 1] * 32 + [0, 0] * 32, np.int32)

    ts = TextSet.from_texts(texts, labels)
    ts = ts.tokenize().word2idx().shape_sequence(16)
    ids, y = ts.to_arrays()

    clf = BERTClassifier(num_classes=2, vocab=len(ts.word_index) + 2,
                         hidden_size=64, n_block=2, n_head=2, seq_len=16,
                         intermediate_size=128)
    # for a real checkpoint:
    #   import torch; sd = torch.load("bert_base.pt")
    #   clf.load_pretrained(sd)
    # mask the pad tokens (id 0) so attention ignores them
    inputs = clf.make_inputs(ids, attention_mask=(ids != 0))
    clf.compile(optimizer="adam", loss="scce", metrics=["accuracy"], lr=1e-3)
    clf.fit(inputs, y, batch_size=16, nb_epoch=6)
    print("accuracy:", clf.evaluate(inputs, y, batch_size=16))


if __name__ == "__main__":
    main()
