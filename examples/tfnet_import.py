"""TensorFlow model import — the reference's ``apps/tfnet`` and
``apps/model-inference-examples`` roles (TFNet runs frozen TF graphs inside
the zoo pipeline; reference: ``apps/tfnet/*.ipynb``,
``pipeline/api/net/TFNet.scala``).

A TF-Keras MLP is exported as a SavedModel, imported WITHOUT the TF runtime
in the serving process (`pipeline/api/saved_model.py` parses the graph and
restores the variables through the in-repo proto codec), verified against
TF's own output, then fine-tuned with the native loop — the reference's
frozen TFNet cannot do that last step.

Needs tensorflow only for the EXPORT; skips gracefully without it.

Run:  python examples/tfnet_import.py
"""

import tempfile

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.net import Net


def export_savedmodel(path, x):
    import tensorflow as tf
    tf1 = tf.compat.v1
    g = tf1.Graph()
    rng = np.random.default_rng(1)
    with g.as_default():
        xin = tf1.placeholder(tf.float32, (None, 16), name="x")
        w1 = rng.normal(size=(16, 32)).astype(np.float32) * 0.3
        w2 = rng.normal(size=(32, 3)).astype(np.float32) * 0.3
        vw1 = tf1.get_variable("d1/kernel", initializer=w1)
        vb1 = tf1.get_variable("d1/bias",
                               initializer=np.zeros(32, np.float32))
        h = tf.nn.relu(tf1.matmul(xin, vw1) + vb1)
        vw2 = tf1.get_variable("d2/kernel", initializer=w2)
        vb2 = tf1.get_variable("d2/bias",
                               initializer=np.zeros(3, np.float32))
        probs = tf.nn.softmax(tf1.matmul(h, vw2) + vb2, name="probs")
        with tf1.Session(graph=g) as sess:
            sess.run(tf1.global_variables_initializer())
            want = sess.run(probs, {xin: x})
            tf1.saved_model.simple_save(sess, path, inputs={"x": xin},
                                        outputs={"probs": probs})
    return want


def main():
    try:
        import tensorflow  # noqa: F401
    except ImportError:
        print("tensorflow not installed — skipping the export step "
              "(the import side needs no TF runtime)")
        return

    init_zoo_context()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    with tempfile.TemporaryDirectory() as d:
        want = export_savedmodel(d + "/sm", x)
        tfnet = Net.load_tf(d + "/sm")  # no TF runtime used from here on
    net = Sequential([tfnet])           # the imported graph is a Layer
    net.init_weights(sample_input=x[:2])
    got = np.asarray(net.predict(x, batch_size=32))
    # TPU fp32 matmuls run via bf16 passes at default precision -> ~1e-3
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=2e-3)
    print(f"TF parity OK: max |diff| = {np.abs(got - want).max():.2e}")

    # the imported graph is a native trainable model — fine-tune it
    w = rng.normal(size=(16, 3)).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    net.compile(optimizer="adam", loss="scce", metrics=["accuracy"], lr=0.01)
    net.fit(x, y, batch_size=32, nb_epoch=15)
    acc = net.evaluate(x, y, batch_size=32)["accuracy"]
    print(f"fine-tuned imported TF model: accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
