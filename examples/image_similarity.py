"""Image similarity search (the reference's ``apps/image-similarity``
notebook: real-estate images ranked by semantic similarity — a pretrained
backbone's pooled features + cosine nearest neighbours, served per query).

Flow (matching the notebook): build an Inception-v1 backbone → cut the
graph at the global pooled features (``new_graph`` surgery, the same move
the transfer-learning bench uses) → embed a gallery of images → for each
query, return the top-k cosine neighbours. The synthetic gallery has known
ground-truth groups (shared "scene prototype"), so retrieval quality is
asserted, not eyeballed.

Run:  python examples/image_similarity.py
"""

import numpy as np

import jax
import jax.numpy as jnp

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.image.imageclassification import ImageClassifier


def make_gallery(n_groups=8, per_group=12, hw=112, seed=0):
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(n_groups, hw, hw, 3)).astype(np.float32)
    xs, gids = [], []
    for g in range(n_groups):
        for _ in range(per_group):
            xs.append(protos[g] + rng.normal(0, 0.35, protos[g].shape))
            gids.append(g)
    order = rng.permutation(len(xs))
    return (np.asarray(xs, np.float32)[order],
            np.asarray(gids, np.int32)[order])


def main():
    init_zoo_context()
    x, gid = make_gallery()

    m = ImageClassifier("inception-v1", num_classes=1000,
                        input_shape=(112, 112, 3))
    m.init_weights(sample_input=x[:2])
    extractor = m.model.new_graph(["gap"])      # pooled 1024-d features

    @jax.jit
    def embed(params, state, xb):
        feats, _ = extractor.apply(params, state, xb, training=False,
                                   rng=None)
        return feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)

    emb = np.concatenate([
        np.asarray(embed(m.params, m.net_state, jnp.asarray(x[i:i + 32])))
        for i in range(0, len(x), 32)])

    sims = emb @ emb.T
    np.fill_diagonal(sims, -np.inf)
    k = 5
    topk = np.argsort(-sims, axis=1)[:, :k]
    hit = (gid[topk] == gid[:, None]).mean()
    print(f"gallery={len(x)} groups=8; top-{k} same-group precision={hit:.3f}")
    assert hit > 0.8, hit

    # per-query flow, the serving shape of the notebook
    q = 3
    neighbours = topk[q]
    print(f"query {q} (group {gid[q]}): neighbour groups "
          f"{gid[neighbours].tolist()}")
    print("OK")


if __name__ == "__main__":
    main()
