"""Streaming text classification — the reference's
``examples/streaming/textclassification`` flow (a Spark DStream pulling raw
text lines, tokenizing through the TextSet pipeline, classifying with a
fitted TextClassifier) on the Cluster Serving stack: a producer thread
streams raw sentences into the input queue, the serving loop batches the
tokenized sequences through the classifier, and the consumer prints a label
per line as results arrive (reference:
``pyzoo/zoo/examples/streaming/textclassification/streaming_text_classification.py``).

Run:  python examples/streaming_text_classification.py
"""

import threading
import time

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.feature.text import TextSet
from analytics_zoo_tpu.models.textclassification import TextClassifier
from analytics_zoo_tpu.pipeline.inference import InferenceModel
from analytics_zoo_tpu.serving import ClusterServing, InputQueue, OutputQueue
from analytics_zoo_tpu.serving.backend import LocalBackend

SEQ_LEN = 20
LABELS = ["sports", "tech"]
STREAM = [
    "the team won the match in the final game",
    "the new chip doubles machine learning performance",
    "a great goal and the championship race was close",
    "software update improves the device battery",
]


def make_corpus(rng, n_per_class=96):
    sports = ["the team won the match", "a great goal in the final game",
              "the player scored again", "championship race was close"]
    tech = ["the new chip doubles performance", "software update improves the",
            "machine learning model training", "the device battery lasts"]
    texts, labels = [], []
    for label, pool in enumerate((sports, tech)):
        for _ in range(n_per_class):
            words = []
            for _ in range(3):
                words.extend(rng.choice(pool).split())
            texts.append(" ".join(words))
            labels.append(label)
    return texts, np.asarray(labels, np.int32)


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)
    texts, labels = make_corpus(rng)
    ts = (TextSet.from_texts(texts, labels)
          .tokenize().word2idx().shape_sequence(SEQ_LEN))
    x, y = ts.to_arrays()

    model = TextClassifier(class_num=len(LABELS), token_length=32,
                           sequence_length=SEQ_LEN, encoder="cnn",
                           vocab_size=len(ts.word_index) + 2)
    model.compile(optimizer="adam", loss="scce", lr=2e-3)
    model.fit(x, y, batch_size=32, nb_epoch=8)

    # serve the ZooModel itself — fit stores the trained params on it, not
    # on the inner Sequential
    im = InferenceModel().from_keras(model)
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=4).start()
    inq, outq = InputQueue(backend), OutputQueue(backend)

    def socket_stream():
        """Producer — the socketTextStream role: each raw line is tokenized
        with the TRAINING vocabulary and enqueued as it 'arrives'."""
        for i, line in enumerate(STREAM):
            seq = (TextSet.from_texts([line]).tokenize()
                   .word2idx(existing_map=ts.word_index)
                   .shape_sequence(SEQ_LEN).to_arrays()[0][0])
            inq.enqueue(f"line-{i}", seq.astype(np.float32))
            time.sleep(0.01)

    producer = threading.Thread(target=socket_stream)
    producer.start()
    producer.join()

    for i, line in enumerate(STREAM):
        scores = outq.query(f"line-{i}", timeout=30.0)
        print(f"{LABELS[int(np.argmax(scores))]:>7}  <-  {line}")
    serving.stop()
    print(f"served {serving.served} lines")


if __name__ == "__main__":
    main()
