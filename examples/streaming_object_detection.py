"""Streaming object detection — the reference's
``examples/streaming/objectdetection`` flow (a Spark Structured Streaming
loop pulling image batches and running SSD) on the Cluster Serving stack: a
producer thread streams frames into the input queue, the serving loop
batches them through the SSD detector, and a consumer drains boxes as they
arrive.

Run:  python examples/streaming_object_detection.py
"""

import threading
import time

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.image.objectdetection import ObjectDetector
from analytics_zoo_tpu.pipeline.inference import InferenceModel
from analytics_zoo_tpu.serving import ClusterServing, InputQueue, OutputQueue
from analytics_zoo_tpu.serving.backend import LocalBackend

FRAMES, HW = 24, 96


def main():
    init_zoo_context()
    det = ObjectDetector("ssd-lite", num_classes=4, resolution=HW)
    det.init_weights()

    # serving runs the raw score model; detection decode happens client-side
    # on the streamed scores (the reference decodes in its streaming job too)
    im = InferenceModel(concurrent_num=2).from_keras(det.model)
    backend = LocalBackend()
    serving = ClusterServing(im, backend=backend, batch_size=8).start()
    inq, outq = InputQueue(backend), OutputQueue(backend)

    rng = np.random.default_rng(0)

    def camera():  # producer: one "frame" every few ms
        for i in range(FRAMES):
            frame = rng.normal(size=(HW, HW, 3)).astype(np.float32)
            inq.enqueue(f"frame-{i:03d}", frame)
            time.sleep(0.01)

    t = threading.Thread(target=camera)
    t.start()

    got = 0
    deadline = time.time() + 120
    while got < FRAMES and time.time() < deadline:
        ready = outq.dequeue()
        if getattr(outq, "last_errors", None):
            raise RuntimeError(f"serving errors: {outq.last_errors}")
        for uri, scores in sorted(ready.items()):
            dets = det.decode(np.asarray(scores), conf_thresh=0.3)[0]
            kept = dets[dets[:, 1] > 0]
            print(f"{uri}: {len(kept)} boxes "
                  + " ".join(f"cls{int(b[0])}:{b[1]:.2f}" for b in kept[:3]))
            got += 1
        time.sleep(0.05)
    t.join()
    serving.stop()
    assert got == FRAMES, f"only {got}/{FRAMES} frames came back"
    print("streaming object detection OK")


if __name__ == "__main__":
    main()
