"""NNFrames-style tabular pipeline (the reference's nnframes examples):
a columnar dict-of-arrays table through NNClassifier — schema adapter,
fit, transform-style prediction.

Run:  python examples/nnframes_tabular.py
"""

import numpy as np
import optax

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.recommendation import (ColumnFeatureInfo,
                                                     WideAndDeep)
from analytics_zoo_tpu.pipeline.nnframes import NNClassifier


def make_census_like(n, rng):
    table = {
        "gender": rng.integers(0, 2, n),
        "occupation": rng.integers(0, 10, n),
        "education": rng.integers(0, 16, n),
        "age_bucket": rng.integers(0, 10, n),
        "hours": rng.normal(size=n).astype(np.float32),
        "capital_gain": rng.normal(size=n).astype(np.float32),
    }
    table["gender_x_occupation"] = table["gender"] * 10 + table["occupation"]
    table["label"] = ((table["occupation"] + table["education"]) % 2
                      ).astype(np.int32)
    return table


def main():
    init_zoo_context()
    rng = np.random.default_rng(0)
    table = make_census_like(20_000, rng)

    info = ColumnFeatureInfo(
        wide_base_cols=["gender", "occupation"], wide_base_dims=[2, 10],
        wide_cross_cols=["gender_x_occupation"], wide_cross_dims=[20],
        indicator_cols=["education"], indicator_dims=[16],
        embed_cols=["occupation", "age_bucket"], embed_in_dims=[10, 10],
        embed_out_dims=[16, 16],
        continuous_cols=["hours", "capital_gain"])
    model = WideAndDeep(model_type="wide_n_deep", num_classes=2,
                        column_info=info)
    clf = (NNClassifier(model, feature_preprocessing=lambda t:
                        info.input_arrays(t, "wide_n_deep"))
           .set_optim_method(optax.adam(1e-3))
           .set_batch_size(512).set_max_epoch(4))
    nn_model = clf.fit(table)  # → NNClassifierModel (the Spark-ML shape)

    # transform: table-in → table-out with a prediction column
    test = make_census_like(2_000, rng)
    out = nn_model.transform(test)
    acc = (np.asarray(out["prediction"]) == test["label"]).mean()
    print(f"held-out accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
