"""TFPark generic estimator end-to-end (the reference's model_fn pattern,
``pyzoo/zoo/tfpark/estimator.py:84``): bring-your-own graph code — native
layers + autograd loss expression — wrapped in a TFEstimator, fed by a
TFDataset, with train/evaluate/predict and model_dir weight persistence.

Run:  python examples/tfpark_estimator.py
"""

import tempfile

import numpy as np

import jax.numpy as jnp

from analytics_zoo_tpu import init_zoo_context
import analytics_zoo_tpu.pipeline.api.autograd as A
from analytics_zoo_tpu.pipeline.api.keras.engine import Lambda
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, Dropout
from analytics_zoo_tpu.tfpark import (ModeKeys, TFDataset, TFEstimator,
                                      TFEstimatorSpec)


def make_data(n=2048, d=20, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(d, classes))
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ w + 0.3 * rng.normal(size=(n, classes))).argmax(1)
    return x, y.astype(np.int32)


def sparse_ce(probs, labels):
    """Loss as a graph expression over (probs, labels) Variables."""
    def f(p, y):
        p = jnp.clip(p, 1e-7, 1.0)
        picked = jnp.take_along_axis(
            p, y.astype(jnp.int32).reshape(-1, 1), axis=1)[:, 0]
        return -jnp.log(picked)
    return A.mean(Lambda(f, name="sparse_ce")([probs, labels]), axis=0)


def model_fn(features, labels, mode, params):
    """The user-authored part: any layers/ops, returns a TFEstimatorSpec."""
    h = Dense(64, activation="relu")(features)
    h = Dropout(0.1)(h)
    probs = Dense(params["classes"], activation="softmax")(h)
    loss = sparse_ce(probs, labels) if labels is not None else None
    return TFEstimatorSpec(mode, predictions=probs, loss=loss)


def main():
    init_zoo_context()
    x, y = make_data()
    n_train = 1536
    model_dir = tempfile.mkdtemp(prefix="tfpark_estimator_")

    def input_fn(mode):
        if mode == ModeKeys.TRAIN:
            return TFDataset(x[:n_train], y[:n_train], batch_size=128)
        if mode == ModeKeys.EVAL:
            return TFDataset(x[n_train:], y[n_train:], batch_per_thread=128)
        return TFDataset(x[n_train:], batch_per_thread=128)

    est = TFEstimator(model_fn, optimizer="adam", lr=3e-3,
                      params={"classes": 3}, model_dir=model_dir)
    est.train(input_fn, steps=300)
    metrics = est.evaluate(input_fn, ["accuracy", "loss"])
    print(f"held-out accuracy={metrics['accuracy']:.3f} "
          f"loss={metrics['loss']:.3f}")

    preds = est.predict(input_fn)
    print(f"predictions: {np.asarray(preds).shape}, "
          f"first row={np.round(np.asarray(preds)[0], 3)}")

    # a fresh estimator restores the trained weights from model_dir
    est2 = TFEstimator(model_fn, params={"classes": 3}, model_dir=model_dir)
    preds2 = est2.predict(input_fn)
    drift = float(np.abs(np.asarray(preds) - np.asarray(preds2)).max())
    print(f"fresh-estimator restore drift: {drift:.2e}")
    assert metrics["accuracy"] > 0.85
    assert drift < 1e-5
    print("OK")


if __name__ == "__main__":
    main()
