"""Parameter-server training on the Ray-equivalent task runtime — the
reference's ``pyzoo/zoo/examples/ray/parameter_server`` (sync and async
modes over RayOnSpark actors, ``raycontext.py:192``) on this framework's
process-pool actor runtime.

A ``ParameterServer`` actor owns the weights; worker TASKS pull weights,
compute a logistic-regression gradient on their data shard (pure numpy —
actor processes stay off the TPU; the chip belongs to the main process),
and push updates back. Two modes: sync (average all shard gradients, one
barriered update per round) and async (shard gradients computed
concurrently from a stale snapshot, applied one by one as they arrive).

Run:  python examples/ray_parameter_server.py
"""

import numpy as np

from analytics_zoo_tpu.ray import RayContext

DIM, N, WORKERS, ROUNDS = 16, 4096, 4, 30


class ParameterServer:
    """Weight owner (the reference's PS actor): apply_gradient / pull."""

    def __init__(self, dim: int, lr: float):
        self.w = np.zeros(dim, np.float32)
        self.lr = lr
        self.updates = 0

    def get_weights(self):
        return self.w

    def apply_gradient(self, grad):
        self.w = self.w - self.lr * np.asarray(grad, np.float32)
        self.updates += 1
        return self.updates


def grad_shard(w, x, y):
    """Logistic-regression gradient on one shard (runs in a pool worker)."""
    z = 1.0 / (1.0 + np.exp(-(x @ w)))
    return x.T @ (z - y) / len(y)


def loss_of(w, x, y):
    z = 1.0 / (1.0 + np.exp(-(x @ w)))
    z = np.clip(z, 1e-7, 1 - 1e-7)
    return float(-np.mean(y * np.log(z) + (1 - y) * np.log(1 - z)))


def main():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, DIM)).astype(np.float32)
    w_true = rng.normal(size=DIM).astype(np.float32)
    y = (x @ w_true > 0).astype(np.float32)
    shards = [(x[i::WORKERS], y[i::WORKERS]) for i in range(WORKERS)]

    ctx = RayContext(num_workers=WORKERS).init()
    try:
        # ---- sync mode: barrier per round --------------------------------
        ps = ctx.actor(ParameterServer, DIM, 0.5)
        for r in range(ROUNDS):
            w = ctx.get(ps.get_weights.remote())
            grads = ctx.get([ctx.remote(grad_shard, w, sx, sy)
                             for sx, sy in shards])
            ps.apply_gradient.remote(np.mean(grads, axis=0))
        w = ctx.get(ps.get_weights.remote())
        sync_loss = loss_of(w, x, y)
        print(f"sync   PS: loss={sync_loss:.4f} "
              f"acc={(((x @ w) > 0) == y).mean():.3f}")
        ps.terminate()

        # ---- async mode: shard gradients compute CONCURRENTLY from the
        # same (stale) weight snapshot and apply as each arrives — between
        # applies the weights the others used are already out of date,
        # the Hogwild-style staleness the reference's async PS exhibits
        ps = ctx.actor(ParameterServer, DIM, 0.5)
        last = None
        for r in range(ROUNDS):
            w = ctx.get(ps.get_weights.remote())
            grads = [ctx.remote(grad_shard, w, sx, sy) for sx, sy in shards]
            for g in grads:
                last = ps.apply_gradient.remote(ctx.get(g))
        ctx.get(last)
        w = ctx.get(ps.get_weights.remote())
        async_loss = loss_of(w, x, y)
        print(f"async  PS: loss={async_loss:.4f} "
              f"acc={(((x @ w) > 0) == y).mean():.3f}")
        ps.terminate()

        assert sync_loss < 0.3 and async_loss < 0.3, (sync_loss, async_loss)
        print("parameter server OK")
    finally:
        ctx.stop()


if __name__ == "__main__":
    main()
