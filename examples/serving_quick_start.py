"""Cluster Serving quick start (the reference's serving/quick_start.py):
wrap a trained model in the inference runtime, start the serving loop,
push requests through the input queue and read predictions back.

Also demonstrates end-to-end trace-id propagation (the ROADMAP follow-up
for RedisBackend-facing deployments): the caller mints one trace id per
request — in production this is the upstream request id — and passes it
to ``enqueue(trace=...)``. The id rides the stream record as a plain
field, so it survives the Redis hop in a multi-process deployment
unchanged, and the server emits four parent-linked phase events
(enqueue→dequeue→dispatch→publish) under it. Reading the JSON event log
back by trace id reconciles each request's exact latency breakdown even
when producer and server are different processes.

Run:  python examples/serving_quick_start.py
"""

import os
import tempfile

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu import observability as obs
from analytics_zoo_tpu.pipeline.api.keras.engine import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.pipeline.inference import InferenceModel
from analytics_zoo_tpu.serving import ClusterServing, InputQueue, OutputQueue
from analytics_zoo_tpu.serving.backend import LocalBackend


def main():
    init_zoo_context()
    model = Sequential()
    model.add(Dense(16, activation="relu", input_shape=(8,)))
    model.add(Dense(3, activation="softmax"))
    model.init_weights()

    im = InferenceModel(concurrent_num=2).from_keras(model)
    backend = LocalBackend()  # swap for RedisBackend(...) in production —
    #                           the trace field rides the stream verbatim
    events_path = os.path.join(tempfile.mkdtemp(), "serving_events.jsonl")
    serving = (ClusterServing(im, backend=backend, batch_size=16)
               .set_json_events(events_path)       # before start()
               .start())

    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(0)
    # adopt explicit trace ids: in a real deployment this is the upstream
    # request id (any non-empty string); minting via new_trace_id() keeps
    # the documented 16-hex-char wire format
    traces = {f"req-{i}": obs.new_trace_id() for i in range(8)}
    for uri, trace in traces.items():
        inq.enqueue(uri, rng.normal(size=(8,)).astype(np.float32),
                    trace=trace)
    for uri in traces:
        probs = outq.query(uri, timeout=60.0)
        if probs is None:
            raise TimeoutError(f"{uri}: no prediction within 60s")
        print(f"{uri}: class={int(np.argmax(probs))}")
    serving.stop()

    # cross-process reconciliation: group the event log by OUR ids —
    # every request shows its four phases with per-phase durations
    by_trace = {}
    for e in obs.read_events(events_path, kind="request"):
        by_trace.setdefault(e["trace"], {})[e["phase"]] = e
    for uri, trace in traces.items():
        phases = by_trace[trace]
        assert set(phases) == {"enqueue", "dequeue", "dispatch", "publish"}
        print(f"{uri} trace={trace}: queue-wait "
              f"{phases['dequeue']['dur_s'] * 1e3:.2f} ms, e2e "
              f"{phases['publish']['e2e_s'] * 1e3:.2f} ms")


if __name__ == "__main__":
    main()
