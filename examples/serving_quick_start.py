"""Cluster Serving quick start (the reference's serving/quick_start.py):
wrap a trained model in the inference runtime, start the serving loop,
push requests through the input queue and read predictions back.

Run:  python examples/serving_quick_start.py
"""

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.pipeline.api.keras.engine import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.pipeline.inference import InferenceModel
from analytics_zoo_tpu.serving import ClusterServing, InputQueue, OutputQueue
from analytics_zoo_tpu.serving.backend import LocalBackend


def main():
    init_zoo_context()
    model = Sequential()
    model.add(Dense(16, activation="relu", input_shape=(8,)))
    model.add(Dense(3, activation="softmax"))
    model.init_weights()

    im = InferenceModel(concurrent_num=2).from_keras(model)
    backend = LocalBackend()  # swap for RedisBackend(...) in production
    serving = ClusterServing(im, backend=backend, batch_size=16).start()

    inq, outq = InputQueue(backend), OutputQueue(backend)
    rng = np.random.default_rng(0)
    for i in range(8):
        inq.enqueue(f"req-{i}", rng.normal(size=(8,)).astype(np.float32))
    for i in range(8):
        probs = outq.query(f"req-{i}", timeout=60.0)
        if probs is None:
            raise TimeoutError(f"req-{i}: no prediction within 60s")
        print(f"req-{i}: class={int(np.argmax(probs))}")
    serving.stop()


if __name__ == "__main__":
    main()
