// Native host-side image ops for the data pipeline — the TPU-native
// equivalent of the reference's OpenCV-JNI layer (SURVEY §2.3: "OpenCV
// image ops … C++ decode/augment library on TPU-VM hosts feeding the
// custom loader"; reference interface feature/image/OpenCVMethod.scala,
// transformers feature/image/*.scala running OpenCV through BigDL JNI).
//
// Scope: the two bandwidth-critical batch ops the Python pipeline runs per
// training batch —
//  * resize():    separable triangle-filter ("bilinear") resampling, the
//                 same algorithm family PIL/OpenCV area-aware bilinear use
//                 (filter widens by the scale factor on downscale, so
//                 minification averages instead of aliasing);
//  * normalize(): fused dtype-convert + per-channel (x - mean) * inv_std
//                 in one pass over the batch.
// Both are threaded over the batch dimension. Everything else in the
// transformer zoo (crops, flips, color jitter) is already a cheap numpy
// slice/arithmetic; the wins here are the per-image Python/PIL loop and
// the double pass over a float batch.
//
// C ABI (ctypes-consumed; no pybind11 in the image); all return 0/-1:
//   int zoo_image_resize(const void* src, int is_f32, long n, long h,
//                        long w, long c, void* dst, long oh, long ow,
//                        int nthreads);
//   int zoo_image_normalize(const void* src, int is_f32, long n,
//                           long hw, long c, const float* mean,
//                           const float* inv_std, float* dst,
//                           int nthreads);

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Coeffs {
  // for each output index: input window [lo, lo+len) and its weights
  std::vector<long> lo;
  std::vector<int> len;
  std::vector<float> w;  // ragged, max_len stride
  int max_len = 0;
};

// Triangle-filter coefficient table, PIL-style: on downscale the filter
// support widens by the scale factor so every source pixel contributes.
Coeffs build_coeffs(long in, long out) {
  Coeffs co;
  const double scale = static_cast<double>(in) / static_cast<double>(out);
  const double fscale = std::max(scale, 1.0);
  const double support = fscale;  // triangle support 1.0, scaled
  co.max_len = static_cast<int>(std::ceil(support)) * 2 + 1;
  co.lo.resize(out);
  co.len.resize(out);
  co.w.assign(static_cast<size_t>(out) * co.max_len, 0.0f);
  for (long x = 0; x < out; ++x) {
    const double center = (x + 0.5) * scale;
    long lo = static_cast<long>(std::floor(center - support));
    long hi = static_cast<long>(std::ceil(center + support));
    lo = std::max<long>(lo, 0);
    hi = std::min<long>(hi, in);
    double total = 0.0;
    std::vector<double> tmp(hi - lo);
    for (long i = lo; i < hi; ++i) {
      const double t = std::abs((i + 0.5 - center) / fscale);
      const double v = t < 1.0 ? 1.0 - t : 0.0;  // triangle
      tmp[i - lo] = v;
      total += v;
    }
    if (total <= 0.0) {  // degenerate window: nearest
      lo = std::min<long>(std::max<long>(
          static_cast<long>(center), 0), in - 1);
      co.lo[x] = lo;
      co.len[x] = 1;
      co.w[static_cast<size_t>(x) * co.max_len] = 1.0f;
      continue;
    }
    co.lo[x] = lo;
    co.len[x] = static_cast<int>(hi - lo);
    for (long i = 0; i < hi - lo; ++i)
      co.w[static_cast<size_t>(x) * co.max_len + i] =
          static_cast<float>(tmp[i] / total);
  }
  return co;
}

// One image: (h, w, c) -> (oh, ow, c), horizontal then vertical pass.
template <typename T>
void resize_one(const T* src, long h, long w, long c, T* dst, long oh,
                long ow, const Coeffs& cw, const Coeffs& ch,
                std::vector<float>& mid) {
  mid.resize(static_cast<size_t>(h) * ow * c);
  // horizontal: (h, w, c) -> (h, ow, c)
  for (long y = 0; y < h; ++y) {
    const T* row = src + static_cast<size_t>(y) * w * c;
    float* orow = mid.data() + static_cast<size_t>(y) * ow * c;
    for (long x = 0; x < ow; ++x) {
      const float* wt = cw.w.data() + static_cast<size_t>(x) * cw.max_len;
      const long lo = cw.lo[x];
      const int len = cw.len[x];
      for (long ch_i = 0; ch_i < c; ++ch_i) {
        float acc = 0.0f;
        for (int k = 0; k < len; ++k)
          acc += wt[k] * static_cast<float>(row[(lo + k) * c + ch_i]);
        orow[x * c + ch_i] = acc;
      }
    }
  }
  // vertical: (h, ow, c) -> (oh, ow, c)
  for (long y = 0; y < oh; ++y) {
    const float* wt = ch.w.data() + static_cast<size_t>(y) * ch.max_len;
    const long lo = ch.lo[y];
    const int len = ch.len[y];
    T* orow = dst + static_cast<size_t>(y) * ow * c;
    for (long xc = 0; xc < ow * c; ++xc) {
      float acc = 0.0f;
      for (int k = 0; k < len; ++k)
        acc += wt[k] * mid[static_cast<size_t>(lo + k) * ow * c + xc];
      if (std::is_same<T, uint8_t>::value) {
        acc = acc < 0.0f ? 0.0f : (acc > 255.0f ? 255.0f : acc);
        orow[xc] = static_cast<T>(acc + 0.5f);
      } else {
        orow[xc] = static_cast<T>(acc);
      }
    }
  }
}

template <typename Fn>
void parallel_over(long n, int nthreads, Fn fn) {
  const long want = nthreads > 0
      ? nthreads
      : static_cast<long>(std::thread::hardware_concurrency());
  const int workers = static_cast<int>(
      std::max<long>(1, std::min<long>(want, n)));
  if (workers == 1) {
    fn(0, n, 0);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(workers);
  const long per = (n + workers - 1) / workers;
  for (int t = 0; t < workers; ++t) {
    const long b = t * per, e = std::min<long>(n, b + per);
    if (b >= e) break;
    ts.emplace_back([=] { fn(b, e, t); });
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

int zoo_image_resize(const void* src, int is_f32, long n, long h, long w,
                     long c, void* dst, long oh, long ow, int nthreads) {
  if (!src || !dst || n < 0 || h <= 0 || w <= 0 || c <= 0 || oh <= 0 ||
      ow <= 0)
    return -1;
  if (n == 0) return 0;
  const Coeffs cw = build_coeffs(w, ow);
  const Coeffs ch = build_coeffs(h, oh);
  const size_t in_px = static_cast<size_t>(h) * w * c;
  const size_t out_px = static_cast<size_t>(oh) * ow * c;
  parallel_over(n, nthreads, [&](long b, long e, int) {
    std::vector<float> mid;
    for (long i = b; i < e; ++i) {
      if (is_f32)
        resize_one(static_cast<const float*>(src) + i * in_px, h, w, c,
                   static_cast<float*>(dst) + i * out_px, oh, ow, cw, ch,
                   mid);
      else
        resize_one(static_cast<const uint8_t*>(src) + i * in_px, h, w, c,
                   static_cast<uint8_t*>(dst) + i * out_px, oh, ow, cw, ch,
                   mid);
    }
  });
  return 0;
}

int zoo_image_normalize(const void* src, int is_f32, long n, long hw,
                        long c, const float* mean, const float* inv_std,
                        float* dst, int nthreads) {
  if (!src || !dst || !mean || !inv_std || n < 0 || hw <= 0 || c <= 0)
    return -1;
  if (n == 0) return 0;
  const size_t px = static_cast<size_t>(hw) * c;
  parallel_over(n, nthreads, [&](long b, long e, int) {
    for (long i = b; i < e; ++i) {
      float* out = dst + i * px;
      // pixel-outer / channel-inner: no per-element modulo, and the
      // small fixed-trip inner loop vectorizes
      if (is_f32) {
        const float* in = static_cast<const float*>(src) + i * px;
        for (long p = 0; p < hw; ++p, in += c, out += c)
          for (long ch = 0; ch < c; ++ch)
            out[ch] = (in[ch] - mean[ch]) * inv_std[ch];
      } else {
        const uint8_t* in = static_cast<const uint8_t*>(src) + i * px;
        for (long p = 0; p < hw; ++p, in += c, out += c)
          for (long ch = 0; ch < c; ++ch)
            out[ch] = (static_cast<float>(in[ch]) - mean[ch]) * inv_std[ch];
      }
    }
  });
  return 0;
}

}  // extern "C"
