// Native host-side IO for the disk data tier — the TPU-native replacement
// for the reference's PMEM/disk cache natives (PersistentMemoryAllocator
// JNI, zoo/src/main/java/.../pmem/PersistentMemoryAllocator.java:37-43, and
// the DISK_ONLY RDD under DiskFeatureSet, FeatureSet.scala:332-409).
//
// Design: datasets are memory-mapped read-only files; the OS page cache is
// the DRAM tier. The library adds what numpy.memmap alone can't do cheaply:
//  * gather(): one C++ loop copying an index-selected set of fixed-size
//    records into a caller buffer (a DRAM slice materialization) without
//    per-row Python/numpy overhead;
//  * prefetch(): madvise(WILLNEED) plus a background touch thread per
//    handle, so the NEXT slice's pages stream in from disk while the
//    current slice trains — the double-buffering DiskFeatureSet gets from
//    Spark's async persistence.
//
// C ABI (ctypes-consumed; no pybind11 in the image):
//   void*  zoo_open(const char* path);
//   long   zoo_size(void* h);                       // bytes
//   const void* zoo_data(void* h);                  // mapped base
//   int    zoo_gather(void* h, long offset, long record_bytes,
//                     const long* indices, long n, void* dst);
//   int    zoo_prefetch(void* h, long offset, long nbytes);   // async
//   void   zoo_prefetch_wait(void* h);
//   void   zoo_close(void* h);
// All functions return 0/-1 for status where applicable; errno preserved.

#include <atomic>
#include <cerrno>
#include <cstring>
#include <thread>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Handle {
  int fd = -1;
  const char* base = nullptr;
  long size = 0;
  std::thread prefetcher;
  std::atomic<bool> prefetch_running{false};
};

}  // namespace

extern "C" {

void* zoo_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* base = ::mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  // sequential scans are the common post-gather pattern; let readahead work
  ::madvise(base, st.st_size, MADV_NORMAL);
  Handle* h = new Handle();
  h->fd = fd;
  h->base = static_cast<const char*>(base);
  h->size = static_cast<long>(st.st_size);
  return h;
}

long zoo_size(void* hp) { return static_cast<Handle*>(hp)->size; }

const void* zoo_data(void* hp) { return static_cast<Handle*>(hp)->base; }

int zoo_gather(void* hp, long offset, long record_bytes, const long* indices,
               long n, void* dst) {
  Handle* h = static_cast<Handle*>(hp);
  if (record_bytes <= 0 || offset < 0) {
    errno = EINVAL;
    return -1;
  }
  char* out = static_cast<char*>(dst);
  const char* src = h->base + offset;
  const long max_index = (h->size - offset) / record_bytes;
  for (long i = 0; i < n; ++i) {
    const long idx = indices[i];
    if (idx < 0 || idx >= max_index) {
      errno = ERANGE;
      return -1;
    }
    std::memcpy(out + i * record_bytes, src + idx * record_bytes,
                record_bytes);
  }
  return 0;
}

void zoo_prefetch_wait(void* hp) {
  Handle* h = static_cast<Handle*>(hp);
  if (h->prefetcher.joinable()) h->prefetcher.join();
  h->prefetch_running = false;
}

int zoo_prefetch(void* hp, long offset, long nbytes) {
  Handle* h = static_cast<Handle*>(hp);
  if (offset < 0 || offset + nbytes > h->size) {
    errno = ERANGE;
    return -1;
  }
  zoo_prefetch_wait(hp);  // one in-flight prefetch per handle
  const char* base = h->base + offset;
  ::madvise(const_cast<char*>(base), nbytes, MADV_WILLNEED);
  h->prefetch_running = true;
  h->prefetcher = std::thread([base, nbytes, h]() {
    // touch one byte per page to force residency even when WILLNEED is
    // only advisory; volatile sink defeats dead-read elimination
    volatile char sink = 0;
    const long page = ::sysconf(_SC_PAGESIZE);
    for (long i = 0; i < nbytes; i += page) sink ^= base[i];
    (void)sink;
    h->prefetch_running = false;
  });
  return 0;
}

void zoo_close(void* hp) {
  Handle* h = static_cast<Handle*>(hp);
  zoo_prefetch_wait(hp);
  if (h->base) ::munmap(const_cast<char*>(h->base), h->size);
  if (h->fd >= 0) ::close(h->fd);
  delete h;
}

}  // extern "C"
