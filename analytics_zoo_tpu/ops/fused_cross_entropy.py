"""Fused blockwise LM-head cross-entropy — the bandwidth-proportional
replacement for the full-logits ``sparse_categorical_crossentropy_from_logits``
training objective (the oracle it is equivalence-tested against in
``tests/test_fused_ce.py``).

The full-logits objective materializes ``(B·T, V)`` fp32 log-probabilities —
2 GB at the 4k long-context bench shape, 8 GB at 32k — three times over
(forward, the softmax backward, the label pick's scatter). This op streams
the hidden states through the vocab projection in row-chunked tiles instead
(Liu & Abbeel 2023's blockwise-parallel formulation applied to the LM head):

* **forward** — per chunk, form the ``(chunk, V)`` logits tile once, fold
  its ``logsumexp`` and the label's logit online, discard the tile. On TPU
  the tile never even reaches HBM: ``ops/pallas/cross_entropy.py`` computes
  both scalars in one VMEM-resident pass (``zoo.pallas.cross_entropy=auto``
  routing, same convention as flash attention).
* **backward** (custom VJP) — re-form one tile at a time from the saved
  row ``logsumexp``: ``dlogits = (softmax - onehot) * g``, then
  ``dW += hᵀ @ dlogits`` and ``dh = dlogits @ Wᵀ`` — both on the MXU in the
  compute dtype (bf16 operands, f32 accumulation), with the ``dW`` carry
  accumulated in f32 across chunks. With the pallas routing on, the tile
  re-formation and both product matmuls run inside the
  ``fused_ce_backward`` kernel pair — the probability tile never reaches
  HBM in the backward either.
* **vocab-sharded** (``sharded_fused_cross_entropy_rows``) — the
  Megatron-LM-style model-parallel form: the head weight shards over the
  ``model`` mesh axis, each rank streams only its ``(chunk, V/n)`` slice
  with a LOCAL online logsumexp, and one ``pmax``+``psum`` pair merges the
  per-rank ``(m, l)`` carries and the label logit (the label's owning
  shard contributes it; every other rank contributes 0). The custom VJP
  re-forms only local tiles, so ``dW`` stays sharded end to end and the
  full-vocab logits row never exists on ANY rank. Label semantics are the
  unsharded op's exactly: labels < 0 are masked out of loss and grads,
  labels >= V NaN-poison their row. Numerics match the unsharded path to
  reassociation-level rounding (the row max, the label logit and every
  per-element term are bit-identical; only the cross-shard denominator
  sum is re-associated by the psum).

Memory is O(chunk·V) end to end (O(chunk·V/n) per rank sharded); FLOPs are
identical to the full-logits path, so the win is pure HBM bandwidth. Labels
< 0 are masked out of the loss and every gradient (padded/ignored
positions); labels >= V poison the row to NaN, exactly as loudly as the
full-logits objective's fill-mode gather — a dataset off-by-one can never
train on silently.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["fused_cross_entropy_rows", "fused_sparse_cross_entropy",
           "sharded_fused_cross_entropy_rows",
           "sharded_fused_sparse_cross_entropy", "vocab_shard_count",
           "pallas_ce_enabled", "DEFAULT_CHUNK", "AUTO_MIN_VOCAB"]

#: rows per streamed logits tile: 512·V·4 B of transient f32 per tile
#: (16 MB at V=8192) — small enough to live in cache-adjacent HBM, large
#: enough that the (chunk, V) matmuls stay MXU-shaped
DEFAULT_CHUNK = 512

#: ``zoo.train.fused_ce=auto`` engages the fused loss at/above this head
#: width: below it the full-logits tensor is small, XLA's fused softmax is
#: fine, and the scan's sequentialization would only add dispatch overhead
#: (the flash-attention FLASH_AUTO_MIN_SEQ convention, applied to vocab)
AUTO_MIN_VOCAB = 1024

#: bias value for vocab-padding columns of the sharded path: far enough
#: down that ``exp(pad_logit - anything_real)`` underflows to exactly 0
#: (so pad columns are inert in the logsumexp), finite so no -inf NaN
#: traps, and representable in bfloat16 (the bias is added in the compute
#: dtype, replicating Dense.call's rounding)
_NEG_PAD = -1e30


def _conf(key: str, default):
    from ..common.context import get_zoo_context
    try:
        return get_zoo_context().get(key, default)
    except Exception:  # context not constructible (odd device counts)
        return default


def pallas_ce_enabled() -> bool:
    """``zoo.pallas.cross_entropy``: auto (TPU only) | true | false — the
    flash-attention flag convention. Covers BOTH the forward kernel and
    the ``fused_ce_backward`` kernel pair."""
    from ..common.context import tri_state_conf
    flag = tri_state_conf("zoo.pallas.cross_entropy")
    if flag == "auto":
        return jax.default_backend() == "tpu"
    return flag


def _pad_rows(a: jax.Array, n_pad: int, value=0):
    if n_pad == 0:
        return a
    cfg = [(0, n_pad)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, cfg, constant_values=value)


def _chunk_logits(hc, wc, bc):
    """One (chunk, V) logits tile with Dense.call's EXACT rounding: f32
    MXU accumulation, round to the compute dtype, bias added in the
    compute dtype, final f32 upcast — under bf16 policy the oracle's
    logits carry that rounding, and the silent substitution must not be
    more precise than the path it replaces (loss-gate comparability
    across the flag)."""
    logits = jax.lax.dot_general(hc, wc, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32
                                 ).astype(hc.dtype)
    if bc is not None:
        logits = logits + bc
    return logits.astype(jnp.float32)


def _fwd_scan_parts(h, w, b, labels, chunk: int
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """XLA path: per-row ``(m, l, label_logit)`` — the row max, the
    max-shifted denominator and the label's logit — via a lax.scan over
    row chunks; the (chunk, V) logits tile is the largest live tensor.
    ``lse = m + log(l)`` for the unsharded path; the sharded path merges
    the raw ``(m, l)`` pairs across vocab shards first. Labels < 0 (and
    the sharded path's not-my-shard -1 sentinel) contribute a 0 label
    logit."""
    n, hidden = h.shape
    n_pad = (-n) % chunk
    hp = _pad_rows(h, n_pad)
    lp = _pad_rows(labels, n_pad, value=-1)
    k = hp.shape[0] // chunk
    wc = w.astype(h.dtype)
    bc = None if b is None else b.astype(h.dtype)

    def one(_, inp):
        hc, lc = inp
        logits = _chunk_logits(hc, wc, bc)
        m = jnp.max(logits, axis=-1)
        l = jnp.sum(jnp.exp(logits - m[:, None]), axis=-1)
        idx = jnp.clip(lc, 0, logits.shape[-1] - 1)
        ll = jnp.take_along_axis(logits, idx[:, None], axis=-1)[:, 0]
        return None, (m, l, jnp.where(lc >= 0, ll, 0.0))

    _, (m, l, ll) = jax.lax.scan(
        one, None, (hp.reshape(k, chunk, hidden), lp.reshape(k, chunk)))
    return (m.reshape(-1)[:n], l.reshape(-1)[:n], ll.reshape(-1)[:n])


def _fwd_scan(h, w, b, labels, chunk: int) -> Tuple[jax.Array, jax.Array]:
    """Per-row (logsumexp, label_logit) — the unsharded finish of
    :func:`_fwd_scan_parts`."""
    m, l, ll = _fwd_scan_parts(h, w, b, labels, chunk)
    return m + jnp.log(l), ll


def _fwd(h, w, b, labels, chunk: int, use_pallas: bool,
         interpret: Optional[bool]):
    if use_pallas:
        from .pallas.cross_entropy import fused_ce_forward
        return fused_ce_forward(h, w.astype(h.dtype), b, labels,
                                block_n=min(chunk, 256),
                                interpret=interpret)
    return _fwd_scan(h, w, b, labels, chunk)


def _grad_scale(labels, g, v: int) -> jax.Array:
    """The per-row dlogits multiplier shared by every backward: the
    incoming cotangent for valid rows, exactly 0 for masked (label < 0)
    rows, NaN for over-range (label >= v) rows — the poison the forward
    already applied, now spread across dW/dh by the matmuls just as the
    full-logits objective's backward would."""
    scale = jnp.where(labels >= 0, g.astype(jnp.float32), 0.0)
    return jnp.where(labels >= v, jnp.nan, scale)


def _bwd_scan(h, w, b, labels, lse, scale, chunk: int,
              dh_dtype=None):
    """Tile-at-a-time backward: re-form each (chunk, V) probability tile
    from the saved row logsumexp, fold ``dW``/``db`` into an f32 scan carry,
    emit ``dh`` per chunk. The dW/dh matmuls run in the compute dtype on
    the MXU with f32 accumulation.

    ``labels`` are the HIT labels (column index or -1 for no local hit —
    the sharded path feeds not-my-shard rows through as -1); ``scale`` is
    the precomputed :func:`_grad_scale` vector. ``dh_dtype`` overrides the
    per-chunk dh rounding (the sharded path keeps f32 across the
    cross-shard psum and rounds once)."""
    n, hidden = h.shape
    v = w.shape[1]
    n_pad = (-n) % chunk
    hp = _pad_rows(h, n_pad)
    lp = _pad_rows(labels, n_pad, value=-1)
    # pad the saved logsumexp with +inf: a padded row's logits are the
    # bare bias (h = 0), and exp(bias - 0) overflows to inf for bias >
    # ~88 — inf * the row's zero grad-scale is NaN, and the dW matmul
    # spreads it everywhere. exp(bias - inf) = 0 keeps pad rows inert.
    lsep = _pad_rows(lse, n_pad, value=jnp.inf)
    sp = _pad_rows(scale, n_pad)
    k = hp.shape[0] // chunk
    wc = w.astype(h.dtype)
    bc = None if b is None else b.astype(h.dtype)
    dh_dtype = dh_dtype or h.dtype

    def one(carry, inp):
        dw, db = carry
        hc, lc, lsec, sc = inp
        # tile re-formation carries the SAME compute-dtype rounding as
        # the forward (see _fwd_scan_parts) so p is re-formed bit-for-bit
        logits = _chunk_logits(hc, wc, bc)
        p = jnp.exp(logits - lsec[:, None])
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (chunk, v), 1)
                  == lc[:, None])
        dl = (p - onehot) * sc[:, None]
        dlc = dl.astype(h.dtype)
        dh = jax.lax.dot_general(dlc, wc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32
                                 ).astype(dh_dtype)
        dw = dw + jax.lax.dot_general(hc, dlc, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        if db is not None:
            db = db + jnp.sum(dl, axis=0)
        return (dw, db), dh

    dw0 = jnp.zeros((hidden, v), jnp.float32)
    db0 = None if b is None else jnp.zeros((v,), jnp.float32)
    (dw, db), dh = jax.lax.scan(
        one, (dw0, db0),
        (hp.reshape(k, chunk, hidden), lp.reshape(k, chunk),
         lsep.reshape(k, chunk), sp.reshape(k, chunk)))
    dh = dh.reshape(-1, hidden)[:n]
    return dh, dw, db


def _bwd(h, w, b, labels, lse, scale, chunk: int, use_pallas: bool,
         interpret: Optional[bool], dh_dtype=None):
    """Backward dispatcher: the pallas kernel pair when routed (the tile
    re-formation and both product matmuls stay VMEM-resident), else the
    XLA scan. Returns f32 (dh-as-requested, dW, db)."""
    if use_pallas:
        from .pallas.cross_entropy import fused_ce_backward
        # block dims unset on purpose: the kernel's per-signature
        # heuristic/sweep picks the PAIR (the chunk knob governs the XLA
        # scan's streaming granularity, not the kernel's tiling)
        return fused_ce_backward(h, w.astype(h.dtype), b, labels, lse,
                                 scale, interpret=interpret,
                                 dh_dtype=dh_dtype or h.dtype)
    return _bwd_scan(h, w, b, labels, lse, scale, chunk,
                     dh_dtype=dh_dtype)


def _poison_over_range(rows, labels, v):
    """Labels >= V poison their row to NaN — the full-logits oracle's
    fill-mode ``take_along_axis`` fails just as loudly, so a dataset
    off-by-one can never train on silently under either path."""
    return jnp.where(labels >= v, jnp.float32(jnp.nan), rows)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_rows(h, w, b, labels, chunk, use_pallas, interpret):
    lse, ll = _fwd(h, w, b, labels, chunk, use_pallas, interpret)
    return _poison_over_range(jnp.where(labels >= 0, lse - ll, 0.0),
                              labels, w.shape[1])


def _fused_rows_vjp_fwd(h, w, b, labels, chunk, use_pallas, interpret):
    lse, ll = _fwd(h, w, b, labels, chunk, use_pallas, interpret)
    rows = _poison_over_range(jnp.where(labels >= 0, lse - ll, 0.0),
                              labels, w.shape[1])
    return rows, (h, w, b, labels, lse)


def _fused_rows_vjp_bwd(chunk, use_pallas, interpret, res, g):
    h, w, b, labels, lse = res
    scale = _grad_scale(labels, g, w.shape[1])
    dh, dw, db = _bwd(h, w, b, labels, lse, scale, chunk, use_pallas,
                      interpret)
    # integer primals take float0 cotangents (jax custom_vjp contract)
    dlabels = np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return (dh.astype(h.dtype), dw.astype(w.dtype),
            None if b is None else db.astype(b.dtype), dlabels)


_fused_rows.defvjp(_fused_rows_vjp_fwd, _fused_rows_vjp_bwd)


def _resolve_chunk(n: int, chunk: Optional[int]) -> int:
    if chunk is None:
        chunk = int(_conf("zoo.train.fused_ce_chunk", DEFAULT_CHUNK)
                    or DEFAULT_CHUNK)
    if chunk <= 0:
        raise ValueError(f"fused-CE chunk must be positive, got {chunk}")
    return max(1, min(chunk, max(n, 1)))


def fused_cross_entropy_rows(hidden: jax.Array, w: jax.Array,
                             b: Optional[jax.Array], labels: jax.Array,
                             chunk: Optional[int] = None,
                             use_pallas: Optional[bool] = None,
                             interpret: Optional[bool] = None) -> jax.Array:
    """Per-row cross-entropy of ``softmax(hidden @ w [+ b])`` against int
    ``labels`` — f32 ``(N,)``, rows with label < 0 contribute 0 loss and 0
    gradient; rows with label >= V are NaN (loss and gradient — the
    full-logits objective fails the same way). Differentiable in
    ``hidden``/``w``/``b`` via the tile-streamed custom VJP; the ``(N, V)``
    logits tensor is never materialized."""
    n = hidden.shape[0]
    labels = labels.reshape(-1).astype(jnp.int32)
    if labels.shape[0] != n:
        raise ValueError(f"fused CE: {n} hidden rows vs "
                         f"{labels.shape[0]} labels")
    chunk = _resolve_chunk(n, chunk)
    if use_pallas is None:
        use_pallas = pallas_ce_enabled()
    return _fused_rows(hidden, w, b, labels, chunk, bool(use_pallas),
                       interpret)


def fused_sparse_cross_entropy(y_true, hidden, w, b=None, *,
                               chunk: Optional[int] = None,
                               use_pallas: Optional[bool] = None,
                               interpret: Optional[bool] = None) -> jax.Array:
    """Scalar mean fused CE — the drop-in for
    ``sparse_categorical_crossentropy_from_logits(y, hidden @ w + b)``.
    ``hidden`` may be (..., H); labels broadcast-reshape to the leading
    dims. The mean runs over valid (label >= 0) rows."""
    h2 = hidden.reshape(-1, hidden.shape[-1])
    l2 = jnp.asarray(y_true).reshape(-1).astype(jnp.int32)
    rows = fused_cross_entropy_rows(h2, w, b, l2, chunk=chunk,
                                    use_pallas=use_pallas,
                                    interpret=interpret)
    count = jnp.maximum(jnp.sum((l2 >= 0).astype(jnp.float32)), 1.0)
    return jnp.sum(rows) / count


# ---------------------------------------------------------------------------
# vocab-sharded fused CE (model-parallel head — Megatron-style)
# ---------------------------------------------------------------------------

def vocab_shard_count(mesh=None) -> int:
    """Size of the ``model`` mesh axis — the vocab shard count the
    sharded path splits the head over (1 = no tensor parallelism, the
    unsharded op applies)."""
    from ..parallel import mesh as mesh_lib
    mesh = mesh or mesh_lib.global_mesh()
    return int(mesh.shape[mesh_lib.MODEL_AXIS])


def _localize_labels(labels, off, vs: int):
    """Map global labels onto this rank's column space: the local column
    index when the label lives in ``[off, off + vs)``, else -1 (masked
    rows, other ranks' labels, over-range labels — all of which must
    contribute neither a label logit nor a onehot subtraction HERE;
    over-range poisoning rides the separately-computed grad scale and
    the row-level NaN, both keyed on the GLOBAL label)."""
    loc = labels - off
    mine = (labels >= 0) & (loc >= 0) & (loc < vs)
    return jnp.where(mine, loc, -1)


def _sharded_fwd_local(h, w, b, labels, chunk, use_pallas, interpret):
    """Per-rank forward half: local online logsumexp over this rank's
    vocab slice, then ONE pmax + ONE psum merge the per-rank ``(m, l)``
    carries and the label logit across the ``model`` axis. Runs INSIDE
    shard_map — every array here is the rank-local block; the returned
    (lse, label_logit) rows are identical on every model rank."""
    from ..parallel import mesh as mesh_lib

    vs = w.shape[1]
    rank = jax.lax.axis_index(mesh_lib.MODEL_AXIS)
    lab_loc = _localize_labels(labels, rank * vs, vs)
    if use_pallas:
        from .pallas.cross_entropy import fused_ce_forward
        lse_i, ll_i = fused_ce_forward(h, w.astype(h.dtype), b, lab_loc,
                                       block_n=min(chunk, 256),
                                       interpret=interpret)
        # a finished local lse is the (m, l) pair (lse_i, 1): the merge
        # formula below reduces to logsumexp over the per-rank lse's
        m_i, l_i = lse_i, jnp.ones_like(lse_i)
    else:
        m_i, l_i, ll_i = _fwd_scan_parts(h, w, b, lab_loc, chunk)
    # the pmax/psum pair rides PARALLELISM.md's collective-catalog rows
    # for the `model` axis; adding a collective here needs a row too
    # (ZL025 reconciles both directions).
    m = jax.lax.pmax(m_i, mesh_lib.MODEL_AXIS)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    scaled = jnp.where(jnp.isneginf(m_i), 0.0,
                       l_i * jnp.exp(m_i - m_safe))
    l, ll = jax.lax.psum((scaled, ll_i), mesh_lib.MODEL_AXIS)
    lse = m_safe + jnp.log(jnp.where(l == 0.0, 1.0, l))
    return lse, ll


def _sharded_bwd_local(h, w, b, labels, lse, scale, chunk, use_pallas,
                       interpret):
    """Per-rank backward half: re-form only the local ``(chunk, V/n)``
    tiles (the merged lse re-forms each rank's exact softmax slice).
    dW/db are summed over the row-sharding axes — the data-parallel
    gradient allreduce, landing on the still-sharded ``(H, V/n)`` blocks
    instead of a full ``(H, V)`` tensor — and stay vocab-LOCAL: they
    assemble straight back onto the sharded head params. Only the
    (N, H)-sized dh partials cross the model axis, accumulated in f32
    and rounded once."""
    from ..parallel import mesh as mesh_lib

    vs = w.shape[1]
    rank = jax.lax.axis_index(mesh_lib.MODEL_AXIS)
    lab_loc = _localize_labels(labels, rank * vs, vs)
    dh, dw, db = _bwd(h, w, b, lab_loc, lse, scale, chunk, use_pallas,
                      interpret, dh_dtype=jnp.float32)
    dh = jax.lax.psum(dh, mesh_lib.MODEL_AXIS).astype(h.dtype)
    row_axes = (mesh_lib.DATA_AXIS, mesh_lib.SEQ_AXIS)
    if db is None:
        dw = jax.lax.psum(dw, row_axes)
        return dh, dw.astype(w.dtype), None
    dw, db = jax.lax.psum((dw, db), row_axes)
    return dh, dw.astype(w.dtype), db.astype(b.dtype)


def _sharded_specs(mesh, had_bias: bool):
    """(row_spec, in_specs for (h, w, [b], labels)) — rows shard over
    (data, seq): the flattened (B·T) layout the training step produces;
    the head weight columns over model."""
    from jax.sharding import PartitionSpec as P

    from ..parallel import mesh as mesh_lib

    row_spec = P((mesh_lib.DATA_AXIS, mesh_lib.SEQ_AXIS))
    in_specs = (P((mesh_lib.DATA_AXIS, mesh_lib.SEQ_AXIS), None),
                P(None, mesh_lib.MODEL_AXIS)) \
        + ((P(mesh_lib.MODEL_AXIS),) if had_bias else ()) \
        + (row_spec,)
    return row_spec, in_specs


def _sharded_fwd_global(h, w, b, labels, mesh, chunk, use_pallas,
                        interpret):
    """(lse, label_logit) on GLOBAL arrays via shard_map. Both outputs
    are data-sharded rows, replicated across the model axis (every rank
    holds the merged values)."""
    from ..parallel import compat

    had_bias = b is not None
    row_spec, in_specs = _sharded_specs(mesh, had_bias)
    local = functools.partial(_sharded_fwd_local, chunk=chunk,
                              use_pallas=use_pallas, interpret=interpret)
    if had_bias:
        def run(hh, ww, bb, ll):
            return local(hh, ww, bb, ll)
    else:
        def run(hh, ww, ll):
            return local(hh, ww, None, ll)
    fn = compat.shard_map(run, mesh=mesh, in_specs=in_specs,
                          out_specs=(row_spec, row_spec), check_vma=False)
    args = (h, w) + ((b,) if had_bias else ()) + (labels,)
    return fn(*args)


# the custom VJP sits OUTSIDE the shard_map on purpose: both directions
# are explicit shard_map calls whose bodies own every cross-rank
# reduction (the fwd merge psum, the bwd dh-psum and the dW/db
# data-axis allreduce) — nothing is left to shard_map's transpose
# machinery, whose unmentioned-axis cotangent conventions are exactly
# the kind of version-sensitive detail compat.py exists to avoid
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _sharded_rows(h, w, b, labels, mesh, chunk, v_total, use_pallas,
                  interpret):
    lse, ll = _sharded_fwd_global(h, w, b, labels, mesh, chunk,
                                  use_pallas, interpret)
    return _poison_over_range(jnp.where(labels >= 0, lse - ll, 0.0),
                              labels, v_total)


def _sharded_rows_vjp_fwd(h, w, b, labels, mesh, chunk, v_total,
                          use_pallas, interpret):
    lse, ll = _sharded_fwd_global(h, w, b, labels, mesh, chunk,
                                  use_pallas, interpret)
    rows = _poison_over_range(jnp.where(labels >= 0, lse - ll, 0.0),
                              labels, v_total)
    return rows, (h, w, b, labels, lse)


def _sharded_rows_vjp_bwd(mesh, chunk, v_total, use_pallas, interpret,
                          res, g):
    from ..parallel import compat

    h, w, b, labels, lse = res
    had_bias = b is not None
    # the grad scale keys on the GLOBAL label: masked rows zero, rows
    # whose label lives on another rank keep the softmax pull (no local
    # onehot), over-range rows NaN on EVERY rank — the matmuls spread the
    # poison across the full sharded dW exactly like the unsharded path
    scale = _grad_scale(labels, g, v_total)
    row_spec, in_specs = _sharded_specs(mesh, had_bias)
    from jax.sharding import PartitionSpec as P

    from ..parallel import mesh as mesh_lib
    w_spec = P(None, mesh_lib.MODEL_AXIS)
    b_spec = P(mesh_lib.MODEL_AXIS)
    local = functools.partial(_sharded_bwd_local, chunk=chunk,
                              use_pallas=use_pallas, interpret=interpret)
    if had_bias:
        def run(hh, ww, bb, ll, ls, sc):
            return local(hh, ww, bb, ll, ls, sc)
        out_specs = (in_specs[0], w_spec, b_spec)
    else:
        def run(hh, ww, ll, ls, sc):
            dh, dw, _ = local(hh, ww, None, ll, ls, sc)
            return dh, dw
        out_specs = (in_specs[0], w_spec)
    fn = compat.shard_map(run, mesh=mesh,
                          in_specs=in_specs + (row_spec, row_spec),
                          out_specs=out_specs, check_vma=False)
    args = (h, w) + ((b,) if had_bias else ()) + (labels, lse, scale)
    out = fn(*args)
    dh, dw = out[0], out[1]
    db = out[2] if had_bias else None
    dlabels = np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dh, dw, db, dlabels


_sharded_rows.defvjp(_sharded_rows_vjp_fwd, _sharded_rows_vjp_bwd)


def sharded_fused_cross_entropy_rows(hidden: jax.Array, w: jax.Array,
                                     b: Optional[jax.Array],
                                     labels: jax.Array,
                                     mesh=None,
                                     chunk: Optional[int] = None,
                                     use_pallas: Optional[bool] = None,
                                     interpret: Optional[bool] = None
                                     ) -> jax.Array:
    """Vocab-sharded :func:`fused_cross_entropy_rows`: ``w`` (H, V) is
    split column-wise over the ``model`` mesh axis, rows over
    ``data``/``seq``, and each rank only ever forms ``(chunk, V/n)``
    tiles — the model-parallel LM head whose weight (and weight
    gradient) never fit one chip. Semantics are the unsharded op's:
    label < 0 rows contribute 0 loss/grad, label >= V rows NaN. ``V``
    not divisible by the shard count pads the weight internally (pad
    columns are pinned to a ``-1e30`` bias, exactly inert); row counts
    pad to the row-sharding divisor with masked labels. On a mesh with
    ``model == 1`` this is exactly the unsharded op."""
    from ..parallel import mesh as mesh_lib
    from .pallas.common import round_up

    mesh = mesh or mesh_lib.global_mesh()
    n_model = int(mesh.shape[mesh_lib.MODEL_AXIS])
    if n_model <= 1:
        return fused_cross_entropy_rows(hidden, w, b, labels, chunk=chunk,
                                        use_pallas=use_pallas,
                                        interpret=interpret)
    n = hidden.shape[0]
    v = w.shape[1]
    labels = labels.reshape(-1).astype(jnp.int32)
    if labels.shape[0] != n:
        raise ValueError(f"sharded fused CE: {n} hidden rows vs "
                         f"{labels.shape[0]} labels")
    if use_pallas is None:
        use_pallas = pallas_ce_enabled()

    # rows pad to the row-sharding divisor with label -1 (inert) and are
    # sliced back off below
    row_div = int(mesh.shape[mesh_lib.DATA_AXIS]
                  * mesh.shape[mesh_lib.SEQ_AXIS])
    n_row_pad = (-n) % row_div
    hidden = _pad_rows(hidden, n_row_pad)
    labels = _pad_rows(labels, n_row_pad, value=-1)
    chunk = _resolve_chunk(hidden.shape[0] // row_div, chunk)

    # vocab pads to the shard count; pad columns get zero weights and a
    # _NEG_PAD bias so they are exactly inert in every logsumexp (and
    # their dW/db slots transpose to the sliced-off pad region)
    vp = round_up(v, n_model)
    if vp != v:
        w = jnp.pad(w, ((0, 0), (0, vp - v)))
        bias = b if b is not None else jnp.zeros((v,), jnp.float32)
        b = jnp.pad(bias, (0, vp - v), constant_values=_NEG_PAD)

    rows = _sharded_rows(hidden, w, b, labels, mesh, chunk, v,
                         bool(use_pallas), interpret)
    return rows[:n]


def sharded_fused_sparse_cross_entropy(y_true, hidden, w, b=None, *,
                                       mesh=None,
                                       chunk: Optional[int] = None,
                                       use_pallas: Optional[bool] = None,
                                       interpret: Optional[bool] = None
                                       ) -> jax.Array:
    """Scalar mean vocab-sharded fused CE — the model-parallel drop-in
    for :func:`fused_sparse_cross_entropy` (same reduction: mean over
    valid label >= 0 rows)."""
    h2 = hidden.reshape(-1, hidden.shape[-1])
    l2 = jnp.asarray(y_true).reshape(-1).astype(jnp.int32)
    rows = sharded_fused_cross_entropy_rows(h2, w, b, l2, mesh=mesh,
                                            chunk=chunk,
                                            use_pallas=use_pallas,
                                            interpret=interpret)
    count = jnp.maximum(jnp.sum((l2 >= 0).astype(jnp.float32)), 1.0)
    return jnp.sum(rows) / count
