"""Fused blockwise LM-head cross-entropy — the bandwidth-proportional
replacement for the full-logits ``sparse_categorical_crossentropy_from_logits``
training objective (the oracle it is equivalence-tested against in
``tests/test_fused_ce.py``).

The full-logits objective materializes ``(B·T, V)`` fp32 log-probabilities —
2 GB at the 4k long-context bench shape, 8 GB at 32k — three times over
(forward, the softmax backward, the label pick's scatter). This op streams
the hidden states through the vocab projection in row-chunked tiles instead
(Liu & Abbeel 2023's blockwise-parallel formulation applied to the LM head):

* **forward** — per chunk, form the ``(chunk, V)`` logits tile once, fold
  its ``logsumexp`` and the label's logit online, discard the tile. On TPU
  the tile never even reaches HBM: ``ops/pallas/cross_entropy.py`` computes
  both scalars in one VMEM-resident pass (``zoo.pallas.cross_entropy=auto``
  routing, same convention as flash attention).
* **backward** (custom VJP) — re-form one tile at a time from the saved
  row ``logsumexp``: ``dlogits = (softmax - onehot) * g``, then
  ``dW += hᵀ @ dlogits`` and ``dh = dlogits @ Wᵀ`` — both on the MXU in the
  compute dtype (bf16 operands, f32 accumulation), with the ``dW`` carry
  accumulated in f32 across chunks.

Memory is O(chunk·V) end to end; FLOPs are identical to the full-logits
path, so the win is pure HBM bandwidth. Labels < 0 are masked out of the
loss and every gradient (padded/ignored positions); labels >= V poison
the row to NaN, exactly as loudly as the full-logits objective's
fill-mode gather — a dataset off-by-one can never train on silently.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["fused_cross_entropy_rows", "fused_sparse_cross_entropy",
           "pallas_ce_enabled", "DEFAULT_CHUNK", "AUTO_MIN_VOCAB"]

#: rows per streamed logits tile: 512·V·4 B of transient f32 per tile
#: (16 MB at V=8192) — small enough to live in cache-adjacent HBM, large
#: enough that the (chunk, V) matmuls stay MXU-shaped
DEFAULT_CHUNK = 512

#: ``zoo.train.fused_ce=auto`` engages the fused loss at/above this head
#: width: below it the full-logits tensor is small, XLA's fused softmax is
#: fine, and the scan's sequentialization would only add dispatch overhead
#: (the flash-attention FLASH_AUTO_MIN_SEQ convention, applied to vocab)
AUTO_MIN_VOCAB = 1024


def _conf(key: str, default):
    from ..common.context import get_zoo_context
    try:
        return get_zoo_context().get(key, default)
    except Exception:  # context not constructible (odd device counts)
        return default


def pallas_ce_enabled() -> bool:
    """``zoo.pallas.cross_entropy``: auto (TPU only) | true | false — the
    flash-attention flag convention."""
    from ..common.context import tri_state_conf
    flag = tri_state_conf("zoo.pallas.cross_entropy")
    if flag == "auto":
        return jax.default_backend() == "tpu"
    return flag


def _pad_rows(a: jax.Array, n_pad: int, value=0):
    if n_pad == 0:
        return a
    cfg = [(0, n_pad)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, cfg, constant_values=value)


def _fwd_scan(h, w, b, labels, chunk: int) -> Tuple[jax.Array, jax.Array]:
    """XLA path: per-row (logsumexp, label_logit) via a lax.scan over row
    chunks — the (chunk, V) logits tile is the largest live tensor."""
    n, hidden = h.shape
    n_pad = (-n) % chunk
    hp = _pad_rows(h, n_pad)
    lp = _pad_rows(labels, n_pad, value=-1)
    k = hp.shape[0] // chunk
    wc = w.astype(h.dtype)
    bc = None if b is None else b.astype(h.dtype)

    def one(_, inp):
        hc, lc = inp
        # replicate Dense.call's rounding exactly: f32 MXU accumulation,
        # round to the compute dtype, bias added in the compute dtype —
        # under bf16 policy the oracle's logits carry that rounding, and
        # the silent substitution must not be more precise than the path
        # it replaces (loss-gate comparability across the flag)
        logits = jax.lax.dot_general(hc, wc, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32
                                     ).astype(hc.dtype)
        if bc is not None:
            logits = logits + bc
        logits = logits.astype(jnp.float32)
        m = jnp.max(logits, axis=-1, keepdims=True)
        lse = (m + jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1,
                                   keepdims=True)))[:, 0]
        idx = jnp.clip(lc, 0, logits.shape[-1] - 1)
        ll = jnp.take_along_axis(logits, idx[:, None], axis=-1)[:, 0]
        return None, (lse, jnp.where(lc >= 0, ll, 0.0))

    _, (lse, ll) = jax.lax.scan(
        one, None, (hp.reshape(k, chunk, hidden), lp.reshape(k, chunk)))
    return lse.reshape(-1)[:n], ll.reshape(-1)[:n]


def _fwd(h, w, b, labels, chunk: int, use_pallas: bool,
         interpret: Optional[bool]):
    if use_pallas:
        from .pallas.cross_entropy import fused_ce_forward
        return fused_ce_forward(h, w.astype(h.dtype), b, labels,
                                block_n=min(chunk, 256),
                                interpret=interpret)
    return _fwd_scan(h, w, b, labels, chunk)


def _bwd_scan(h, w, b, labels, lse, g, chunk: int):
    """Tile-at-a-time backward: re-form each (chunk, V) probability tile
    from the saved row logsumexp, fold ``dW``/``db`` into an f32 scan carry,
    emit ``dh`` per chunk. The dW/dh matmuls run in the compute dtype on
    the MXU with f32 accumulation."""
    n, hidden = h.shape
    v = w.shape[1]
    n_pad = (-n) % chunk
    hp = _pad_rows(h, n_pad)
    lp = _pad_rows(labels, n_pad, value=-1)
    # pad the saved logsumexp with +inf: a padded row's logits are the
    # bare bias (h = 0), and exp(bias - 0) overflows to inf for bias >
    # ~88 — inf * the row's zero grad-scale is NaN, and the dW matmul
    # spreads it everywhere. exp(bias - inf) = 0 keeps pad rows inert.
    lsep = _pad_rows(lse, n_pad, value=jnp.inf)
    gp = _pad_rows(g.astype(jnp.float32), n_pad)
    k = hp.shape[0] // chunk
    wc = w.astype(h.dtype)
    bc = None if b is None else b.astype(h.dtype)

    def one(carry, inp):
        dw, db = carry
        hc, lc, lsec, gc = inp
        # tile re-formation carries the SAME compute-dtype rounding as
        # the forward (see _fwd_scan) so p is re-formed bit-for-bit
        logits = jax.lax.dot_general(hc, wc, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32
                                     ).astype(hc.dtype)
        if bc is not None:
            logits = logits + bc
        logits = logits.astype(jnp.float32)
        p = jnp.exp(logits - lsec[:, None])
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (chunk, v), 1)
                  == lc[:, None])
        scale = jnp.where(lc >= 0, gc, 0.0)       # masked rows: zero grad
        scale = jnp.where(lc >= v, jnp.nan, scale)  # over-range: NaN out
        dl = (p - onehot) * scale[:, None]
        dlc = dl.astype(h.dtype)
        dh = jax.lax.dot_general(dlc, wc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32
                                 ).astype(h.dtype)
        dw = dw + jax.lax.dot_general(hc, dlc, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        if db is not None:
            db = db + jnp.sum(dl, axis=0)
        return (dw, db), dh

    dw0 = jnp.zeros((hidden, v), jnp.float32)
    db0 = None if b is None else jnp.zeros((v,), jnp.float32)
    (dw, db), dh = jax.lax.scan(
        one, (dw0, db0),
        (hp.reshape(k, chunk, hidden), lp.reshape(k, chunk),
         lsep.reshape(k, chunk), gp.reshape(k, chunk)))
    dh = dh.reshape(-1, hidden)[:n]
    return (dh, dw.astype(w.dtype),
            None if b is None else db.astype(b.dtype))


def _poison_over_range(rows, labels, v):
    """Labels >= V poison their row to NaN — the full-logits oracle's
    fill-mode ``take_along_axis`` fails just as loudly, so a dataset
    off-by-one can never train on silently under either path."""
    return jnp.where(labels >= v, jnp.float32(jnp.nan), rows)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_rows(h, w, b, labels, chunk, use_pallas, interpret):
    lse, ll = _fwd(h, w, b, labels, chunk, use_pallas, interpret)
    return _poison_over_range(jnp.where(labels >= 0, lse - ll, 0.0),
                              labels, w.shape[1])


def _fused_rows_vjp_fwd(h, w, b, labels, chunk, use_pallas, interpret):
    lse, ll = _fwd(h, w, b, labels, chunk, use_pallas, interpret)
    rows = _poison_over_range(jnp.where(labels >= 0, lse - ll, 0.0),
                              labels, w.shape[1])
    return rows, (h, w, b, labels, lse)


def _fused_rows_vjp_bwd(chunk, use_pallas, interpret, res, g):
    h, w, b, labels, lse = res
    dh, dw, db = _bwd_scan(h, w, b, labels, lse, g, chunk)
    # integer primals take float0 cotangents (jax custom_vjp contract)
    dlabels = np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dh, dw, db, dlabels


_fused_rows.defvjp(_fused_rows_vjp_fwd, _fused_rows_vjp_bwd)


def _resolve_chunk(n: int, chunk: Optional[int]) -> int:
    if chunk is None:
        chunk = int(_conf("zoo.train.fused_ce_chunk", DEFAULT_CHUNK)
                    or DEFAULT_CHUNK)
    if chunk <= 0:
        raise ValueError(f"fused-CE chunk must be positive, got {chunk}")
    return max(1, min(chunk, max(n, 1)))


def fused_cross_entropy_rows(hidden: jax.Array, w: jax.Array,
                             b: Optional[jax.Array], labels: jax.Array,
                             chunk: Optional[int] = None,
                             use_pallas: Optional[bool] = None,
                             interpret: Optional[bool] = None) -> jax.Array:
    """Per-row cross-entropy of ``softmax(hidden @ w [+ b])`` against int
    ``labels`` — f32 ``(N,)``, rows with label < 0 contribute 0 loss and 0
    gradient; rows with label >= V are NaN (loss and gradient — the
    full-logits objective fails the same way). Differentiable in
    ``hidden``/``w``/``b`` via the tile-streamed custom VJP; the ``(N, V)``
    logits tensor is never materialized."""
    n = hidden.shape[0]
    labels = labels.reshape(-1).astype(jnp.int32)
    if labels.shape[0] != n:
        raise ValueError(f"fused CE: {n} hidden rows vs "
                         f"{labels.shape[0]} labels")
    chunk = _resolve_chunk(n, chunk)
    if use_pallas is None:
        use_pallas = pallas_ce_enabled()
    return _fused_rows(hidden, w, b, labels, chunk, bool(use_pallas),
                       interpret)


def fused_sparse_cross_entropy(y_true, hidden, w, b=None, *,
                               chunk: Optional[int] = None,
                               use_pallas: Optional[bool] = None,
                               interpret: Optional[bool] = None) -> jax.Array:
    """Scalar mean fused CE — the drop-in for
    ``sparse_categorical_crossentropy_from_logits(y, hidden @ w + b)``.
    ``hidden`` may be (..., H); labels broadcast-reshape to the leading
    dims. The mean runs over valid (label >= 0) rows."""
    h2 = hidden.reshape(-1, hidden.shape[-1])
    l2 = jnp.asarray(y_true).reshape(-1).astype(jnp.int32)
    rows = fused_cross_entropy_rows(h2, w, b, l2, chunk=chunk,
                                    use_pallas=use_pallas,
                                    interpret=interpret)
    count = jnp.maximum(jnp.sum((l2 >= 0).astype(jnp.float32)), 1.0)
    return jnp.sum(rows) / count
