"""Attention core ops — the compute kernel behind TransformerLayer/BERT
(reference: ``pipeline/api/keras/layers/TransformerLayer.scala:56``,
``BERT.scala:66``, pyzoo ``layers/self_attention.py``).

Kept separate from the layer classes so the same interface can be served by
(a) this fused XLA softmax-attention, (b) a Pallas flash-attention kernel, or
(c) ring attention over the ``seq`` mesh axis (``parallel/ring_attention``) —
swap happens at the layer level without touching model code.

Logits/softmax run in float32 regardless of compute dtype (bfloat16 QKV is
fine into the MXU; accumulating attention weights in bf16 is not).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          mask: Optional[jax.Array] = None,
                          causal: bool = False,
                          dropout_rate: float = 0.0,
                          dropout_rng: Optional[jax.Array] = None,
                          ) -> jax.Array:
    """Multi-head scaled dot-product attention.

    q, k, v: (B, n_head, T, d_head); ``mask``: broadcastable to
    (B, n_head, Tq, Tk), 1.0 = attend / 0.0 = hide. Returns (B, n_head, T, d_head).
    """
    d_head = q.shape[-1]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(d_head, jnp.float32))
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((tq, tk), jnp.bool_), k=tk - tq)
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    if mask is not None:
        logits = logits + (1.0 - mask.astype(jnp.float32)) * NEG_INF
    weights = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(v.dtype)


def split_heads(x: jax.Array, n_head: int) -> jax.Array:
    """(B, T, H) → (B, n_head, T, H/n_head)."""
    b, t, h = x.shape
    return x.reshape(b, t, n_head, h // n_head).transpose(0, 2, 1, 3)


def merge_heads(x: jax.Array) -> jax.Array:
    """(B, n_head, T, d) → (B, T, n_head*d)."""
    b, nh, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, nh * d)
