"""Out-of-core sharded embedding engine — row-partitioned tables,
dedup'd gathers, sparse scatter-add gradients, and a host-RAM cold tier.

The DLRM-style big-embedding problem (Naumov et al.): recommender tables
outgrow one chip long before the dense trunk does. This module solves it
with the Megatron-style idiom PR 14 proved on the vocab-sharded fused CE
— shard the parameter over the ``model`` mesh axis and own every
collective explicitly — plus two memory-motion optimizations and a host
tier:

* **Row partitioning** (:func:`sharded_embedding_lookup`): the
  ``(V, D)`` table splits row-wise ``P(model, None)`` under
  ``shard_map``; each rank gathers only the rows it owns and ONE
  ``psum`` over the ``model`` axis merges them (every non-owner
  contributes exact zeros, so the merge is bit-exact, not an
  accumulation). The custom VJP sits OUTSIDE the shard_map exactly like
  ``fused_cross_entropy._sharded_rows`` — both directions are explicit
  shard_map calls owning every cross-rank reduction; nothing rides
  shard_map's transpose conventions.
* **Dedup'd unique-lookup gathers**: ids are deduplicated per step with
  a fixed-``size`` ``jnp.unique`` (:func:`dedup_capacity` buckets the
  capacity to powers of two so compiled shapes stay stable — the PR-13
  retrace guard), so each *distinct* row crosses the interconnect once;
  the ``(capacity, D)`` unique-row block replaces the
  ``(batch·pooling, D)`` naive gather whenever the table (or the bucket)
  is smaller than the id stream.
* **Sparse scatter-add gradients**: the backward never forms a dense
  ``(V, D)`` cotangent. The row cotangents scatter-add onto the
  ``(capacity, D)`` unique block (repeated ids collide additively —
  f32 accumulation per the ZL021 discipline), then rank-locally onto the
  owned ``(V/n, D)`` slice via the dump-row trick, and the only
  collective is the data/seq-axis allreduce of the still-sharded blocks
  — reduced BEFORE the shard_map returns, so ``out_specs`` claims
  exactly what the body produced (ZL026).
* **Host-RAM cold tier** (:class:`OutOfCoreEmbeddingCache`): the table's
  cold tail lives in pinned host numpy (the TPU-native answer to the
  reference platform's PMEM FeatureSet tier), a device-resident hot set
  serves the head, and an async prefetch thread (the
  ``feature_set._ThreadedIterator`` machinery) stages the NEXT batch's
  missing rows while the current step runs. Hit/miss/prefetch/dedup
  counters export through the metrics registry and a
  :class:`~..observability.goodput.GoodputLedger` charges ``data_wait``
  whenever a step actually blocks on a fetch (the
  ``prefetch_to_device`` seam discipline).

Out-of-range ids clamp into ``[0, V)`` — ``jnp.take``'s clip mode, which
is also what the ``Embedding`` layer's gather compiles to.

The optional Pallas expand-gather kernel (``ops/pallas/embedding.py``,
``zoo.pallas.embed_gather``) accelerates the unique-block → row-stream
expansion on the MXU; it is priced through the shared
``ops/pallas/common.py`` VMEM estimator like every other kernel.
"""

from __future__ import annotations

import collections
import functools
import threading
from typing import Iterable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .pallas.common import round_up

__all__ = ["sharded_embedding_lookup", "dedup_embedding_lookup",
           "model_row_shard_count", "dedup_capacity", "oocore_gather",
           "EmbeddingFetchPlan", "OutOfCoreEmbeddingCache"]


def _conf(key: str, default):
    """Config read through the zoo context when one is constructible,
    else the default (keeps the op usable standalone)."""
    try:
        from ..common.context import get_zoo_context
        return get_zoo_context().get(key, default)
    except Exception:  # zoolint: disable=ZL007 no context constructible
        return default


def model_row_shard_count(mesh=None) -> int:
    """Size of the ``model`` mesh axis — the row shard count the sharded
    lookup splits the table over (1 = no tensor parallelism, the
    unsharded dedup'd lookup applies)."""
    from ..parallel import mesh as mesh_lib
    mesh = mesh or mesh_lib.global_mesh()
    return int(mesh.shape[mesh_lib.MODEL_AXIS])


def dedup_capacity(n_ids: int, vocab: int) -> int:
    """The static unique-id capacity for a ``(n_ids,)`` id block over a
    ``vocab``-row table: the exact unique count is data-dependent, so
    the compiled shape uses the safe ceiling ``min(n_ids, vocab)``
    bucketed up to a power of two — nearby problem sizes share one
    compiled shape (the PR-13 retrace guard) and ``jnp.unique`` can
    never truncate. Capped at the (sublane-rounded) id count: a bucket
    larger than the id stream would gather MORE rows than no dedup at
    all."""
    need = max(min(int(n_ids), int(vocab)), 1)
    cap = 1 << (need - 1).bit_length()
    return max(min(cap, round_up(int(n_ids), 8)), 8)


def _unique_ids(ids, capacity: int, fill: int):
    """Fixed-shape dedup: ``(uniq, inv)`` with ``uniq`` padded to
    ``capacity`` with ``fill`` (an id no shard owns — fill slots are
    never referenced by ``inv`` and gather exact zeros)."""
    uniq, inv = jnp.unique(ids, size=capacity, fill_value=fill,
                           return_inverse=True)
    return uniq, inv.reshape(-1)


def _expand_rows(rows, inv, use_pallas: bool, interpret: Optional[bool]):
    """``rows[inv]`` — the unique-block → row-stream expansion, routed
    through the Pallas one-hot MXU gather when enabled."""
    if use_pallas:
        from .pallas.embedding import embed_expand
        return embed_expand(rows, inv, interpret=interpret)
    return jnp.take(rows, inv, axis=0)


# ---------------------------------------------------------------------------
# unsharded dedup'd lookup (model == 1), sparse-grad custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _dedup_take(table, ids, capacity, use_pallas, interpret):
    out, _ = _dedup_take_fwd(table, ids, capacity, use_pallas, interpret)
    return out


def _dedup_take_fwd(table, ids, capacity, use_pallas, interpret):
    v = table.shape[0]
    uniq, inv = _unique_ids(ids, capacity, fill=v)
    rows = jnp.take(table, jnp.clip(uniq, 0, v - 1), axis=0)
    out = _expand_rows(rows, inv, use_pallas, interpret)
    return out, (uniq, inv, jnp.zeros((), table.dtype), v)


def _dedup_take_bwd(capacity, use_pallas, interpret, res, g):
    uniq, inv, dtype_token, v = res
    d = g.shape[-1]
    # dedup'd scatter-add: repeated ids collide additively on the unique
    # block first (f32 accumulation), then one scatter onto the table —
    # cost proportional to touched rows, never a dense (V, D) cotangent
    d_rows = jnp.zeros((capacity, d), jnp.float32).at[inv].add(
        g.astype(jnp.float32))
    # dump-row trick: fill slots (uniq == v) land on the sliced-off row
    safe = jnp.clip(uniq, 0, v)
    dw = jnp.zeros((v + 1, d), jnp.float32).at[safe].add(d_rows)[:v]
    dids = np.zeros(inv.shape, dtype=jax.dtypes.float0)
    return dw.astype(dtype_token.dtype), dids


_dedup_take.defvjp(_dedup_take_fwd, _dedup_take_bwd)


def dedup_embedding_lookup(table, ids, capacity: Optional[int] = None,
                           use_pallas: Optional[bool] = None,
                           interpret: Optional[bool] = None):
    """Single-shard dedup'd gather with the sparse scatter-add VJP —
    numerically identical to ``jnp.take(table, ids, axis=0)`` (f32
    bit-exact; grads are the same scatter-adds the dense transpose
    performs, accumulated in f32)."""
    v, d = table.shape
    orig = ids.shape
    flat = jnp.clip(ids.reshape(-1).astype(jnp.int32), 0, v - 1)
    if capacity is None:
        capacity = dedup_capacity(flat.shape[0], v)
    if use_pallas is None:
        from .pallas.embedding import pallas_embed_gather_enabled
        use_pallas = pallas_embed_gather_enabled()
    out = _dedup_take(table, flat, int(capacity), bool(use_pallas),
                      interpret)
    return out.reshape(*orig, d)


# ---------------------------------------------------------------------------
# row-sharded lookup (model > 1) — explicit-collective custom VJP
# ---------------------------------------------------------------------------

def _row_specs(mesh):
    """(id/row spec, table spec): ids/rows shard over (data, seq) — the
    flattened (B·T) layout the training step produces — and the table
    rows over ``model``."""
    from jax.sharding import PartitionSpec as P

    from ..parallel import mesh as mesh_lib
    row_spec = P((mesh_lib.DATA_AXIS, mesh_lib.SEQ_AXIS))
    table_spec = P(mesh_lib.MODEL_AXIS, None)
    return row_spec, table_spec


def _sharded_fwd_local(table, ids, capacity, n_model, use_pallas,
                       interpret):
    """Per-rank forward half. ``table`` is the rank-local ``(V/n, D)``
    row block, ``ids`` the rank-local id slice (replicated over
    ``model``). Dedup → masked local gather of owned rows → ONE psum
    over ``model`` (each distinct row crosses the interconnect once;
    non-owners contribute exact zeros) → expand back to the id stream.
    The psum/axis_index pair rides PARALLELISM.md's collective-catalog
    rows for the ``model`` axis (ZL025 reconciles both directions)."""
    from ..parallel import mesh as mesh_lib

    vs = table.shape[0]
    rank = jax.lax.axis_index(mesh_lib.MODEL_AXIS)
    uniq, inv = _unique_ids(ids, capacity, fill=vs * n_model)
    loc = uniq - rank * vs
    own = (loc >= 0) & (loc < vs)
    rows_local = jnp.where(
        own[:, None],
        jnp.take(table, jnp.clip(loc, 0, vs - 1), axis=0
                 ).astype(jnp.float32),
        0.0)
    rows = jax.lax.psum(rows_local, mesh_lib.MODEL_AXIS)
    out = _expand_rows(rows.astype(table.dtype), inv, use_pallas,
                       interpret)
    return out, uniq, inv


def _sharded_bwd_local(uniq, inv, g, vs, dtype):
    """Per-rank backward half: the sparse ``(unique_ids, partial_dW)``
    scatter-add. Row cotangents collide additively onto the unique block
    in f32, non-owned rows route to the dump row, and the partial sums
    over the row-sharding axes are psum'd HERE — before the shard_map
    returns — so the ``P(model, None)`` out_specs claim is exact
    (ZL026: no partial_sum escapes the manual region)."""
    from ..parallel import mesh as mesh_lib

    capacity = uniq.shape[0]
    d = g.shape[-1]
    rank = jax.lax.axis_index(mesh_lib.MODEL_AXIS)
    d_rows = jnp.zeros((capacity, d), jnp.float32).at[inv].add(
        g.astype(jnp.float32))
    loc = uniq - rank * vs
    own = (loc >= 0) & (loc < vs)
    safe = jnp.where(own, jnp.clip(loc, 0, vs - 1), vs)
    dw = jnp.zeros((vs + 1, d), jnp.float32).at[safe].add(d_rows)[:vs]
    dw = jax.lax.psum(dw, (mesh_lib.DATA_AXIS, mesh_lib.SEQ_AXIS))
    return dw.astype(dtype)


# the custom VJP sits OUTSIDE the shard_map on purpose (the
# fused_cross_entropy._sharded_rows structure): both directions are
# explicit shard_map calls whose bodies own every cross-rank reduction —
# nothing is left to shard_map's transpose machinery
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _sharded_lookup(table, ids, mesh, capacity, vp, use_pallas,
                    interpret):
    out, _ = _sharded_lookup_fwd(table, ids, mesh, capacity, vp,
                                 use_pallas, interpret)
    return out


def _sharded_lookup_fwd(table, ids, mesh, capacity, vp, use_pallas,
                        interpret):
    from jax.sharding import PartitionSpec as P

    from ..parallel import compat
    row_spec, table_spec = _row_specs(mesh)
    n_model = model_row_shard_count(mesh)

    def run(tt, ii):
        return _sharded_fwd_local(tt, ii, capacity, n_model, use_pallas,
                                  interpret)

    fn = compat.shard_map(run, mesh=mesh,
                          in_specs=(table_spec, row_spec),
                          out_specs=(P(row_spec[0], None), row_spec,
                                     row_spec),
                          check_vma=False)
    out, uniq, inv = fn(table, ids)
    return out, (uniq, inv, jnp.zeros((), table.dtype))


def _sharded_lookup_bwd(mesh, capacity, vp, use_pallas, interpret, res,
                        g):
    from jax.sharding import PartitionSpec as P

    from ..parallel import compat
    uniq, inv, dtype_token = res
    row_spec, table_spec = _row_specs(mesh)
    n_model = model_row_shard_count(mesh)
    vs = vp // n_model

    def run(uu, ii, gg):
        return _sharded_bwd_local(uu, ii, gg, vs, dtype_token.dtype)

    fn = compat.shard_map(run, mesh=mesh,
                          in_specs=(row_spec, row_spec,
                                    P(row_spec[0], None)),
                          out_specs=table_spec, check_vma=False)
    dw = fn(uniq, inv, g)
    dids = np.zeros(inv.shape, dtype=jax.dtypes.float0)
    return dw, dids


_sharded_lookup.defvjp(_sharded_lookup_fwd, _sharded_lookup_bwd)


def sharded_embedding_lookup(table, ids, mesh=None, *,
                             capacity: Optional[int] = None,
                             dedup: Optional[bool] = None,
                             use_pallas: Optional[bool] = None,
                             interpret: Optional[bool] = None):
    """Row-sharded embedding gather: ``table`` ``(V, D)`` splits row-wise
    over the ``model`` mesh axis, ids over ``data``/``seq``; semantics
    are ``jnp.take(table, ids, axis=0)`` with out-of-range ids clamped.
    ``V`` not divisible by the shard count pads the table internally
    (pad rows are never gathered and their grad slots transpose to the
    sliced-off region); id counts pad to the row-sharding divisor with
    id 0 (inert: outputs sliced off, cotangents zero). On a mesh with
    ``model == 1`` this is the unsharded dedup'd lookup — same sparse
    scatter-add VJP, no collectives.

    ``dedup=False`` keeps the same code path but sizes the unique
    capacity at the full id count (``zoo.embed.dedup`` default on)."""
    from ..parallel import mesh as mesh_lib

    mesh = mesh or mesh_lib.global_mesh()
    n_model = model_row_shard_count(mesh)
    v, d = table.shape
    orig = ids.shape
    flat = jnp.clip(ids.reshape(-1).astype(jnp.int32), 0, v - 1)
    n = flat.shape[0]
    if dedup is None:
        dedup = bool(_conf("zoo.embed.dedup", True))
    if use_pallas is None:
        from .pallas.embedding import pallas_embed_gather_enabled
        use_pallas = pallas_embed_gather_enabled()

    if n_model <= 1:
        cap = capacity or (dedup_capacity(n, v) if dedup
                           else round_up(n, 8))
        out = _dedup_take(table, flat, int(cap), bool(use_pallas),
                          interpret)
        return out.reshape(*orig, d)

    vp = round_up(v, n_model)
    if vp != v:
        table = jnp.pad(table, ((0, vp - v), (0, 0)))
    row_div = int(mesh.shape[mesh_lib.DATA_AXIS]
                  * mesh.shape[mesh_lib.SEQ_AXIS])
    n_pad = (-n) % row_div
    if n_pad:
        flat = jnp.pad(flat, (0, n_pad))
    n_loc = flat.shape[0] // row_div
    cap = capacity or (dedup_capacity(n_loc, vp) if dedup
                       else round_up(n_loc, 8))
    if cap < min(n_loc, vp):
        raise ValueError(
            f"dedup capacity {cap} cannot hold the worst-case "
            f"{min(n_loc, vp)} unique ids per shard — jnp.unique would "
            f"silently truncate; raise capacity or leave it unset")
    out = _sharded_lookup(table, flat, mesh, int(cap), vp,
                          bool(use_pallas), interpret)
    return out[:n].reshape(*orig, d)


# ---------------------------------------------------------------------------
# host-RAM cold tier
# ---------------------------------------------------------------------------

def oocore_gather(hot, cold, remap):
    """The jit-stable two-tier gather: ``remap`` indexes the virtual
    table ``[hot; cold]`` — ``hot`` is the device-resident head,
    ``cold`` the staged ``(capacity, D)`` rows the host plan uploaded.
    Differentiable in both tiers (the standard take transpose);
    :meth:`EmbeddingFetchPlan.scatter_grad` reassembles a dense table
    gradient from the tier cotangents."""
    hr = hot.shape[0]
    cold_part = jnp.take(cold, jnp.clip(remap - hr, 0, cold.shape[0] - 1),
                         axis=0)
    if hr == 0:
        return cold_part
    hot_part = jnp.take(hot, jnp.clip(remap, 0, hr - 1), axis=0)
    return jnp.where((remap < hr)[..., None], hot_part, cold_part)


class EmbeddingFetchPlan:
    """One batch's resolved host plan: the compiled-shape ``cold`` row
    block, the ``remap`` into the virtual ``[hot; cold]`` table, and the
    bookkeeping to reassemble dense gradients."""

    __slots__ = ("ids", "remap", "cold", "cold_ids", "hot_rows",
                 "table_shape")

    def __init__(self, ids, remap, cold, cold_ids, hot_rows, table_shape):
        self.ids = ids
        self.remap = remap
        self.cold = cold
        self.cold_ids = cold_ids
        self.hot_rows = int(hot_rows)
        self.table_shape = tuple(table_shape)

    def scatter_grad(self, d_hot, d_cold) -> np.ndarray:
        """Dense ``(V, D)`` f32 gradient from the tier cotangents of
        :func:`oocore_gather` — the host-side scatter-add the optimizer
        (or a parity test) applies to the master table."""
        v, d = self.table_shape
        dw = np.zeros((v, d), np.float32)
        if self.hot_rows:
            dw[:self.hot_rows] += np.asarray(d_hot, np.float32)
        dc = np.asarray(d_cold, np.float32)
        np.add.at(dw, self.cold_ids, dc[:self.cold_ids.size])
        return dw


class OutOfCoreEmbeddingCache:
    """Two-tier table: a device-resident hot head (sized by the
    ``zoo.embed.hot_rows_budget_mb`` device budget) and a pinned
    host-numpy cold tail. :meth:`plan` resolves one batch's missing rows
    (dedup'd — each distinct cold row is fetched and uploaded once);
    :meth:`stream` overlaps that resolution with device compute on a
    background prefetch thread, degrading to a synchronous fetch when a
    prefetch fails (``embed.prefetch`` fault site) — a step can stall,
    never wedge. Row fetches from host RAM run through the
    ``embed.host_fetch`` fault site; a ledger charges blocked time to
    ``data_wait``."""

    def __init__(self, table, *, hot_rows: Optional[int] = None,
                 prefetch_depth: Optional[int] = None,
                 staged_rows: int = 8192, registry=None, ledger=None):
        from ..observability import default_registry
        self._table = np.ascontiguousarray(np.asarray(table, np.float32))
        v, d = self._table.shape
        if hot_rows is None:
            budget_mb = float(_conf("zoo.embed.hot_rows_budget_mb", 64))
            hot_rows = int((budget_mb * 1024 * 1024) // max(d * 4, 1))
        self.hot_rows = max(0, min(int(hot_rows), v))
        self._hot = jnp.asarray(self._table[:self.hot_rows])
        # the cold tier stays host-resident, contiguous for fast slicing
        self._cold = np.ascontiguousarray(self._table[self.hot_rows:])
        if prefetch_depth is None:
            prefetch_depth = int(_conf("zoo.embed.prefetch_depth", 2))
        self.prefetch_depth = max(1, int(prefetch_depth))
        self._staged_max = max(int(staged_rows), 1)
        self._staged: "collections.OrderedDict[int, np.ndarray]" = \
            collections.OrderedDict()
        # one jitted gather shared by every rows() call — the pow2 cold
        # bucket keeps the compiled shapes stable across batches
        self._gather = jax.jit(oocore_gather)
        self._lock = threading.Lock()
        self._ledger = ledger
        reg = registry if registry is not None else default_registry()
        self._c_hits = reg.counter(
            "zoo_embed_cache_hits_total",
            "unique ids served without a host fetch (device-resident hot "
            "tier or already-staged cold rows)")
        self._c_misses = reg.counter(
            "zoo_embed_cache_misses_total",
            "unique cold-tier ids that required a host-RAM row fetch")
        self._c_prefetch = reg.counter(
            "zoo_embed_prefetch_rows_total",
            "cold rows staged ahead of the consuming step by the "
            "prefetch thread")
        self._c_dedup = reg.counter(
            "zoo_embed_dedup_saved_rows_total",
            "gathered rows saved by per-batch id dedup (ids seen minus "
            "unique ids)")
        self._c_prefetch_err = reg.counter(
            "zoo_embed_prefetch_errors_total",
            "prefetch attempts that failed and degraded to a "
            "synchronous fetch on the consumer thread")
        self._g_ids = reg.counter(
            "zoo_embed_ids_total",
            "ids resolved through the cache (dedup ratio denominator)")
        reg.gauge("zoo_embed_prefetch_depth",
                  "plan buffer depth of the cold-tier prefetch thread"
                  ).set(self.prefetch_depth)
        reg.gauge("zoo_embed_hot_rows",
                  "rows of the embedding table resident on device (the "
                  "hot tier; the rest live in host RAM)"
                  ).set(self.hot_rows)

    # -- table views ---------------------------------------------------------
    @property
    def hot(self):
        """The device-resident hot tier (differentiable operand of
        :func:`oocore_gather`)."""
        return self._hot

    @property
    def table(self) -> np.ndarray:
        """The host master copy (tests reconcile against it)."""
        return self._table

    # -- host planning -------------------------------------------------------
    def plan(self, ids) -> EmbeddingFetchPlan:
        """Resolve one batch: dedup the ids, serve hot/staged rows from
        cache, fetch the missing cold rows from host RAM
        (``embed.host_fetch``), and build the compiled-shape ``(cold,
        remap)`` pair :func:`oocore_gather` consumes."""
        v, d = self._table.shape
        ids_np = np.asarray(ids)
        flat = np.clip(ids_np.reshape(-1).astype(np.int64), 0,
                       max(v - 1, 0))
        uniq, inv = np.unique(flat, return_inverse=True)
        self._g_ids.inc(int(flat.size))
        self._c_dedup.inc(int(flat.size - uniq.size))
        hot_mask = uniq < self.hot_rows
        self._c_hits.inc(int(hot_mask.sum()))
        cold_ids = uniq[~hot_mask]
        rows = self._cold_rows(cold_ids)
        cap = dedup_capacity(max(int(cold_ids.size), 1), max(v, 1))
        cold = np.zeros((cap, d), np.float32)
        cold[:cold_ids.size] = rows
        slot = np.empty(uniq.size, np.int32)
        slot[hot_mask] = uniq[hot_mask].astype(np.int32)
        slot[~hot_mask] = self.hot_rows + np.arange(cold_ids.size,
                                                    dtype=np.int32)
        remap = slot[inv].astype(np.int32).reshape(ids_np.shape)
        return EmbeddingFetchPlan(ids_np, remap, cold, cold_ids,
                                  self.hot_rows, (v, d))

    def _cold_rows(self, cold_ids: np.ndarray) -> np.ndarray:
        """Rows for the (unique) cold ids: staged-LRU hits first, one
        batched host fetch for the misses."""
        d = self._table.shape[1]
        out = np.empty((cold_ids.size, d), np.float32)
        miss_pos, miss_ids = [], []
        with self._lock:
            for j, i in enumerate(cold_ids.tolist()):
                row = self._staged.get(i)
                if row is not None:
                    self._staged.move_to_end(i)
                    out[j] = row
                else:
                    miss_pos.append(j)
                    miss_ids.append(i)
        self._c_hits.inc(cold_ids.size - len(miss_ids))
        if miss_ids:
            self._c_misses.inc(len(miss_ids))
            fetched = self._host_fetch(np.asarray(miss_ids, np.int64))
            out[np.asarray(miss_pos)] = fetched
            with self._lock:
                for i, row in zip(miss_ids, fetched):
                    self._staged[i] = row
                while len(self._staged) > self._staged_max:
                    self._staged.popitem(last=False)
        return out

    def _host_fetch(self, miss_ids: np.ndarray) -> np.ndarray:
        from ..common import faults
        faults.inject("embed.host_fetch")
        return self._cold[miss_ids - self.hot_rows]

    # -- device lookup -------------------------------------------------------
    def rows(self, plan: EmbeddingFetchPlan):
        """Device rows for a planned batch: ``(ids.shape..., D)`` — one
        staged-block + remap upload, then the jitted two-tier gather."""
        return self._gather(self._hot, jnp.asarray(plan.cold),
                            jnp.asarray(plan.remap))

    # -- pipelined streaming -------------------------------------------------
    def stream(self, batches: Iterable, *, ledger=None
               ) -> Iterator[Tuple[np.ndarray, EmbeddingFetchPlan]]:
        """Yield ``(ids, plan)`` with upcoming plans staged by a
        background thread (``feature_set._ThreadedIterator`` — the same
        machinery ``prefetch_to_device`` rides). A prefetch failure
        (``embed.prefetch``) is counted and the plan is rebuilt
        synchronously on the consumer thread; the step never wedges.
        Ledger attribution follows the ``prefetch_to_device`` seam
        discipline: blocked pulls (and degraded synchronous fetches)
        are ``data_wait``, the consumer's compute is ``device_step``."""
        from ..common import faults
        from ..feature.feature_set import _ThreadedIterator
        ledger = ledger if ledger is not None else self._ledger

        def note(category):
            if ledger is not None:
                ledger.note(category)

        def staged():
            for ids in batches:
                try:
                    faults.inject("embed.prefetch")
                    p = self.plan(ids)
                    self._c_prefetch.inc(int(p.cold_ids.size))
                    yield ids, p
                # degrade, never wedge: the consumer refetches in line
                except Exception:  # zoolint: disable=ZL007
                    self._c_prefetch_err.inc()
                    yield ids, None
        src = _ThreadedIterator(staged(),
                                buffer_size=self.prefetch_depth)
        note("idle")
        try:
            for ids, p in src:
                if p is None:
                    p = self.plan(ids)    # synchronous degraded fetch
                note("data_wait")
                yield ids, p
                note("device_step")
        finally:
            note("data_wait")
            src.close()
