"""L0 — Pallas TPU kernels (SURVEY §2.3). The compute-critical ops the XLA
autofusion can't produce: blockwise flash attention (O(block^2) VMEM instead
of an HBM (T,T) score matrix) and fused int8 weight-only dequant-matmul.
Kernels auto-select interpreter mode off-TPU so the same code paths test on
the CPU mesh."""

from .cross_entropy import fused_ce_forward  # noqa: F401
from .embedding import embed_expand  # noqa: F401
from .flash_attention import flash_attention  # noqa: F401
from .quantized import int8_matmul  # noqa: F401
