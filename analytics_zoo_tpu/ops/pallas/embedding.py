"""Pallas TPU expand-gather for the sharded embedding engine.

The dedup'd lookup (``ops/sharded_embedding.py``) reduces every batch to
a compact ``(capacity, D)`` unique-row block plus an inverse-index
stream; the last hop — expanding the block back to the ``(N, D)`` row
stream — is a gather XLA lowers to per-row dynamic slices. This kernel
does it as a **one-hot MXU contraction** instead: each ``(block_n, D)``
output tile is ``onehot(inv) @ rows``, a 0/1 matmul that selects exactly
one row per output position (products exact, a single nonzero term per
sum), so the result is bit-identical to ``rows[inv]`` in any dtype while
the memory traffic is a dense, tile-aligned streaming read of the
unique block.

Flag: ``zoo.pallas.embed_gather`` (auto = TPU only). Block sizes come
from the shared VMEM pricing formula
(``common.embed_gather_vmem_bytes``) with the flash-attention shrink
discipline; when even the ``SUBLANES`` floor cannot fit — a huge unique
block — the caller's ``jnp.take`` path is used instead.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .common import LANES as _LANES
from .common import SUBLANES as _SUBLANES
from .common import (embed_gather_vmem_bytes, pad_to_multiple, round_up,
                     vmem_usable_bytes)

__all__ = ["embed_expand", "pallas_embed_gather_enabled"]


def pallas_embed_gather_enabled() -> bool:
    """``zoo.pallas.embed_gather``: auto (TPU only) | true | false — the
    flash-attention flag convention. Routes the dedup'd lookup's
    unique-block → row-stream expansion through the one-hot MXU
    kernel."""
    from ...common.context import tri_state_conf
    flag = tri_state_conf("zoo.pallas.embed_gather")
    if flag == "auto":
        return jax.default_backend() == "tpu"
    return flag


def _select_block_n(n_pad: int, capacity: int, d_pad: int,
                    itemsize: int) -> int:
    """Largest ``block_n`` (≤ 1024, ≥ the sublane floor) whose priced
    footprint fits the usable VMEM budget — the ``_budget_blocks``
    shrink discipline, re-landing on the tile floor every step. A pure
    function of the abstract signature, so the jit cache is stable.
    Returns 0 when even the floor does not fit (caller falls back to
    ``jnp.take``)."""
    budget = vmem_usable_bytes()
    block_n = round_up(min(1024, max(n_pad, 1)), _SUBLANES)
    while (embed_gather_vmem_bytes(block_n, capacity, d_pad,
                                   itemsize) > budget
           and block_n > _SUBLANES):
        block_n = max(_SUBLANES, block_n // 2 // _SUBLANES * _SUBLANES)
    if embed_gather_vmem_bytes(block_n, capacity, d_pad,
                               itemsize) > budget:
        return 0
    return block_n


def _expand_kernel(inv_ref, rows_ref, out_ref, *, capacity: int):
    """One ``(block_n, D)`` output tile: build the ``(block_n,
    capacity)`` one-hot selector from the inverse ids and contract it
    against the whole unique-row block on the MXU."""
    inv = inv_ref[:, :1]
    onehot = (jax.lax.broadcasted_iota(
        jnp.int32, (inv_ref.shape[0], capacity), 1) == inv
        ).astype(rows_ref.dtype)
    out_ref[...] = jax.lax.dot_general(
        onehot, rows_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(out_ref.dtype)


def embed_expand(rows: jax.Array, inv: jax.Array,
                 interpret: Optional[bool] = None) -> jax.Array:
    """``rows[inv]`` via the one-hot MXU kernel: ``rows`` is the
    ``(capacity, D)`` unique-row block, ``inv`` the ``(N,)`` int32
    inverse indices; returns ``(N, D)``. Bit-identical to ``jnp.take``
    (which it falls back to when the priced footprint cannot fit even
    at the sublane-floor block size)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = inv.shape[0]
    rp = pad_to_multiple(pad_to_multiple(rows, 0, _LANES), 1, _LANES)
    capacity, d_pad = rp.shape
    itemsize = jnp.dtype(rows.dtype).itemsize
    block_n = _select_block_n(round_up(max(n, 1), _SUBLANES), capacity,
                              d_pad, itemsize)
    if block_n == 0:
        return jnp.take(rows, inv, axis=0)
    n_pad = round_up(max(n, 1), block_n)
    ip = jnp.pad(inv.astype(jnp.int32), (0, n_pad - n))
    inv2 = jnp.broadcast_to(ip[:, None], (n_pad, _LANES))
    kernel = functools.partial(_expand_kernel, capacity=capacity)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((rp.shape[0], rp.shape[1]), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, d_pad), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, d_pad), rows.dtype),
        interpret=interpret,
    )(inv2, rp)
    return out[:n, :rows.shape[1]]
