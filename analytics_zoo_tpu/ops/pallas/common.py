"""Shared helpers for the pallas kernel package: the hardware tile
constants, alignment/padding utilities, and the **parameterized VMEM
footprint estimator** every block selector prices kernels with.

The estimator (:func:`kernel_vmem_bytes` + the per-kernel wrappers
:func:`attention_vmem_bytes` / :func:`ce_vmem_bytes`) is the single
source of truth for "does this block configuration fit VMEM": the
flash-attention autotuner (``flash_attention.select_attention_blocks`` /
``_sweep_candidates``), the fused-CE forward's budget clamp
(``cross_entropy.fused_ce_forward``) and zoolint's static ZL024 check
(``analysis/device.py``) all call the same functions, so a kernel edit
cannot silently change the runtime budget math without the lint-time
check moving with it (``tests/test_pallas.py`` property-tests the
agreement over the autotuner's full candidate set).

IMPORT CONTRACT: this module must stay importable WITHOUT jax — zoolint
loads it standalone (``importlib`` straight off the file, no package
``__init__`` chain) to price pallas_call sites at lint time, and the
linter is jax-free by design. jax imports live inside the functions
that need them (:func:`pad_to_multiple`); everything else is pure-int
arithmetic.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple

LANES = 128     # lane width (TPU min tile last dim)
SUBLANES = 8    # sublane width (TPU min tile second-to-last dim)

#: per-core VMEM (the pallas guide's ~16 MB/core); overridable per run via
#: ``zoo.pallas.vmem_budget_mb`` for chips with a different budget
VMEM_BYTES_DEFAULT = 16 * 1024 * 1024
#: fraction of VMEM the block selectors hand a kernel — the rest stays
#: with the compiler (spills, the backward's second operand window,
#: semaphores)
VMEM_USABLE_FRACTION = 0.5


def round_up(n: int, mult: int) -> int:
    """``n`` rounded up to the next multiple of ``mult``."""
    return ((n + mult - 1) // mult) * mult


def pad_to_multiple(x, axis: int, mult: int):
    """Zero-pad ``axis`` up to the next multiple of ``mult`` (no-op when
    already aligned)."""
    import jax.numpy as jnp  # lazy: keep this module importable sans jax
    rem = (-x.shape[axis]) % mult
    if rem == 0:
        return x
    cfg = [(0, 0)] * x.ndim
    cfg[axis] = (0, rem)
    return jnp.pad(x, cfg)


def vmem_budget_bytes() -> int:
    """The live per-core VMEM budget: ``zoo.pallas.vmem_budget_mb`` when a
    zoo context is constructible and sets it, else the 16 MiB default."""
    try:
        from ...common.context import get_zoo_context
        mb = float(get_zoo_context().get("zoo.pallas.vmem_budget_mb", 0) or 0)
        if mb > 0:
            return int(mb * 1024 * 1024)
    # no context constructible (odd device counts, standalone lint load)
    # — the default budget holds
    except Exception:  # zoolint: disable=ZL007
        pass
    return VMEM_BYTES_DEFAULT


def vmem_usable_bytes(budget_bytes: Optional[int] = None) -> int:
    """The slice of the budget a kernel may claim for its windows."""
    budget = budget_bytes if budget_bytes is not None else vmem_budget_bytes()
    return int(budget * VMEM_USABLE_FRACTION)


_ShapeBytes = Tuple[Sequence[int], int]     # ((dims...), itemsize)


def _tile_widened(shape: Sequence[int]) -> int:
    """Element count of ``shape`` with the trailing dim widened to the
    lane tile floor and the second-to-last to the sublane floor — how the
    hardware actually lays a VMEM window out."""
    dims = [max(int(d), 1) for d in shape]
    if not dims:
        return 1
    dims[-1] = round_up(dims[-1], LANES)
    if len(dims) >= 2:
        dims[-2] = round_up(dims[-2], SUBLANES)
    total = 1
    for d in dims:
        total *= d
    return total


def kernel_vmem_bytes(operands: Iterable[_ShapeBytes] = (),
                      outputs: Iterable[_ShapeBytes] = (),
                      scratch: Iterable[_ShapeBytes] = (),
                      compute: Iterable[_ShapeBytes] = (),
                      copies: int = 2) -> int:
    """Parameterized per-grid-cell VMEM footprint: operand and output
    windows are double-buffered (``copies``, the pallas pipeline's
    prefetch depth), scratch and transient compute tiles are single.
    Every shape is widened to the hardware tile floors. Entries are
    ``(shape, itemsize)`` pairs."""
    total = 0
    for shape, itemsize in operands:
        total += copies * _tile_widened(shape) * itemsize
    for shape, itemsize in outputs:
        total += copies * _tile_widened(shape) * itemsize
    for shape, itemsize in scratch:
        total += _tile_widened(shape) * itemsize
    for shape, itemsize in compute:
        total += _tile_widened(shape) * itemsize
    return total


def attention_vmem_bytes(block_q: int, block_k: int, d: int, itemsize: int,
                         has_mask: bool = False) -> int:
    """Estimated per-grid-cell VMEM of the flash-attention forward kernel
    (the backward's tiles are the same sizes): q/k/v operand windows +
    the acc/m/l scratch carries + o/lse outputs + the f32 score and
    probability compute tiles. ``block_k`` prices at the lane floor even
    as a sublane-position window dim because the (block_q, block_k)
    score tile needs it lane-aligned anyway."""
    d_eff = round_up(max(d, 1), LANES)
    bq = round_up(max(block_q, 1), SUBLANES)
    bk = round_up(max(block_k, 1), LANES)
    ops = [((bq, d_eff), itemsize),             # q window
           ((bk, d_eff), itemsize),             # k window
           ((bk, d_eff), itemsize)]             # v window
    if has_mask:
        ops.append(((SUBLANES, bk), 4))         # key-padding mask slice
    outs = [((bq, d_eff), itemsize),            # o
            ((bq, LANES), 4)]                   # lse
    scr = [((bq, d_eff), 4),                    # acc
           ((bq, LANES), 4), ((bq, LANES), 4)]  # running max / denom
    comp = [((bq, bk), 4), ((bq, bk), 4)]       # s and p tiles, f32
    return kernel_vmem_bytes(operands=ops, outputs=outs, scratch=scr,
                             compute=comp)


def ce_vmem_bytes(block_n: int, block_v: int, hidden: int, itemsize: int,
                  has_bias: bool = True) -> int:
    """Estimated per-grid-cell VMEM of the fused-CE forward kernel
    (``cross_entropy.fused_ce_forward``): h/w operand windows (+ the f32
    bias slice and the int32 label broadcast) + the m/l/label-logit
    scratch carries + lse/ll outputs + the f32 logits and probability
    compute tiles."""
    h_eff = round_up(max(hidden, 1), LANES)
    bn = round_up(max(block_n, 1), SUBLANES)
    bv = round_up(max(block_v, 1), LANES)
    ops = [((bn, h_eff), itemsize),             # h window
           ((h_eff, bv), itemsize),             # w window
           ((bn, LANES), 4)]                    # labels (int32 broadcast)
    if has_bias:
        ops.append(((SUBLANES, bv), 4))         # f32 bias slice
    outs = [((bn, LANES), 4), ((bn, LANES), 4)]     # lse / label logit
    scr = [((bn, LANES), 4), ((bn, LANES), 4), ((bn, LANES), 4)]
    comp = [((bn, bv), 4), ((bn, bv), 4)]       # logits and p tiles, f32
    return kernel_vmem_bytes(operands=ops, outputs=outs, scratch=scr,
                             compute=comp)


def embed_gather_vmem_bytes(block_n: int, capacity: int, d: int,
                            itemsize: int) -> int:
    """Estimated per-grid-cell VMEM of the embedding expand-gather
    kernel (``embedding.embed_expand``): the whole unique-row block +
    the int32 index broadcast as operands, the expanded row window as
    output, and the (block_n, capacity) one-hot selection tile the MXU
    contraction holds live. The runtime budget fallback and zoolint's
    static ZL024 check price through this one formula."""
    cap = round_up(max(capacity, 1), LANES)
    d_eff = round_up(max(d, 1), LANES)
    bn = round_up(max(block_n, 1), SUBLANES)
    ops = [((cap, d_eff), itemsize),            # unique-row block (whole)
           ((bn, LANES), 4)]                    # inverse ids (int32)
    outs = [((bn, d_eff), itemsize)]            # expanded rows
    comp = [((bn, cap), itemsize)]              # one-hot selection tile
    return kernel_vmem_bytes(operands=ops, outputs=outs, compute=comp)


def ce_bwd_vmem_bytes(block_n: int, block_v: int, hidden: int,
                      itemsize: int, has_bias: bool = True) -> int:
    """Estimated per-grid-cell VMEM of the fused-CE BACKWARD kernel pair
    (``cross_entropy.fused_ce_backward``): the max of the dh kernel
    (dh output + (block_n, H) f32 accumulator) and the dW/db kernel
    ((H, block_v) f32 accumulator + outputs), each over the shared
    operand set — h/w windows, the int32 label broadcast, the f32
    lse/scale rows and the optional bias slice — plus the f32 logits,
    probability and dlogits compute tiles the tile re-formation holds
    live. The block selectors, the runtime budget clamp and zoolint's
    static ZL024 check all price through this one formula."""
    h_eff = round_up(max(hidden, 1), LANES)
    bn = round_up(max(block_n, 1), SUBLANES)
    bv = round_up(max(block_v, 1), LANES)
    ops = [((bn, h_eff), itemsize),             # h window
           ((h_eff, bv), itemsize),             # w window
           ((bn, LANES), 4),                    # labels (int32 broadcast)
           ((bn, LANES), 4),                    # saved row lse
           ((bn, LANES), 4)]                    # grad scale
    if has_bias:
        ops.append(((SUBLANES, bv), 4))         # f32 bias slice
    comp = [((bn, bv), 4), ((bn, bv), 4), ((bn, bv), 4)]  # logits/p/dl
    dh = kernel_vmem_bytes(
        operands=ops, outputs=[((bn, h_eff), 4)],
        scratch=[((bn, h_eff), 4)], compute=comp)
    dw_outs = [((h_eff, bv), 4)]
    dw_scr = [((h_eff, bv), 4)]
    if has_bias:
        dw_outs.append(((SUBLANES, bv), 4))
        dw_scr.append(((SUBLANES, bv), 4))
    dw = kernel_vmem_bytes(operands=ops, outputs=dw_outs, scratch=dw_scr,
                           compute=comp)
    return max(dh, dw)
