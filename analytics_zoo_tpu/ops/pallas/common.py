"""Shared helpers for the pallas kernel package."""

from __future__ import annotations

import jax
import jax.numpy as jnp

LANES = 128     # lane width (TPU min tile last dim)
SUBLANES = 8    # sublane width (TPU min tile second-to-last dim)


def round_up(n: int, mult: int) -> int:
    """``n`` rounded up to the next multiple of ``mult``."""
    return ((n + mult - 1) // mult) * mult


def pad_to_multiple(x: jax.Array, axis: int, mult: int) -> jax.Array:
    """Zero-pad ``axis`` up to the next multiple of ``mult`` (no-op when
    already aligned)."""
    rem = (-x.shape[axis]) % mult
    if rem == 0:
        return x
    cfg = [(0, 0)] * x.ndim
    cfg[axis] = (0, rem)
    return jnp.pad(x, cfg)
