"""Shared helpers for the pallas kernel package."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pad_to_multiple(x: jax.Array, axis: int, mult: int) -> jax.Array:
    """Zero-pad ``axis`` up to the next multiple of ``mult`` (no-op when
    already aligned)."""
    rem = (-x.shape[axis]) % mult
    if rem == 0:
        return x
    cfg = [(0, 0)] * x.ndim
    cfg[axis] = (0, rem)
    return jnp.pad(x, cfg)
