"""Fused LM-head cross-entropy forward — the L0 Pallas kernel behind
``ops/fused_cross_entropy.py`` (routing: ``zoo.pallas.cross_entropy``, same
auto-on-TPU convention as the flash-attention kernel).

One pass computes, per hidden-state row, the two scalars the blockwise loss
needs — ``logsumexp(h @ W + b)`` and the label's logit — WITHOUT ever writing
a logits tile back to HBM: grid ``(row-blocks, vocab-blocks)`` with the vocab
dimension innermost (TPU pallas runs the grid sequentially, so the online
logsumexp carry ``m``/``l`` and the label-logit accumulator live in VMEM
scratch across the vocab steps of one row block, exactly the flash-attention
carry scheme). The ``(block_n, block_v)`` logits tile exists only in
registers/VMEM; HBM traffic is the streamed ``W`` tiles plus O(N) outputs,
which is what makes the LM head bandwidth-proportional instead of
logits-proportional (Liu & Abbeel 2023's blockwise-parallel argument applied
to the head instead of attention).

The matmul runs on the MXU in the input dtype (bf16 operands at full rate)
with float32 accumulation. The backward stays in
``ops/fused_cross_entropy.py`` as chunked XLA tile re-formation — it needs
the dW/dx matmuls anyway, which XLA already emits tiled; only the forward's
extra logits round-trip is worth a hand-written kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import LANES as _LANES
from .common import SUBLANES as _SUBLANES
from .common import (ce_vmem_bytes, pad_to_multiple, round_up,
                     vmem_usable_bytes)

__all__ = ["fused_ce_forward"]


def _budget_blocks(block_n: int, block_v: int, hidden_padded: int,
                   itemsize: int, has_bias: bool):
    """Shrink ``(block_n, block_v)`` until the kernel's estimated
    footprint — the SAME shared formula the flash-attention autotuner
    prices with (``common.ce_vmem_bytes``) — fits the usable VMEM
    budget. Deterministic in the abstract signature, so jit caches stay
    stable; every shrink step re-lands on the tile floors (the
    flash-attention discipline)."""
    budget = vmem_usable_bytes()
    while (ce_vmem_bytes(block_n, block_v, hidden_padded, itemsize,
                         has_bias) > budget
           and (block_n > _SUBLANES or block_v > _LANES)):
        if block_v >= 2 * block_n and block_v > _LANES:
            block_v = max(_LANES, block_v // 2 // _LANES * _LANES)
        elif block_n > _SUBLANES:
            block_n = max(_SUBLANES, block_n // 2 // _SUBLANES * _SUBLANES)
        else:
            block_v = max(_LANES, block_v // 2 // _LANES * _LANES)
    return block_n, block_v


def _ce_fwd_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, ll_ref, m_ref,
                   l_ref, a_ref, *, block_n: int, block_v: int, v_total: int,
                   has_bias: bool):
    """Grid cell (ri, vi). h (block_n, H); w (H, block_v);
    [b (SUBLANES, block_v)]; labels (block_n, LANES) int32 broadcast;
    outputs lse/ll (block_n, LANES) f32; scratch m/l/a (block_n, LANES).
    Row vectors carry the LANES broadcast dim — TPU blocks need tileable
    trailing dims (the flash-attention l/m layout)."""
    vi = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        a_ref[:] = jnp.zeros_like(a_ref)

    # operands stay in the input dtype (bf16 = full MXU rate); the product
    # accumulates f32 via preferred_element_type, then rounds to the
    # compute dtype with the bias added in it — Dense.call's exact
    # rounding, which the oracle's logits carry under bf16 policy
    logits = jax.lax.dot_general(h_ref[...], w_ref[...],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32
                                 ).astype(h_ref.dtype)
    if has_bias:
        logits = logits + b_ref[0:1, :].astype(h_ref.dtype)
    logits = logits.astype(jnp.float32)
    col = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)
    ok = col < v_total              # mask vocab padding out of the lse
    logits = jnp.where(ok, logits, -jnp.inf)

    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    # padded rows (h = 0, all-real columns) stay finite, but a fully-padded
    # vocab tile is all -inf — guard the exp shift like the flash kernel
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.where(ok, jnp.exp(logits - m_safe), 0.0)
    corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_safe))
    l_ref[:, :1] = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[:, :1] = m_new
    # label logit: at most one column of one tile matches each row's label
    # (padded rows carry label -1 and never match)
    hit = (col == lab_ref[:, :1]) & ok
    a_ref[:, :1] += jnp.sum(jnp.where(hit, logits, 0.0), axis=-1,
                            keepdims=True)

    @pl.when(vi == n_v - 1)
    def _finish():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        lse = m + jnp.log(jnp.where(l == 0.0, 1.0, l))
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)
        ll_ref[...] = jnp.broadcast_to(a_ref[:, :1], ll_ref.shape)


def fused_ce_forward(h: jax.Array, w: jax.Array, b: Optional[jax.Array],
                     labels: jax.Array, block_n: int = 256,
                     block_v: int = 512,
                     interpret: Optional[bool] = None,
                     ) -> Tuple[jax.Array, jax.Array]:
    """Per-row ``(logsumexp, label_logit)`` of ``h @ w [+ b]`` — f32 ``(N,)``
    pairs, no ``(N, V)`` tensor in HBM.

    ``h`` (N, H) in the compute dtype, ``w`` (H, V) pre-cast to match,
    ``b`` (V,) or None, ``labels`` (N,) int32 — rows with label < 0 get a
    zero label logit (the caller masks their loss). ``interpret`` defaults
    to auto: compiled on TPU, interpreter elsewhere (tests)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, hidden = h.shape
    v = w.shape[1]
    # blocks stay on the hardware tile floors (Mosaic needs sublane/lane
    # alignment on compiled TPU runs — the interpreter would not care);
    # the row/vocab padding below absorbs the overshoot. A wide hidden
    # dim then shrinks the blocks until the kernel's estimated footprint
    # fits the usable VMEM budget (shared estimator, common.py).
    block_n = round_up(min(block_n, max(n, 1)), _SUBLANES)
    block_v = round_up(min(block_v, max(v, 1)), _LANES)
    block_n, block_v = _budget_blocks(
        block_n, block_v, round_up(max(hidden, 1), _LANES),
        jnp.dtype(h.dtype).itemsize, b is not None)
    hp = pad_to_multiple(pad_to_multiple(h, 0, block_n), 1, _LANES)
    wp = pad_to_multiple(pad_to_multiple(w, 0, _LANES), 1, block_v)
    lp = jnp.pad(labels.astype(jnp.int32), (0, hp.shape[0] - n),
                 constant_values=-1)
    lab2 = jnp.broadcast_to(lp[:, None], (hp.shape[0], _LANES))
    has_bias = b is not None
    operands = [hp, wp]
    in_specs = [
        pl.BlockSpec((block_n, hp.shape[1]), lambda ri, vi: (ri, 0)),
        pl.BlockSpec((wp.shape[0], block_v), lambda ri, vi: (0, vi)),
    ]
    if has_bias:
        bp = pad_to_multiple(b.astype(jnp.float32).reshape(1, -1), 1, block_v)
        operands.append(jnp.broadcast_to(bp, (_SUBLANES, bp.shape[1])))
        in_specs.append(pl.BlockSpec((_SUBLANES, block_v),
                                     lambda ri, vi: (0, vi)))
    operands.append(lab2)
    in_specs.append(pl.BlockSpec((block_n, _LANES), lambda ri, vi: (ri, 0)))

    kernel = functools.partial(_ce_fwd_kernel, block_n=block_n,
                               block_v=block_v, v_total=v, has_bias=has_bias)
    if not has_bias:
        # keep the kernel's positional layout: splice a no-op bias ref out
        def kernel(h_ref, w_ref, lab_ref, lse_ref, ll_ref, m_ref, l_ref,
                   a_ref):
            return _ce_fwd_kernel(h_ref, w_ref, None, lab_ref, lse_ref,
                                  ll_ref, m_ref, l_ref, a_ref,
                                  block_n=block_n, block_v=block_v,
                                  v_total=v, has_bias=False)
    rowspec = pl.BlockSpec((block_n, _LANES), lambda ri, vi: (ri, 0))
    lse, ll = pl.pallas_call(
        kernel,
        grid=(hp.shape[0] // block_n, wp.shape[1] // block_v),
        in_specs=in_specs,
        out_specs=[rowspec, rowspec],
        out_shape=[jax.ShapeDtypeStruct((hp.shape[0], _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((hp.shape[0], _LANES), jnp.float32)],
        scratch_shapes=[
            pltpu.VMEM((block_n, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_n, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_n, _LANES), jnp.float32),  # label logit
        ],
        interpret=interpret,
    )(*operands)
    return lse[:n, 0], ll[:n, 0]
