"""Fused LM-head cross-entropy kernels — the L0 Pallas pair behind
``ops/fused_cross_entropy.py`` (routing: ``zoo.pallas.cross_entropy``, same
auto-on-TPU convention as the flash-attention kernel).

**Forward** (``fused_ce_forward``): one pass computes, per hidden-state row,
the two scalars the blockwise loss needs — ``logsumexp(h @ W + b)`` and the
label's logit — WITHOUT ever writing a logits tile back to HBM: grid
``(row-blocks, vocab-blocks)`` with the vocab dimension innermost (TPU
pallas runs the grid sequentially, so the online logsumexp carry ``m``/``l``
and the label-logit accumulator live in VMEM scratch across the vocab steps
of one row block, exactly the flash-attention carry scheme). The
``(block_n, block_v)`` logits tile exists only in registers/VMEM; HBM
traffic is the streamed ``W`` tiles plus O(N) outputs, which is what makes
the LM head bandwidth-proportional instead of logits-proportional (Liu &
Abbeel 2023's blockwise-parallel argument applied to the head instead of
attention).

**Backward** (``fused_ce_backward``): the flash-attention two-kernel
recompute scheme applied to the head — each kernel re-forms one
``(block_n, block_v)`` probability tile from the saved row logsumexp
(``p = exp(logits - lse)``, the same compute-dtype rounding as the
forward), builds ``dlogits = (p - onehot) * scale`` in VMEM, and folds it
straight into its product matmul:

* the **dh kernel** (grid row-blocks × vocab-blocks, vocab innermost)
  accumulates ``dlogits @ Wᵀ`` in a ``(block_n, H)`` f32 scratch carry;
* the **dW/db kernel** (grid vocab-blocks × row-blocks, rows innermost)
  accumulates ``hᵀ @ dlogits`` (and the bias row-sum) in an
  ``(H, block_v)`` f32 carry.

The probability tile therefore never reaches HBM in the backward either —
the XLA scan formulation this replaces streams every re-formed tile through
HBM three times (form, dh product, dW product). All matmuls run on the MXU
in the input dtype (bf16 operands at full rate) with float32 accumulation;
block sizes ride the same VMEM-budget heuristic + optional one-shot
on-device sweep (``zoo.pallas.block_sweep``) as flash attention, priced by
the shared estimator (``common.ce_vmem_bytes`` / ``ce_bwd_vmem_bytes``)
zoolint's ZL024 checks against statically.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import LANES as _LANES
from .common import SUBLANES as _SUBLANES
from .common import (ce_bwd_vmem_bytes, ce_vmem_bytes, pad_to_multiple,
                     round_up, vmem_usable_bytes)

__all__ = ["fused_ce_forward", "fused_ce_backward", "select_ce_blocks"]


def _budget_blocks(block_n: int, block_v: int, hidden_padded: int,
                   itemsize: int, has_bias: bool, price=ce_vmem_bytes):
    """Shrink ``(block_n, block_v)`` until the kernel's estimated
    footprint — the SAME shared formula the flash-attention autotuner
    prices with (``common.ce_vmem_bytes`` forward /
    ``common.ce_bwd_vmem_bytes`` backward) — fits the usable VMEM
    budget. Deterministic in the abstract signature, so jit caches stay
    stable; every shrink step re-lands on the tile floors (the
    flash-attention discipline)."""
    budget = vmem_usable_bytes()
    while (price(block_n, block_v, hidden_padded, itemsize,
                 has_bias) > budget
           and (block_n > _SUBLANES or block_v > _LANES)):
        if block_v >= 2 * block_n and block_v > _LANES:
            block_v = max(_LANES, block_v // 2 // _LANES * _LANES)
        elif block_n > _SUBLANES:
            block_n = max(_SUBLANES, block_n // 2 // _SUBLANES * _SUBLANES)
        else:
            block_v = max(_LANES, block_v // 2 // _LANES * _LANES)
    return block_n, block_v


def select_ce_blocks(n: int, v: int, hidden: int, dtype,
                     has_bias: bool = True, bwd: bool = False
                     ) -> Tuple[int, int]:
    """VMEM-budget-aware ``(block_n, block_v)`` for the CE kernels: the
    (256, 512) starting point clamped to the problem (rounded back onto
    the tile floors), then shrunk until the priced footprint fits —
    a pure function of the abstract signature, so the jit cache is
    stable. ``bwd`` prices with the backward pair's formula."""
    itemsize = jnp.dtype(dtype).itemsize
    block_n = round_up(min(256, max(n, 1)), _SUBLANES)
    block_v = round_up(min(512, max(v, 1)), _LANES)
    return _budget_blocks(block_n, block_v, round_up(max(hidden, 1), _LANES),
                          itemsize, has_bias,
                          price=ce_bwd_vmem_bytes if bwd else ce_vmem_bytes)


# ---------------------------------------------------------------------------
# block sweep + cache (the flash-attention machinery, for the CE backward)
# ---------------------------------------------------------------------------

#: abstract signature -> (block_n, block_v), resolved once per process
_CE_BLOCK_CACHE: dict = {}


def _ce_sweep_candidates(n: int, v: int, hidden: int, itemsize: int,
                         has_bias: bool, heuristic):
    budget = vmem_usable_bytes()
    out = []
    for bn, bv in (heuristic, (256, 512), (128, 512), (256, 256),
                   (512, 512), (128, 1024)):
        cand = (round_up(min(bn, max(n, 1)), _SUBLANES),
                round_up(min(bv, max(v, 1)), _LANES))
        if cand in out:
            continue
        if ce_bwd_vmem_bytes(*cand, hidden=round_up(max(hidden, 1), _LANES),
                             itemsize=itemsize,
                             has_bias=has_bias) <= budget:
            out.append(cand)
    return out or [heuristic]


def _time_ce_bwd(n, v, hidden, dtype, has_bias, bn, bv,
                 repeats: int = 2) -> float:
    """Best-of-``repeats`` wall seconds for one compiled backward pair at
    the given blocks, on synthetic on-device operands."""
    import time

    import numpy as np
    rng = np.random.default_rng(0)
    h = jax.device_put(jnp.asarray(
        rng.normal(size=(n, hidden)).astype(np.float32), dtype))
    w = jax.device_put(jnp.asarray(
        rng.normal(size=(hidden, v)).astype(np.float32), dtype))
    b = (jax.device_put(jnp.zeros((v,), jnp.float32)) if has_bias
         else None)
    lab = jax.device_put(jnp.asarray(
        rng.integers(0, v, n).astype(np.int32)))
    lse = jax.device_put(jnp.full((n,), float(np.log(v)), jnp.float32))
    scale = jax.device_put(jnp.ones((n,), jnp.float32))

    fn = jax.jit(functools.partial(fused_ce_backward, block_n=bn,
                                   block_v=bv, interpret=False))
    jax.block_until_ready(fn(h, w, b, lab, lse, scale))   # compile + warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(h, w, b, lab, lse, scale))
        best = min(best, time.perf_counter() - t0)
    return best


def _record_ce_block_choice(sig: str, choice) -> None:
    try:
        from ...observability import default_registry
        # sig/choice are bounded by the distinct abstract kernel
        # signatures a process compiles (each also a jit cache entry)
        default_registry().gauge(  # zoolint: disable=ZL015 bounded label set
            "zoo_pallas_block_choice",
            "selected pallas kernel block sizes per abstract signature "
            "(1 = active choice)",
            labels={"kernel": "cross_entropy", "sig": sig,
                    "choice": f"{choice[0]}x{choice[1]}"}).set(1)
    # metrics must never break the compute path
    except Exception:  # zoolint: disable=ZL007
        pass


def _auto_ce_bwd_blocks(n: int, v: int, hidden: int, dtype,
                        has_bias: bool, interpret: bool) -> Tuple[int, int]:
    """Cached per-signature (block_n, block_v) for the backward pair:
    the VMEM heuristic, optionally refined by the one-shot on-device
    sweep (``zoo.pallas.block_sweep``; compiled TPU runs only — the
    interpreter's timings say nothing about the MXU)."""
    dt = jnp.dtype(dtype)
    sweep = False
    try:
        from ...common.context import get_zoo_context
        sweep = bool(get_zoo_context().get("zoo.pallas.block_sweep", False))
    # no context constructible — the sweep stays off, heuristic holds
    except Exception:  # zoolint: disable=ZL007
        pass
    sweep = sweep and not interpret and jax.default_backend() == "tpu"
    budget = vmem_usable_bytes()
    sig = (budget, "ce_bwd", sweep, n, v, hidden, dt.name, has_bias)
    cached = _CE_BLOCK_CACHE.get(sig)
    if cached is not None:
        return cached
    choice = select_ce_blocks(n, v, hidden, dt, has_bias=has_bias,
                              bwd=True)
    if sweep:
        best, best_t = choice, float("inf")
        for cand in _ce_sweep_candidates(n, v, hidden, dt.itemsize,
                                         has_bias, choice):
            try:
                t = _time_ce_bwd(n, v, hidden, dt, has_bias, *cand)
            # a candidate that fails to compile/run just loses the sweep
            except Exception:  # zoolint: disable=ZL007
                continue
            if t < best_t:
                best, best_t = cand, t
        choice = best
    _CE_BLOCK_CACHE[sig] = choice
    _record_ce_block_choice(
        f"bwd_n{n}v{v}h{hidden}{dt.name}{'b' if has_bias else ''}", choice)
    return choice


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _ce_fwd_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, ll_ref, m_ref,
                   l_ref, a_ref, *, block_n: int, block_v: int, v_total: int,
                   has_bias: bool):
    """Grid cell (ri, vi). h (block_n, H); w (H, block_v);
    [b (SUBLANES, block_v)]; labels (block_n, LANES) int32 broadcast;
    outputs lse/ll (block_n, LANES) f32; scratch m/l/a (block_n, LANES).
    Row vectors carry the LANES broadcast dim — TPU blocks need tileable
    trailing dims (the flash-attention l/m layout)."""
    vi = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        a_ref[:] = jnp.zeros_like(a_ref)

    # operands stay in the input dtype (bf16 = full MXU rate); the product
    # accumulates f32 via preferred_element_type, then rounds to the
    # compute dtype with the bias added in it — Dense.call's exact
    # rounding, which the oracle's logits carry under bf16 policy
    logits = jax.lax.dot_general(h_ref[...], w_ref[...],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32
                                 ).astype(h_ref.dtype)
    if has_bias:
        logits = logits + b_ref[0:1, :].astype(h_ref.dtype)
    logits = logits.astype(jnp.float32)
    col = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)
    ok = col < v_total              # mask vocab padding out of the lse
    logits = jnp.where(ok, logits, -jnp.inf)

    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    # padded rows (h = 0, all-real columns) stay finite, but a fully-padded
    # vocab tile is all -inf — guard the exp shift like the flash kernel
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.where(ok, jnp.exp(logits - m_safe), 0.0)
    corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_safe))
    l_ref[:, :1] = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[:, :1] = m_new
    # label logit: at most one column of one tile matches each row's label
    # (padded rows carry label -1 and never match)
    hit = (col == lab_ref[:, :1]) & ok
    a_ref[:, :1] += jnp.sum(jnp.where(hit, logits, 0.0), axis=-1,
                            keepdims=True)

    @pl.when(vi == n_v - 1)
    def _finish():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        lse = m + jnp.log(jnp.where(l == 0.0, 1.0, l))
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)
        ll_ref[...] = jnp.broadcast_to(a_ref[:, :1], ll_ref.shape)


def fused_ce_forward(h: jax.Array, w: jax.Array, b: Optional[jax.Array],
                     labels: jax.Array, block_n: int = 256,
                     block_v: int = 512,
                     interpret: Optional[bool] = None,
                     ) -> Tuple[jax.Array, jax.Array]:
    """Per-row ``(logsumexp, label_logit)`` of ``h @ w [+ b]`` — f32 ``(N,)``
    pairs, no ``(N, V)`` tensor in HBM.

    ``h`` (N, H) in the compute dtype, ``w`` (H, V) pre-cast to match,
    ``b`` (V,) or None, ``labels`` (N,) int32 — rows with label < 0 get a
    zero label logit (the caller masks their loss). ``interpret`` defaults
    to auto: compiled on TPU, interpreter elsewhere (tests)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, hidden = h.shape
    v = w.shape[1]
    # blocks stay on the hardware tile floors (Mosaic needs sublane/lane
    # alignment on compiled TPU runs — the interpreter would not care);
    # the row/vocab padding below absorbs the overshoot. A wide hidden
    # dim then shrinks the blocks until the kernel's estimated footprint
    # fits the usable VMEM budget (shared estimator, common.py).
    block_n = round_up(min(block_n, max(n, 1)), _SUBLANES)
    block_v = round_up(min(block_v, max(v, 1)), _LANES)
    block_n, block_v = _budget_blocks(
        block_n, block_v, round_up(max(hidden, 1), _LANES),
        jnp.dtype(h.dtype).itemsize, b is not None)
    hp = pad_to_multiple(pad_to_multiple(h, 0, block_n), 1, _LANES)
    wp = pad_to_multiple(pad_to_multiple(w, 0, _LANES), 1, block_v)
    lp = jnp.pad(labels.astype(jnp.int32), (0, hp.shape[0] - n),
                 constant_values=-1)
    lab2 = jnp.broadcast_to(lp[:, None], (hp.shape[0], _LANES))
    has_bias = b is not None
    operands = [hp, wp]
    in_specs = [
        pl.BlockSpec((block_n, hp.shape[1]), lambda ri, vi: (ri, 0)),
        pl.BlockSpec((wp.shape[0], block_v), lambda ri, vi: (0, vi)),
    ]
    if has_bias:
        bp = pad_to_multiple(b.astype(jnp.float32).reshape(1, -1), 1, block_v)
        operands.append(jnp.broadcast_to(bp, (_SUBLANES, bp.shape[1])))
        in_specs.append(pl.BlockSpec((_SUBLANES, block_v),
                                     lambda ri, vi: (0, vi)))
    operands.append(lab2)
    in_specs.append(pl.BlockSpec((block_n, _LANES), lambda ri, vi: (ri, 0)))

    kernel = functools.partial(_ce_fwd_kernel, block_n=block_n,
                               block_v=block_v, v_total=v, has_bias=has_bias)
    if not has_bias:
        # keep the kernel's positional layout: splice a no-op bias ref out
        def kernel(h_ref, w_ref, lab_ref, lse_ref, ll_ref, m_ref, l_ref,
                   a_ref):
            return _ce_fwd_kernel(h_ref, w_ref, None, lab_ref, lse_ref,
                                  ll_ref, m_ref, l_ref, a_ref,
                                  block_n=block_n, block_v=block_v,
                                  v_total=v, has_bias=False)
    rowspec = pl.BlockSpec((block_n, _LANES), lambda ri, vi: (ri, 0))
    lse, ll = pl.pallas_call(
        kernel,
        grid=(hp.shape[0] // block_n, wp.shape[1] // block_v),
        in_specs=in_specs,
        out_specs=[rowspec, rowspec],
        out_shape=[jax.ShapeDtypeStruct((hp.shape[0], _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((hp.shape[0], _LANES), jnp.float32)],
        scratch_shapes=[
            pltpu.VMEM((block_n, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_n, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_n, _LANES), jnp.float32),  # label logit
        ],
        interpret=interpret,
    )(*operands)
    return lse[:n, 0], ll[:n, 0]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _ce_bwd_tile(h_ref, w_ref, b_ref, lab_ref, lse_ref, s_ref, vi,
                 block_n: int, block_v: int, v_total: int, has_bias: bool):
    """The shared tile re-formation: one (block_n, block_v) dlogits tile
    rebuilt from the saved row lse — the same compute-dtype rounding as
    the forward, so ``p`` is re-formed bit-for-bit."""
    logits = jax.lax.dot_general(h_ref[...], w_ref[...],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32
                                 ).astype(h_ref.dtype)
    if has_bias:
        logits = logits + b_ref[0:1, :].astype(h_ref.dtype)
    logits = logits.astype(jnp.float32)
    col = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)
    ok = col < v_total
    # pad rows carry lse = +inf: exp(x - inf) = 0 keeps them exactly inert
    p = jnp.where(ok, jnp.exp(logits - lse_ref[:, :1]), 0.0)
    onehot = ((col == lab_ref[:, :1]) & ok).astype(jnp.float32)
    # masked rows carry scale 0, over-range rows carry scale NaN — the
    # matmuls below spread the poison exactly like the XLA formulation
    return (p - onehot) * s_ref[:, :1]


def _ce_bwd_dh_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, s_ref, dh_ref,
                      acc_ref, *, block_n: int, block_v: int, v_total: int,
                      has_bias: bool):
    """Grid (ri, vi), vocab innermost: dh = dlogits @ Wᵀ accumulates over
    the vocab blocks of one row block in f32 scratch."""
    vi = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    dl = _ce_bwd_tile(h_ref, w_ref, b_ref, lab_ref, lse_ref, s_ref, vi,
                      block_n, block_v, v_total, has_bias)
    acc_ref[:] += jax.lax.dot_general(
        dl.astype(h_ref.dtype), w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vi == n_v - 1)
    def _finish():
        dh_ref[...] = acc_ref[:].astype(dh_ref.dtype)


def _ce_bwd_dw_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, s_ref, dw_ref,
                      db_ref, dw_acc, db_acc, *, block_n: int, block_v: int,
                      v_total: int, has_bias: bool):
    """Grid (vi, ri), rows innermost: dW = hᵀ @ dlogits (and the db
    row-sum) accumulate over the row blocks of one vocab block in f32
    scratch."""
    vi = pl.program_id(0)
    ri = pl.program_id(1)
    n_r = pl.num_programs(1)

    @pl.when(ri == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)
        if has_bias:
            db_acc[:] = jnp.zeros_like(db_acc)

    dl = _ce_bwd_tile(h_ref, w_ref, b_ref, lab_ref, lse_ref, s_ref, vi,
                      block_n, block_v, v_total, has_bias)
    dw_acc[:] += jax.lax.dot_general(
        h_ref[...], dl.astype(h_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if has_bias:
        db_acc[:1, :] += jnp.sum(dl, axis=0, keepdims=True)

    @pl.when(ri == n_r - 1)
    def _finish():
        dw_ref[...] = dw_acc[:]
        if has_bias:
            db_ref[...] = db_acc[:]


def fused_ce_backward(h: jax.Array, w: jax.Array, b: Optional[jax.Array],
                      labels: jax.Array, lse: jax.Array, scale: jax.Array,
                      block_n: Optional[int] = None,
                      block_v: Optional[int] = None,
                      interpret: Optional[bool] = None,
                      dh_dtype=None):
    """Fused CE backward — ``(dh, dW, db)`` of the blockwise loss, tile
    re-formation and both product matmuls in VMEM (see module docstring).

    ``h`` (N, H) in the compute dtype, ``w`` (H, V) pre-cast to match,
    ``b`` (V,) f32 or None, ``labels`` (N,) int32 HIT labels (the local
    column index, or -1 for no hit — masked rows and, on the sharded
    path, rows owned by another vocab shard), ``lse`` (N,) f32 saved row
    logsumexp, ``scale`` (N,) f32 per-row dlogits multiplier
    (``fused_cross_entropy._grad_scale``: cotangent / 0 / NaN). Returns
    ``dh`` in ``dh_dtype`` (default ``h.dtype``), ``dW``/``db`` in f32.
    An unset block dim resolves through the per-signature cache +
    optional on-device sweep (``zoo.pallas.block_sweep``); the sweep
    times PAIRS, so both halves of its choice are honored unless the
    caller pins one explicitly."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, hidden = h.shape
    v = w.shape[1]
    has_bias = b is not None
    if block_n is None or block_v is None:
        abn, abv = _auto_ce_bwd_blocks(n, v, hidden, h.dtype, has_bias,
                                       interpret)
        block_n = abn if block_n is None else block_n
        block_v = abv if block_v is None else block_v
    block_n = round_up(min(block_n, max(n, 1)), _SUBLANES)
    block_v = round_up(min(block_v, max(v, 1)), _LANES)
    block_n, block_v = _budget_blocks(
        block_n, block_v, round_up(max(hidden, 1), _LANES),
        jnp.dtype(h.dtype).itemsize, has_bias, price=ce_bwd_vmem_bytes)
    hp = pad_to_multiple(pad_to_multiple(h, 0, block_n), 1, _LANES)
    wp = pad_to_multiple(pad_to_multiple(w, 0, _LANES), 1, block_v)
    n_pad = hp.shape[0] - n
    lp = jnp.pad(labels.astype(jnp.int32), (0, n_pad), constant_values=-1)
    # pad rows: lse = +inf (every re-formed probability underflows to 0)
    # and scale = 0 — exactly the XLA scan's pad-row discipline
    lsep = jnp.pad(lse.astype(jnp.float32), (0, n_pad),
                   constant_values=jnp.inf)
    sp = jnp.pad(scale.astype(jnp.float32), (0, n_pad))
    rows = [jnp.broadcast_to(a[:, None], (hp.shape[0], _LANES))
            for a in (lp, lsep, sp)]
    operands = [hp, wp]
    if has_bias:
        bp = pad_to_multiple(b.astype(jnp.float32).reshape(1, -1), 1,
                             block_v)
        operands.append(jnp.broadcast_to(bp, (_SUBLANES, bp.shape[1])))
    operands.extend(rows)
    n_r = hp.shape[0] // block_n
    n_v = wp.shape[1] // block_v

    def specs(idx_h, idx_w, idx_row):
        out = [pl.BlockSpec((block_n, hp.shape[1]), idx_h),
               pl.BlockSpec((wp.shape[0], block_v), idx_w)]
        if has_bias:
            out.append(pl.BlockSpec((_SUBLANES, block_v), idx_w))
        out.extend(pl.BlockSpec((block_n, _LANES), idx_row)
                   for _ in range(3))
        return out

    static = dict(block_n=block_n, block_v=block_v, v_total=v,
                  has_bias=has_bias)

    dh_kernel = functools.partial(_ce_bwd_dh_kernel, **static)
    if not has_bias:
        def dh_kernel(h_ref, w_ref, lab_ref, lse_ref, s_ref, dh_ref,
                      acc_ref):
            return _ce_bwd_dh_kernel(h_ref, w_ref, None, lab_ref, lse_ref,
                                     s_ref, dh_ref, acc_ref, **static)
    dh = pl.pallas_call(
        dh_kernel,
        grid=(n_r, n_v),
        in_specs=specs(lambda ri, vi: (ri, 0), lambda ri, vi: (0, vi),
                       lambda ri, vi: (ri, 0)),
        out_specs=pl.BlockSpec((block_n, hp.shape[1]),
                               lambda ri, vi: (ri, 0)),
        out_shape=jax.ShapeDtypeStruct(hp.shape, dh_dtype or h.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, hp.shape[1]), jnp.float32)],
        interpret=interpret,
    )(*operands)

    dw_kernel = functools.partial(_ce_bwd_dw_kernel, **static)
    if not has_bias:
        def dw_kernel(h_ref, w_ref, lab_ref, lse_ref, s_ref, dw_ref,
                      dw_acc):
            return _ce_bwd_dw_kernel(h_ref, w_ref, None, lab_ref, lse_ref,
                                     s_ref, dw_ref, None, dw_acc, None,
                                     **static)
    out_specs = [pl.BlockSpec((wp.shape[0], block_v),
                              lambda vi, ri: (0, vi))]
    out_shape = [jax.ShapeDtypeStruct(wp.shape, jnp.float32)]
    scratch = [pltpu.VMEM((wp.shape[0], block_v), jnp.float32)]
    if has_bias:
        out_specs.append(pl.BlockSpec((_SUBLANES, block_v),
                                      lambda vi, ri: (0, vi)))
        out_shape.append(jax.ShapeDtypeStruct((_SUBLANES, wp.shape[1]),
                                              jnp.float32))
        scratch.append(pltpu.VMEM((_SUBLANES, block_v), jnp.float32))
    res = pl.pallas_call(
        dw_kernel,
        grid=(n_v, n_r),
        in_specs=specs(lambda vi, ri: (ri, 0), lambda vi, ri: (0, vi),
                       lambda vi, ri: (ri, 0)),
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
        scratch_shapes=scratch,
    )(*operands)

    dh = dh[:n, :hidden]
    dw = res[0][:hidden, :v]
    db = res[1][0, :v] if has_bias else None
    return dh, dw, db
