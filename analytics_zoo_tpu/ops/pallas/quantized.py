"""Int8 weight-only fused dequant-matmul kernel (SURVEY §2.3; the
reference's int8 story is OpenVINO VNNI on Xeon,
``InferenceModel.scala:622-656``).

``y = x @ (w_q * scale)`` with per-output-column scales, fused so the int8
weights upcast in VMEM tile-by-tile — HBM traffic stays 1 byte/weight, the
point of weight-only quantization. Standalone public API: the
``pipeline/inference`` int8 predict path currently dequantizes in-jit and
relies on XLA fusing the convert+scale into consumers; this kernel is the
hand-scheduled alternative for callers that matmul against a quantized
table directly.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .common import LANES as _LANES
from .common import SUBLANES as _SUBLANES
from .common import pad_to_multiple
from .common import round_up as _round_up

__all__ = ["int8_matmul"]


def _kernel(x_ref, wq_ref, scale_ref, o_ref):
    """x (BM, K) f32 · wq (K, BN) int8 ∘ scale (1, BN) → o (BM, BN)."""
    w = wq_ref[:].astype(jnp.float32)
    acc = jax.lax.dot_general(x_ref[:].astype(jnp.float32), w,
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[:] = (acc * scale_ref[0, :][None, :]).astype(o_ref.dtype)


def int8_matmul(x: jax.Array, w_q: jax.Array, scales: jax.Array,
                block_m: int = 128, block_n: int = 128,
                interpret: Optional[bool] = None) -> jax.Array:
    """``x (M, K) @ dequant(w_q (K, N) int8, scales (N,))`` → (M, N) in
    ``x.dtype``. Equivalent to ``x @ (w_q.astype(f32) * scales)``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, kdim = x.shape
    k2, n = w_q.shape
    if kdim != k2 or scales.shape != (n,):
        raise ValueError(f"shape mismatch: x {x.shape}, w_q {w_q.shape}, "
                         f"scales {scales.shape}")
    # the short-matrix clamp re-lands on the tile floors — a raw min()
    # against an unaligned M/N (m=100 -> block_m=100) hands Mosaic an
    # untileable block on compiled TPU runs; the padding below absorbs
    # the round-up and the [:m, :n] slice drops it again
    block_m = _round_up(min(block_m, max(m, 1)), _SUBLANES)
    block_n = _round_up(min(block_n, max(n, 1)), _LANES)

    xp = pad_to_multiple(x, 0, block_m)
    wp = pad_to_multiple(w_q, 1, block_n)
    sp = pad_to_multiple(scales.reshape(1, n), 1, block_n)

    out = pl.pallas_call(
        _kernel,
        grid=(xp.shape[0] // block_m, wp.shape[1] // block_n),
        in_specs=[
            pl.BlockSpec((block_m, kdim), lambda i, j: (i, 0)),
            pl.BlockSpec((kdim, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((xp.shape[0], wp.shape[1]), x.dtype),
        interpret=interpret,
    )(xp, wp, sp)
    return out[:m, :n]
