"""Flash attention — the L0 Pallas TPU kernel behind the attention stack
(SURVEY §2.3; the reference has no custom kernels at all — its attention is
whole-matrix softmax inside ``TransformerLayer.scala:56``/``BERT.scala:66``,
materializing the (T, T) score matrix in HBM).

Design: grid (batch*head, q-blocks, k-blocks) with the k dimension innermost —
TPU pallas runs the grid sequentially, so the online-softmax carry (acc/m/l)
lives in VMEM scratch across the k steps of one q block: initialized at
``ki == 0``, folded per k block, written out at the last k block. VMEM per
cell is O(block_q·D + block_k·D) — K/V stream block-by-block, never the whole
sequence — and both matmuls (QK^T, PV) hit the MXU at tile-aligned sizes.
Causal cells predicate away k blocks strictly right of the diagonal.

Causal masking is BOTTOM-RIGHT aligned like the XLA oracle
(``ops/attention.py:41``): query i attends keys ``j <= i + (t_kv - t_q)``.
Rows with no visible key (t_q > t_kv tails) return zeros — the one spot the
oracle differs (its -1e9 fill degrades to uniform weights there).

Backward runs as XLA recompute (``jax.custom_vjp`` whose bwd re-derives the
probabilities like the checkpointed form) — the classic flash trade: don't
store the (T, T) weights, re-make them.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import pad_to_multiple

__all__ = ["flash_attention"]

_LANES = 128  # scratch lane width (TPU min tile last dim)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                scale: float, block_q: int, block_k: int, t_q: int,
                t_kv: int, causal: bool):
    """Grid cell (bh, qi, ki). q (1, block_q, D); k/v (1, block_k, D);
    o (1, block_q, D); scratch acc (block_q, D), m/l (block_q, LANES)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = t_kv - t_q  # bottom-right causal alignment

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: the first row of this q block sees keys up to
    # qi*block_q + offset; the last row up to (qi+1)*block_q - 1 + offset.
    # Blocks fully beyond the latter contribute nothing — skip their math.
    needed = True
    if causal:
        needed = ki * block_k <= (qi + 1) * block_q - 1 + offset

    @pl.when(needed)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        ok = k_pos < t_kv                              # kv padding mask
        if causal:
            ok = ok & (k_pos <= q_pos + offset)
        s = jnp.where(ok, s, -jnp.inf)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.where(ok, jnp.exp(s - m_safe), 0.0)
        corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_safe))
        l_ref[:, :1] = l_ref[:, :1] * corr + jnp.sum(p, axis=-1,
                                                     keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, :1] = m_new

    @pl.when(ki == n_k - 1)
    def _finish():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
                    ).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, block_q: int, block_k: int,
               interpret: bool):
    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    scale = 1.0 / float(d) ** 0.5
    block_q = min(block_q, max(t_q, 1))
    block_k = min(block_k, max(t_kv, 1))

    qr = pad_to_multiple(q.reshape(b * h, t_q, d), 1, block_q)
    kr = pad_to_multiple(k.reshape(b * h, t_kv, d), 1, block_k)
    vr = pad_to_multiple(v.reshape(b * h, t_kv, d), 1, block_k)
    n_q = qr.shape[1] // block_q
    n_k = kr.shape[1] // block_k

    kernel = functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                               block_k=block_k, t_q=t_q, t_kv=t_kv,
                               causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qr.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),       # acc
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out[:, :t_q, :].reshape(b, h, t_q, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, block_q: int = 256,
                    block_k: int = 256,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Blockwise-softmax attention: q/k/v (B, H, T, D) → (B, H, Tq, D).

    Numerically equivalent to ``ops.attention.dot_product_attention`` (minus
    dropout/mask arguments — those paths stay on the XLA op). ``interpret``
    defaults to auto: compiled on TPU, interpreter elsewhere (tests).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_fwd(q, k, v, causal, block_q, block_k, interpret)


def _vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _vjp_bwd(causal, block_q, block_k, interpret, res, g):
    """Recompute-form backward: differentiate the reference attention math
    (no (T,T) tensor was saved by the forward; XLA re-materializes it here,
    which is the standard flash-attention memory/compute trade)."""
    q, k, v = res

    def ref(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32)
        s = s / jnp.sqrt(jnp.asarray(d, jnp.float32))
        if causal:
            tq, tk = s.shape[-2], s.shape[-1]
            cm = jnp.tril(jnp.ones((tq, tk), jnp.bool_), k=tk - tq)
            s = jnp.where(cm[None, None], s, -1e9)
        w = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        if causal:
            # match the kernel exactly: rows with NO visible key (t_q > t_kv
            # tails) are zero in the forward, so they must be constants here
            # too — the -1e9 fill alone would leak uniform-weight gradients
            has_key = (jnp.arange(s.shape[-2])
                       + (s.shape[-1] - s.shape[-2])) >= 0
            out = out * has_key[None, None, :, None].astype(out.dtype)
        return out.astype(v.dtype)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
