"""Flash attention — the L0 Pallas TPU kernel behind the attention stack
(SURVEY §2.3; the reference has no custom kernels at all — its attention is
whole-matrix softmax inside ``TransformerLayer.scala:56``/``BERT.scala:66``,
materializing the (T, T) score matrix in HBM).

Forward design: grid (batch*head, q-blocks, k-blocks) with the k dimension
innermost — TPU pallas runs the grid sequentially, so the online-softmax
carry (acc/m/l) lives in VMEM scratch across the k steps of one q block:
initialized at ``ki == 0``, folded per k block, written out (with the row
log-sum-exp for the backward) at the last k block. VMEM per cell is
O(block_q·D + block_k·D) — K/V stream block-by-block, never the whole
sequence — and both matmuls (QK^T, PV) hit the MXU at tile-aligned sizes in
the input dtype (bfloat16 operands run the MXU at full rate; accumulation is
always float32). Causal cells predicate away k blocks strictly right of the
diagonal. An optional per-batch key-padding mask (B, Tk) streams in
(1, block_k) slices — this is the BERT ``attention_mask`` path.

Causal masking is BOTTOM-RIGHT aligned like the XLA oracle
(``ops/attention.py:41``): query i attends keys ``j <= i + (t_kv - t_q)``.
Rows with no visible key (t_q > t_kv tails, or fully-masked rows) return
zeros — the one spot the oracle differs (its -1e9 fill degrades to uniform
weights there).

Backward: the standard two-kernel recompute scheme (no (T, T) tensor is ever
materialized, unlike the r3 XLA-recompute fallback this replaces):
``delta = rowsum(dO·O)`` in XLA, then a dq kernel (grid bh, qi, ki — k
innermost, dq accumulates in VMEM) and a dk/dv kernel (grid bh, ki, qi — q
innermost, dk/dv accumulate in VMEM), each re-forming one (block_q, block_k)
probability tile at a time from the saved log-sum-exp. Memory stays
O(block²) end to end, which is what makes long-context *training* fit.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import LANES as _LANES
from .common import SUBLANES as _SUBLANES
from .common import attention_vmem_bytes, pad_to_multiple, vmem_usable_bytes
from .common import round_up as _round_up

__all__ = ["flash_attention", "select_attention_blocks"]


# ---------------------------------------------------------------------------
# block autotuning: VMEM-budget heuristic + optional one-shot on-device sweep
# (the footprint formula itself is the SHARED estimator in common.py —
# cross_entropy's clamp and zoolint's static ZL024 check price with the
# same function, property-tested in tests/test_pallas.py)
# ---------------------------------------------------------------------------

#: preferred default, swept on a v5e (causal, D=64, T=32k, fwd+bwd):
#: (256, 512) hit 29.3 TF/s vs 21.2 for (256, 256), 23.1 for (512, 512),
#: 24.4-24.9 for k-blocks of 1024/2048 — the larger k block amortizes the
#: per-k-step carry fold without outgrowing VMEM
_PREFERRED_BLOCKS = (256, 512)

#: abstract signature -> (block_q, block_k), resolved once per process
_BLOCK_CACHE: dict = {}

#: back-compat aliases — the estimator and budget constants moved to
#: ``common.py`` so the fused-CE clamp and the zoolint device pass share
#: one formula
_kernel_vmem_bytes = attention_vmem_bytes
from .common import VMEM_BYTES_DEFAULT as _VMEM_BYTES_DEFAULT  # noqa: E402
from .common import VMEM_USABLE_FRACTION as _VMEM_USABLE_FRACTION  # noqa: E402


def select_attention_blocks(t_q: int, t_kv: int, d: int, dtype,
                            causal: bool = False, has_mask: bool = False,
                            budget_bytes: Optional[int] = None):
    """VMEM-budget-aware (block_q, block_k): start from the swept
    ``(256, 512)`` sweet spot, clamp to the sequence lengths, then shrink
    the larger block until the kernel's estimated footprint fits the
    budget. Deterministic — a pure function of the abstract signature, so
    the jit cache is stable."""
    budget = budget_bytes if budget_bytes is not None else \
        vmem_usable_bytes()
    itemsize = jnp.dtype(dtype).itemsize
    bq, bk = _PREFERRED_BLOCKS
    bq = max(_SUBLANES, min(bq, _round_up(max(t_q, 1), _SUBLANES)))
    bk = max(_LANES, min(bk, _round_up(max(t_kv, 1), _LANES)))
    # every shrink step rounds DOWN to the tile floor — halving an
    # already-clamped odd block (bq 56 -> 28, or 200 -> 100) would hand
    # Mosaic an untileable pair on the default path every caller hits
    while (_kernel_vmem_bytes(bq, bk, d, itemsize, has_mask) > budget
           and (bq > _SUBLANES or bk > _LANES)):
        if bk >= 2 * bq and bk > _LANES:
            bk = max(_LANES, bk // 2 // _LANES * _LANES)
        elif bq > _SUBLANES:
            bq = max(_SUBLANES, bq // 2 // _SUBLANES * _SUBLANES)
        else:
            bk = max(_LANES, bk // 2 // _LANES * _LANES)
    return bq, bk


def _sweep_candidates(t_q: int, t_kv: int, d: int, itemsize: int,
                      has_mask: bool, heuristic):
    budget = vmem_usable_bytes()
    out = []
    for bq, bk in (heuristic, (256, 512), (128, 512), (256, 256),
                   (512, 512), (128, 1024)):
        # clamp to the sequence lengths WITH the tile rounding the kernel
        # needs (a raw min() against an unaligned T yields untileable
        # pairs like (128, 1000) that can only fail to compile)
        cand = (max(_SUBLANES, min(bq, _round_up(max(t_q, 1), _SUBLANES))),
                max(_LANES, min(bk, _round_up(max(t_kv, 1), _LANES))))
        if cand in out:
            continue
        if _kernel_vmem_bytes(*cand, d=d, itemsize=itemsize,
                              has_mask=has_mask) <= budget:
            out.append(cand)
    return out or [heuristic]


def _time_blocks(b, h, t_q, t_kv, d, dtype, causal, has_mask, block_q,
                 block_k, repeats: int = 2) -> float:
    """Best-of-``repeats`` wall seconds for one compiled fwd+bwd of the
    kernel at the given blocks, on synthetic on-device operands. Masked
    signatures time the MASKED kernel — the winner is cached per
    signature (has_mask included), so it must be measured on the kernel
    that signature will actually run."""
    import time

    import numpy as np
    rng = np.random.default_rng(0)
    q = jax.device_put(jnp.asarray(
        rng.normal(size=(b, h, t_q, d)).astype(np.float32), dtype))
    k = jax.device_put(jnp.asarray(
        rng.normal(size=(b, h, t_kv, d)).astype(np.float32), dtype))
    v = jax.device_put(jnp.asarray(
        rng.normal(size=(b, h, t_kv, d)).astype(np.float32), dtype))
    m = (jax.device_put(jnp.ones((b, t_kv), jnp.float32))
         if has_mask else None)

    def fwd_bwd(q, k, v):
        return jax.grad(lambda q: jnp.sum(
            _flash(q, k, v, m, causal, block_q, block_k, False)
            .astype(jnp.float32)))(q)

    fn = jax.jit(fwd_bwd)
    jax.block_until_ready(fn(q, k, v))      # compile + warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(q, k, v))
        best = min(best, time.perf_counter() - t0)
    return best


def _sweep_blocks(b, h, t_q, t_kv, d, dtype, causal, has_mask, heuristic,
                  timer=None):
    """``zoo.pallas.block_sweep``: one-shot on-device sweep over the
    candidate block pairs, winner cached per abstract signature. ``timer``
    is injectable for tests; the default times a real compiled fwd+bwd."""
    timer = timer or (lambda bq, bk: _time_blocks(
        b, h, t_q, t_kv, d, dtype, causal, has_mask, bq, bk))
    best, best_t = heuristic, float("inf")
    for bq, bk in _sweep_candidates(t_q, t_kv, d,
                                    jnp.dtype(dtype).itemsize, has_mask,
                                    heuristic):
        try:
            t = timer(bq, bk)
        # a candidate that fails to compile/run just loses the sweep
        except Exception:  # zoolint: disable=ZL007
            continue
        if t < best_t:
            best, best_t = (bq, bk), t
    return best


def _record_block_choice(sig: str, choice) -> None:
    try:
        from ...observability import default_registry
        # sig/choice are bounded by the distinct abstract kernel
        # signatures a process compiles (each also a jit cache entry)
        default_registry().gauge(  # zoolint: disable=ZL015 bounded label set
            "zoo_pallas_block_choice",
            "selected pallas kernel block sizes per abstract signature "
            "(1 = active choice)",
            labels={"kernel": "flash_attention", "sig": sig,
                    "choice": f"{choice[0]}x{choice[1]}"}).set(1)
    # metrics must never break the compute path
    except Exception:  # zoolint: disable=ZL007
        pass


def _auto_blocks(q_shape, t_kv: int, dtype, causal: bool, has_mask: bool,
                 interpret: bool):
    """Cached per-signature block choice: the VMEM heuristic, optionally
    refined by the one-shot on-device sweep (compiled TPU runs only — the
    interpreter's timings say nothing about the MXU). The heuristic is a
    pure function of (T, D, dtype, causal, mask), so its cache key drops
    batch/heads — a ragged final batch or an evaluate at a different B
    must not re-resolve (or worse, re-SWEEP: compiling and timing six
    candidates with live training state resident). Only sweep-timed
    entries key on the full shape, since wall time does scale with B·H."""
    b, h, t_q, d = q_shape
    dt = jnp.dtype(dtype)
    sweep = False
    try:
        from ...common.context import get_zoo_context
        sweep = bool(get_zoo_context().get("zoo.pallas.block_sweep", False))
    # no context constructible — the sweep stays off, heuristic holds
    except Exception:  # zoolint: disable=ZL007
        pass
    sweep = sweep and not interpret and jax.default_backend() == "tpu"
    # the live budget is part of the key — re-initializing the context
    # with zoo.pallas.vmem_budget_mb must take effect at the next call,
    # not silently keep blocks sized for the old budget
    budget = vmem_usable_bytes()
    base = (t_q, t_kv, d, dt.name, causal, has_mask)
    sig = (budget, "sweep", b, h) + base if sweep else (budget,) + base
    cached = _BLOCK_CACHE.get(sig)
    if cached is not None:
        return cached
    choice = select_attention_blocks(t_q, t_kv, d, dt, causal=causal,
                                     has_mask=has_mask,
                                     budget_bytes=budget)
    if sweep:
        choice = _sweep_blocks(b, h, t_q, t_kv, d, dt, causal, has_mask,
                               choice)
    _BLOCK_CACHE[sig] = choice
    # the metric label mirrors the cache key: heuristic entries apply to
    # EVERY batch/head shape at this (T, D, dtype) signature, so baking
    # the first caller's b/h into the label would misdescribe the scope
    _record_block_choice(
        (f"b{b}h{h}" if sweep else "")
        + f"tq{t_q}tk{t_kv}d{d}{dt.name}"
        f"{'c' if causal else ''}{'m' if has_mask else ''}", choice)
    return choice


def _visibility(qi, ki, s_shape, *, t_q, t_kv, offset, causal, mask_blk):
    """The (block_q, block_k) keep-mask of one probability tile: kv padding,
    causal alignment, and the optional key-padding mask row."""
    block_q, block_k = s_shape
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    ok = k_pos < t_kv
    if causal:
        ok = ok & (k_pos <= q_pos + offset)
    if mask_blk is not None:
        # keep-masks are a binary contract (1.0 = attend); >= 1.0 matches
        # the XLA oracle's additive -1e9*(1-mask) on stray soft values too
        # (anything < 1 is effectively hidden there)
        ok = ok & (mask_blk[None, :] >= 1.0)
    return ok


def _fwd_kernel(*refs, scale: float, block_q: int, block_k: int, t_q: int,
                t_kv: int, causal: bool, has_mask: bool, want_lse: bool):
    """Grid cell (bh, qi, ki). q (1, block_q, D); k/v (1, block_k, D);
    [mask (1, SUBLANES, block_k)]; o (1, block_q, D);
    lse (1, block_q, LANES); scratch acc (block_q, D), m/l (block_q, LANES).
    Row/key vectors carry 8-sublane/128-lane broadcast dims — TPU blocks
    need tileable trailing dims (the same layout jax's reference TPU flash
    kernel uses for segment ids and l/m)."""
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    mask_ref = refs[3] if has_mask else None
    rest = refs[3 + int(has_mask):]
    o_ref = rest[0]
    lse_ref = rest[1] if want_lse else None
    acc_ref, m_ref, l_ref = rest[-3:]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = t_kv - t_q  # bottom-right causal alignment

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: the first row of this q block sees keys up to
    # qi*block_q + offset; the last row up to (qi+1)*block_q - 1 + offset.
    # Blocks fully beyond the latter contribute nothing — skip their math.
    needed = True
    if causal:
        needed = ki * block_k <= (qi + 1) * block_q - 1 + offset

    @pl.when(needed)
    def _step():
        # operands stay in the input dtype (bf16 operands = full MXU rate);
        # the product accumulates f32 via preferred_element_type
        q = q_ref[0]
        s = jax.lax.dot_general(q, k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        ok = _visibility(qi, ki, (block_q, block_k), t_q=t_q, t_kv=t_kv,
                         offset=offset, causal=causal,
                         mask_blk=mask_ref[0, 0] if has_mask else None)
        s = jnp.where(ok, s, -jnp.inf)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.where(ok, jnp.exp(s - m_safe), 0.0)
        corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_safe))
        l_ref[:, :1] = l_ref[:, :1] * corr + jnp.sum(p, axis=-1,
                                                     keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, :1] = m_new

    @pl.when(ki == n_k - 1)
    def _finish():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        o_ref[0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
                    ).astype(o_ref.dtype)
        if want_lse:
            # rows with no visible key: +inf sentinel makes every backward
            # probability exp(s - inf) = 0, matching the zero forward output
            lse = jnp.where(l == 0.0, jnp.inf, m + jnp.log(jnp.where(
                l == 0.0, 1.0, l)))
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _prep(q, k, v, mask, block_q, block_k):
    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    # the short-sequence clamp must land back ON the tile floors: a raw
    # min() against an unaligned T (t_q=100 -> block_q=100) hands Mosaic
    # an untileable block on compiled TPU runs — the padding below
    # absorbs the round-up, and the kernels mask past t_q/t_kv
    block_q = _round_up(min(block_q, max(t_q, 1)), _SUBLANES)
    block_k = _round_up(min(block_k, max(t_kv, 1)), _LANES)
    qr = pad_to_multiple(q.reshape(b * h, t_q, d), 1, block_q)
    kr = pad_to_multiple(k.reshape(b * h, t_kv, d), 1, block_k)
    vr = pad_to_multiple(v.reshape(b * h, t_kv, d), 1, block_k)
    mr = None
    if mask is not None:
        mr = pad_to_multiple(mask.astype(jnp.float32), 1, block_k)
        mr = jnp.broadcast_to(mr[:, None, :],
                              (mr.shape[0], _SUBLANES, mr.shape[1]))
    return qr, kr, vr, mr, block_q, block_k


def _flash_fwd(q, k, v, mask, causal: bool, block_q: int, block_k: int,
               interpret: bool, want_lse: bool):
    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    scale = 1.0 / float(d) ** 0.5
    qr, kr, vr, mr, block_q, block_k = _prep(q, k, v, mask, block_q, block_k)
    n_q = qr.shape[1] // block_q
    n_k = kr.shape[1] // block_k
    has_mask = mr is not None

    kernel = functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                               block_k=block_k, t_q=t_q, t_kv=t_kv,
                               causal=causal, has_mask=has_mask,
                               want_lse=want_lse)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
    ]
    operands = [qr, kr, vr]
    if has_mask:
        in_specs.append(pl.BlockSpec(
            (1, _SUBLANES, block_k), lambda bh, qi, ki: (bh // h, 0, ki)))
        operands.append(mr)
    out_specs = [pl.BlockSpec((1, block_q, d),
                              lambda bh, qi, ki: (bh, qi, 0))]
    out_shape = [jax.ShapeDtypeStruct(qr.shape, q.dtype)]
    if want_lse:
        # inference/primal calls skip the lse output entirely — pallas
        # outputs are opaque to XLA DCE, so an unconditional write would
        # cost real HBM traffic on every no-grad forward
        out_specs.append(pl.BlockSpec((1, block_q, _LANES),
                                      lambda bh, qi, ki: (bh, qi, 0)))
        out_shape.append(jax.ShapeDtypeStruct(
            (qr.shape[0], qr.shape[1], _LANES), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),       # acc
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
        ],
        interpret=interpret,
    )(*operands)
    out = res[0]  # out_shape is a list either way
    o = out[:, :t_q, :].reshape(b, h, t_q, d)
    return (o, res[1]) if want_lse else o


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(*refs, scale: float, block_q: int, block_k: int,
                   t_q: int, t_kv: int, causal: bool, has_mask: bool):
    """Grid (bh, qi, ki), k innermost: dq accumulates over k blocks."""
    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, mask_ref, dq_ref,
         acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, acc_ref = refs
        mask_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    offset = t_kv - t_q

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    needed = True
    if causal:
        needed = ki * block_k <= (qi + 1) * block_q - 1 + offset

    @pl.when(needed)
    def _step():
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        ok = _visibility(qi, ki, (block_q, block_k), t_q=t_q, t_kv=t_kv,
                         offset=offset, causal=causal,
                         mask_blk=mask_ref[0, 0] if has_mask else None)
        lse = lse_ref[0, :, :1]
        p = jnp.where(ok, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, :, :1])
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale: float, block_q: int, block_k: int,
                    t_q: int, t_kv: int, causal: bool, has_mask: bool):
    """Grid (bh, ki, qi), q innermost: dk/dv accumulate over q blocks."""
    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, mask_ref, dk_ref,
         dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref,
         dk_acc, dv_acc) = refs
        mask_ref = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)
    offset = t_kv - t_q

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    needed = True
    if causal:
        needed = ki * block_k <= (qi + 1) * block_q - 1 + offset

    @pl.when(needed)
    def _step():
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        ok = _visibility(qi, ki, (block_q, block_k), t_q=t_q, t_kv=t_kv,
                         offset=offset, causal=causal,
                         mask_blk=mask_ref[0, 0] if has_mask else None)
        lse = lse_ref[0, :, :1]
        p = jnp.where(ok, jnp.exp(s - lse), 0.0)
        do = do_ref[0]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, :, :1])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, mask, out, lse, g, causal, block_q, block_k,
               interpret):
    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    scale = 1.0 / float(d) ** 0.5
    qr, kr, vr, mr, block_q, block_k = _prep(q, k, v, mask, block_q, block_k)
    gr = pad_to_multiple(g.reshape(b * h, t_q, d), 1, block_q)
    orr = pad_to_multiple(out.reshape(b * h, t_q, d), 1, block_q)
    # delta_i = sum_d dO_id * O_id — rowwise, cheap in XLA (no (T,T) tensor)
    delta = jnp.sum(gr.astype(jnp.float32) * orr.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, :, None],
                             (*delta.shape, _LANES))
    n_q = qr.shape[1] // block_q
    n_k = kr.shape[1] // block_k
    has_mask = mr is not None

    qspec = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0))
    rowspec = pl.BlockSpec((1, block_q, _LANES),
                           lambda bh, qi, ki: (bh, qi, 0))
    mspec = pl.BlockSpec((1, _SUBLANES, block_k),
                         lambda bh, qi, ki: (bh // h, 0, ki))
    operands = [qr, kr, vr, gr, lse, delta] + ([mr] if has_mask else [])

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, t_q=t_q, t_kv=t_kv, causal=causal,
                          has_mask=has_mask),
        grid=(b * h, n_q, n_k),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec]
                 + ([mspec] if has_mask else []),
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(qr.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*operands)

    # dk/dv grid: (bh, ki, qi) — remap the spec index args accordingly
    qspec2 = pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0))
    kspec2 = pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0))
    rowspec2 = pl.BlockSpec((1, block_q, _LANES),
                            lambda bh, ki, qi: (bh, qi, 0))
    mspec2 = pl.BlockSpec((1, _SUBLANES, block_k),
                          lambda bh, ki, qi: (bh // h, 0, ki))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, t_q=t_q, t_kv=t_kv, causal=causal,
                          has_mask=has_mask),
        grid=(b * h, n_k, n_q),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2]
                 + ([mspec2] if has_mask else []),
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct(kr.shape, k.dtype),
                   jax.ShapeDtypeStruct(vr.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(*operands)

    dq = dq[:, :t_q, :].reshape(b, h, t_q, d)
    dk = dk[:, :t_kv, :].reshape(b, h, t_kv, d)
    dv = dv[:, :t_kv, :].reshape(b, h, t_kv, d)
    dmask = None if mask is None else jnp.zeros_like(mask,
                                                     dtype=jnp.float32)
    return dq, dk, dv, dmask


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, mask, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, mask, causal, block_q, block_k, interpret,
                      want_lse=False)


def _vjp_fwd(q, k, v, mask, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, mask, causal, block_q, block_k, interpret,
                          want_lse=True)
    return out, (q, k, v, mask, out, lse)


def _vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, mask, out, lse = res
    return _flash_bwd(q, k, v, mask, out, lse, g, causal, block_q, block_k,
                      interpret)


_flash.defvjp(_vjp_fwd, _vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    mask: Optional[jax.Array] = None,
                    causal: bool = False, block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Blockwise-softmax attention: q/k/v (B, H, T, D) → (B, H, Tq, D).

    ``mask``: optional per-batch key-padding keep-mask, (B, Tk), a BINARY
    contract: values >= 1.0 attend, anything below is hidden — matching the
    XLA oracle's additive ``-1e9*(1-mask)`` on stray soft values (the BERT
    ``attention_mask``; full (B, H, Tq, Tk) masks stay on the XLA op). Numerically equivalent to
    ``ops.attention.dot_product_attention`` (minus dropout — that path
    stays on the XLA op). Forward and backward are both Pallas kernels with
    O(block²) memory; gradients flow to q/k/v (the mask gets zeros).
    ``interpret`` defaults to auto: compiled on TPU, interpreter elsewhere
    (tests).

    ``block_q``/``block_k`` default to auto selection
    (``select_attention_blocks``): the VMEM-budget-aware heuristic around
    the swept v5e sweet spot (256, 512) — which hit 29.3 TF/s vs 21.2 for
    (256, 256), 23.1 for (512, 512), 24.4-24.9 for k-blocks of 1024/2048
    at causal D=64 T=32k fwd+bwd — shrunk when the abstract signature
    (T, D, dtype, mask) would outgrow VMEM. ``zoo.pallas.block_sweep``
    refines the heuristic with a one-shot on-device sweep, cached per
    signature and surfaced as ``zoo_pallas_block_choice`` info metrics.
    Explicit ints pin the blocks (tests, reproductions)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if mask is not None:
        if isinstance(mask, bool):
            raise TypeError("flash_attention's 4th argument is now the "
                            "key-padding mask; pass causal=... by keyword")
        if mask.ndim != 2:
            raise ValueError(f"flash_attention mask must be (B, Tk); got "
                             f"shape {mask.shape} — reduce broadcast masks "
                             f"at the layer level")
        mask = jax.lax.stop_gradient(mask.astype(jnp.float32))
    if block_q is None or block_k is None:
        abq, abk = _auto_blocks(q.shape, k.shape[2], q.dtype, causal,
                                mask is not None, interpret)
        block_q = block_q if block_q is not None else abq
        block_k = block_k if block_k is not None else abk
    return _flash(q, k, v, mask, causal, block_q, block_k, interpret)
