"""AnomalyDetector — LSTM time-series anomaly detection, parity with
``models/anomalydetection/AnomalyDetector.scala:40,65`` (pyzoo
``models/anomalydetection/anomaly_detector.py:30``).

Stacked return-sequence LSTMs + dropouts, final LSTM + Dense(1) regressor;
anomalies = the top-N absolute prediction errors (``detectAnomalies``).
``unroll`` converts a 1-D/2-D series into (windows, unroll_length, features)
training tensors, the ``FeatureLabelIndex`` role.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ...pipeline.api.keras.engine import Sequential
from ...pipeline.api.keras.layers import LSTM, Dense, Dropout
from ..common.zoo_model import ZooModel, register_model


@register_model
class AnomalyDetector(ZooModel):
    """``AnomalyDetector(featureShape, hiddenLayers, dropouts)``."""

    def __init__(self, feature_shape: Sequence[int],
                 hidden_layers: Sequence[int] = (8, 32, 15),
                 dropouts: Sequence[float] = (0.2, 0.2, 0.2),
                 name: Optional[str] = None):
        if len(hidden_layers) != len(dropouts):
            raise ValueError("hidden_layers and dropouts must align")
        self.feature_shape = tuple(int(d) for d in feature_shape)
        self.hidden_layers = tuple(int(h) for h in hidden_layers)
        self.dropouts = tuple(float(d) for d in dropouts)
        super().__init__(name=name)

    def build_model(self) -> Sequential:
        m = Sequential()
        first = True
        # all but the last hidden layer return sequences
        for units, drop in zip(self.hidden_layers[:-1], self.dropouts[:-1]):
            m.add(LSTM(units, return_sequences=True,
                       **({"input_shape": self.feature_shape} if first else {})))
            m.add(Dropout(drop))
            first = False
        m.add(LSTM(self.hidden_layers[-1], return_sequences=False,
                   **({"input_shape": self.feature_shape} if first else {})))
        m.add(Dropout(self.dropouts[-1]))
        m.add(Dense(1))
        return m

    def get_config(self) -> Dict[str, Any]:
        return {"feature_shape": list(self.feature_shape),
                "hidden_layers": list(self.hidden_layers),
                "dropouts": list(self.dropouts)}


def unroll(data: np.ndarray, unroll_length: int,
           predict_step: int = 1) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Windowize a series — ``AnomalyDetector.unroll`` / ``FeatureLabelIndex``:
    returns (features (N, unroll_length, D), labels (N,), indices (N,)).
    The label is the first feature dimension ``predict_step`` after the
    window, i.e. next-value prediction."""
    data = np.asarray(data, np.float32)
    if data.ndim == 1:
        data = data[:, None]
    n = data.shape[0] - unroll_length - predict_step + 1
    if n <= 0:
        raise ValueError("series too short for the requested unroll_length")
    idx = np.arange(unroll_length)[None, :] + np.arange(n)[:, None]
    x = data[idx]
    y = data[np.arange(n) + unroll_length + predict_step - 1, 0]
    return x, y, np.arange(n)


def detect_anomalies(y_truth: np.ndarray, y_predict: np.ndarray,
                     anomaly_size: int = 5) -> np.ndarray:
    """``detectAnomalies``: rank |truth - prediction|; the ``anomaly_size``
    most distant points are anomalies. Returns a float array shaped like
    ``y_truth`` holding the anomalous truth values and NaN elsewhere."""
    t = np.asarray(y_truth, np.float32).reshape(-1)
    p = np.asarray(y_predict, np.float32).reshape(-1)
    dist = np.abs(t - p)
    thresh_idx = np.argsort(-dist)[:anomaly_size]
    out = np.full_like(t, np.nan)
    out[thresh_idx] = t[thresh_idx]
    return out
