from .image_model import ImageModel

__all__ = ["ImageModel"]
