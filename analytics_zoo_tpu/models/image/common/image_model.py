"""ImageModel base — parity with ``models/image/common/ImageModel.scala:116``:
a ZooModel that carries an attached preprocessing chain and predicts straight
from an ``ImageSet``."""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ....feature.common import Preprocessing
from ....feature.image import ImageSet
from ...common.zoo_model import ZooModel

__all__ = ["ImageModel"]


class ImageModel(ZooModel):
    """Base for vision zoo models. ``config`` attaches the preprocessing the
    published topology expects (``ImageConfig``/``ImageClassificationConfig``
    role, ``ImageClassificationConfig.scala:34-51``)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(name=name)
        self.preprocessing: Optional[Preprocessing] = None

    def set_preprocessing(self, preprocessing: Preprocessing) -> "ImageModel":
        self.preprocessing = preprocessing
        return self

    def predict_image_set(self, image_set: ImageSet, batch_size: int = 32
                          ) -> np.ndarray:
        """``predictImageSet`` (``ImageModel.scala:40-70``): apply the
        attached preprocessing, then the sharded predict path."""
        if self.preprocessing is not None:
            image_set = image_set.transform(self.preprocessing)
        return self.predict(image_set.to_array(), batch_size=batch_size)

    def predict_classes_image_set(self, image_set: ImageSet,
                                  batch_size: int = 32) -> np.ndarray:
        from ....utils.prediction import probs_to_classes
        return probs_to_classes(
            self.predict_image_set(image_set, batch_size=batch_size))
