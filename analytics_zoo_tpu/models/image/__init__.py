"""Vision model zoo (``models/image`` of the reference, L5)."""

from .common.image_model import ImageModel
from .imageclassification.image_classifier import ImageClassifier, inception_v1

__all__ = ["ImageModel", "ImageClassifier", "inception_v1"]
