"""SSD topologies — parity with ``objectdetection/ssd/SSD.scala`` (SSDVGG:
VGG16 backbone with a conv4_3 L2-norm+scale feature, atrous fc6/fc7, extra
feature layers, shared-location multibox heads) built natively with the
NHWC Keras-style graph API.

The model outputs ONE tensor ``(B, n_priors, 4 + num_classes)`` —
loc offsets concatenated with class logits — which
:class:`~.multibox_loss.MultiBoxLoss` consumes directly and
``ObjectDetector`` post-processes with ``batched_detection_output``. (The
reference wires loc/conf/priors as a 3-output graph into a JVM-side
DetectionOutput module; a single fused tensor keeps the whole step one XLA
program.)

``ssd_lite`` is a small 2-feature-map variant of the same head structure
for tests and small datasets (the reference's test fixtures play this
role).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ....pipeline.api.keras.engine import Input, KerasNet, Lambda, Model
from ....pipeline.api.keras.layers import (Convolution2D, L2Normalize,
                                           MaxPooling2D, Scale, merge)
from .priors import PriorBox, SSD300_PASCAL_SIZES, ssd_priors

__all__ = ["ssd_vgg", "ssd_lite"]


def _conv(x, nf, k, name, stride=(1, 1), border="same", activation="relu",
          dilation=None):
    if dilation:
        from ....pipeline.api.keras.layers import AtrousConvolution2D
        return AtrousConvolution2D(nf, k, k, atrous_rate=(dilation, dilation),
                                   activation=activation, border_mode=border,
                                   name=name)(x)
    return Convolution2D(nf, k, k, subsample=stride, activation=activation,
                         border_mode=border, name=name)(x)


def _heads(features, num_priors_per_map: Sequence[int], num_classes: int):
    """Shared-location loc/conf conv heads; returns the fused
    (B, n_priors, 4+C) output node."""
    locs, confs = [], []
    for i, (feat, k) in enumerate(zip(features, num_priors_per_map)):
        loc = Convolution2D(k * 4, 3, 3, border_mode="same",
                            name=f"mbox{i}_loc")(feat)
        conf = Convolution2D(k * num_classes, 3, 3, border_mode="same",
                             name=f"mbox{i}_conf")(feat)
        locs.append(Lambda(lambda t: t.reshape(t.shape[0], -1, 4),
                           name=f"mbox{i}_loc_flat")(loc))
        confs.append(Lambda(
            lambda t, c=num_classes: t.reshape(t.shape[0], -1, c),
            name=f"mbox{i}_conf_flat")(conf))
    loc_all = (merge(locs, "concat", concat_axis=1, name="mbox_loc")
               if len(locs) > 1 else locs[0])
    conf_all = (merge(confs, "concat", concat_axis=1, name="mbox_conf")
                if len(confs) > 1 else confs[0])
    return merge([loc_all, conf_all], "concat", concat_axis=2, name="mbox")


def ssd_vgg(num_classes: int, resolution: int = 300,
            sizes: Sequence[float] = SSD300_PASCAL_SIZES
            ) -> Tuple[KerasNet, np.ndarray]:
    """SSD300-VGG16 (``SSDVGG.build``, pascal config). Returns
    ``(model, priors)`` — priors are the host-side constant the loss and
    postprocessor close over."""
    if resolution != 300:
        raise ValueError("only the 300x300 config is built in; pass a "
                         "custom topology for 512")
    inp = Input(shape=(resolution, resolution, 3), name="image")
    x = _conv(inp, 64, 3, "conv1_1")
    x = _conv(x, 64, 3, "conv1_2")
    x = MaxPooling2D((2, 2), name="pool1")(x)
    x = _conv(x, 128, 3, "conv2_1")
    x = _conv(x, 128, 3, "conv2_2")
    x = MaxPooling2D((2, 2), name="pool2")(x)
    x = _conv(x, 256, 3, "conv3_1")
    x = _conv(x, 256, 3, "conv3_2")
    x = _conv(x, 256, 3, "conv3_3")
    x = MaxPooling2D((2, 2), border_mode="same", name="pool3")(x)  # 38
    x = _conv(x, 512, 3, "conv4_1")
    x = _conv(x, 512, 3, "conv4_2")
    conv4_3 = _conv(x, 512, 3, "conv4_3")
    # conv4_3 feature: channelwise L2 normalize + learned scale (init 20)
    f0 = L2Normalize(axis=-1, name="conv4_3_norm")(conv4_3)
    f0 = Scale((512,), init_weight=20.0, name="conv4_3_scale")(f0)
    x = MaxPooling2D((2, 2), name="pool4")(conv4_3)  # 19
    x = _conv(x, 512, 3, "conv5_1")
    x = _conv(x, 512, 3, "conv5_2")
    x = _conv(x, 512, 3, "conv5_3")
    x = MaxPooling2D((3, 3), strides=(1, 1), border_mode="same",
                     name="pool5")(x)
    x = _conv(x, 1024, 3, "fc6", dilation=6)         # atrous fc6
    f1 = _conv(x, 1024, 1, "fc7")                    # 19
    x = _conv(f1, 256, 1, "conv6_1")
    f2 = _conv(x, 512, 3, "conv6_2", stride=(2, 2))  # 10
    x = _conv(f2, 128, 1, "conv7_1")
    f3 = _conv(x, 256, 3, "conv7_2", stride=(2, 2))  # 5
    x = _conv(f3, 128, 1, "conv8_1")
    f4 = _conv(x, 256, 3, "conv8_2", border="valid")  # 3
    x = _conv(f4, 128, 1, "conv9_1")
    f5 = _conv(x, 256, 3, "conv9_2", border="valid")  # 1

    features = [f0, f1, f2, f3, f4, f5]
    feat_shapes = [(38, 38), (19, 19), (10, 10), (5, 5), (3, 3), (1, 1)]
    s = list(sizes)
    prior_specs = [
        PriorBox(s[0], s[1], aspect_ratios=(2.0,)),
        PriorBox(s[1], s[2], aspect_ratios=(2.0, 3.0)),
        PriorBox(s[2], s[3], aspect_ratios=(2.0, 3.0)),
        PriorBox(s[3], s[4], aspect_ratios=(2.0, 3.0)),
        PriorBox(s[4], s[5], aspect_ratios=(2.0,)),
        PriorBox(s[5], s[6], aspect_ratios=(2.0,)),
    ]
    out = _heads(features, [p.num_priors for p in prior_specs], num_classes)
    priors = ssd_priors(feat_shapes, prior_specs, float(resolution))
    return Model(input=inp, output=out), priors


def ssd_lite(num_classes: int, resolution: int = 64,
             base_filters: int = 16) -> Tuple[KerasNet, np.ndarray]:
    """Small SSD with the same head/prior/loss structure: conv stack to two
    feature maps (res/8 and res/16)."""
    inp = Input(shape=(resolution, resolution, 3), name="image")
    x = _conv(inp, base_filters, 3, "c1")
    x = MaxPooling2D((2, 2), name="p1")(x)
    x = _conv(x, base_filters * 2, 3, "c2")
    x = MaxPooling2D((2, 2), name="p2")(x)
    x = _conv(x, base_filters * 4, 3, "c3")
    f0 = MaxPooling2D((2, 2), name="p3")(x)          # res/8
    f1 = _conv(f0, base_filters * 8, 3, "c4", stride=(2, 2))  # res/16

    g0, g1 = resolution // 8, resolution // 16
    prior_specs = [
        PriorBox(resolution * 0.2, resolution * 0.4, aspect_ratios=(2.0,)),
        PriorBox(resolution * 0.5, resolution * 0.8, aspect_ratios=(2.0,)),
    ]
    out = _heads([f0, f1], [p.num_priors for p in prior_specs], num_classes)
    priors = ssd_priors([(g0, g0), (g1, g1)], prior_specs, float(resolution))
    return Model(input=inp, output=out), priors
