"""MultiBoxLoss — SSD training objective (parity with
``objectdetection/common/loss/MultiBoxLoss.scala``: prior↔gt matching with
a forced best-prior-per-gt assignment, smooth-L1 localization loss on
encoded offsets, softmax confidence loss with 3:1 hard negative mining,
normalized by the positive count).

TPU-first: the whole loss — matching included — is one jittable function
over fixed shapes. Ground truth arrives as a padded ``(B, max_gt, 5)``
tensor ``[label, x1, y1, x2, y2]`` with label ``-1`` marking padding (the
reference instead carries ragged per-image tables through the JVM; padding
+ masking is the XLA-native equivalent). Hard negative mining uses a
rank-vs-threshold mask instead of sort-and-slice so shapes stay static.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .bbox import bbox_iou, encode_boxes

__all__ = ["MultiBoxLoss", "match_priors"]


def match_priors(gt: jnp.ndarray, priors: jnp.ndarray,
                 iou_threshold: float = 0.5
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One image. gt: (max_gt, 5) padded with label -1; priors (P, 4).

    Returns (matched_gt_idx (P,), positive mask (P,)):
    * a prior is positive when its best gt IoU > threshold, OR when it is
      the single best prior for some valid gt (the forced assignment that
      guarantees every object gets at least one prior);
    * matched_gt_idx points each prior at its assigned gt row.
    """
    valid = gt[:, 0] >= 0  # (G,)
    iou = bbox_iou(priors, gt[:, 1:5]) * valid[None, :]  # (P, G)
    best_gt = jnp.argmax(iou, axis=1)                    # (P,)
    best_gt_iou = jnp.max(iou, axis=1)
    # forced: for each valid gt g, its argmax prior is matched to g.
    # Padding rows scatter to an out-of-range index and are dropped.
    best_prior = jnp.argmax(iou, axis=0)                 # (G,)
    scatter_to = jnp.where(valid, best_prior, priors.shape[0])
    forced = jnp.zeros(priors.shape[0], bool).at[scatter_to].set(
        True, mode="drop")
    gt_idx = best_gt.at[scatter_to].set(jnp.arange(gt.shape[0]), mode="drop")
    pos = (best_gt_iou > iou_threshold) | forced
    return gt_idx, pos


def _smooth_l1(x):
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


class MultiBoxLoss:
    """Callable loss for ``compile(loss=MultiBoxLoss(...))``. The model
    output is the concatenated ``(B, P, 4 + num_classes)`` loc‖conf-logits
    tensor; targets are padded ``(B, max_gt, 5)`` boxes."""

    def __init__(self, num_classes: int, priors: np.ndarray,
                 iou_threshold: float = 0.5, neg_pos_ratio: float = 3.0,
                 bg_label: int = 0,
                 variances=(0.1, 0.1, 0.2, 0.2)):
        self.num_classes = int(num_classes)
        self.priors = jnp.asarray(priors, jnp.float32)
        self.iou_threshold = float(iou_threshold)
        self.neg_pos_ratio = float(neg_pos_ratio)
        self.bg_label = int(bg_label)
        self.variances = tuple(variances)
        self.__name__ = "multibox_loss"

    def __call__(self, y_true, y_pred):
        gt = jnp.asarray(y_true, jnp.float32)        # (B, G, 5)
        loc = y_pred[..., :4]                        # (B, P, 4)
        logits = y_pred[..., 4:]                     # (B, P, C)

        def one(gt_i, loc_i, logits_i):
            gt_idx, pos = match_priors(gt_i, self.priors, self.iou_threshold)
            npos = jnp.sum(pos.astype(jnp.float32))

            # localization: smooth-L1 on encoded offsets, positives only
            target = encode_boxes(gt_i[gt_idx, 1:5], self.priors,
                                  self.variances)
            loc_loss = jnp.sum(_smooth_l1(loc_i - target).sum(-1) * pos)

            # confidence: CE against matched label (bg for negatives)
            labels = jnp.where(pos, gt_i[gt_idx, 0].astype(jnp.int32),
                               self.bg_label)
            # detection class head (~21 classes); the per-prior CE
            # vector is reused below for hard-negative mining, so the
            # log-probs must materialize regardless
            logp = jax.nn.log_softmax(logits_i, axis=-1)  # zoolint: disable=ZL012 small class head; CE reused for mining
            ce = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]

            # hard negative mining: top (ratio * npos) negatives by CE
            neg_ce = jnp.where(pos, -jnp.inf, ce)
            order = jnp.argsort(-neg_ce)
            rank = jnp.argsort(order)  # rank[i] = position of prior i
            n_neg = jnp.minimum(self.neg_pos_ratio * npos,
                                jnp.sum(~pos).astype(jnp.float32))
            neg = (rank < n_neg) & ~pos
            conf_loss = jnp.sum(ce * pos) + jnp.sum(ce * neg)
            return (loc_loss + conf_loss) / jnp.maximum(npos, 1.0)

        return jnp.mean(jax.vmap(one)(gt, loc, logits))
