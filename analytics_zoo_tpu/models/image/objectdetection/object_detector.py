"""ObjectDetector — zoo-model wrapper for SSD detection (parity with
``objectdetection/ObjectDetector.scala`` + ``Postprocessor.scala``:
model forward → decode → per-class NMS → keep-topk, plus save/load through
the ZooModel registry).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from ...common.zoo_model import ZooModel, register_model
from .bbox import batched_detection_output
from .multibox_loss import MultiBoxLoss
from .ssd import ssd_lite, ssd_vgg

__all__ = ["ObjectDetector", "DetectionOutputParam"]


@dataclass
class DetectionOutputParam:
    """``DetectionOutputParam`` (``Postprocessor.scala``) — postprocess
    knobs."""
    nms_thresh: float = 0.45
    nms_topk: int = 400
    keep_topk: int = 200
    conf_thresh: float = 0.01
    bg_label: int = 0


_TOPOLOGIES = {"ssd-vgg16-300": ssd_vgg, "ssd-lite": ssd_lite}


@register_model
class ObjectDetector(ZooModel):
    """``ObjectDetector(model_name, num_classes)``. Class 0 is background
    (``bgLabel=0``, ``SSD.scala``). ``detect`` returns a fixed
    ``(B, keep_topk, 6)`` table ``[label, score, x1, y1, x2, y2]`` with
    label ``-1`` padding."""

    def __init__(self, model_name: str = "ssd-lite", num_classes: int = 21,
                 resolution: Optional[int] = None,
                 post_param: Optional[DetectionOutputParam] = None,
                 name: Optional[str] = None):
        if model_name not in _TOPOLOGIES:
            raise ValueError(f"unknown topology {model_name!r}; "
                             f"available: {sorted(_TOPOLOGIES)}")
        self.model_name = model_name
        self.num_classes = int(num_classes)
        self.resolution = int(resolution) if resolution else (
            300 if model_name == "ssd-vgg16-300" else 64)
        self.post_param = post_param or DetectionOutputParam()
        self.priors: Optional[np.ndarray] = None
        super().__init__(name=name)

    def build_model(self):
        net, priors = _TOPOLOGIES[self.model_name](
            num_classes=self.num_classes, resolution=self.resolution)
        self.priors = priors
        return net

    def get_config(self) -> Dict[str, Any]:
        p = self.post_param
        return {"model_name": self.model_name,
                "num_classes": self.num_classes,
                "resolution": self.resolution,
                "post_param": {"nms_thresh": p.nms_thresh,
                               "nms_topk": p.nms_topk,
                               "keep_topk": p.keep_topk,
                               "conf_thresh": p.conf_thresh,
                               "bg_label": p.bg_label}}

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "ObjectDetector":
        cfg = dict(config)
        pp = cfg.pop("post_param", None)
        if pp is not None:
            cfg["post_param"] = DetectionOutputParam(**pp)
        return cls(**cfg)

    def multibox_loss(self, **kw) -> MultiBoxLoss:
        """The matching loss bound to this model's priors — pass to
        ``compile(loss=...)``."""
        if self.priors is None:  # build_model always ran in __init__
            raise RuntimeError("model priors missing — build_model() did "
                               "not run")
        return MultiBoxLoss(self.num_classes, self.priors,
                            bg_label=self.post_param.bg_label, **kw)

    def decode(self, raw: np.ndarray,
               conf_thresh: Optional[float] = None) -> np.ndarray:
        """Raw scores (B, priors, 4 + classes) → detections
        (B, keep_topk, 6). The decode half of :meth:`detect`, exposed so
        out-of-process consumers (Cluster Serving clients streaming raw
        scores) run the identical post-processing."""
        raw = np.asarray(raw)
        if raw.ndim == 2:
            raw = raw[None]
        loc, conf = raw[..., :4], raw[..., 4:]
        import jax
        probs = np.asarray(jax.nn.softmax(conf, axis=-1))
        p = self.post_param
        return np.asarray(batched_detection_output(
            loc, probs, self.priors, num_classes=self.num_classes,
            conf_thresh=(p.conf_thresh if conf_thresh is None
                         else conf_thresh),
            nms_thresh=p.nms_thresh, nms_topk=p.nms_topk,
            keep_topk=p.keep_topk, bg_label=p.bg_label))

    def detect(self, images: np.ndarray, batch_size: int = 32,
               conf_thresh: Optional[float] = None) -> np.ndarray:
        """Images (B, H, W, 3) → detections (B, keep_topk, 6)."""
        raw = self.predict(images, batch_size=batch_size)
        return self.decode(raw, conf_thresh=conf_thresh)
