"""SSD prior (anchor) boxes — parity with the Caffe-SSD ``PriorBox``
conventions the reference wires up in ``ssd/SSD.scala`` (min/max sizes per
feature map, aspect ratios with flip, offset 0.5, variances
(0.1, 0.1, 0.2, 0.2), optional clip).

Priors are data-independent, so they're generated once on the host in
numpy at model-build time and baked into the jitted loss/postprocess as a
constant — XLA treats them as weights resident in HBM.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PriorBox", "ssd_priors", "SSD300_PASCAL_SIZES"]

# min/max size division boundaries for 300x300 pascal (SSD.scala:116)
SSD300_PASCAL_SIZES = (30.0, 60.0, 111.0, 162.0, 213.0, 264.0, 315.0)


class PriorBox:
    """Priors for ONE feature map."""

    def __init__(self, min_size: float, max_size: Optional[float] = None,
                 aspect_ratios: Sequence[float] = (2.0,), flip: bool = True,
                 clip: bool = False, step: Optional[float] = None,
                 offset: float = 0.5,
                 variances: Tuple[float, ...] = (0.1, 0.1, 0.2, 0.2)):
        self.min_size = float(min_size)
        self.max_size = None if max_size is None else float(max_size)
        ars = [1.0]
        for ar in aspect_ratios:
            if any(abs(ar - a) < 1e-6 for a in ars):
                continue
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
        self.aspect_ratios = ars
        self.clip = clip
        self.step = step
        self.offset = offset
        self.variances = tuple(variances)

    @property
    def num_priors(self) -> int:
        # one per aspect ratio + the sqrt(min*max) box when max_size is set
        return len(self.aspect_ratios) + (1 if self.max_size else 0)

    def generate(self, feat_h: int, feat_w: int,
                 img_size: float) -> np.ndarray:
        """(feat_h * feat_w * num_priors, 4) corner-form normalized."""
        step_w = self.step or img_size / feat_w
        step_h = self.step or img_size / feat_h
        whs: List[Tuple[float, float]] = []
        s = self.min_size
        whs.append((s, s))
        if self.max_size:
            sp = math.sqrt(s * self.max_size)
            whs.append((sp, sp))
        for ar in self.aspect_ratios:
            if abs(ar - 1.0) < 1e-6:
                continue
            whs.append((s * math.sqrt(ar), s / math.sqrt(ar)))
        whs_a = np.asarray(whs, np.float32)  # (K, 2) in pixels

        xs = (np.arange(feat_w, dtype=np.float32) + self.offset) * step_w
        ys = (np.arange(feat_h, dtype=np.float32) + self.offset) * step_h
        cx, cy = np.meshgrid(xs, ys)  # (H, W)
        centers = np.stack([cx, cy], axis=-1).reshape(-1, 1, 2)  # (HW, 1, 2)
        half = whs_a[None, :, :] * 0.5
        boxes = np.concatenate([centers - half, centers + half], axis=-1)
        boxes = boxes.reshape(-1, 4) / img_size
        if self.clip:
            boxes = np.clip(boxes, 0.0, 1.0)
        return boxes.astype(np.float32)


def ssd_priors(feature_shapes: Sequence[Tuple[int, int]],
               prior_boxes: Sequence[PriorBox],
               img_size: float) -> np.ndarray:
    """Stack per-feature-map priors in head order → (n_priors_total, 4)."""
    if len(feature_shapes) != len(prior_boxes):
        raise ValueError(f"{len(feature_shapes)} feature maps vs "
                         f"{len(prior_boxes)} PriorBox specs")
    return np.concatenate([pb.generate(h, w, img_size)
                           for (h, w), pb in zip(feature_shapes, prior_boxes)],
                          axis=0)
