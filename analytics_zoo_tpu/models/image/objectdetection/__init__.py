from .bbox import (bbox_iou, decode_boxes, encode_boxes, nms_mask,  # noqa: F401
                   batched_detection_output)
from .priors import PriorBox, ssd_priors  # noqa: F401
from .multibox_loss import MultiBoxLoss  # noqa: F401
from .ssd import ssd_vgg, ssd_lite  # noqa: F401
from .object_detector import ObjectDetector, DetectionOutputParam  # noqa: F401
from .evaluation import MeanAveragePrecision, average_precision  # noqa: F401
