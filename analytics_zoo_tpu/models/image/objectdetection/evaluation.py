"""Detection evaluation — Pascal-VOC mean average precision (parity with
``objectdetection/common/evaluation/MeanAveragePrecision.scala`` +
``EvalUtil``/``PascalVocEvaluator``: greedy score-ordered matching at IoU
0.5, optional VOC-2007 11-point interpolation, per-class AP then mean over
non-background classes).

Host-side numpy: evaluation is a once-per-epoch ragged reduction — the
wrong shape for the accelerator, the right shape for the host.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["average_precision", "MeanAveragePrecision"]


def _voc_ap(recall: np.ndarray, precision: np.ndarray,
            use_07_metric: bool = False) -> float:
    if use_07_metric:  # 11-point interpolation
        ap = 0.0
        for t in np.arange(0.0, 1.01, 0.1):
            p = precision[recall >= t].max() if np.any(recall >= t) else 0.0
            ap += p / 11.0
        return float(ap)
    # integral AP: precision envelope over recall steps
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(mpre.size - 1, 0, -1):
        mpre[i - 1] = max(mpre[i - 1], mpre[i])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def average_precision(scores: np.ndarray, tp: np.ndarray, n_gt: int,
                      use_07_metric: bool = False) -> float:
    """AP from per-detection (score, is-true-positive) pairs for one
    class. ``n_gt`` is the number of ground-truth boxes of that class."""
    if n_gt == 0:
        return 0.0
    order = np.argsort(-np.asarray(scores))
    tp_s = np.asarray(tp, np.float64)[order]
    fp_s = 1.0 - tp_s
    tp_cum = np.cumsum(tp_s)
    fp_cum = np.cumsum(fp_s)
    recall = tp_cum / n_gt
    precision = tp_cum / np.maximum(tp_cum + fp_cum, 1e-12)
    return _voc_ap(recall, precision, use_07_metric)


def _match_detections(dets: np.ndarray, gts: np.ndarray,
                      iou_thresh: float) -> np.ndarray:
    """Greedy match for one image+class: dets (D, 5) [score, box] sorted by
    score desc, gts (G, 4). Returns tp flags (D,). Each gt matches at most
    one detection (VOC rule)."""
    tp = np.zeros(len(dets))
    if len(gts) == 0:
        return tp
    taken = np.zeros(len(gts), bool)
    for i, d in enumerate(dets):
        box = d[1:5]
        lt = np.maximum(box[:2], gts[:, :2])
        rb = np.minimum(box[2:4], gts[:, 2:4])
        wh = np.clip(rb - lt, 0.0, None)
        inter = wh[:, 0] * wh[:, 1]
        area_d = max((box[2] - box[0]) * (box[3] - box[1]), 0.0)
        area_g = np.clip(gts[:, 2] - gts[:, 0], 0, None) * \
            np.clip(gts[:, 3] - gts[:, 1], 0, None)
        iou = inter / np.maximum(area_d + area_g - inter, 1e-12)
        j = int(np.argmax(iou))
        if iou[j] >= iou_thresh and not taken[j]:
            tp[i] = 1.0
            taken[j] = True
    return tp


class MeanAveragePrecision:
    """Streaming VOC mAP. Feed per-batch ``(detections, ground_truth)``
    with ``update``; ``result()`` returns (mAP, per-class AP dict).

    * detections: (B, K, 6) ``[label, score, x1, y1, x2, y2]``, label -1 =
      padding (the :func:`~.bbox.batched_detection_output` format);
    * ground truth: (B, G, 5) ``[label, x1, y1, x2, y2]``, label -1 =
      padding (the :class:`~.multibox_loss.MultiBoxLoss` target format).
    """

    def __init__(self, num_classes: int, iou_thresh: float = 0.5,
                 use_07_metric: bool = False, bg_label: int = 0,
                 class_names: Optional[Sequence[str]] = None):
        self.num_classes = int(num_classes)
        self.iou_thresh = float(iou_thresh)
        self.use_07 = bool(use_07_metric)
        self.bg_label = int(bg_label)
        self.class_names = (list(class_names) if class_names else
                            [str(c) for c in range(num_classes)])
        self._scores: Dict[int, List[np.ndarray]] = {}
        self._tps: Dict[int, List[np.ndarray]] = {}
        self._n_gt = np.zeros(self.num_classes, np.int64)

    def update(self, detections: np.ndarray, ground_truth: np.ndarray):
        det = np.asarray(detections)
        gt = np.asarray(ground_truth)
        for b in range(det.shape[0]):
            d_img = det[b][det[b, :, 0] >= 0]
            g_img = gt[b][gt[b, :, 0] >= 0]
            for c in range(self.num_classes):
                if c == self.bg_label:
                    continue
                g_c = g_img[g_img[:, 0] == c][:, 1:5]
                self._n_gt[c] += len(g_c)
                d_c = d_img[d_img[:, 0] == c][:, 1:6]
                if len(d_c) == 0:
                    continue
                d_c = d_c[np.argsort(-d_c[:, 0])]
                tp = _match_detections(d_c, g_c, self.iou_thresh)
                self._scores.setdefault(c, []).append(d_c[:, 0])
                self._tps.setdefault(c, []).append(tp)

    def result(self) -> Tuple[float, Dict[str, float]]:
        aps = {}
        for c in range(self.num_classes):
            # VOC rule: classes absent from the eval set don't enter the mean
            if c == self.bg_label or self._n_gt[c] == 0:
                continue
            scores = (np.concatenate(self._scores[c]) if c in self._scores
                      else np.zeros(0))
            tps = (np.concatenate(self._tps[c]) if c in self._tps
                   else np.zeros(0))
            aps[self.class_names[c]] = average_precision(
                scores, tps, int(self._n_gt[c]), self.use_07)
        mean = float(np.mean(list(aps.values()))) if aps else 0.0
        return mean, aps
