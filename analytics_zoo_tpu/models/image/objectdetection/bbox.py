"""Box utilities — the jittable core under SSD training and detection
output (parity with ``objectdetection/common/BboxUtil.scala``: IoU, the
Caffe-SSD center-offset encode/decode with variances, per-class NMS, and
the decode→NMS→keep-topk detection output of ``Postprocessor.scala``).

TPU-first design: every function is static-shape. NMS is a fixed-size
suppression mask computed from the full IoU matrix with a ``fori_loop``
(no dynamic gather/compaction — XLA keeps it on-chip), and the detection
output is a fixed ``(keep_topk, 6)`` table padded with label ``-1`` rather
than a ragged per-image list.

Boxes are corner-format ``(x1, y1, x2, y2)``, normalized to [0, 1].
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["bbox_iou", "encode_boxes", "decode_boxes", "nms_mask",
           "detection_output", "batched_detection_output"]


def bbox_iou(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU. a: (N, 4), b: (M, 4) → (N, M)."""
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0.0) * jnp.clip(a[:, 3] - a[:, 1], 0.0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0.0) * jnp.clip(b[:, 3] - b[:, 1], 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_form(boxes):
    wh = boxes[..., 2:] - boxes[..., :2]
    return (boxes[..., :2] + boxes[..., 2:]) * 0.5, wh


def encode_boxes(gt: jnp.ndarray, priors: jnp.ndarray,
                 variances=(0.1, 0.1, 0.2, 0.2)) -> jnp.ndarray:
    """Caffe-SSD regression targets (``BboxUtil.encodeBoxes``): center
    offsets scaled by prior size / variance, log-space sizes."""
    g_c, g_wh = _center_form(jnp.asarray(gt, jnp.float32))
    p_c, p_wh = _center_form(jnp.asarray(priors, jnp.float32))
    v = jnp.asarray(variances, jnp.float32)
    p_wh = jnp.maximum(p_wh, 1e-8)
    g_wh = jnp.maximum(g_wh, 1e-8)
    d_c = (g_c - p_c) / (p_wh * v[:2])
    d_wh = jnp.log(g_wh / p_wh) / v[2:]
    return jnp.concatenate([d_c, d_wh], axis=-1)


def decode_boxes(loc: jnp.ndarray, priors: jnp.ndarray,
                 variances=(0.1, 0.1, 0.2, 0.2)) -> jnp.ndarray:
    """Inverse of :func:`encode_boxes` (``BboxUtil.decodeBoxes``)."""
    p_c, p_wh = _center_form(jnp.asarray(priors, jnp.float32))
    v = jnp.asarray(variances, jnp.float32)
    c = loc[..., :2] * v[:2] * p_wh + p_c
    wh = jnp.exp(loc[..., 2:] * v[2:]) * p_wh
    return jnp.concatenate([c - wh * 0.5, c + wh * 0.5], axis=-1)


def nms_mask(boxes: jnp.ndarray,
             iou_threshold: float = 0.45) -> jnp.ndarray:
    """Greedy NMS as a keep-mask over score-DESCENDING-sorted boxes.

    Returns a bool (N,) mask: True where the box survives. Caller sorts
    (row order IS the suppression priority); keeping the sort outside makes
    the suppression loop a pure static-shape scan over the IoU matrix
    (O(N²) memory — N here is nms_topk, a few hundred, so the matrix is
    tiny next to the conv activations).
    """
    n = boxes.shape[0]
    iou = bbox_iou(boxes, boxes)
    idx = jnp.arange(n)

    def body(i, keep):
        sup = (iou[i] > iou_threshold) & (idx > i) & keep[i]
        return keep & ~sup

    return jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def _top_rows(arr: jnp.ndarray, scores: jnp.ndarray, k: int):
    """Rows of ``arr`` at the top-k scores (descending), static shape."""
    top_scores, top_idx = jax.lax.top_k(scores, k)
    return arr[top_idx], top_scores


@partial(jax.jit, static_argnames=("num_classes", "nms_topk", "keep_topk",
                                   "bg_label"))
def detection_output(loc: jnp.ndarray, conf: jnp.ndarray,
                     priors: jnp.ndarray, *, num_classes: int,
                     conf_thresh: float = 0.01, nms_thresh: float = 0.45,
                     nms_topk: int = 400, keep_topk: int = 200,
                     bg_label: int = 0,
                     variances=(0.1, 0.1, 0.2, 0.2)) -> jnp.ndarray:
    """One image: (n_priors, 4) loc + (n_priors, C) scores → fixed
    ``(keep_topk, 6)`` detections ``[label, score, x1, y1, x2, y2]`` sorted
    by score, padded with label -1 (``Postprocessor.scala`` semantics:
    per-class conf-threshold → per-class NMS → global keep-topk)."""
    boxes = jnp.clip(decode_boxes(loc, priors, variances), 0.0, 1.0)

    def per_class(c):
        s = jnp.where(conf[:, c] >= conf_thresh, conf[:, c], 0.0)
        cand_boxes, cand_scores = _top_rows(boxes, s, min(nms_topk, s.shape[0]))
        keep = nms_mask(cand_boxes, nms_thresh)
        return cand_boxes, jnp.where(keep, cand_scores, 0.0)

    classes = jnp.arange(num_classes)
    all_boxes, all_scores = jax.vmap(per_class)(classes)  # (C, K, 4/[])
    # background contributes nothing
    all_scores = jnp.where(classes[:, None] == bg_label, 0.0, all_scores)
    labels = jnp.broadcast_to(classes[:, None], all_scores.shape)
    flat_boxes = all_boxes.reshape(-1, 4)
    flat_scores = all_scores.reshape(-1)
    flat_labels = labels.reshape(-1)
    top_scores, top_idx = jax.lax.top_k(flat_scores,
                                        min(keep_topk, flat_scores.shape[0]))
    out_label = jnp.where(top_scores > 0,
                          flat_labels[top_idx].astype(jnp.float32), -1.0)
    det = jnp.concatenate([out_label[:, None], top_scores[:, None],
                           flat_boxes[top_idx]], axis=-1)
    if det.shape[0] < keep_topk:  # pad when total candidates < keep_topk
        pad = jnp.full((keep_topk - det.shape[0], 6), -1.0)
        det = jnp.concatenate([det, pad.at[:, 1:].set(0.0)], axis=0)
    return det


def batched_detection_output(loc, conf, priors, **kw) -> jnp.ndarray:
    """(B, n_priors, 4) + (B, n_priors, C) → (B, keep_topk, 6)."""
    return jax.vmap(lambda l, c: detection_output(l, c, priors, **kw))(
        jnp.asarray(loc), jnp.asarray(conf))
