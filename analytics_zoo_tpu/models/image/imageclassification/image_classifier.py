"""ImageClassifier — parity with
``models/image/imageclassification/ImageClassifier.scala`` and its published
topologies (``ImageClassificationConfig.scala:34-51``).

Topologies are built natively with the Keras-style graph API (channels-last
NHWC — the TPU conv layout):

* ``inception-v1`` — full GoogLeNet (Szegedy et al. 2015): 7x7/2 stem, 9
  inception blocks, global average pool. The reference ships Inception-v1 as
  its flagship published classifier (``examples/inception/Train.scala``).
* ``simple-cnn`` — a small conv stack for tests/transfer-learning demos.

Transfer learning: ``new_head(num_classes)`` swaps the classification head
(the ``newGraph(output)`` surgery of ``NetUtils.scala``) keeping backbone
weights; freeze the backbone by training with a per-submodule optimizer
mapping the backbone prefix to a zero-lr optimizer
(``Estimator(optim_methods={...})``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ....pipeline.api.keras.engine import Input, KerasNet, Model
from ....pipeline.api.keras.layers import (AveragePooling2D, Convolution2D,
                                           Dense, Dropout, Flatten,
                                           GlobalAveragePooling2D,
                                           MaxPooling2D, merge)
from ...common.zoo_model import register_model
from ..common.image_model import ImageModel
from .topologies import (alexnet, densenet_161, inception_v3, mobilenet,
                         mobilenet_v2, resnet_50, squeezenet, vgg_16,
                         vgg_19)

__all__ = ["ImageClassifier", "inception_v1"]


def _conv(x, nb_filter, nb_row, nb_col, subsample=(1, 1), name=None):
    return Convolution2D(nb_filter, nb_row, nb_col, activation="relu",
                         border_mode="same", subsample=subsample,
                         name=name)(x)


def _inception_block(x, c1, c3r, c3, c5r, c5, pp, name):
    """One GoogLeNet inception module: 1x1 / 3x3 / 5x5 / pool-proj branches,
    channel-concat (NHWC => concat_axis=-1)."""
    b1 = _conv(x, c1, 1, 1, name=f"{name}_1x1")
    b3 = _conv(_conv(x, c3r, 1, 1, name=f"{name}_3x3r"), c3, 3, 3,
               name=f"{name}_3x3")
    b5 = _conv(_conv(x, c5r, 1, 1, name=f"{name}_5x5r"), c5, 5, 5,
               name=f"{name}_5x5")
    bp = _conv(MaxPooling2D((3, 3), strides=(1, 1), border_mode="same",
                            name=f"{name}_pool")(x), pp, 1, 1,
               name=f"{name}_proj")
    return merge([b1, b3, b5, bp], "concat", name=f"{name}_out")


def inception_v1(input_shape: Tuple[int, int, int] = (224, 224, 3),
                 num_classes: int = 1000, dropout: float = 0.4) -> KerasNet:
    """GoogLeNet / Inception-v1 backbone + classifier head (the reference's
    ``examples/inception/Train.scala`` topology), NHWC."""
    inp = Input(shape=input_shape, name="image")
    x = _conv(inp, 64, 7, 7, subsample=(2, 2), name="stem_conv7")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="stem_pool1")(x)
    x = _conv(x, 64, 1, 1, name="stem_conv1")
    x = _conv(x, 192, 3, 3, name="stem_conv3")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="stem_pool2")(x)
    x = _inception_block(x, 64, 96, 128, 16, 32, 32, "inc3a")
    x = _inception_block(x, 128, 128, 192, 32, 96, 64, "inc3b")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="pool3")(x)
    x = _inception_block(x, 192, 96, 208, 16, 48, 64, "inc4a")
    x = _inception_block(x, 160, 112, 224, 24, 64, 64, "inc4b")
    x = _inception_block(x, 128, 128, 256, 24, 64, 64, "inc4c")
    x = _inception_block(x, 112, 144, 288, 32, 64, 64, "inc4d")
    x = _inception_block(x, 256, 160, 320, 32, 128, 128, "inc4e")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="pool4")(x)
    x = _inception_block(x, 256, 160, 320, 32, 128, 128, "inc5a")
    x = _inception_block(x, 384, 192, 384, 48, 128, 128, "inc5b")
    x = GlobalAveragePooling2D(name="gap")(x)
    x = Dropout(dropout, name="head_dropout")(x)
    out = Dense(num_classes, activation="softmax", name="head_dense")(x)
    return Model(input=inp, output=out)


def _simple_cnn(input_shape, num_classes, dropout):
    inp = Input(shape=input_shape, name="image")
    x = _conv(inp, 16, 3, 3, name="backbone_conv1")
    x = MaxPooling2D((2, 2), name="backbone_pool1")(x)
    x = _conv(x, 32, 3, 3, name="backbone_conv2")
    x = MaxPooling2D((2, 2), name="backbone_pool2")(x)
    x = GlobalAveragePooling2D(name="backbone_gap")(x)
    x = Dropout(dropout, name="head_dropout")(x)
    out = Dense(num_classes, activation="softmax", name="head_dense")(x)
    return Model(input=inp, output=out)


_TOPOLOGIES = {
    "inception-v1": inception_v1,
    "simple-cnn": _simple_cnn,
    "alexnet": alexnet,
    "inception-v3": inception_v3,
    "resnet-50": resnet_50,
    "vgg-16": vgg_16,
    "vgg-19": vgg_19,
    "densenet-161": densenet_161,
    "squeezenet": squeezenet,
    "mobilenet": mobilenet,
    "mobilenet-v2": mobilenet_v2,
}


@register_model
class ImageClassifier(ImageModel):
    """``ImageClassifier(model, topology)``
    (``ImageClassifier.scala`` + config registry
    ``ImageClassificationConfig.scala:34-51``)."""

    def __init__(self, model_name: str = "inception-v1",
                 num_classes: int = 1000,
                 input_shape: Tuple[int, int, int] = (224, 224, 3),
                 dropout: float = 0.4, name: Optional[str] = None):
        # "-quantize"/"-int8" suffixed registry names
        # (ImageClassificationConfig.scala) share the float graph; the
        # precision lives in the inference runtime (as_inference_model)
        base = model_name
        self.quantize: Optional[str] = None
        for suffix in ("-quantize", "-int8"):
            if base.endswith(suffix):
                base = base[:-len(suffix)]
                self.quantize = "int8"
        if base not in _TOPOLOGIES:
            raise ValueError(f"unknown topology {model_name!r}; "
                             f"available: {sorted(_TOPOLOGIES)} "
                             f"(+ '-quantize'/'-int8' suffixes)")
        self._base_name = base
        self.model_name = model_name
        self.num_classes = int(num_classes)
        self._input_shape = tuple(int(d) for d in input_shape)
        self.dropout = float(dropout)
        super().__init__(name=name)

    def build_model(self) -> KerasNet:
        return _TOPOLOGIES[self._base_name](
            input_shape=self._input_shape, num_classes=self.num_classes,
            dropout=self.dropout)

    def as_inference_model(self, concurrent_num: int = 1):
        """The serving-side counterpart: wrap the (trained) classifier in an
        InferenceModel; ``*-quantize``/``*-int8`` names load int8
        weight-only quantized."""
        from ....pipeline.inference import InferenceModel
        return InferenceModel(concurrent_num).from_keras(
            self, quantize=self.quantize)

    def get_config(self) -> Dict[str, Any]:
        return {"model_name": self.model_name,
                "num_classes": self.num_classes,
                "input_shape": list(self._input_shape),
                "dropout": self.dropout}

    # ---- transfer learning (NetUtils.scala newGraph role) -----------------
    def new_head(self, num_classes: int) -> "ImageClassifier":
        """Re-head for fine-tuning: keep every backbone weight, replace the
        class-count-dependent head. Grafting is shape-aware — a donor layer
        is copied only when its whole param subtree matches the clone's
        freshly-built shapes, so heads named ``fc8``/``conv10``/
        ``head_dense`` alike keep their fresh init when ``num_classes``
        changes. The returned model shares no buffers with ``self``."""
        import jax
        import numpy as np
        clone = ImageClassifier(self.model_name, num_classes,
                                self._input_shape, self.dropout)
        clone.init_weights()
        if self.params is not None:
            donor = dict(self.params)

            def shapes_match(a, b):
                la = jax.tree_util.tree_flatten(a)
                lb = jax.tree_util.tree_flatten(b)
                return (la[1] == lb[1]
                        and all(np.shape(x) == np.shape(y)
                                for x, y in zip(la[0], lb[0])))

            for k in clone.params:
                if k in donor and shapes_match(donor[k], clone.params[k]):
                    clone.params[k] = jax.tree.map(
                        lambda a: a.copy() if hasattr(a, "copy") else a,
                        donor[k])
        return clone
