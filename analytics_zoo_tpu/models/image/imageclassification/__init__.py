from .image_classifier import ImageClassifier, inception_v1

__all__ = ["ImageClassifier", "inception_v1"]
