"""Published classifier topologies — parity with the reference's registry
(``models/image/imageclassification/ImageClassificationConfig.scala:34-51``:
alexnet, inception-v1/v3, resnet-50, vgg-16/19, densenet-161, squeezenet,
mobilenet, mobilenet-v2; the ``-quantize``/``-int8`` suffixes are handled
by the inference runtime's weight quantization, not separate graphs).

All topologies are NHWC graphs over the native layer set; each function
takes ``(input_shape, num_classes, dropout)`` and returns a ``KerasNet``
so the :class:`ImageClassifier` registry can build any of them uniformly.
"""

from __future__ import annotations

from typing import Sequence, Tuple

from ....pipeline.api.keras.engine import Input, KerasNet, Model
from ....pipeline.api.keras.layers import (Activation, AveragePooling2D,
                                           BatchNormalization, Convolution2D,
                                           Dense, DepthwiseConvolution2D,
                                           Dropout, Flatten,
                                           GlobalAveragePooling2D,
                                           MaxPooling2D, merge)

__all__ = ["alexnet", "vgg_16", "vgg_19", "resnet_50", "squeezenet",
           "mobilenet", "mobilenet_v2", "densenet_161", "inception_v3"]


def _conv(x, nf, k, name, stride=(1, 1), border="same", act="relu"):
    return Convolution2D(nf, k, k, subsample=stride, activation=act,
                         border_mode=border, name=name)(x)


def _conv_bn(x, nf, kr, kc, name, stride=(1, 1), border="same"):
    x = Convolution2D(nf, kr, kc, subsample=stride, border_mode=border,
                      bias=False, name=name)(x)
    x = BatchNormalization(name=f"{name}_bn")(x)
    return Activation("relu", name=f"{name}_relu")(x)


def _head(x, num_classes, dropout, name="head"):
    x = GlobalAveragePooling2D(name=f"{name}_gap")(x)
    if dropout:
        x = Dropout(dropout, name=f"{name}_dropout")(x)
    return Dense(num_classes, activation="softmax", name=f"{name}_dense")(x)


# ---------------------------------------------------------------------------
# AlexNet / VGG
# ---------------------------------------------------------------------------

def alexnet(input_shape=(227, 227, 3), num_classes=1000, dropout=0.5):
    inp = Input(shape=input_shape, name="image")
    x = _conv(inp, 96, 11, "conv1", stride=(4, 4), border="valid")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool1")(x)
    x = _conv(x, 256, 5, "conv2")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool2")(x)
    x = _conv(x, 384, 3, "conv3")
    x = _conv(x, 384, 3, "conv4")
    x = _conv(x, 256, 3, "conv5")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool5")(x)
    x = Flatten(name="flatten")(x)
    x = Dense(4096, activation="relu", name="fc6")(x)
    x = Dropout(dropout, name="drop6")(x)
    x = Dense(4096, activation="relu", name="fc7")(x)
    x = Dropout(dropout, name="drop7")(x)
    out = Dense(num_classes, activation="softmax", name="fc8")(x)
    return Model(input=inp, output=out)


def _vgg(blocks: Sequence[int], input_shape, num_classes, dropout):
    inp = Input(shape=input_shape, name="image")
    x = inp
    filters = (64, 128, 256, 512, 512)
    for b, (n, nf) in enumerate(zip(blocks, filters), start=1):
        for i in range(n):
            x = _conv(x, nf, 3, f"conv{b}_{i + 1}")
        x = MaxPooling2D((2, 2), name=f"pool{b}")(x)
    x = Flatten(name="flatten")(x)
    x = Dense(4096, activation="relu", name="fc6")(x)
    x = Dropout(dropout, name="drop6")(x)
    x = Dense(4096, activation="relu", name="fc7")(x)
    x = Dropout(dropout, name="drop7")(x)
    out = Dense(num_classes, activation="softmax", name="fc8")(x)
    return Model(input=inp, output=out)


def vgg_16(input_shape=(224, 224, 3), num_classes=1000, dropout=0.5):
    return _vgg((2, 2, 3, 3, 3), input_shape, num_classes, dropout)


def vgg_19(input_shape=(224, 224, 3), num_classes=1000, dropout=0.5):
    return _vgg((2, 2, 4, 4, 4), input_shape, num_classes, dropout)


# ---------------------------------------------------------------------------
# ResNet-50
# ---------------------------------------------------------------------------

def _bottleneck(x, nf, name, stride=(1, 1), project=False):
    sc = x
    if project:
        sc = Convolution2D(nf * 4, 1, 1, subsample=stride, border_mode="same",
                           bias=False, name=f"{name}_proj")(x)
        sc = BatchNormalization(name=f"{name}_proj_bn")(sc)
    y = _conv_bn(x, nf, 1, 1, f"{name}_a", stride=stride)
    y = _conv_bn(y, nf, 3, 3, f"{name}_b")
    y = Convolution2D(nf * 4, 1, 1, border_mode="same", bias=False,
                      name=f"{name}_c")(y)
    y = BatchNormalization(name=f"{name}_c_bn")(y)
    out = merge([y, sc], "sum", name=f"{name}_add")
    return Activation("relu", name=f"{name}_out")(out)


def resnet_50(input_shape=(224, 224, 3), num_classes=1000, dropout=0.0):
    inp = Input(shape=input_shape, name="image")
    x = _conv_bn(inp, 64, 7, 7, "conv1", stride=(2, 2))
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="pool1")(x)
    for stage, (nf, n, stride) in enumerate(
            [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)], start=2):
        for block in range(n):
            s = (stride, stride) if block == 0 else (1, 1)
            x = _bottleneck(x, nf, f"res{stage}{chr(97 + block)}",
                            stride=s, project=(block == 0))
    return Model(input=inp, output=_head(x, num_classes, dropout))


# ---------------------------------------------------------------------------
# SqueezeNet
# ---------------------------------------------------------------------------

def _fire(x, squeeze, expand, name):
    s = _conv(x, squeeze, 1, f"{name}_squeeze")
    e1 = _conv(s, expand, 1, f"{name}_e1")
    e3 = _conv(s, expand, 3, f"{name}_e3")
    return merge([e1, e3], "concat", concat_axis=-1, name=f"{name}_out")


def squeezenet(input_shape=(224, 224, 3), num_classes=1000, dropout=0.5):
    inp = Input(shape=input_shape, name="image")
    x = _conv(inp, 64, 3, "conv1", stride=(2, 2))
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool1")(x)
    x = _fire(x, 16, 64, "fire2")
    x = _fire(x, 16, 64, "fire3")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool3")(x)
    x = _fire(x, 32, 128, "fire4")
    x = _fire(x, 32, 128, "fire5")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="pool5")(x)
    x = _fire(x, 48, 192, "fire6")
    x = _fire(x, 48, 192, "fire7")
    x = _fire(x, 64, 256, "fire8")
    x = _fire(x, 64, 256, "fire9")
    if dropout:
        x = Dropout(dropout, name="drop9")(x)
    x = _conv(x, num_classes, 1, "conv10")
    x = GlobalAveragePooling2D(name="gap")(x)
    return Model(input=inp, output=Activation("softmax", name="probs")(x))


# ---------------------------------------------------------------------------
# MobileNet v1 / v2
# ---------------------------------------------------------------------------

def _dw_bn(x, name, stride=(1, 1)):
    x = DepthwiseConvolution2D(3, 3, subsample=stride, border_mode="same",
                               bias=False, name=name)(x)
    x = BatchNormalization(name=f"{name}_bn")(x)
    return Activation("relu", name=f"{name}_relu")(x)


def mobilenet(input_shape=(224, 224, 3), num_classes=1000, dropout=0.001,
              alpha: float = 1.0):
    inp = Input(shape=input_shape, name="image")
    x = _conv_bn(inp, int(32 * alpha), 3, 3, "conv1", stride=(2, 2))
    plan = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1)]
    for i, (nf, s) in enumerate(plan, start=1):
        x = _dw_bn(x, f"dw{i}", stride=(s, s))
        x = _conv_bn(x, int(nf * alpha), 1, 1, f"pw{i}")
    return Model(input=inp, output=_head(x, num_classes, dropout))


def _inverted_residual(x, in_ch, nf, name, stride=1, expand=6):
    h = x
    if expand != 1:
        h = _conv_bn(h, in_ch * expand, 1, 1, f"{name}_expand")
    h = DepthwiseConvolution2D(3, 3, subsample=(stride, stride),
                               border_mode="same", bias=False,
                               name=f"{name}_dw")(h)
    h = BatchNormalization(name=f"{name}_dw_bn")(h)
    h = Activation("relu", name=f"{name}_dw_relu")(h)
    h = Convolution2D(nf, 1, 1, border_mode="same", bias=False,
                      name=f"{name}_project")(h)
    h = BatchNormalization(name=f"{name}_project_bn")(h)
    if stride == 1 and in_ch == nf:
        return merge([x, h], "sum", name=f"{name}_add")
    return h


def mobilenet_v2(input_shape=(224, 224, 3), num_classes=1000, dropout=0.2):
    inp = Input(shape=input_shape, name="image")
    x = _conv_bn(inp, 32, 3, 3, "conv1", stride=(2, 2))
    in_ch = 32
    plan = [  # (expansion, out_ch, repeats, first-stride)
        (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
    b = 0
    for t, c, n, s in plan:
        for i in range(n):
            x = _inverted_residual(x, in_ch, c, f"block{b}",
                                   stride=(s if i == 0 else 1), expand=t)
            in_ch = c
            b += 1
    x = _conv_bn(x, 1280, 1, 1, "conv_last")
    return Model(input=inp, output=_head(x, num_classes, dropout))


# ---------------------------------------------------------------------------
# DenseNet-161
# ---------------------------------------------------------------------------

def _dense_block(x, n_layers, growth, name):
    for i in range(n_layers):
        h = BatchNormalization(name=f"{name}_{i}_bn1")(x)
        h = Activation("relu", name=f"{name}_{i}_relu1")(h)
        h = Convolution2D(4 * growth, 1, 1, border_mode="same", bias=False,
                          name=f"{name}_{i}_conv1")(h)
        h = BatchNormalization(name=f"{name}_{i}_bn2")(h)
        h = Activation("relu", name=f"{name}_{i}_relu2")(h)
        h = Convolution2D(growth, 3, 3, border_mode="same", bias=False,
                          name=f"{name}_{i}_conv2")(h)
        x = merge([x, h], "concat", concat_axis=-1, name=f"{name}_{i}_cat")
    return x


def _transition(x, out_ch, name):
    x = BatchNormalization(name=f"{name}_bn")(x)
    x = Activation("relu", name=f"{name}_relu")(x)
    x = Convolution2D(out_ch, 1, 1, border_mode="same", bias=False,
                      name=f"{name}_conv")(x)
    return AveragePooling2D((2, 2), name=f"{name}_pool")(x)


def densenet_161(input_shape=(224, 224, 3), num_classes=1000, dropout=0.0,
                 growth: int = 48,
                 blocks: Tuple[int, ...] = (6, 12, 36, 24)):
    inp = Input(shape=input_shape, name="image")
    ch = 2 * growth
    x = _conv_bn(inp, ch, 7, 7, "conv1", stride=(2, 2))
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                     name="pool1")(x)
    for bi, n in enumerate(blocks):
        x = _dense_block(x, n, growth, f"dense{bi + 2}")
        ch += n * growth
        if bi != len(blocks) - 1:
            ch //= 2
            x = _transition(x, ch, f"trans{bi + 2}")
    x = BatchNormalization(name="final_bn")(x)
    x = Activation("relu", name="final_relu")(x)
    return Model(input=inp, output=_head(x, num_classes, dropout))


# ---------------------------------------------------------------------------
# Inception-v3
# ---------------------------------------------------------------------------

def _inc3_a(x, pool_proj, name):
    b1 = _conv_bn(x, 64, 1, 1, f"{name}_1x1")
    b2 = _conv_bn(_conv_bn(x, 48, 1, 1, f"{name}_5x5r"), 64, 5, 5,
                  f"{name}_5x5")
    b3 = _conv_bn(_conv_bn(_conv_bn(x, 64, 1, 1, f"{name}_3x3r"),
                           96, 3, 3, f"{name}_3x3a"), 96, 3, 3,
                  f"{name}_3x3b")
    bp = AveragePooling2D((3, 3), strides=(1, 1), border_mode="same",
                          name=f"{name}_avg")(x)
    bp = _conv_bn(bp, pool_proj, 1, 1, f"{name}_pool")
    return merge([b1, b2, b3, bp], "concat", name=f"{name}_out")


def _inc3_b(x, c7, name):
    b1 = _conv_bn(x, 192, 1, 1, f"{name}_1x1")
    b2 = _conv_bn(x, c7, 1, 1, f"{name}_7x7r")
    b2 = _conv_bn(b2, c7, 1, 7, f"{name}_1x7a")
    b2 = _conv_bn(b2, 192, 7, 1, f"{name}_7x1a")
    b3 = _conv_bn(x, c7, 1, 1, f"{name}_d7r")
    b3 = _conv_bn(b3, c7, 7, 1, f"{name}_d7a")
    b3 = _conv_bn(b3, c7, 1, 7, f"{name}_d7b")
    b3 = _conv_bn(b3, c7, 7, 1, f"{name}_d7c")
    b3 = _conv_bn(b3, 192, 1, 7, f"{name}_d7d")
    bp = AveragePooling2D((3, 3), strides=(1, 1), border_mode="same",
                          name=f"{name}_avg")(x)
    bp = _conv_bn(bp, 192, 1, 1, f"{name}_pool")
    return merge([b1, b2, b3, bp], "concat", name=f"{name}_out")


def _inc3_c(x, name):
    b1 = _conv_bn(x, 320, 1, 1, f"{name}_1x1")
    b2 = _conv_bn(x, 384, 1, 1, f"{name}_3x3r")
    b2a = _conv_bn(b2, 384, 1, 3, f"{name}_1x3")
    b2b = _conv_bn(b2, 384, 3, 1, f"{name}_3x1")
    b3 = _conv_bn(_conv_bn(x, 448, 1, 1, f"{name}_d3r"), 384, 3, 3,
                  f"{name}_d3a")
    b3a = _conv_bn(b3, 384, 1, 3, f"{name}_d1x3")
    b3b = _conv_bn(b3, 384, 3, 1, f"{name}_d3x1")
    bp = AveragePooling2D((3, 3), strides=(1, 1), border_mode="same",
                          name=f"{name}_avg")(x)
    bp = _conv_bn(bp, 192, 1, 1, f"{name}_pool")
    return merge([b1, b2a, b2b, b3a, b3b, bp], "concat", name=f"{name}_out")


def inception_v3(input_shape=(299, 299, 3), num_classes=1000, dropout=0.2):
    inp = Input(shape=input_shape, name="image")
    x = _conv_bn(inp, 32, 3, 3, "stem1", stride=(2, 2), border="valid")
    x = _conv_bn(x, 32, 3, 3, "stem2", border="valid")
    x = _conv_bn(x, 64, 3, 3, "stem3")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="stem_pool1")(x)
    x = _conv_bn(x, 80, 1, 1, "stem4", border="valid")
    x = _conv_bn(x, 192, 3, 3, "stem5", border="valid")
    x = MaxPooling2D((3, 3), strides=(2, 2), name="stem_pool2")(x)
    x = _inc3_a(x, 32, "mixed0")
    x = _inc3_a(x, 64, "mixed1")
    x = _inc3_a(x, 64, "mixed2")
    # reduction A
    r1 = _conv_bn(x, 384, 3, 3, "mixed3_3x3", stride=(2, 2), border="valid")
    r2 = _conv_bn(_conv_bn(_conv_bn(x, 64, 1, 1, "mixed3_d3r"),
                           96, 3, 3, "mixed3_d3a"),
                  96, 3, 3, "mixed3_d3b", stride=(2, 2), border="valid")
    rp = MaxPooling2D((3, 3), strides=(2, 2), name="mixed3_pool")(x)
    x = merge([r1, r2, rp], "concat", name="mixed3_out")
    x = _inc3_b(x, 128, "mixed4")
    x = _inc3_b(x, 160, "mixed5")
    x = _inc3_b(x, 160, "mixed6")
    x = _inc3_b(x, 192, "mixed7")
    # reduction B
    r1 = _conv_bn(_conv_bn(x, 192, 1, 1, "mixed8_3x3r"), 320, 3, 3,
                  "mixed8_3x3", stride=(2, 2), border="valid")
    r2 = _conv_bn(x, 192, 1, 1, "mixed8_7x7r")
    r2 = _conv_bn(r2, 192, 1, 7, "mixed8_1x7")
    r2 = _conv_bn(r2, 192, 7, 1, "mixed8_7x1")
    r2 = _conv_bn(r2, 192, 3, 3, "mixed8_3x3b", stride=(2, 2),
                  border="valid")
    rp = MaxPooling2D((3, 3), strides=(2, 2), name="mixed8_pool")(x)
    x = merge([r1, r2, rp], "concat", name="mixed8_out")
    x = _inc3_c(x, "mixed9")
    x = _inc3_c(x, "mixed10")
    return Model(input=inp, output=_head(x, num_classes, dropout))
