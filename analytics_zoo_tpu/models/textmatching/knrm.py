"""KNRM — kernel-pooling neural ranking for text matching, parity with
``models/textmatching/KNRM.scala:60`` (pyzoo ``models/textmatching/knrm.py:32``).

Topology (identical to the reference): concatenated [query ids, doc ids]
(B, text1_length + text2_length) → shared embedding → split → translation
matrix of cosine-free dot products (batchDot axes=(2,2)) → per-kernel RBF
soft-TF counts (mu sweeping -0.9..1.0, exact-match kernel at mu=1 with
exact_sigma) → log-sum pooling over doc then query → Dense(1)
(sigmoid for classification mode).

TPU note: the kernel bank is ONE broadcasted elementwise expression over a
(B, T1, T2, K) tensor — XLA fuses it into the batched matmul's epilogue.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from ...pipeline.api.keras.engine import Input, Lambda, Model, unique_name
from ...pipeline.api.keras.layers import Dense, Embedding, WordEmbedding
from ..common.ranker import RankerMixin
from ..common.zoo_model import ZooModel, register_model


@register_model
class KNRM(RankerMixin, ZooModel):
    """``KNRM(text1Length, text2Length, vocabSize, embedSize, kernelNum,
    sigma, exactSigma, targetMode)``."""

    def __init__(self, text1_length: int, text2_length: int,
                 vocab_size: int, embed_size: int = 300,
                 embed_weights: Optional[np.ndarray] = None,
                 train_embed: bool = True, kernel_num: int = 21,
                 sigma: float = 0.1, exact_sigma: float = 0.001,
                 target_mode: str = "ranking", name: Optional[str] = None):
        if kernel_num <= 1:
            raise ValueError(f"kernel_num must be > 1, got {kernel_num}")
        if target_mode not in ("ranking", "classification"):
            raise ValueError(f"target_mode must be ranking|classification, "
                             f"got {target_mode!r}")
        self.text1_length = int(text1_length)
        self.text2_length = int(text2_length)
        self.vocab_size = int(vocab_size)
        self.embed_size = int(embed_size)
        self.embed_weights = (np.asarray(embed_weights, np.float32)
                              if embed_weights is not None else None)
        self.train_embed = bool(train_embed)
        self.kernel_num = int(kernel_num)
        self.sigma = float(sigma)
        self.exact_sigma = float(exact_sigma)
        self.target_mode = target_mode
        super().__init__(name=name)

    def build_model(self) -> Model:
        t1, t2, k = self.text1_length, self.text2_length, self.kernel_num
        inp = Input(shape=(t1 + t2,))
        if self.embed_weights is not None:
            embed = WordEmbedding(self.embed_weights,
                                  trainable=self.train_embed)(inp)
        else:
            embed = Embedding(self.vocab_size, self.embed_size,
                              init="uniform")(inp)

        # mu grid exactly as KNRM.scala:86-92
        mus, sigmas = [], []
        for i in range(k):
            mu = 1.0 / (k - 1) + (2.0 * i) / (k - 1) - 1.0
            if mu > 1.0:
                mus.append(1.0)
                sigmas.append(self.exact_sigma)
            else:
                mus.append(mu)
                sigmas.append(self.sigma)
        mu_arr = np.asarray(mus, np.float32)
        sig_arr = np.asarray(sigmas, np.float32)

        def kernel_pool(e):
            q = e[:, :t1, :].astype(jnp.float32)
            d = e[:, t1:, :].astype(jnp.float32)
            mm = jnp.einsum("bqe,bde->bqd", q, d)            # translation matrix
            diff = mm[..., None] - mu_arr[None, None, None, :]
            rbf = jnp.exp(-0.5 * (diff / sig_arr) ** 2)      # (B, T1, T2, K)
            soft_tf = jnp.sum(rbf, axis=2)                   # sum over doc
            logs = jnp.log1p(soft_tf)                        # log(1 + x)
            return jnp.sum(logs, axis=1)                     # (B, K)

        phi = Lambda(kernel_pool, name=unique_name("kernelpool_"))(embed)
        if self.target_mode == "ranking":
            out = Dense(1, init="uniform")(phi)
        else:
            out = Dense(1, init="uniform", activation="sigmoid")(phi)
        return Model(inp, out)

    def get_config(self) -> Dict[str, Any]:
        return {"text1_length": self.text1_length,
                "text2_length": self.text2_length,
                "vocab_size": self.vocab_size,
                "embed_size": self.embed_size,
                "train_embed": self.train_embed,
                "kernel_num": self.kernel_num,
                "sigma": self.sigma,
                "exact_sigma": self.exact_sigma,
                "target_mode": self.target_mode}

    def extra_arrays(self):
        # only the FROZEN path needs the constructor table back at load time;
        # a trainable table lives in (and is restored from) the p_ leaves,
        # and after training it no longer dedups against the original
        if self.embed_weights is not None and not self.train_embed:
            return {"embed_weights": self.embed_weights}
        return {}
