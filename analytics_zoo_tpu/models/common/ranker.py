"""Ranking metrics + the Ranker evaluation mixin — parity with
``models/common/Ranker.scala:33-160`` (NDCG@k and MAP over per-query record
groups) plus the HitRate@k the reference's NCF example reports.

The reference wraps each query's candidate batch in one Sample and maps a
metric closure over an RDD; here a "group" is one (x, y) pair of arrays for
a single query/user, metrics are pure numpy on the predicted scores, and the
model forward for ALL groups goes through the normal batched ``predict``.
"""

from __future__ import annotations

from typing import Iterable, Sequence, Tuple

import numpy as np

__all__ = ["ndcg", "mean_average_precision", "hit_rate", "RankerMixin"]


def ndcg(y_pred: np.ndarray, y_true: np.ndarray, k: int,
         threshold: float = 0.0) -> float:
    """NDCG@k for ONE query: gain ``2^label / ln(2 + rank)`` over the top-k
    by predicted score, normalized by the ideal ordering
    (``Ranker.scala:113-146`` exactly, including the natural log)."""
    if k <= 0:
        raise ValueError(f"k must be a positive integer, got {k}")
    g = np.asarray(y_true, np.float64).reshape(-1)
    p = np.asarray(y_pred, np.float64).reshape(-1)

    def _dcg(order):
        total = 0.0
        for i, idx in enumerate(order[:k]):
            if g[idx] > threshold:
                total += (2.0 ** g[idx]) / np.log(2.0 + i)
        return total

    idcg = _dcg(np.argsort(-g, kind="stable"))
    dcg = _dcg(np.argsort(-p, kind="stable"))
    return 0.0 if idcg == 0.0 else dcg / idcg


def mean_average_precision(y_pred: np.ndarray, y_true: np.ndarray,
                           threshold: float = 0.0) -> float:
    """Average precision for ONE query (``Ranker.scala:149-168``): mean over
    positives of (positives seen so far / rank)."""
    g = np.asarray(y_true, np.float64).reshape(-1)
    p = np.asarray(y_pred, np.float64).reshape(-1)
    order = np.argsort(-p, kind="stable")
    hits, total = 0, 0.0
    for i, idx in enumerate(order):
        if g[idx] > threshold:
            hits += 1
            total += hits / (i + 1.0)
    return 0.0 if hits == 0 else total / hits


def hit_rate(y_pred: np.ndarray, y_true: np.ndarray, k: int,
             threshold: float = 0.0) -> float:
    """HitRate@k for ONE query: 1.0 if any positive lands in the top-k by
    score (the NCF example's HR metric)."""
    g = np.asarray(y_true, np.float64).reshape(-1)
    p = np.asarray(y_pred, np.float64).reshape(-1)
    top = np.argsort(-p, kind="stable")[:k]
    return float((g[top] > threshold).any())


class RankerMixin:
    """Adds ``evaluate_ndcg`` / ``evaluate_map`` / ``evaluate_hit_rate`` to a
    model with ``predict``. ``groups`` is an iterable of per-query (x, y)
    pairs — the analogue of the reference's one-Sample-per-query TextSet."""

    def _scores(self, groups: Iterable[Tuple[np.ndarray, np.ndarray]],
                batch_size: int):
        for x, y in groups:
            yield np.asarray(self.predict(x, batch_size=batch_size)), y

    def evaluate_ndcg(self, groups: Sequence[Tuple[np.ndarray, np.ndarray]],
                      k: int, threshold: float = 0.0,
                      batch_size: int = 1024) -> float:
        vals = [ndcg(p, y, k, threshold)
                for p, y in self._scores(groups, batch_size)]
        return float(np.mean(vals))

    def evaluate_map(self, groups: Sequence[Tuple[np.ndarray, np.ndarray]],
                     threshold: float = 0.0, batch_size: int = 1024) -> float:
        vals = [mean_average_precision(p, y, threshold)
                for p, y in self._scores(groups, batch_size)]
        return float(np.mean(vals))

    def evaluate_hit_rate(self, groups: Sequence[Tuple[np.ndarray, np.ndarray]],
                          k: int, threshold: float = 0.0,
                          batch_size: int = 1024) -> float:
        vals = [hit_rate(p, y, k, threshold)
                for p, y in self._scores(groups, batch_size)]
        return float(np.mean(vals))
