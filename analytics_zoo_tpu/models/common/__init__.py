from .zoo_model import ZooModel, load_model, register_model  # noqa: F401
