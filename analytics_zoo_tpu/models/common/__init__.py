from .ranker import (RankerMixin, hit_rate,  # noqa: F401
                     mean_average_precision, ndcg)
from .zoo_model import ZooModel, load_model, register_model  # noqa: F401
