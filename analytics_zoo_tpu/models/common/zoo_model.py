"""ZooModel — the built-in model-zoo base, parity with
``models/common/ZooModel.scala:38-154`` (save/load, predict_classes,
summary) re-designed for the functional JAX core:

* a ZooModel subclass declares its constructor config and builds an inner
  Keras-style graph in ``build_model()``; all training/inference methods come
  from ``KerasNet`` (compile/fit/evaluate/predict are the same jitted paths),
* ``save(path)`` writes ONE ``.npz`` holding the constructor config (JSON),
  the registered class name, and every param/state leaf in deterministic
  ``tree_flatten`` order — ``loadModel`` (``ZooModel.scala:119-154``) becomes
  ``load_model(path)`` via the class registry.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Type

import jax
import numpy as np

from ...common.context import get_zoo_context
from ...pipeline.api.keras.engine import KerasNet

_REGISTRY: Dict[str, Type["ZooModel"]] = {}


def register_model(cls: Type["ZooModel"]) -> Type["ZooModel"]:
    """Class decorator: make a ZooModel loadable by name."""
    _REGISTRY[cls.__name__] = cls
    return cls


class ZooModel(KerasNet):
    """Base for built-in models. Subclasses implement ``build_model()``
    returning a ``Sequential``/``Model`` and ``get_config()`` returning the
    constructor kwargs (used to rebuild on load)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(name=name)
        self.model = self.build_model()

    # ---- to be overridden -------------------------------------------------
    def build_model(self) -> KerasNet:
        raise NotImplementedError(type(self).__name__)

    def get_config(self) -> Dict[str, Any]:
        raise NotImplementedError(type(self).__name__)

    def extra_arrays(self) -> Dict[str, np.ndarray]:
        """Constructor kwargs that are ndarrays (e.g. pretrained embedding
        tables) — too big for the JSON config, so they ride in the .npz as
        ``x_<kwarg>`` entries and are passed back to ``__init__`` on load."""
        return {}

    # ---- Layer protocol: delegate to the inner graph ----------------------
    @property
    def input_shape(self):
        return self.model.input_shape

    def build(self, rng, input_shape=None):
        return self.model.build(rng, input_shape or self.model.input_shape)

    def initial_state(self, input_shape=None):
        return self.model.initial_state(input_shape or self.model.input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        return self.model.apply(params, state, x, training=training, rng=rng)

    def call(self, params, x, *, training=False, rng=None):
        return self.model.call(params, x, training=training, rng=rng)

    def param_sharding(self, params):
        return self.model.param_sharding(params)

    def fused_head(self):
        """Fused LM-head loss resolution (``keras/fused_loss.py``) sees
        through the ZooModel facade to the inner graph's logits head."""
        from ...pipeline.api.keras.fused_loss import find_head
        return find_head(self.model)

    # ---- save / load (ZooModel.scala:38-154) ------------------------------
    def save(self, path: str, over_write: bool = True) -> str:
        """``saveModel(path, overWrite)``: one .npz with config + weights."""
        import os
        if not path.endswith(".npz"):
            path += ".npz"  # np.savez appends it anyway; normalize up front
        if os.path.exists(path) and not over_write:
            raise FileExistsError(f"{path} exists and over_write=False")
        if self.params is None:
            self.init_weights()
        p_leaves = jax.tree_util.tree_leaves(self.params)
        s_leaves = jax.tree_util.tree_leaves(self.net_state)
        arrays = {f"p_{i}": np.asarray(jax.device_get(l))
                  for i, l in enumerate(p_leaves)}
        arrays.update({f"s_{i}": np.asarray(jax.device_get(l))
                       for i, l in enumerate(s_leaves)})
        # constructor arrays that are bit-identical to a saved weight leaf
        # (e.g. a frozen WordEmbedding table in net_state) are stored once,
        # as a named reference, so a 480MB GloVe table doesn't ride twice
        extra_refs: Dict[str, str] = {}
        for k, v in self.extra_arrays().items():
            v = np.asarray(v)
            ref = next((name for name, a in arrays.items()
                        if a.shape == v.shape and a.dtype == v.dtype
                        and np.array_equal(a, v)), None)
            if ref is not None:
                extra_refs[k] = ref
            else:
                arrays[f"x_{k}"] = v
                extra_refs[k] = f"x_{k}"
        header = json.dumps({"class": type(self).__name__,
                             "config": self.get_config(),
                             "extra": extra_refs,
                             "n_params": len(p_leaves),
                             "n_state": len(s_leaves)})
        np.savez(path, __zoo_header__=np.frombuffer(
            header.encode("utf-8"), dtype=np.uint8), **arrays)
        return path

    @classmethod
    def load(cls, path: str) -> "ZooModel":
        return load_model(path)

    def summary(self) -> str:
        """Param-count summary (``ZooModel`` summary parity)."""
        if self.params is None:
            self.init_weights()
        n = sum(int(np.prod(np.shape(l)))
                for l in jax.tree_util.tree_leaves(self.params))
        lines = [f"Model: {type(self).__name__} ({self.name})",
                 f"Trainable parameters: {n:,}"]
        return "\n".join(lines)


def _populate_registry() -> None:
    """Import the built-in model packages so their ``@register_model``
    decorators run — a fresh process (e.g. the serving CLI) may call
    ``load_model`` before any zoo model module was imported."""
    import importlib
    import logging
    for mod in ("analytics_zoo_tpu.models.recommendation",
                "analytics_zoo_tpu.models.anomalydetection",
                "analytics_zoo_tpu.models.textclassification",
                "analytics_zoo_tpu.models.textmatching",
                "analytics_zoo_tpu.models.seq2seq",
                "analytics_zoo_tpu.models.image.imageclassification",
                "analytics_zoo_tpu.models.image.objectdetection",
                "analytics_zoo_tpu.tfpark"):
        try:
            importlib.import_module(mod)
        except ImportError as e:  # pragma: no cover - partial installs
            # keep going (other packages may hold the class) but say why a
            # class might later come up missing
            logging.getLogger("analytics_zoo_tpu.models").warning(
                "model package %s failed to import (%s); its classes will "
                "be unavailable to load_model", mod, e)


def load_model(path: str) -> ZooModel:
    """``ZooModel.loadModel`` (``ZooModel.scala:119-154``): rebuild from the
    registered class + config, then install saved weights."""
    if not path.endswith(".npz"):
        path += ".npz"
    with np.load(path) as data:
        header = json.loads(bytes(data["__zoo_header__"]).decode("utf-8"))
        p_loaded = [data[f"p_{i}"] for i in range(header["n_params"])]
        s_loaded = [data[f"s_{i}"] for i in range(header["n_state"])]
        extras = {k: data[ref]
                  for k, ref in header.get("extra", {}).items()}
    cls = _REGISTRY.get(header["class"])
    if cls is None:
        # fresh process: the class's module may simply not be imported yet —
        # sweep the built-in packages before giving up
        _populate_registry()
        cls = _REGISTRY.get(header["class"])
    if cls is None:
        raise ValueError(f"unknown model class {header['class']!r}; "
                         f"registered: {sorted(_REGISTRY)}")
    model = cls(**header["config"], **extras)
    model.init_weights(rng=get_zoo_context().rng())
    _, p_def = jax.tree_util.tree_flatten(model.params)
    _, s_def = jax.tree_util.tree_flatten(model.net_state)
    model.params = jax.tree_util.tree_unflatten(p_def, p_loaded)
    model.net_state = jax.tree_util.tree_unflatten(s_def, s_loaded)
    return model
