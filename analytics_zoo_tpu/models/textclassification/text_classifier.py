"""TextClassifier — CNN/LSTM/GRU text classification, parity with
``models/textclassification/TextClassifier.scala:34`` (pyzoo
``models/textclassification/text_classifier.py``).

Pipeline: token ids (B, sequence_length) → embedding (pretrained frozen GloVe
via ``WordEmbedding`` or a trainable table) → encoder (cnn: Conv1D +
GlobalMaxPooling1D; lstm/gru: last hidden state) → Dense(128) relu →
Dropout(0.2) → Dense(class_num) softmax — the reference's exact topology.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ...pipeline.api.keras.engine import Sequential
from ...pipeline.api.keras.layers import (GRU, LSTM, Convolution1D, Dense,
                                          Dropout, Embedding,
                                          GlobalMaxPooling1D, WordEmbedding)
from ..common.zoo_model import ZooModel, register_model


@register_model
class TextClassifier(ZooModel):
    """``TextClassifier(classNum, tokenLength, sequenceLength, encoder,
    encoderOutputDim)``. Provide either ``vocab_size`` (trainable embedding)
    or ``embedding_weights`` (pretrained, frozen — the GloVe path)."""

    def __init__(self, class_num: int, token_length: int = 200,
                 sequence_length: int = 500, encoder: str = "cnn",
                 encoder_output_dim: int = 256,
                 vocab_size: Optional[int] = None,
                 embedding_weights: Optional[np.ndarray] = None,
                 name: Optional[str] = None):
        if encoder not in ("cnn", "lstm", "gru"):
            raise ValueError(f"encoder must be cnn|lstm|gru, got {encoder!r}")
        if vocab_size is None and embedding_weights is None:
            raise ValueError("provide vocab_size or embedding_weights")
        self.class_num = int(class_num)
        self.token_length = int(token_length)
        self.sequence_length = int(sequence_length)
        self.encoder = encoder
        self.encoder_output_dim = int(encoder_output_dim)
        self.vocab_size = vocab_size
        self.embedding_weights = (np.asarray(embedding_weights, np.float32)
                                  if embedding_weights is not None else None)
        super().__init__(name=name)

    def build_model(self) -> Sequential:
        m = Sequential()
        if self.embedding_weights is not None:
            m.add(WordEmbedding(self.embedding_weights, trainable=False,
                                input_shape=(self.sequence_length,)))
        else:
            m.add(Embedding(self.vocab_size, self.token_length,
                            input_shape=(self.sequence_length,)))
        if self.encoder == "cnn":
            m.add(Convolution1D(self.encoder_output_dim, 5,
                                activation="relu"))
            m.add(GlobalMaxPooling1D())
        elif self.encoder == "lstm":
            m.add(LSTM(self.encoder_output_dim))
        else:
            m.add(GRU(self.encoder_output_dim))
        m.add(Dense(128, activation="relu"))
        m.add(Dropout(0.2))
        m.add(Dense(self.class_num, activation="softmax"))
        return m

    def get_config(self) -> Dict[str, Any]:
        cfg = {"class_num": self.class_num,
               "token_length": self.token_length,
               "sequence_length": self.sequence_length,
               "encoder": self.encoder,
               "encoder_output_dim": self.encoder_output_dim}
        if self.vocab_size is not None:
            cfg["vocab_size"] = self.vocab_size
        return cfg

    def extra_arrays(self):
        if self.embedding_weights is not None:
            return {"embedding_weights": self.embedding_weights}
        return {}
