from .caffe_loader import CaffeLoader, CaffePooling2D, load_caffe  # noqa: F401
