"""Caffe model import — parity with ``models/caffe/CaffeLoader.scala`` (+
``LayerConverter.scala`` / ``V1LayerConverter.scala``): read a binary
``.caffemodel`` (NetParameter, V1 or V2 layer messages) with the in-repo
proto codec and build a native, fine-tunable Keras-style graph with the
pretrained weights installed.

Layout translation is the TPU-relevant design decision: caffe is NCHW with
OIHW kernels; the native layers are NHWC. Conv kernels are transposed to
HWIO at load; the first 4D→2D transition (InnerProduct/Flatten) inserts an
NHWC→NCHW transpose so caffe's ``C*H*W`` flatten order — and therefore the
pretrained FC weights — stay bit-correct.

Caffe's pooling is ceil-mode with count-include-pad averaging; neither maps
onto the stock pooling layers, so :class:`CaffePooling2D` reproduces the
exact ``pooling_layer.cpp`` arithmetic (window extent capped at
``size + pad``, left pad counted in the divisor) with a static divisor
table — one ``reduce_window`` per pool, no dynamic shapes.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ...pipeline.api.keras.engine import Input, KerasNet, Lambda, Layer, Model
from ...pipeline.api.keras.layers import (Activation, BatchNormalization,
                                          Convolution2D, Dense, Dropout,
                                          Flatten, LRN2D, LeakyReLU, Scale,
                                          ZeroPadding2D, merge)
from ...utils.proto import parse_fields, parse_varint

__all__ = ["CaffeLoader", "CaffePooling2D", "load_caffe"]


# ---------------------------------------------------------------------------
# caffe.proto subset decoding
# ---------------------------------------------------------------------------

def _ints(payload: bytes, wt: int) -> List[int]:
    if wt == 2:  # packed
        out, i = [], 0
        while i < len(payload):
            v, i = parse_varint(payload, i)
            out.append(v)
        return out
    v, _ = parse_varint(payload, 0)
    return [v]


def _int(payload: bytes) -> int:
    v, _ = parse_varint(payload, 0)
    return v


def _floats(payload: bytes, wt: int) -> np.ndarray:
    if wt == 2:
        return np.frombuffer(payload, "<f4")
    return np.frombuffer(payload[:4], "<f4")


def _f32(payload: bytes) -> float:
    return struct.unpack("<f", payload)[0]


def _decode_blob(buf: bytes) -> np.ndarray:
    dims: List[int] = []
    old = [0, 0, 0, 0]  # num/channels/height/width legacy 4D fields
    data: List[np.ndarray] = []
    for num, wt, payload in parse_fields(buf):
        if num == 7 and wt == 2:       # shape: BlobShape{dim=1}
            for n2, wt2, p2 in parse_fields(payload):
                if n2 == 1:
                    dims.extend(_ints(p2, wt2))
        elif num == 5:                 # data (packed floats)
            data.append(_floats(payload, wt))
        elif num in (1, 2, 3, 4):
            old[num - 1] = _int(payload)
    arr = (np.concatenate(data).astype(np.float32) if data
           else np.zeros(0, np.float32))
    if dims:
        return arr.reshape(dims)
    if any(old):
        # legacy blobs are always 4D; squeeze leading 1s later as needed
        return arr.reshape([d or 1 for d in old])
    return arr


def _decode_conv_param(buf: bytes) -> Dict[str, Any]:
    # pad/kernel/stride/dilation are proto2 repeated WITHOUT [packed=true]:
    # each value arrives as its own field — extend, never overwrite
    p: Dict[str, Any] = {"num_output": 0, "bias_term": True, "pad": [],
                         "kernel": [], "stride": [], "group": 1,
                         "dilation": []}
    for num, wt, payload in parse_fields(buf):
        if num == 1:
            p["num_output"] = _int(payload)
        elif num == 2:
            p["bias_term"] = bool(_int(payload))
        elif num == 3:
            p["pad"].extend(_ints(payload, wt))
        elif num == 4:
            p["kernel"].extend(_ints(payload, wt))
        elif num == 5:
            p["group"] = _int(payload)
        elif num == 6:
            p["stride"].extend(_ints(payload, wt))
        elif num == 9:
            p["pad_h"] = _int(payload)
        elif num == 10:
            p["pad_w"] = _int(payload)
        elif num == 11:
            p["kernel_h"] = _int(payload)
        elif num == 12:
            p["kernel_w"] = _int(payload)
        elif num == 13:
            p["stride_h"] = _int(payload)
        elif num == 14:
            p["stride_w"] = _int(payload)
        elif num == 18:
            p["dilation"].extend(_ints(payload, wt))
    for key, default in (("pad", 0), ("kernel", 0), ("stride", 1),
                         ("dilation", 1)):
        if not p[key]:
            p[key] = [default]
    return p


def _decode_pool_param(buf: bytes) -> Dict[str, Any]:
    p: Dict[str, Any] = {"mode": 0, "kernel": 0, "stride": 1, "pad": 0,
                         "global": False}
    for num, wt, payload in parse_fields(buf):
        if num == 1:
            p["mode"] = _int(payload)           # 0 MAX, 1 AVE
        elif num == 2:
            p["kernel"] = _int(payload)
        elif num == 3:
            p["stride"] = _int(payload)
        elif num == 4:
            p["pad"] = _int(payload)
        elif num == 5:
            p["kernel_h"] = _int(payload)
        elif num == 6:
            p["kernel_w"] = _int(payload)
        elif num == 7:
            p["stride_h"] = _int(payload)
        elif num == 8:
            p["stride_w"] = _int(payload)
        elif num == 9:
            p["pad_h"] = _int(payload)
        elif num == 10:
            p["pad_w"] = _int(payload)
        elif num == 12:
            p["global"] = bool(_int(payload))
    return p


def _decode_simple(buf: bytes, fields: Dict[int, Tuple[str, str]],
                   defaults: Dict[str, Any]) -> Dict[str, Any]:
    """Generic decoder: fields maps num → (name, kind) with kind in
    int/float/bool."""
    p = dict(defaults)
    for num, wt, payload in parse_fields(buf):
        if num in fields:
            name, kind = fields[num]
            if kind == "int":
                p[name] = _int(payload)
            elif kind == "float":
                p[name] = _f32(payload)
            elif kind == "bool":
                p[name] = bool(_int(payload))
    return p


# V1LayerParameter type enum → canonical type string
_V1_TYPES = {3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout",
             8: "Flatten", 14: "InnerProduct", 15: "LRN", 17: "Pooling",
             18: "ReLU", 19: "Sigmoid", 20: "Softmax", 21: "SoftmaxWithLoss",
             22: "Split", 23: "TanH", 25: "Eltwise", 26: "Power",
             39: "Deconvolution"}

# LayerParameter(V2) / V1LayerParameter sub-message field numbers
_PARAM_FIELDS = {
    "v2": {"concat": 104, "conv": 106, "dropout": 108, "eltwise": 110,
           "inner": 117, "lrn": 118, "pool": 121, "power": 122, "relu": 123,
           "softmax": 125, "batch_norm": 139, "scale": 142},
    "v1": {"concat": 9, "conv": 10, "dropout": 12, "eltwise": 24,
           "inner": 17, "lrn": 18, "pool": 19, "power": 21, "relu": 30,
           "softmax": 39},
}


def _decode_layer(buf: bytes, version: str) -> Dict[str, Any]:
    v1 = version == "v1"
    f = _PARAM_FIELDS[version]
    layer: Dict[str, Any] = {"name": "", "type": "", "bottoms": [],
                             "tops": [], "blobs": [], "param": {}}
    for num, wt, payload in parse_fields(buf):
        if num == (4 if v1 else 1) and wt == 2:
            layer["name"] = payload.decode("utf-8")
        elif num == (5 if v1 else 2):
            layer["type"] = (_V1_TYPES.get(_int(payload), f"V1#{_int(payload)}")
                             if v1 else payload.decode("utf-8"))
        elif num == (2 if v1 else 3) and wt == 2:
            layer["bottoms"].append(payload.decode("utf-8"))
        elif num == (3 if v1 else 4) and wt == 2:
            layer["tops"].append(payload.decode("utf-8"))
        elif num == (6 if v1 else 7) and wt == 2:
            layer["blobs"].append(_decode_blob(payload))
        elif num == f["conv"] and wt == 2:
            layer["param"] = _decode_conv_param(payload)
        elif num == f["pool"] and wt == 2:
            layer["param"] = _decode_pool_param(payload)
        elif num == f["inner"] and wt == 2:
            layer["param"] = _decode_simple(
                payload, {1: ("num_output", "int"), 2: ("bias_term", "bool")},
                {"num_output": 0, "bias_term": True})
        elif num == f["lrn"] and wt == 2:
            layer["param"] = _decode_simple(
                payload, {1: ("local_size", "int"), 2: ("alpha", "float"),
                          3: ("beta", "float"), 4: ("region", "int"),
                          5: ("k", "float")},
                {"local_size": 5, "alpha": 1.0, "beta": 0.75, "region": 0,
                 "k": 1.0})
        elif num == f["dropout"] and wt == 2:
            layer["param"] = _decode_simple(
                payload, {1: ("ratio", "float")}, {"ratio": 0.5})
        elif num == f["concat"] and wt == 2:
            layer["param"] = _decode_simple(
                payload, {1: ("concat_dim", "int"), 2: ("axis", "int")},
                {"concat_dim": 1})
        elif num == f["eltwise"] and wt == 2:
            layer["param"] = _decode_simple(
                payload, {1: ("operation", "int")}, {"operation": 1})
        elif num == f["relu"] and wt == 2:
            layer["param"] = _decode_simple(
                payload, {1: ("negative_slope", "float")},
                {"negative_slope": 0.0})
        elif num == f["power"] and wt == 2:
            layer["param"] = _decode_simple(
                payload, {1: ("power", "float"), 2: ("scale", "float"),
                          3: ("shift", "float")},
                {"power": 1.0, "scale": 1.0, "shift": 0.0})
        elif not v1 and num == f["batch_norm"] and wt == 2:
            layer["param"] = _decode_simple(
                payload, {1: ("use_global_stats", "bool"),
                          3: ("eps", "float")},
                {"eps": 1e-5})
        elif not v1 and num == f["scale"] and wt == 2:
            layer["param"] = _decode_simple(
                payload, {1: ("axis", "int"), 4: ("bias_term", "bool")},
                {"axis": 1, "bias_term": False})
    return layer


def _decode_net(buf: bytes) -> Dict[str, Any]:
    net: Dict[str, Any] = {"name": "", "inputs": [], "input_dims": [],
                           "layers": []}
    shapes: List[List[int]] = []
    for num, wt, payload in parse_fields(buf):
        if num == 1 and wt == 2:
            net["name"] = payload.decode("utf-8")
        elif num == 3 and wt == 2:
            net["inputs"].append(payload.decode("utf-8"))
        elif num == 4:
            net["input_dims"].extend(_ints(payload, wt))
        elif num == 8 and wt == 2:     # input_shape: BlobShape
            dims = []
            for n2, wt2, p2 in parse_fields(payload):
                if n2 == 1:
                    dims.extend(_ints(p2, wt2))
            shapes.append(dims)
        elif num == 2 and wt == 2:     # V1 layers
            net["layers"].append(_decode_layer(payload, "v1"))
        elif num == 100 and wt == 2:   # V2 layer
            net["layers"].append(_decode_layer(payload, "v2"))
    if shapes and not net["input_dims"]:
        net["input_dims"] = [d for s in shapes for d in s]
    return net


# ---------------------------------------------------------------------------
# caffe-exact pooling
# ---------------------------------------------------------------------------

class CaffePooling2D(Layer):
    """Pooling with caffe's exact arithmetic (``pooling_layer.cpp``):
    ceil-mode output size (clipped so the last window starts inside the
    padded extent), MAX ignores padding, AVE divides by the window clipped
    to ``size + pad`` with left pad included. NHWC."""

    def __init__(self, mode: str, kernel: Tuple[int, int],
                 stride: Tuple[int, int], pad: Tuple[int, int] = (0, 0),
                 **kwargs):
        super().__init__(**kwargs)
        if mode not in ("max", "ave"):
            raise ValueError(f"unsupported caffe pool mode {mode!r}")
        self.mode = mode
        self.kernel = tuple(kernel)
        self.stride = tuple(stride)
        self.pad = tuple(pad)

    @staticmethod
    def _out(size: int, k: int, s: int, p: int) -> int:
        o = -(-(size + 2 * p - k) // s) + 1  # ceil
        if p > 0 and (o - 1) * s >= size + p:
            o -= 1
        return o

    def call(self, params, x, *, training=False, rng=None):
        (kh, kw), (sh, sw), (ph, pw) = self.kernel, self.stride, self.pad
        h, w = x.shape[1], x.shape[2]
        oh, ow = self._out(h, kh, sh, ph), self._out(w, kw, sw, pw)
        pe_h = max((oh - 1) * sh + kh - h - ph, 0)
        pe_w = max((ow - 1) * sw + kw - w - pw, 0)
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pads = ((0, 0), (ph, pe_h), (pw, pe_w), (0, 0))
        if self.mode == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides,
                                     pads)
        acc = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, dims,
                                strides, pads)

        def counts(size, k, s, p, out):
            start = np.arange(out) * s - p          # ≥ -p always
            end = np.minimum(start + k, size + p)   # capped at size+pad
            return (end - start).astype(np.float32)

        div = np.outer(counts(h, kh, sh, ph, oh),
                       counts(w, kw, sw, pw, ow))[None, :, :, None]
        return (acc / div).astype(x.dtype)


# ---------------------------------------------------------------------------
# graph construction
# ---------------------------------------------------------------------------

def _nchw_to_nhwc_shape(dims: Sequence[int]) -> Tuple[int, int, int]:
    if len(dims) != 4:
        raise ValueError(f"expected a 4D NCHW input, got dims {list(dims)}")
    _, c, h, w = dims
    return (int(h), int(w), int(c))


def _conv_kernel(blob: np.ndarray) -> np.ndarray:
    return np.transpose(blob, (2, 3, 1, 0))  # OIHW → HWIO


class CaffeLoader:
    """``CaffeLoader.load(model_path)`` — class-name parity with
    ``CaffeLoader.scala`` (binary NetParameter carries both topology and
    weights; the deploy prototxt is unnecessary here)."""

    @staticmethod
    def load(model_path: str,
             input_shape: Optional[Sequence[int]] = None) -> KerasNet:
        return load_caffe(model_path, input_shape)


def load_caffe(model_path: str,
               input_shape: Optional[Sequence[int]] = None) -> KerasNet:
    """Build a native NHWC graph from a ``.caffemodel``. ``input_shape`` is
    NCHW sans batch (caffe convention) and overrides the net's own input
    declaration. Returns a KerasNet with pretrained weights installed."""
    with open(model_path, "rb") as fh:
        net = _decode_net(fh.read())

    if input_shape is not None:
        dims = [1] + [int(d) for d in input_shape]
    elif net["input_dims"]:
        dims = net["input_dims"][:4]
    else:
        raise ValueError("net declares no input; pass input_shape=(C, H, W)")

    inp = Input(shape=_nchw_to_nhwc_shape(dims), name="data")
    blob: Dict[str, Any] = {}
    blob_4d: Dict[str, bool] = {}
    input_names = net["inputs"] or ["data"]
    for n in input_names:
        blob[n] = inp
        blob_4d[n] = True
    weights: Dict[str, Dict[str, np.ndarray]] = {}
    states: Dict[str, Dict[str, np.ndarray]] = {}
    last_node = None  # last layer actually built (loss/accuracy tails skip)

    def to_chw_flat(x_node, name):
        """NHWC → NCHW-ordered flatten, preserving caffe's C*H*W order."""
        t = Lambda(lambda t: jnp.transpose(t, (0, 3, 1, 2)),
                   name=f"{name}_nchw")(x_node)
        return Flatten(name=f"{name}_flat")(t)

    for layer in net["layers"]:
        lt, name = layer["type"], layer["name"] or f"layer{len(blob)}"
        bots, tops = layer["bottoms"], layer["tops"]
        p = layer["param"]
        blobs = layer["blobs"]
        if lt in ("Data", "Input", "Accuracy", "SoftmaxWithLoss",
                  "EuclideanLoss", "SigmoidCrossEntropyLoss"):
            if lt == "Input" and tops:
                for t in tops:
                    blob[t] = inp
                    blob_4d[t] = True
            continue
        if lt == "Split":
            for t in tops:
                blob[t] = blob[bots[0]]
                blob_4d[t] = blob_4d[bots[0]]
            continue
        x = blob[bots[0]] if bots else inp
        is4d = blob_4d.get(bots[0] if bots else input_names[0], True)

        if lt == "Convolution":
            k = blobs[0]
            if k.ndim != 4:
                raise ValueError(f"{name}: conv weight blob must be 4D")
            if p.get("group", 1) != 1:
                raise NotImplementedError(f"{name}: grouped caffe conv")
            kh = p.get("kernel_h", p["kernel"][0])
            kw = p.get("kernel_w", p["kernel"][-1])
            sh = p.get("stride_h", p["stride"][0])
            sw = p.get("stride_w", p["stride"][-1])
            ph = p.get("pad_h", p["pad"][0])
            pw = p.get("pad_w", p["pad"][-1])
            if (ph, pw) != (0, 0):
                x = ZeroPadding2D((ph, pw), name=f"{name}_pad")(x)
            dil = p["dilation"][0] if p["dilation"] else 1
            node = Convolution2D(p["num_output"], kh, kw,
                                 subsample=(sh, sw), border_mode="valid",
                                 dilation=(dil, dil),
                                 bias=p["bias_term"], name=name)(x)
            w = {"W": _conv_kernel(k)}
            if p["bias_term"]:
                w["b"] = blobs[1].reshape(-1)
            weights[name] = w
            out4d = True
        elif lt == "InnerProduct":
            if is4d:
                x = to_chw_flat(x, name)
            node = Dense(p["num_output"], bias=p.get("bias_term", True),
                         name=name)(x)
            wblob = blobs[0].reshape(p["num_output"], -1)
            w = {"W": wblob.T}
            if p.get("bias_term", True):
                w["b"] = blobs[1].reshape(-1)
            weights[name] = w
            out4d = False
        elif lt == "Pooling":
            if p["global"]:
                # bind the mode NOW — Lambda.fn runs at apply time, when the
                # loop variable p belongs to a different layer
                if p["mode"] == 1:
                    node = Lambda(lambda t: jnp.mean(t, axis=(1, 2)),
                                  name=name)(x)
                else:
                    node = Lambda(lambda t: jnp.max(t, axis=(1, 2)),
                                  name=name)(x)
                out4d = False
            else:
                kh = p.get("kernel_h", p["kernel"])
                kw = p.get("kernel_w", p["kernel"])
                sh = p.get("stride_h", p["stride"])
                sw = p.get("stride_w", p["stride"])
                ph = p.get("pad_h", p["pad"])
                pw = p.get("pad_w", p["pad"])
                mode = {0: "max", 1: "ave"}.get(p["mode"])
                if mode is None:
                    raise NotImplementedError(f"{name}: caffe pool mode "
                                              f"{p['mode']}")
                node = CaffePooling2D(mode, (kh, kw), (sh, sw), (ph, pw),
                                      name=name)(x)
                out4d = True
        elif lt == "ReLU":
            slope = p.get("negative_slope", 0.0)
            node = (LeakyReLU(slope, name=name)(x) if slope
                    else Activation("relu", name=name)(x))
            out4d = is4d
        elif lt == "Sigmoid":
            node = Activation("sigmoid", name=name)(x)
            out4d = is4d
        elif lt == "TanH":
            node = Activation("tanh", name=name)(x)
            out4d = is4d
        elif lt == "Softmax":
            node = Activation("softmax", name=name)(x)
            out4d = is4d
        elif lt == "LRN":
            if p.get("region", 0) != 0:
                raise NotImplementedError(f"{name}: WITHIN_CHANNEL LRN")
            node = LRN2D(alpha=p["alpha"], beta=p["beta"], k=p["k"],
                         n=p["local_size"], name=name)(x)
            out4d = is4d
        elif lt == "Dropout":
            node = Dropout(p.get("ratio", 0.5), name=name)(x)
            out4d = is4d
        elif lt == "Concat":
            axis_nchw = p.get("axis", p.get("concat_dim", 1))
            axis = {0: 0, 1: -1, 2: 1, 3: 2}[axis_nchw] if is4d else axis_nchw
            node = merge([blob[b] for b in bots], "concat",
                         concat_axis=axis, name=name)
            out4d = is4d
        elif lt == "Eltwise":
            op = {0: "mul", 1: "sum", 2: "max"}.get(p.get("operation", 1))
            node = merge([blob[b] for b in bots], op, name=name)
            out4d = is4d
        elif lt == "Power":
            node = Lambda(lambda t, pw_=p["power"], sc=p["scale"],
                          sh_=p["shift"]: jnp.power(sc * t + sh_, pw_),
                          name=name)(x)
            out4d = is4d
        elif lt == "Flatten":
            node = to_chw_flat(x, name) if is4d else x
            out4d = False
        elif lt == "BatchNorm":
            node = BatchNormalization(epsilon=p.get("eps", 1e-5),
                                      scale=False, center=False,
                                      name=name)(x)
            sf = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 else 1.0
            sf = 1.0 / sf if sf != 0 else 0.0
            states[name] = {"moving_mean": blobs[0].reshape(-1) * sf,
                            "moving_var": blobs[1].reshape(-1) * sf}
            out4d = is4d
        elif lt == "Scale":
            ch = blobs[0].reshape(-1).shape[0]
            node = Scale((ch,), name=name)(x)
            w = {"weight": blobs[0].reshape(-1)}
            w["bias"] = (blobs[1].reshape(-1) if p.get("bias_term")
                         and len(blobs) > 1 else np.zeros(ch, np.float32))
            weights[name] = w
            out4d = is4d
        else:
            raise NotImplementedError(f"caffe layer type {lt!r} "
                                      f"(layer {name!r}) not supported")

        for t in tops or [name]:
            blob[t] = node
            blob_4d[t] = out4d
        last_node = node

    if last_node is None:
        raise ValueError("caffemodel contains no computational layers")
    from ...pipeline.api.keras.engine import install_imported_weights
    model = Model(input=inp, output=last_node)
    return install_imported_weights(model, weights, states, source="caffe")
