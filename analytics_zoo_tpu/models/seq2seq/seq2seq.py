"""Seq2seq — generic encoder/decoder sequence model, parity with
``models/seq2seq/Seq2seq.scala:50`` + ``RNNEncoder.scala`` /
``RNNDecoder.scala`` / ``Bridge.scala:38`` (pyzoo ``models/seq2seq/seq2seq.py:42-158``).

Structure (same as the reference graph):
  encoder: stacked LSTM/GRU over (B, Te, D_in), final states collected
  bridge:  passthrough | dense | densenonlinear over the concatenated states
  decoder: stacked LSTM/GRU over (B, Td, D_dec), layer i initialized from the
           bridged encoder layer-i states (teacher forcing during training)
  generator: optional Dense head applied per timestep

``infer`` runs the greedy feedback loop of ``Seq2seq.infer``
(``Seq2seq.scala:112+``): start sign in, one timestep at a time, outputs fed
back as the next decoder input, early stop on ``stop_sign``. Each step is one
jitted decoder call; the Python loop is host-side control, as the reference's.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...pipeline.api.keras.engine import Layer, compute_dtype, param_dtype
from ...pipeline.api.keras.layers import GRU, LSTM, Dense
from ...pipeline.api.keras.layers.core import get_activation
from ..common.zoo_model import ZooModel, register_model


class _Seq2seqNet(Layer):
    """The wired encoder/bridge/decoder/generator as one functional Layer."""

    def __init__(self, spec: "Seq2seq", **kwargs):
        super().__init__(**kwargs)
        self.spec = spec
        cell_cls = LSTM if spec.rnn_type == "lstm" else GRU
        self.encoder_cells = [
            cell_cls(spec.hidden_size, return_sequences=True,
                     name=f"{self.name}_enc{i}")
            for i in range(spec.num_layers)]
        self.decoder_cells = [
            cell_cls(spec.hidden_size, return_sequences=True,
                     name=f"{self.name}_dec{i}")
            for i in range(spec.num_layers)]
        self.generator = (Dense(spec.generator_dim,
                                activation=spec.generator_activation,
                                name=f"{self.name}_gen")
                          if spec.generator_dim else None)
        # states per layer: LSTM carries (h, c), GRU carries h
        self.state_num = 2 if spec.rnn_type == "lstm" else 1

    @property
    def input_shape(self):
        s = self.spec
        return [(None, None, s.input_dim), (None, None, s.decoder_input_dim)]

    def build(self, rng, input_shape=None):
        s = self.spec
        shapes = input_shape or self.input_shape
        enc_shape, dec_shape = shapes
        keys = jax.random.split(rng, 2 * s.num_layers + 2)
        p: Dict[str, Any] = {}
        shape = enc_shape
        for i, cell in enumerate(self.encoder_cells):
            p[cell.name] = cell.build(keys[i], shape)
            shape = (shape[0], shape[1], s.hidden_size)
        shape = dec_shape
        for i, cell in enumerate(self.decoder_cells):
            p[cell.name] = cell.build(keys[s.num_layers + i], shape)
            shape = (shape[0], shape[1], s.hidden_size)
        if s.bridge in ("dense", "densenonlinear"):
            # Bridge.scala:38: one Dense over the flattened states
            dim = s.hidden_size * self.state_num * s.num_layers
            p["bridge"] = {"W": jax.random.normal(
                keys[-2], (dim, dim), param_dtype()) * (dim ** -0.5)}
        if self.generator is not None:
            p[self.generator.name] = self.generator.build(
                keys[-1], (None, None, s.hidden_size))
        return p

    # ---- pieces reused by call() and infer() ------------------------------
    def encode(self, params, enc_x) -> List:
        h = enc_x
        carries = []
        for cell in self.encoder_cells:
            h, carry = cell.run(params[cell.name], h)
            carries.append(carry)
        return carries

    def apply_bridge(self, params, carries: List) -> List:
        s = self.spec
        if s.bridge == "passthrough":
            return carries
        flat_parts = []
        for carry in carries:
            parts = carry if isinstance(carry, tuple) else (carry,)
            flat_parts.extend(parts)
        flat = jnp.concatenate(flat_parts, axis=-1)
        out = flat @ params["bridge"]["W"].astype(flat.dtype)
        if s.bridge == "densenonlinear":
            out = jnp.tanh(out)
        splits = jnp.split(out, self.state_num * s.num_layers, axis=-1)
        new_carries = []
        for i in range(s.num_layers):
            chunk = splits[i * self.state_num:(i + 1) * self.state_num]
            new_carries.append(tuple(chunk) if self.state_num == 2 else chunk[0])
        return new_carries

    def decode(self, params, dec_x, carries: List) -> Tuple[Any, List]:
        h = dec_x
        new_carries = []
        for cell, carry in zip(self.decoder_cells, carries):
            h, c = cell.run(params[cell.name], h, carry0=carry)
            new_carries.append(c)
        if self.generator is not None:
            h = self.generator.call(params[self.generator.name], h)
        return h, new_carries

    def call(self, params, x, *, training=False, rng=None):
        if not isinstance(x, (list, tuple)) or len(x) != 2:
            raise ValueError(f"{self.name}: Seq2seq expects "
                             f"[encoder_input, decoder_input]")
        enc_x, dec_x = x
        carries = self.apply_bridge(params, self.encode(params, enc_x))
        out, _ = self.decode(params, dec_x, carries)
        return out


@register_model
class Seq2seq(ZooModel):
    """``Seq2seq(encoder, decoder, inputShape, outputShape, bridge,
    generator)`` — configured by type instead of layer objects."""

    def __init__(self, rnn_type: str = "lstm", num_layers: int = 1,
                 hidden_size: int = 64, input_dim: int = 32,
                 decoder_input_dim: Optional[int] = None,
                 bridge: str = "passthrough",
                 generator_dim: Optional[int] = None,
                 generator_activation: Optional[str] = None,
                 name: Optional[str] = None):
        if rnn_type not in ("lstm", "gru"):
            raise ValueError(f"rnn_type must be lstm|gru, got {rnn_type!r}")
        if bridge not in ("passthrough", "dense", "densenonlinear"):
            raise ValueError(f"bridge must be passthrough|dense|densenonlinear,"
                             f" got {bridge!r}")
        self.rnn_type = rnn_type
        self.num_layers = int(num_layers)
        self.hidden_size = int(hidden_size)
        self.input_dim = int(input_dim)
        self.decoder_input_dim = int(decoder_input_dim
                                     if decoder_input_dim is not None
                                     else input_dim)
        self.bridge = bridge
        self.generator_dim = generator_dim
        self.generator_activation = generator_activation
        super().__init__(name=name)

    def build_model(self) -> _Seq2seqNet:
        return _Seq2seqNet(self, name=self.name + "_net")

    def infer(self, input: np.ndarray, start_sign: np.ndarray,
              max_seq_len: int = 30,
              stop_sign: Optional[np.ndarray] = None) -> np.ndarray:
        """Greedy generation (``Seq2seq.scala:112``): feed outputs back as the
        next decoder input. Requires the generator (or hidden) output dim to
        equal ``decoder_input_dim``."""
        if self.params is None:
            raise RuntimeError("no weights; fit() or init_weights() first")
        net: _Seq2seqNet = self.model
        params = self.params
        enc_x = jnp.asarray(np.asarray(input, np.float32))
        if enc_x.ndim == 2:
            enc_x = enc_x[None]
        cur = jnp.asarray(np.asarray(start_sign, np.float32))
        if cur.ndim == 1:
            cur = cur[None, None]  # (1, 1, D)
        elif cur.ndim == 2:
            cur = cur[:, None]

        enc_fn, step_fn = self._infer_fns()
        carries = enc_fn(params, enc_x)
        stop = (np.asarray(stop_sign, np.float32)
                if stop_sign is not None else None)
        done = np.zeros(enc_x.shape[0], bool)  # per-sequence finished flags
        frozen = None
        outs = []
        for _ in range(max_seq_len):
            y, carries = step_fn(params, cur, carries)
            step_out = np.asarray(y[:, 0])
            if frozen is not None:
                step_out = np.where(done[:, None], frozen, step_out)
            outs.append(step_out)
            if stop is not None:
                done |= np.isclose(step_out, stop, atol=1e-4).all(axis=-1)
                frozen = step_out
                if done.all():
                    break
            cur = jnp.asarray(step_out[:, None])
        return np.stack(outs, axis=1)

    def _infer_fns(self):
        """Jitted encode/decode-step closures, built once per model instance
        (re-jitting per ``infer`` call would recompile for every request)."""
        if getattr(self, "_cached_infer_fns", None) is None:
            from ...observability import instrument_jit
            net: _Seq2seqNet = self.model

            def enc_fn(p, e):
                return net.apply_bridge(p, net.encode(p, e))

            def step_fn(p, c, carries):
                return net.decode(p, c, carries)

            # compile accounting: a new encoder input length or batch size
            # is a legitimate compile; a retrace storm under steady load
            # means callers are feeding unpadded dynamic shapes
            self._cached_infer_fns = (
                instrument_jit(enc_fn, name="seq2seq.encode"),
                instrument_jit(step_fn, name="seq2seq.decode_step"))
        return self._cached_infer_fns

    def get_config(self) -> Dict[str, Any]:
        return {"rnn_type": self.rnn_type, "num_layers": self.num_layers,
                "hidden_size": self.hidden_size, "input_dim": self.input_dim,
                "decoder_input_dim": self.decoder_input_dim,
                "bridge": self.bridge, "generator_dim": self.generator_dim,
                "generator_activation": self.generator_activation}
