"""WideAndDeep recommender — parity with
``models/recommendation/WideAndDeep.scala:101`` and the feature builders in
``models/recommendation/Utils.scala:104-132`` (pyzoo ``wide_and_deep.py:29,94``).

TPU-native input contract (vs the reference's SparseTensor wide part): every
categorical column arrives as an integer id per example; the wide linear part
is a gather-sum from a (wide_total_dim, num_classes) weight table — identical
math to the reference's SparseDense over a multi-hot vector, but HBM-friendly
(gather) instead of a giant one-hot matmul. Indicator columns are one-hot
expanded inside the jitted graph (their dims are small), embed columns get
per-column Embedding tables, continuous columns pass through raw.

Inputs (by model_type):
  wide_n_deep: [wide_ids (B, n_wide), ind_ids (B, n_ind),
                embed_ids (B, n_embed), continuous (B, n_cont)]
  wide:        [wide_ids]
  deep:        [ind_ids, embed_ids, continuous]
(empty groups are omitted; ``ColumnFeatureInfo.input_arrays`` builds these
from a column dict, the ``row2Sample`` role.)

The per-column ``Embedding`` tables of the deep part ride the out-of-core
sharded embedding engine (``zoo.embed.sharded``, ``keras/sharded_embed.py``)
without model-code changes — tables row-partition over the ``model`` axis
with dedup'd gathers and sparse scatter-add grads once they outgrow a chip.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ...pipeline.api.keras.engine import Input, Lambda, Model, unique_name
from ...pipeline.api.keras.layers import Dense, Embedding, Merge, Select
from ..common.zoo_model import ZooModel, register_model
from .neural_cf import Recommender


@dataclasses.dataclass
class ColumnFeatureInfo:
    """``ColumnFeatureInfo`` (``WideAndDeep.scala:55-80``) — names + dims of
    each feature group, plus the vectorized sample builder."""

    wide_base_cols: Sequence[str] = ()
    wide_base_dims: Sequence[int] = ()
    wide_cross_cols: Sequence[str] = ()
    wide_cross_dims: Sequence[int] = ()
    indicator_cols: Sequence[str] = ()
    indicator_dims: Sequence[int] = ()
    embed_cols: Sequence[str] = ()
    embed_in_dims: Sequence[int] = ()
    embed_out_dims: Sequence[int] = ()
    continuous_cols: Sequence[str] = ()
    label: str = "label"

    @property
    def wide_dims(self) -> List[int]:
        return list(self.wide_base_dims) + list(self.wide_cross_dims)

    def input_arrays(self, table: Dict[str, np.ndarray], model_type: str
                     ) -> List[np.ndarray]:
        """Vectorized ``row2Sample`` (``Utils.scala:104-132``): build the
        model's input arrays from a dict of per-column numpy arrays."""
        outs: List[np.ndarray] = []
        wide_cols = list(self.wide_base_cols) + list(self.wide_cross_cols)
        if model_type in ("wide", "wide_n_deep") and wide_cols:
            outs.append(np.stack([np.asarray(table[c], np.int32)
                                  for c in wide_cols], axis=1))
        if model_type in ("deep", "wide_n_deep"):
            if self.indicator_cols:
                outs.append(np.stack([np.asarray(table[c], np.int32)
                                      for c in self.indicator_cols], axis=1))
            if self.embed_cols:
                outs.append(np.stack([np.asarray(table[c], np.int32)
                                      for c in self.embed_cols], axis=1))
            if self.continuous_cols:
                outs.append(np.stack([np.asarray(table[c], np.float32)
                                      for c in self.continuous_cols], axis=1))
        return outs


class _WideLinear(Embedding):
    """Wide part: per-column offset + gather from (wide_total, num_classes)
    weights, summed — the SparseDense linear over the concatenated multi-hot
    vector (``WideAndDeep.scala:118``), executed as a gather."""

    def __init__(self, wide_dims: Sequence[int], num_classes: int, **kwargs):
        super().__init__(int(sum(wide_dims)), num_classes, init="zero",
                         **kwargs)
        self.offsets = np.concatenate([[0], np.cumsum(wide_dims)[:-1]]
                                      ).astype(np.int32)

    def build(self, rng, input_shape):
        p = super().build(rng, input_shape)
        p["bias"] = jnp.zeros((self.output_dim,), jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        ids = x.astype(jnp.int32) + jnp.asarray(self.offsets)[None, :]
        rows = jnp.take(params["embeddings"], ids, axis=0)  # (B, n, C)
        return jnp.sum(rows, axis=1) + params["bias"]


@register_model
class WideAndDeep(Recommender):
    """``WideAndDeep(modelType, numClasses, columnInfo, hiddenLayers)``."""

    def __init__(self, model_type: str = "wide_n_deep", num_classes: int = 2,
                 column_info: Optional[ColumnFeatureInfo] = None,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 name: Optional[str] = None, **column_kwargs):
        if model_type not in ("wide", "deep", "wide_n_deep"):
            raise ValueError(f"model_type must be wide|deep|wide_n_deep, "
                             f"got {model_type!r}")
        self.model_type = model_type
        self.num_classes = int(num_classes)
        self.column_info = column_info or ColumnFeatureInfo(**column_kwargs)
        self.hidden_layers = tuple(int(h) for h in hidden_layers)
        ci = self.column_info
        if model_type != "deep" and not ci.wide_dims:
            raise ValueError("wide model needs wide_base/cross dims")
        if model_type != "wide" and not (ci.indicator_cols or ci.embed_cols
                                         or ci.continuous_cols):
            raise ValueError("deep model needs indicator/embed/continuous cols")
        super().__init__(name=name)

    # ---- graph ------------------------------------------------------------
    def _deep_tower(self, inputs: List, ci: ColumnFeatureInfo):
        parts = []
        if ci.indicator_cols:
            ind = inputs.pop(0)
            dims = list(ci.indicator_dims)

            def one_hot_concat(x):
                cols = [jnp.reshape(
                    jnp.eye(d, dtype=jnp.float32)[x[:, i].astype(jnp.int32)],
                    (x.shape[0], d)) for i, d in enumerate(dims)]
                return jnp.concatenate(cols, axis=-1)

            parts.append(Lambda(one_hot_concat, name=unique_name("indicator_"))(ind))
        if ci.embed_cols:
            emb = inputs.pop(0)
            for i, (din, dout) in enumerate(zip(ci.embed_in_dims,
                                                ci.embed_out_dims)):
                col = Select(1, i)(emb)
                parts.append(Embedding(int(din), int(dout), init="normal")(col))
        if ci.continuous_cols:
            parts.append(inputs.pop(0))
        h = (Merge(mode="concat", concat_axis=-1)(parts)
             if len(parts) > 1 else parts[0])
        for units in self.hidden_layers:
            h = Dense(units, activation="relu")(h)
        return Dense(self.num_classes)(h)

    def build_model(self) -> Model:
        ci = self.column_info
        inputs = []
        wide_var = None
        if self.model_type in ("wide", "wide_n_deep"):
            wide_in = Input(shape=(len(ci.wide_dims),))
            inputs.append(wide_in)
            wide_var = _WideLinear(ci.wide_dims, self.num_classes)(wide_in)
        deep_inputs = []
        if self.model_type in ("deep", "wide_n_deep"):
            if ci.indicator_cols:
                deep_inputs.append(Input(shape=(len(ci.indicator_cols),)))
            if ci.embed_cols:
                deep_inputs.append(Input(shape=(len(ci.embed_cols),)))
            if ci.continuous_cols:
                deep_inputs.append(Input(shape=(len(ci.continuous_cols),)))
            inputs.extend(deep_inputs)

        import jax
        softmax = Lambda(lambda z: jax.nn.softmax(z, axis=-1),
                         name=unique_name("softmax_"))
        if self.model_type == "wide":
            out = softmax(wide_var)
        elif self.model_type == "deep":
            out = softmax(self._deep_tower(list(deep_inputs), ci))
        else:
            deep_var = self._deep_tower(list(deep_inputs), ci)
            out = softmax(Merge(mode="sum")([wide_var, deep_var]))
        return Model(inputs if len(inputs) > 1 else inputs[0], out)

    def get_config(self) -> Dict[str, Any]:
        ci = self.column_info
        return {"model_type": self.model_type,
                "num_classes": self.num_classes,
                "hidden_layers": list(self.hidden_layers),
                "wide_base_cols": list(ci.wide_base_cols),
                "wide_base_dims": list(ci.wide_base_dims),
                "wide_cross_cols": list(ci.wide_cross_cols),
                "wide_cross_dims": list(ci.wide_cross_dims),
                "indicator_cols": list(ci.indicator_cols),
                "indicator_dims": list(ci.indicator_dims),
                "embed_cols": list(ci.embed_cols),
                "embed_in_dims": list(ci.embed_in_dims),
                "embed_out_dims": list(ci.embed_out_dims),
                "continuous_cols": list(ci.continuous_cols),
                "label": ci.label}
