"""SessionRecommender — session-based GRU recommender, parity with
``models/recommendation/SessionRecommender.scala:45``.

Topology (identical): session item ids (B, session_length) → Embedding →
stacked GRUs (last one return_sequences=False) → Dense(item_count); with
``include_history``: purchase-history ids → Embedding → sum over time → MLP
→ Dense(item_count); merged by sum; softmax output over item_count classes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ...pipeline.api.keras.engine import Input, Lambda, Model, unique_name
from ...pipeline.api.keras.layers import (GRU, Activation, Dense, Embedding,
                                          Merge)
from ..common.zoo_model import register_model
from .neural_cf import Recommender


@register_model
class SessionRecommender(Recommender):
    """``SessionRecommender(itemCount, itemEmbed, rnnHiddenLayers,
    sessionLength, includeHistory, mlpHiddenLayers, historyLength)``."""

    def __init__(self, item_count: int, item_embed: int = 100,
                 rnn_hidden_layers: Sequence[int] = (40, 20),
                 session_length: int = 10, include_history: bool = False,
                 mlp_hidden_layers: Sequence[int] = (40, 20),
                 history_length: int = 0, name: Optional[str] = None):
        if include_history and history_length <= 0:
            raise ValueError("include_history requires history_length > 0")
        self.item_count = int(item_count)
        self.item_embed = int(item_embed)
        self.rnn_hidden_layers = tuple(int(h) for h in rnn_hidden_layers)
        self.session_length = int(session_length)
        self.include_history = bool(include_history)
        self.mlp_hidden_layers = tuple(int(h) for h in mlp_hidden_layers)
        self.history_length = int(history_length)
        super().__init__(name=name)

    def build_model(self) -> Model:
        inp_rnn = Input(shape=(self.session_length,))
        h = Embedding(self.item_count + 1, self.item_embed,
                      init="normal")(inp_rnn)
        for units in self.rnn_hidden_layers[:-1]:
            h = GRU(units, return_sequences=True)(h)
        h = GRU(self.rnn_hidden_layers[-1], return_sequences=False)(h)
        rnn_logits = Dense(self.item_count)(h)

        if not self.include_history:
            out = Activation("softmax")(rnn_logits)
            return Model(inp_rnn, out)

        inp_mlp = Input(shape=(self.history_length,))
        his = Embedding(self.item_count + 1, self.item_embed)(inp_mlp)
        pooled = Lambda(lambda e: jnp.sum(e, axis=1),
                        name=unique_name("histsum_"))(his)
        m = pooled
        for units in self.mlp_hidden_layers:
            m = Dense(units, activation="relu")(m)
        mlp_logits = Dense(self.item_count)(m)
        merged = Merge(mode="sum")([rnn_logits, mlp_logits])
        out = Activation("softmax")(merged)
        return Model([inp_rnn, inp_mlp], out)

    def recommend_for_session(self, sessions: np.ndarray, max_items: int = 5,
                              zero_based_label: bool = True,
                              batch_size: int = 1024
                              ) -> List[List[Tuple[int, float]]]:
        """``recommendForSession``: top-k (item, probability) per session."""
        probs = self.predict(sessions, batch_size=batch_size)
        top = np.argsort(-probs, axis=1)[:, :max_items]
        base = 0 if zero_based_label else 1
        return [[(int(i) + base, float(p[i])) for i in row]
                for row, p in zip(top, probs)]

    def get_config(self) -> Dict[str, Any]:
        return {"item_count": self.item_count, "item_embed": self.item_embed,
                "rnn_hidden_layers": list(self.rnn_hidden_layers),
                "session_length": self.session_length,
                "include_history": self.include_history,
                "mlp_hidden_layers": list(self.mlp_hidden_layers),
                "history_length": self.history_length}
