"""NeuralCF — neural collaborative filtering recommender, parity with
``models/recommendation/NeuralCF.scala:45-104`` (and pyzoo
``models/recommendation/neuralcf.py:30``).

Graph (same topology as the reference): input (B, 2) of [user_id, item_id] →
MLP tower (user/item embeddings concat → Dense-relu stack) and optionally a
matrix-factorization tower (separate embeddings, elementwise product), concat
→ softmax over ``class_num`` classes.

TPU notes: both towers are embedding gathers feeding dense matmuls — the
whole model is one fused XLA program on the MXU; embedding tables live in
HBM and shard over the ``model`` axis when tensor parallelism is on.
Production-scale tables opt into the out-of-core row-partitioned engine
purely through configuration — ``zoo.embed.sharded`` upgrades the plain
``Embedding`` gathers at step-build time (``keras/sharded_embed.py``:
dedup'd unique-row gathers, sparse scatter-add grads, host-RAM cold
tier) with no change to this model code; parity vs the dense lookup is
asserted in ``tests/test_sharded_embedding.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ...pipeline.api.keras.engine import Input, Model
from ...pipeline.api.keras.layers import Dense, Embedding, Merge, Select
from ..common.ranker import RankerMixin
from ..common.zoo_model import ZooModel, register_model


class Recommender(RankerMixin, ZooModel):
    """Base recommender — ``models/recommendation/Recommender.scala``:
    convenience prediction APIs over (user, item) pairs."""

    def predict_user_item_pair(self, user_item_pairs: np.ndarray,
                               batch_size: int = 1024) -> np.ndarray:
        """Probability per (user, item) row — ``predictUserItemPair``."""
        return self.predict(np.asarray(user_item_pairs), batch_size=batch_size)

    @staticmethod
    def _top_ids(ids: np.ndarray, probs: np.ndarray,
                 max_items: int) -> np.ndarray:
        """Recommender.scala:55,92-96 sorts by (predicted class desc,
        probability of that class desc): a confidently-rated-5 item
        outranks any rated-4 item regardless of probability mass."""
        if probs.ndim > 1:
            cls = np.argmax(probs, axis=1)
            p_cls = probs[np.arange(len(cls)), cls]
            top = np.lexsort((-p_cls, -cls))[:max_items]
        else:
            top = np.argsort(-probs)[:max_items]
        return ids[top]

    def recommend_for_user(self, user_id: int, candidate_items: np.ndarray,
                           max_items: int = 10,
                           batch_size: int = 1024) -> np.ndarray:
        """Top-``max_items`` item ids for one user — ``recommendForUser``.
        Scores every candidate item in one batched forward."""
        items = np.asarray(candidate_items).reshape(-1)
        pairs = np.stack([np.full_like(items, user_id), items], axis=1)
        return self._top_ids(items, self.predict(pairs, batch_size=batch_size),
                             max_items)

    def recommend_for_item(self, item_id: int, candidate_users: np.ndarray,
                           max_items: int = 10,
                           batch_size: int = 1024) -> np.ndarray:
        """Top-``max_items`` user ids for one item — ``recommendForItem``
        (``Recommender.scala:67``), same class-then-probability ordering."""
        users = np.asarray(candidate_users).reshape(-1)
        pairs = np.stack([users, np.full_like(users, item_id)], axis=1)
        return self._top_ids(users, self.predict(pairs, batch_size=batch_size),
                             max_items)


@register_model
class NeuralCF(Recommender):
    """``NeuralCF(userCount, itemCount, numClasses, userEmbed, itemEmbed,
    hiddenLayers, includeMF, mfEmbed)`` — NeuralCF.scala:45-104."""

    def __init__(self, user_count: int, item_count: int, class_num: int,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20,
                 name: Optional[str] = None):
        self.user_count = int(user_count)
        self.item_count = int(item_count)
        self.class_num = int(class_num)
        self.user_embed = int(user_embed)
        self.item_embed = int(item_embed)
        self.hidden_layers = tuple(int(h) for h in hidden_layers)
        self.include_mf = bool(include_mf)
        self.mf_embed = int(mf_embed)
        super().__init__(name=name)

    def build_model(self) -> Model:
        inp = Input(shape=(2,), name=self.name + "_input" if self.name else None)
        user = Select(1, 0)(inp)   # (B,) user ids
        item = Select(1, 1)(inp)   # (B,) item ids

        # +1: the reference reserves id 0 / uses 1-based ids (NeuralCF.scala:67)
        mlp_user = Embedding(self.user_count + 1, self.user_embed,
                             init="normal")(user)
        mlp_item = Embedding(self.item_count + 1, self.item_embed,
                             init="normal")(item)
        h = Merge(mode="concat", concat_axis=-1)([mlp_user, mlp_item])
        for units in self.hidden_layers:
            h = Dense(units, activation="relu")(h)

        if self.include_mf:
            if self.mf_embed <= 0:
                raise ValueError("mf_embed must be positive when include_mf")
            mf_user = Embedding(self.user_count + 1, self.mf_embed,
                                init="normal")(user)
            mf_item = Embedding(self.item_count + 1, self.mf_embed,
                                init="normal")(item)
            mf = Merge(mode="mul")([mf_user, mf_item])
            h = Merge(mode="concat", concat_axis=-1)([h, mf])
        out = Dense(self.class_num, activation="softmax")(h)
        return Model(inp, out)

    def get_config(self) -> Dict[str, Any]:
        return {"user_count": self.user_count, "item_count": self.item_count,
                "class_num": self.class_num, "user_embed": self.user_embed,
                "item_embed": self.item_embed,
                "hidden_layers": list(self.hidden_layers),
                "include_mf": self.include_mf, "mf_embed": self.mf_embed}
