from .neural_cf import NeuralCF, Recommender  # noqa: F401
