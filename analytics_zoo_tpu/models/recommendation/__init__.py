from .neural_cf import NeuralCF, Recommender  # noqa: F401
from .session_recommender import SessionRecommender  # noqa: F401
from .wide_and_deep import ColumnFeatureInfo, WideAndDeep  # noqa: F401
