"""analytics_zoo_tpu — a TPU-native analytics + AI framework with the
capabilities of robert-sbd/analytics-zoo, re-designed for JAX/XLA/pjit/pallas.

Layer map (mirrors SURVEY.md §1):
  common/    runtime bring-up (ZooContext ≅ NNContext)
  feature/   data layer (FeatureSet, image/text pipelines, Preprocessing)
  pipeline/  model API (keras-style + autograd), estimator, nnframes, inference
  models/    built-in model zoo (NCF, Wide&Deep, TextClassifier, ...)
  ops/       pallas TPU kernels
  parallel/  mesh, shardings, collectives, ring attention
  serving/   cluster-serving equivalent
  utils/     tensorboard writer, checkpointing
"""

__version__ = "0.1.0"

from .common.context import init_zoo_context, get_zoo_context  # noqa: F401
