"""analytics_zoo_tpu — a TPU-native analytics + AI framework with the
capabilities of robert-sbd/analytics-zoo, re-designed for JAX/XLA/pjit/pallas.

Layer map (mirrors SURVEY.md §1):
  common/    runtime bring-up (ZooContext ≅ NNContext), triggers
  feature/   data layer (FeatureSet + DiskFeatureSet, image/image3d/text
             pipelines, Preprocessing combinators)
  native/    ctypes binding for the C++ host IO library (native/zoo_io.cc)
  pipeline/  model API (keras/keras2 + autograd + onnx + Net/TorchNet/
             TFNet frozen-graph import), estimator, nnframes, inference
             runtime (bf16 + calibrated static int8)
  models/    built-in model zoo (recommendation, anomaly detection, text,
             seq2seq, image classification, object detection, caffe import)
  ops/       attention + pallas TPU kernels (flash attention, int8 matmul)
  parallel/  mesh (data/pipe/seq/expert/model axes), shardings, ring
             attention, GPipe pipeline schedule; SparseMoE lives with the
             layers; multi-host bring-up in common/
  serving/   cluster-serving equivalent (stream, batching, backpressure)
  tfpark/    BERT estimators, GANEstimator, torch weight import
  ray/       task/actor runtime (RayOnSpark role)
  utils/     tensorboard writer/reader, checkpointing, profiling, proto
"""

__version__ = "0.1.0"

from .common.context import init_zoo_context, get_zoo_context  # noqa: F401
