"""TFDataset — the TFPark feed contract
(reference: ``pyzoo/zoo/pipeline/api/net/tf_dataset.py:112-212``).

The reference's TFDataset describes a distributed collection (RDD-backed)
plus the tensor structure it will be fed into a TF graph as: per-element
name/shape/dtype metas, a global ``batch_size`` for training that must
divide over the cluster's cores, and a per-thread ``batch_per_thread`` for
inference. Here the same contract maps onto the TPU runtime: the structure
feeds graph ``Input`` nodes, ``batch_size`` must divide over the mesh's
``data`` axis (the core-count rule of ``tf_dataset.py:134-141``), and the
payload is served through :class:`~analytics_zoo_tpu.feature.FeatureSet`
(DRAM cache + double-buffered device feed) instead of an RDD.

Structures may be a single array, a list/tuple, or a dict (flattened in
sorted-key order, the same convention as TF's ``nest``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..feature import FeatureSet
from ..parallel import mesh as mesh_lib

__all__ = ["TensorMeta", "TFDataset"]


class TensorMeta:
    """Name/shape/dtype of one element slot (``tf_dataset.py:96-109`` role).
    ``shape`` excludes the batch dimension."""

    def __init__(self, dtype: Any = np.float32,
                 shape: Sequence[int] = (),
                 name: Optional[str] = None):
        self.dtype = np.dtype(dtype)
        self.shape = tuple(int(s) for s in shape)
        self.name = name

    def __repr__(self):
        return f"TensorMeta(dtype={self.dtype}, shape={self.shape}, " \
               f"name={self.name!r})"


def _flatten(structure) -> Tuple[List[Any], Any]:
    """Flatten an array / list / dict structure into (leaves, treedef).
    Dicts flatten in sorted-key order (the TF nest convention)."""
    if isinstance(structure, dict):
        keys = sorted(structure)
        return [structure[k] for k in keys], ("dict", keys)
    if isinstance(structure, (list, tuple)):
        return list(structure), ("list", len(structure))
    return [structure], ("leaf",)


def _pack(leaves: List[Any], treedef):
    if treedef[0] == "dict":
        return dict(zip(treedef[1], leaves))
    if treedef[0] == "list":
        return list(leaves)
    return leaves[0]


class TFDataset:
    """Feed contract: tensor structure + batching policy + data.

    Use the factories: :meth:`from_ndarrays` (in-memory arrays, the
    ``TFNdarrayDataset`` role) or :meth:`from_feature_set` (an existing
    FeatureSet pipeline).

    ``batch_size`` (training) must be a multiple of the mesh's data-parallel
    size — the TPU analogue of the reference's "multiple of total core num"
    rule; ``batch_per_thread`` (inference/eval) is per-device. Exactly one
    of the two is active, as in the reference.
    """

    def __init__(self, features, labels=None, *, batch_size: int = -1,
                 batch_per_thread: int = -1,
                 val_features=None, val_labels=None):
        if batch_size > 0 and batch_per_thread > 0:
            raise ValueError("batch_size and batch_per_thread should not be "
                             "set simultaneously")
        dp = mesh_lib.data_parallel_size(mesh_lib.global_mesh())
        if batch_size > 0 and batch_size % dp != 0:
            raise ValueError(
                f"batch_size should be a multiple of the data-parallel "
                f"device count, but got batch_size: {batch_size} where "
                f"data-parallel count is {dp}")
        self.batch_size = batch_size
        self.batch_per_thread = batch_per_thread
        self.has_batch = batch_size > 0 or batch_per_thread > 0

        feat_leaves, self._feat_def = _flatten(features)
        self.features = [np.asarray(a) for a in feat_leaves]
        n = len(self.features[0])
        for a in self.features:
            if len(a) != n:
                raise ValueError("feature arrays disagree on length")
        self.labels = None
        self._label_def = None
        if labels is not None:
            lab_leaves, self._label_def = _flatten(labels)
            self.labels = [np.asarray(a) for a in lab_leaves]
            for a in self.labels:
                if len(a) != n:
                    raise ValueError("label arrays disagree on length with "
                                     "features")
        self.val_features = self.val_labels = None
        if val_features is not None:
            vf, _ = _flatten(val_features)
            self.val_features = [np.asarray(a) for a in vf]
            if val_labels is not None:
                vl, _ = _flatten(val_labels)
                self.val_labels = [np.asarray(a) for a in vl]

        self.tensor_structure = _pack(
            [TensorMeta(a.dtype, a.shape[1:], name=f"input_{i}")
             for i, a in enumerate(self.features)], self._feat_def)

    # -- factories ----------------------------------------------------------
    @classmethod
    def from_ndarrays(cls, tensors, batch_size: int = -1,
                      batch_per_thread: int = -1,
                      val_tensors=None) -> "TFDataset":
        """``TFDataset.from_ndarrays`` (``tf_dataset.py:807`` role):
        ``tensors`` is either the feature structure, or a (features, labels)
        tuple."""
        feats, labels = cls._split_xy(tensors)
        vf = vl = None
        if val_tensors is not None:
            vf, vl = cls._split_xy(val_tensors)
        return cls(feats, labels, batch_size=batch_size,
                   batch_per_thread=batch_per_thread,
                   val_features=vf, val_labels=vl)

    @classmethod
    def from_feature_set(cls, fs: FeatureSet, batch_size: int = -1,
                         batch_per_thread: int = -1) -> "TFDataset":
        """Wrap an existing FeatureSet (the ``TFDataset.from_feature_set``
        role — the reference feeds FeatureSet RDDs the same way)."""
        return cls(fs.x, fs.y, batch_size=batch_size,
                   batch_per_thread=batch_per_thread)

    @classmethod
    def from_image_set(cls, image_set, batch_size: int = -1,
                       batch_per_thread: int = -1) -> "TFDataset":
        """``TFDataset.from_image_set`` role: a (possibly transformed)
        ``feature.image.ImageSet`` becomes the feed — dense image batch +
        labels when present."""
        x = image_set.to_array()
        y = getattr(image_set, "labels", None)
        return cls(x, y, batch_size=batch_size,
                   batch_per_thread=batch_per_thread)

    @classmethod
    def from_text_set(cls, text_set, batch_size: int = -1,
                      batch_per_thread: int = -1) -> "TFDataset":
        """``TFDataset.from_text_set`` role: a processed
        ``feature.text.TextSet`` (tokenize/word2idx/shape_sequence already
        applied) becomes the feed."""
        x, y = text_set.to_arrays()
        return cls(x, y, batch_size=batch_size,
                   batch_per_thread=batch_per_thread)

    @staticmethod
    def _split_xy(tensors):
        """A 2-TUPLE means (features, labels); use a list for a plain
        two-feature structure (the ambiguity is resolved the same way the
        reference's ndarray factory does)."""
        if isinstance(tensors, tuple) and len(tensors) == 2:
            return tensors[0], tensors[1]
        return tensors, None

    # -- consumption --------------------------------------------------------
    @property
    def n_examples(self) -> int:
        return len(self.features[0])

    def feature_set(self, *, shuffle: bool = True, seed: int = 0) -> FeatureSet:
        x = self.features if len(self.features) > 1 else self.features[0]
        y = None
        if self.labels is not None:
            y = self.labels if len(self.labels) > 1 else self.labels[0]
        return FeatureSet.array(x, y, shuffle=shuffle, seed=seed)

    def feature_arrays(self):
        """Feature payload in fit/predict form (list or single array)."""
        return self.features if len(self.features) > 1 else self.features[0]

    def label_arrays(self):
        if self.labels is None:
            return None
        return self.labels if len(self.labels) > 1 else self.labels[0]

    def validation_arrays(self):
        """(val_x, val_y) in fit form, or None."""
        if self.val_features is None or self.val_labels is None:
            return None
        vx = (self.val_features if len(self.val_features) > 1
              else self.val_features[0])
        vy = (self.val_labels if len(self.val_labels) > 1
              else self.val_labels[0])
        return (vx, vy)

    def effective_batch(self, default: int = 32) -> int:
        """The concrete batch size to run with: global ``batch_size`` for
        training, ``batch_per_thread`` × data-parallel size for inference."""
        dp = mesh_lib.data_parallel_size(mesh_lib.global_mesh())
        if self.batch_size > 0:
            return self.batch_size
        if self.batch_per_thread > 0:
            return self.batch_per_thread * dp
        return default
