"""BERTNER + BERTSQuAD — parity with the reference's prebuilt BERT
estimators (``pyzoo/zoo/tfpark/text/estimator/bert_ner.py``: sequence
output → dense(num_entities) with mask-weighted softmax CE;
``bert_squad.py``: sequence output → dense(2) split into start/end logits).

The native design reuses :mod:`.bert_classifier`'s pattern — one Layer
wrapping the native BERT encoder, trained with compile/fit. Padding
handling is by ignore-labels: token positions labeled ``< 0`` are excluded
from the NER loss (the masked-CE normalization of the reference's
``_bert_ner_model_fn``), so the loss needs no side channel to the
attention mask.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.common.zoo_model import ZooModel, register_model
from ..pipeline.api.keras.engine import Layer
from ..pipeline.api.keras.layers import BERT, Dense, Dropout
from .bert_classifier import install_pretrained_bert, make_bert_inputs

__all__ = ["BERTNER", "BERTSQuAD", "masked_token_scce", "squad_span_loss"]


def masked_token_scce(y_true, y_pred):
    """Mean CE over tokens whose label ≥ 0 (mask-weighted loss of
    ``_bert_ner_model_fn``)."""
    labels = jnp.asarray(y_true, jnp.int32)
    mask = (labels >= 0).astype(jnp.float32)
    # NER tag-set head (~10 labels): the (N, V) tensor is tiny and the
    # masked pick needs the per-token log-probs anyway
    logp = jax.nn.log_softmax(jnp.asarray(y_pred, jnp.float32), axis=-1)  # zoolint: disable=ZL012 small tag-set head
    picked = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None],
                                 axis=-1)[..., 0]
    return jnp.sum(-picked * mask) / jnp.maximum(jnp.sum(mask), 1e-12)


def squad_span_loss(y_true, y_pred):
    """y_true (B, 2) start/end positions; y_pred (B, T, 2) logits.
    Mean of start CE and end CE (``bert_squad.py`` semantics)."""
    spans = jnp.asarray(y_true, jnp.int32)
    logits = jnp.asarray(y_pred, jnp.float32)

    def ce(lg, pos):
        # span logits over T positions (seq-len wide, not vocab-wide)
        logp = jax.nn.log_softmax(lg, axis=-1)  # zoolint: disable=ZL012 seq-len span head, not a vocab head
        return -jnp.take_along_axis(logp, pos[:, None], axis=-1)[:, 0]

    return jnp.mean(0.5 * (ce(logits[..., 0], spans[:, 0])
                           + ce(logits[..., 1], spans[:, 1])))


class _BertTokenHeadNet(Layer):
    """BERT encoder → per-token dense head (shared by NER and SQuAD)."""

    def __init__(self, spec, head_dim: int, **kwargs):
        super().__init__(**kwargs)
        self.spec = spec
        self.bert = BERT(vocab=spec.vocab, hidden_size=spec.hidden_size,
                         n_block=spec.n_block, n_head=spec.n_head,
                         seq_len=spec.seq_len,
                         intermediate_size=spec.intermediate_size,
                         hidden_drop=spec.hidden_drop,
                         attn_drop=spec.attn_drop,
                         name=f"{self.name}_bert")
        self.drop = Dropout(spec.hidden_drop, name=f"{self.name}_drop")
        self.head = Dense(head_dim, name=f"{self.name}_head")

    @property
    def input_shape(self):
        return [(None, self.spec.seq_len)] * 4

    def build(self, rng, input_shape=None):
        shapes = input_shape or self.input_shape
        k1, k2 = jax.random.split(rng)
        return {"bert": self.bert.build(k1, shapes),
                "head": self.head.build(
                    k2, (None, self.spec.seq_len, self.spec.hidden_size))}

    def initial_state(self, input_shape=None):
        return {}

    def call(self, params, x, *, training=False, rng=None):
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        seq, _ = self.bert.call(params["bert"], x, training=training, rng=r1)
        seq = self.drop.call({}, seq, training=training, rng=r2)
        return self.head.call(params["head"], seq)


class _BertTokenEstimator(ZooModel):
    """Shared NER/SQuAD plumbing (config, build, weight import)."""

    _HEAD_DIM: int = 0

    def __init__(self, vocab: int = 40990, hidden_size: int = 768,
                 n_block: int = 12, n_head: int = 12, seq_len: int = 512,
                 intermediate_size: int = 3072, hidden_drop: float = 0.1,
                 attn_drop: float = 0.1, name: Optional[str] = None):
        self.vocab = int(vocab)
        self.hidden_size = int(hidden_size)
        self.n_block = int(n_block)
        self.n_head = int(n_head)
        self.seq_len = int(seq_len)
        self.intermediate_size = int(intermediate_size)
        self.hidden_drop = float(hidden_drop)
        self.attn_drop = float(attn_drop)
        super().__init__(name=name)

    def build_model(self) -> _BertTokenHeadNet:
        return _BertTokenHeadNet(self, self._HEAD_DIM,
                                 name=self.name + "_net")

    def get_config(self) -> Dict[str, Any]:
        return {"vocab": self.vocab, "hidden_size": self.hidden_size,
                "n_block": self.n_block, "n_head": self.n_head,
                "seq_len": self.seq_len,
                "intermediate_size": self.intermediate_size,
                "hidden_drop": self.hidden_drop,
                "attn_drop": self.attn_drop}

    def make_inputs(self, token_ids, token_type_ids=None,
                    attention_mask=None):
        return make_bert_inputs(token_ids, token_type_ids, attention_mask)

    def load_pretrained(self, state_dict: Mapping[str, Any]):
        return install_pretrained_bert(self, state_dict)

    def compile(self, optimizer="adam", loss=None, metrics=None, **kwargs):
        loss = loss or self._default_loss()
        return super().compile(optimizer=optimizer, loss=loss,
                               metrics=metrics, **kwargs)


@register_model
class BERTNER(_BertTokenEstimator):
    """``BERTNER(num_entities, ...)`` — token labels < 0 are ignore
    positions (padding). ``predict_tags`` returns per-token argmax ids."""

    _HEAD_DIM = 0  # set per instance

    def __init__(self, num_entities: int, **kwargs):
        self.num_entities = int(num_entities)
        self._HEAD_DIM = self.num_entities
        super().__init__(**kwargs)

    def get_config(self):
        cfg = super().get_config()
        cfg["num_entities"] = self.num_entities
        return cfg

    def _default_loss(self):
        return masked_token_scce

    def predict_tags(self, inputs, batch_size: int = 32) -> np.ndarray:
        logits = np.asarray(self.predict(inputs, batch_size=batch_size))
        return np.argmax(logits, axis=-1)


@register_model
class BERTSQuAD(_BertTokenEstimator):
    """``BERTSQuAD(...)`` — span extraction: output (B, T, 2) start/end
    logits; targets (B, 2) positions."""

    _HEAD_DIM = 2

    def _default_loss(self):
        return squad_span_loss

    def predict_spans(self, inputs, batch_size: int = 32):
        """(start, end) argmax positions with end ≥ start enforced by a
        triangular joint-score sweep."""
        logits = np.asarray(self.predict(inputs, batch_size=batch_size))
        start_lp = logits[..., 0]
        end_lp = logits[..., 1]
        t = start_lp.shape[1]
        joint = start_lp[:, :, None] + end_lp[:, None, :]
        joint = np.where(np.triu(np.ones((t, t), bool))[None], joint,
                         -np.inf)
        flat = joint.reshape(joint.shape[0], -1).argmax(axis=1)
        return np.stack([flat // t, flat % t], axis=1)
