"""BERTClassifier + HuggingFace/torch weight import — parity with
``pyzoo/zoo/tfpark/text/estimator/bert_classifier.py`` (the reference fine-
tunes a TF BERT under a TFEstimator; here the native ``layers.BERT`` encoder
fine-tunes under the ordinary jitted compile/fit stack) and TFPark's
checkpoint-import role (``bert_estimator.py`` init_from_checkpoint).

Numerical parity with the transformers implementation is golden-tested in
``tests/test_bert_oracle.py`` (same weights → same sequence/pooled outputs).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..pipeline.api.keras.engine import Layer, dispatch_layer
from ..pipeline.api.keras.layers import BERT, Dense, Dropout
from ..models.common.zoo_model import ZooModel, register_model


class _BertClassifierNet(Layer):
    """BERT encoder → pooled → dropout → softmax head, as one Layer."""

    def __init__(self, spec: "BERTClassifier", **kwargs):
        super().__init__(**kwargs)
        self.spec = spec
        self.bert = BERT(vocab=spec.vocab, hidden_size=spec.hidden_size,
                         n_block=spec.n_block, n_head=spec.n_head,
                         seq_len=spec.seq_len,
                         intermediate_size=spec.intermediate_size,
                         hidden_drop=spec.hidden_drop,
                         attn_drop=spec.attn_drop,
                         name=f"{self.name}_bert")
        self.drop = Dropout(spec.hidden_drop, name=f"{self.name}_drop")
        self.cls = Dense(spec.num_classes, activation="softmax",
                         name=f"{self.name}_cls")

    @property
    def input_shape(self):
        t = self.spec.seq_len
        return [(None, t)] * 4

    def build(self, rng, input_shape=None):
        shapes = input_shape or self.input_shape
        k1, k2 = jax.random.split(rng)
        return {"bert": self.bert.build(k1, shapes),
                "cls": self.cls.build(k2, (None, self.spec.hidden_size))}

    def initial_state(self, input_shape=None):
        return {}

    def call(self, params, x, *, training=False, rng=None):
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        _, pooled = self.bert.call(params["bert"], x, training=training,
                                   rng=r1)
        pooled = self.drop.call({}, pooled, training=training, rng=r2)
        # the head goes through dispatch_layer so loss resolution can fuse
        # it (keras/fused_loss.py) and the inference runtime can calibrate/
        # quantize it like any container-dispatched Dense
        y, _ = dispatch_layer(self.cls, params["cls"], {}, pooled,
                              training=training, rng=None)
        return y

    def fused_head(self):
        """Fused LM-head loss resolution (``keras/fused_loss.py``)."""
        return self.cls, ("cls",)


@register_model
class BERTClassifier(ZooModel):
    """``BERTClassifier(num_classes, bert_config...)`` — input
    ``[token_ids, token_type_ids, position_ids, attention_mask]`` (each
    (B, seq_len); build them with ``make_inputs``)."""

    def __init__(self, num_classes: int, vocab: int = 40990,
                 hidden_size: int = 768, n_block: int = 12, n_head: int = 12,
                 seq_len: int = 512, intermediate_size: int = 3072,
                 hidden_drop: float = 0.1, attn_drop: float = 0.1,
                 name: Optional[str] = None):
        self.num_classes = int(num_classes)
        self.vocab = int(vocab)
        self.hidden_size = int(hidden_size)
        self.n_block = int(n_block)
        self.n_head = int(n_head)
        self.seq_len = int(seq_len)
        self.intermediate_size = int(intermediate_size)
        self.hidden_drop = float(hidden_drop)
        self.attn_drop = float(attn_drop)
        super().__init__(name=name)

    def build_model(self) -> _BertClassifierNet:
        return _BertClassifierNet(self, name=self.name + "_net")

    def get_config(self) -> Dict[str, Any]:
        return {"num_classes": self.num_classes, "vocab": self.vocab,
                "hidden_size": self.hidden_size, "n_block": self.n_block,
                "n_head": self.n_head, "seq_len": self.seq_len,
                "intermediate_size": self.intermediate_size,
                "hidden_drop": self.hidden_drop,
                "attn_drop": self.attn_drop}

    def make_inputs(self, token_ids: np.ndarray,
                    token_type_ids: Optional[np.ndarray] = None,
                    attention_mask: Optional[np.ndarray] = None):
        """[ids, token_type, position, mask] from just token ids."""
        return make_bert_inputs(token_ids, token_type_ids, attention_mask)

    def load_pretrained(self, state_dict: Mapping[str, Any]) -> "BERTClassifier":
        """Install encoder weights from a HuggingFace/torch BERT
        ``state_dict`` (classifier head keeps its fresh init — the
        fine-tuning setup of ``bert_classifier.py``)."""
        return install_pretrained_bert(self, state_dict)


def make_bert_inputs(token_ids: np.ndarray,
                     token_type_ids: Optional[np.ndarray] = None,
                     attention_mask: Optional[np.ndarray] = None):
    """[ids, token_type, position, mask] from just token ids — the input
    assembly every BERT estimator shares."""
    ids = np.asarray(token_ids, np.int32)
    b, t = ids.shape
    tt = (np.asarray(token_type_ids, np.int32)
          if token_type_ids is not None else np.zeros((b, t), np.int32))
    pos = np.tile(np.arange(t, dtype=np.int32), (b, 1))
    mask = (np.asarray(attention_mask, np.float32)
            if attention_mask is not None else np.ones((b, t), np.float32))
    return [ids, tt, pos, mask]


def install_pretrained_bert(model, state_dict: Mapping[str, Any]):
    """Install torch BERT encoder weights into a ZooModel whose param tree
    has a ``"bert"`` entry; the task head keeps its fresh init."""
    if model.params is None:
        model.init_weights()
    bert_params = bert_params_from_torch(state_dict, model.n_block)
    params = dict(model.params)
    params["bert"] = _check_tree_shapes(model.params["bert"], bert_params)
    model.params = params
    return model


def _check_tree_shapes(template, loaded):
    t_leaves, t_def = jax.tree_util.tree_flatten(template)
    l_leaves, l_def = jax.tree_util.tree_flatten(loaded)
    if t_def != l_def:
        raise ValueError(f"imported BERT structure mismatch:\n{t_def}\nvs\n{l_def}")
    for a, b in zip(t_leaves, l_leaves):
        if np.shape(a) != np.shape(b):
            raise ValueError(f"shape mismatch: expected {np.shape(a)}, "
                             f"imported {np.shape(b)}")
    return jax.tree_util.tree_unflatten(
        t_def, [jnp.asarray(np.asarray(b), a.dtype)
                for a, b in zip(t_leaves, l_leaves)])


def bert_params_from_torch(state_dict: Mapping[str, Any],
                           n_block: int) -> Dict[str, Any]:
    """Map a transformers ``BertModel.state_dict()`` onto the native
    ``layers.BERT`` param tree. torch ``Linear.weight`` is (out, in) —
    transposed into this package's (in, out) ``W`` layout; per-head q/k/v
    projections concatenate into the fused qkv kernel."""

    def t(key):  # tensor → np, transposing Linear kernels at call sites
        v = state_dict[key]
        return np.asarray(v.detach().cpu().numpy()
                          if hasattr(v, "detach") else v)

    def dense(prefix):
        return {"W": t(f"{prefix}.weight").T, "b": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"gamma": t(f"{prefix}.weight"), "beta": t(f"{prefix}.bias")}

    p: Dict[str, Any] = {
        "word": t("embeddings.word_embeddings.weight"),
        "position": t("embeddings.position_embeddings.weight"),
        "token_type": t("embeddings.token_type_embeddings.weight"),
        "emb_ln": ln("embeddings.LayerNorm"),
        "pooler": dense("pooler.dense"),
    }
    for i in range(n_block):
        b = f"encoder.layer.{i}"
        qkv_w = np.concatenate([t(f"{b}.attention.self.{m}.weight").T
                                for m in ("query", "key", "value")], axis=1)
        qkv_b = np.concatenate([t(f"{b}.attention.self.{m}.bias")
                                for m in ("query", "key", "value")])
        p[f"block{i}"] = {
            "attn": {"qkv": {"W": qkv_w, "b": qkv_b},
                     "proj": dense(f"{b}.attention.output.dense")},
            "ln1": ln(f"{b}.attention.output.LayerNorm"),
            "fc": dense(f"{b}.intermediate.dense"),
            "out": dense(f"{b}.output.dense"),
            "ln2": ln(f"{b}.output.LayerNorm"),
        }
    return p
