"""GANEstimator — parity with ``pyzoo/zoo/tfpark/gan/gan_estimator.py`` +
``GanOptimMethod.scala``: alternating generator/discriminator training
with separate optimizers and step counts.

TPU-native redesign: instead of one graph with a phase-switching
``GanOptimMethod``, the two phases are two independently jitted, donated
train steps (each a single XLA program). The host alternates them by the
same ``counter % (d_steps + g_steps)`` rule the reference evaluates in-graph
— two small compiled programs beat one program carrying dead branches.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..pipeline.api.keras.engine import Layer
from ..pipeline.api.keras.optimizers import get_optimizer

__all__ = ["GANEstimator", "gan_g_loss", "gan_d_loss"]


def gan_g_loss(fake_logits):
    """Non-saturating generator loss: -log sigmoid(D(G(z)))."""
    return jnp.mean(-jax.nn.log_sigmoid(fake_logits))


def gan_d_loss(real_logits, fake_logits):
    """Discriminator loss: -log sigmoid(D(x)) - log(1 - sigmoid(D(G(z))))."""
    return jnp.mean(-jax.nn.log_sigmoid(real_logits)
                    - jax.nn.log_sigmoid(-fake_logits))


class GANEstimator:
    """``GANEstimator(generator, discriminator, ...)`` where generator and
    discriminator are native Layers (e.g. ``Sequential``). ``train`` runs
    ``steps`` total updates, alternating D-then-G phases per the
    ``discriminator_steps``/``generator_steps`` cadence."""

    def __init__(self, generator: Layer, discriminator: Layer,
                 generator_loss_fn: Callable = gan_g_loss,
                 discriminator_loss_fn: Callable = gan_d_loss,
                 generator_optimizer="adam",
                 discriminator_optimizer="adam",
                 generator_steps: int = 1, discriminator_steps: int = 1,
                 generator_lr: float = 1e-4,
                 discriminator_lr: float = 1e-4,
                 seed: int = 0):
        self.generator = generator
        self.discriminator = discriminator
        self.g_loss_fn = generator_loss_fn
        self.d_loss_fn = discriminator_loss_fn
        self.g_steps = int(generator_steps)
        self.d_steps = int(discriminator_steps)
        self._g_opt = get_optimizer(generator_optimizer, lr=generator_lr)
        self._d_opt = get_optimizer(discriminator_optimizer,
                                    lr=discriminator_lr)
        self._rng = jax.random.PRNGKey(seed)
        self.g_params = None
        self.d_params = None
        self._g_opt_state = None
        self._d_opt_state = None
        self._d_step_fn = None
        self._g_step_fn = None
        self.counter = 0

    # ------------------------------------------------------------------
    def _ensure_built(self, noise: np.ndarray, real: np.ndarray):
        if self.g_params is not None:
            return
        # advance the stream: init keys must not alias later step keys
        self._rng, k1, k2 = jax.random.split(self._rng, 3)
        self.g_params = self.generator.build(k1, noise.shape)
        self.d_params = self.discriminator.build(k2, real.shape)
        self._g_opt_state = self._g_opt.init(self.g_params)
        self._d_opt_state = self._d_opt.init(self.d_params)

        gen, disc = self.generator, self.discriminator
        g_loss_fn, d_loss_fn = self.g_loss_fn, self.d_loss_fn

        def d_loss(d_params, g_params, noise, real, rng):
            r1, r2, r3 = jax.random.split(rng, 3)
            fake = gen.call(g_params, noise, training=True, rng=r1)
            real_logits = disc.call(d_params, real, training=True, rng=r2)
            fake_logits = disc.call(d_params, fake, training=True, rng=r3)
            return d_loss_fn(real_logits, fake_logits)

        def g_loss(g_params, d_params, noise, rng):
            r1, r2 = jax.random.split(rng)
            fake = gen.call(g_params, noise, training=True, rng=r1)
            fake_logits = disc.call(d_params, fake, training=True, rng=r2)
            return g_loss_fn(fake_logits)

        d_opt, g_opt = self._d_opt, self._g_opt

        def d_step(d_params, g_params, opt_state, noise, real, rng):
            loss, grads = jax.value_and_grad(d_loss)(d_params, g_params,
                                                     noise, real, rng)
            updates, opt_state = d_opt.update(grads, opt_state, d_params)
            return optax.apply_updates(d_params, updates), opt_state, loss

        def g_step(g_params, d_params, opt_state, noise, rng):
            loss, grads = jax.value_and_grad(g_loss)(g_params, d_params,
                                                     noise, rng)
            updates, opt_state = g_opt.update(grads, opt_state, g_params)
            return optax.apply_updates(g_params, updates), opt_state, loss

        # donate the updated phase's params + opt state (not the frozen
        # counterpart's) — same single-buffering as training.py's steps
        self._d_step_fn = jax.jit(d_step, donate_argnums=(0, 2))
        self._g_step_fn = jax.jit(g_step, donate_argnums=(0, 2))

    # ------------------------------------------------------------------
    def train(self, noise: np.ndarray, real: np.ndarray, *,
              batch_size: int = 32, steps: int = 100
              ) -> Dict[str, List[float]]:
        """``steps`` alternating updates over (noise, real) arrays sampled
        batch-wise. Returns per-step loss history per phase."""
        noise = jnp.asarray(np.asarray(noise, np.float32))  # device once
        real = jnp.asarray(np.asarray(real, np.float32))
        self._ensure_built(noise[:batch_size], real[:batch_size])
        period = self.d_steps + self.g_steps
        history: Dict[str, List[float]] = {"d_loss": [], "g_loss": []}
        n = min(noise.shape[0], real.shape[0])
        for _ in range(steps):
            self._rng, kb, kstep = jax.random.split(self._rng, 3)
            idx = jax.random.randint(kb, (batch_size,), 0, n)
            zb = noise[idx]
            xb = real[idx]
            if self.counter % period < self.d_steps:
                self.d_params, self._d_opt_state, loss = self._d_step_fn(
                    self.d_params, self.g_params, self._d_opt_state, zb, xb,
                    kstep)
                history["d_loss"].append(float(loss))
            else:
                self.g_params, self._g_opt_state, loss = self._g_step_fn(
                    self.g_params, self.d_params, self._g_opt_state, zb,
                    kstep)
                history["g_loss"].append(float(loss))
            self.counter += 1
        return history

    def generate(self, noise: np.ndarray) -> np.ndarray:
        if self.g_params is None:
            raise RuntimeError("train() first — generator has no weights")
        return np.asarray(self.generator.call(
            self.g_params, jnp.asarray(noise, jnp.float32), training=False))
