"""KerasModel — the TFPark keras-model facade
(reference: ``pyzoo/zoo/tfpark/model.py:30-318``).

The reference wraps a compiled tf.keras model and routes fit/evaluate/
predict either through the local keras session or, when handed a TFDataset
or ``distributed=True``, through TFOptimizer/TFPredictor onto the cluster.
Here there is one runtime: the wrapped net is a native compiled ``KerasNet``
and every path runs the jitted mesh-aware loop — ``distributed`` is
accepted for API parity and is a no-op (the mesh decides placement).
Weight IO matches the reference surface (get/set/save/load_weights,
save_model/load_model)."""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..feature import FeatureSet
from .tf_dataset import TFDataset

__all__ = ["KerasModel"]


class KerasModel:
    """``KerasModel(model)`` where ``model`` is a compiled native
    ``Sequential``/``Model`` (``tfpark/model.py:32``)."""

    def __init__(self, model):
        if getattr(model, "_compiled", None) is None:
            raise ValueError("KerasModel expects a compiled model — call "
                             "model.compile(optimizer=..., loss=...) first")
        self.model = model

    # -- weights ------------------------------------------------------------
    @property
    def metrics_names(self) -> List[str]:
        spec = self.model._compiled
        return ["loss"] + [m.name for m in (spec.metrics or [])]

    def get_weights(self) -> List[np.ndarray]:
        if self.model.params is None:
            self.model.init_weights()
        leaves = jax.tree_util.tree_leaves(self.model.params)
        return [np.asarray(w) for w in leaves]

    def set_weights(self, weights: List[np.ndarray]):
        if self.model.params is None:
            self.model.init_weights()
        treedef = jax.tree_util.tree_structure(self.model.params)
        template = jax.tree_util.tree_leaves(self.model.params)
        if len(template) != len(weights):
            raise ValueError(f"expected {len(template)} weight arrays, got "
                             f"{len(weights)}")
        import jax.numpy as jnp
        leaves = []
        for t, w in zip(template, weights):
            if np.shape(t) != np.shape(w):
                raise ValueError(f"weight shape mismatch: model {np.shape(t)}"
                                 f" vs given {np.shape(w)}")
            leaves.append(jnp.asarray(w, np.asarray(t).dtype))  # zoolint: disable=ZL009 one-time set_weights; leaf shapes differ
        self.model.params = jax.tree_util.tree_unflatten(treedef, leaves)

    def save_weights(self, filepath: str, overwrite: bool = True,
                     save_format=None):
        if os.path.exists(filepath) and not overwrite:
            raise IOError(f"{filepath} exists and overwrite=False")
        if self.model.params is None:
            self.model.init_weights()
        leaves, _ = jax.tree_util.tree_flatten_with_path(self.model.params)
        arrays = {jax.tree_util.keystr(k): np.asarray(v) for k, v in leaves}
        with open(filepath, "wb") as f:  # file handle: np.savez would
            np.savez(f, **arrays)        # append ".npz" to a bare path


    def load_weights(self, filepath: str, by_name: bool = False):
        if self.model.params is None:
            self.model.init_weights()
        data = np.load(filepath)
        leaves, treedef = jax.tree_util.tree_flatten_with_path(
            self.model.params)
        import jax.numpy as jnp
        restored = []
        for k, v in leaves:
            key = jax.tree_util.keystr(k)
            if key not in data:
                if by_name:  # tolerate missing entries, keep current value
                    restored.append(v)
                    continue
                raise ValueError(f"{filepath} missing weight {key}")
            restored.append(jnp.asarray(data[key], np.asarray(v).dtype))  # zoolint: disable=ZL009 one-time load_weights; leaf shapes differ
        self.model.params = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self.model.params), restored)

    def save_model(self, path: str):
        """Structure + weights in one file (the HDF5-save role,
        ``tfpark/model.py:56``). Compile state is not serialized — call
        ``compile`` after load, as with the reference's custom-object
        models."""
        net = self.model
        params = (jax.tree_util.tree_map(lambda a: np.asarray(a), net.params)
                  if net.params is not None else None)
        state = (jax.tree_util.tree_map(lambda a: np.asarray(a),
                                        net.net_state)
                 if getattr(net, "net_state", None) else None)
        loop, compiled = net._loop if hasattr(net, "_loop") else None, net._compiled
        net._loop = net._compiled = None
        old_p, old_s = net.params, getattr(net, "net_state", None)
        net.params = net.net_state = None
        try:
            import cloudpickle
            with open(path, "wb") as f:
                cloudpickle.dump({"net": net, "params": params,
                                  "state": state}, f)
        finally:
            net._loop, net._compiled = loop, compiled
            net.params, net.net_state = old_p, old_s

    @staticmethod
    def load_model(path: str) -> "KerasModel":
        import jax.numpy as jnp
        with open(path, "rb") as f:
            blob = pickle.load(f)
        net = blob["net"]
        if blob["params"] is not None:
            net.params = jax.tree_util.tree_map(jnp.asarray, blob["params"])
        if blob["state"] is not None:
            net.net_state = jax.tree_util.tree_map(jnp.asarray,
                                                   blob["state"])
        # loaded nets need a fresh compile; wrap lazily via a passthrough
        km = object.__new__(KerasModel)
        km.model = net
        return km

    # -- summaries (delegate to the native TensorBoard writer) --------------
    def set_train_summary(self, log_dir: str, app_name: str = "kerasmodel"):
        self.model.set_tensorboard(log_dir, app_name)

    set_val_summary = set_train_summary

    # -- train / eval / predict --------------------------------------------
    def fit(self, x=None, y=None, batch_size: Optional[int] = None,
            epochs: int = 1, validation_split: float = 0.0,
            validation_data=None, distributed: bool = False, **kwargs):
        """``tfpark/model.py:90`` — ``x`` may be ndarrays (+ ``y``), a
        ``TFDataset``, or a ``FeatureSet``. ``validation_split`` carves the
        tail off an ndarray dataset like the reference's keras path."""
        del distributed  # one runtime; the mesh decides placement
        if isinstance(x, TFDataset):
            bs = batch_size or x.effective_batch()
            vd = x.validation_arrays()
            return self.model.fit(x.feature_arrays(), x.label_arrays(),
                                  batch_size=bs, nb_epoch=epochs,
                                  validation_data=vd, **kwargs)
        if isinstance(x, FeatureSet):
            return self.model.fit(x, batch_size=batch_size or 32,
                                  nb_epoch=epochs,
                                  validation_data=validation_data, **kwargs)
        if validation_split > 0.0 and validation_data is None:
            xs = x if isinstance(x, (list, tuple)) else [x]
            n = len(xs[0])
            cut = n - int(n * validation_split)
            validation_data = ([a[cut:] for a in xs] if len(xs) > 1
                               else xs[0][cut:], y[cut:])
            x = [a[:cut] for a in xs] if len(xs) > 1 else xs[0][:cut]
            y = y[:cut]
        return self.model.fit(x, y, batch_size=batch_size or 32,
                              nb_epoch=epochs,
                              validation_data=validation_data, **kwargs)

    def evaluate(self, x=None, y=None, batch_per_thread: Optional[int] = None,
                 distributed: bool = False) -> Dict[str, float]:
        del distributed
        if isinstance(x, TFDataset):
            bs = x.effective_batch(batch_per_thread or 32)
            return self.model.evaluate(x.feature_arrays(), x.label_arrays(),
                                       batch_size=bs)
        return self.model.evaluate(x, y, batch_size=batch_per_thread or 32)

    def predict(self, x, batch_per_thread: Optional[int] = None,
                distributed: bool = False):
        del distributed
        if isinstance(x, TFDataset):
            bs = x.effective_batch(batch_per_thread or 32)
            return self.model.predict(x.feature_arrays(), batch_size=bs)
        return self.model.predict(x, batch_size=batch_per_thread or 32)

    # -- single-batch conveniences (``tfpark/model.py:297-317``) ------------
    def train_on_batch(self, x, y=None, sample_weight=None):
        if sample_weight is not None:
            raise ValueError("sample_weight is not supported")
        n = len(x[0] if isinstance(x, (list, tuple)) else x)
        h = self.model.fit(x, y, batch_size=n, nb_epoch=1, shuffle=False)
        return h["loss"][-1]

    def test_on_batch(self, x, y=None, sample_weight=None,
                      reset_metrics: bool = True):
        del reset_metrics
        if sample_weight is not None:
            raise ValueError("sample_weight is not supported")
        n = len(x[0] if isinstance(x, (list, tuple)) else x)
        return self.model.evaluate(x, y, batch_size=n)

    def predict_on_batch(self, x):
        n = len(x[0] if isinstance(x, (list, tuple)) else x)
        return self.model.predict(x, batch_size=n)
