"""TFEstimator — the TFPark generic model_fn estimator
(reference: ``pyzoo/zoo/tfpark/estimator.py:84-377``).

The reference wraps user TF graph code: ``model_fn(features, labels, mode,
params) -> TFEstimatorSpec(mode, predictions, loss)``, trained by a
TFOptimizer over a TFDataset. Here the same contract runs on the native
graph engine: ``features``/``labels`` arrive as graph ``Variable`` handles
(autograd operator overloading + any keras layer, including imported
``TFNet``/``Net.load*`` graphs), and the returned spec's ``loss``/
``predictions`` Variables close over one shared layer graph, so training and
prediction use the same weights without TF-style variable scoping:

* ``train`` builds ``Model(features+labels → loss)`` and runs the ordinary
  jitted fit loop (identity objective over the graph-computed loss).
* ``predict``/``evaluate`` build ``Model(features → predictions)`` over the
  SAME layer objects — the trained params transfer by layer name (names are
  assigned once, by the first Model constructed).

``model_fn`` signature is introspected like the reference's
``add_train_op`` (``estimator.py:32-46``): only the arguments it declares
are passed; declaring no ``labels`` while the dataset carries labels is an
error, mirroring the reference's check.
"""

from __future__ import annotations

import inspect
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.triggers import MaxIteration
from ..feature import FeatureSet
from ..pipeline.api.keras.engine import Input, Model, Variable
from .tf_dataset import TFDataset, _flatten, _pack

__all__ = ["ModeKeys", "TFEstimatorSpec", "TFEstimator"]


class ModeKeys:
    """``tf.estimator.ModeKeys`` equivalents."""
    TRAIN = "train"
    EVAL = "eval"
    PREDICT = "infer"


class TFEstimatorSpec:
    """``zoo.tfpark.estimator.TFEstimatorSpec`` (``estimator.py:76-82``):
    what a model_fn returns. ``predictions`` may be a Variable, a list, or a
    dict of Variables; ``loss`` a (scalar- or per-example-valued)
    Variable."""

    def __init__(self, mode: str, predictions=None, loss: Optional[Variable] = None):
        self.mode = mode
        self.predictions = predictions
        self.loss = loss


def _call_input_fn(input_fn: Callable, mode: str) -> TFDataset:
    args = _fn_args(input_fn)
    ds = input_fn(mode=mode) if "mode" in args else input_fn()
    if not isinstance(ds, TFDataset):
        raise ValueError(f"input_fn must return a TFDataset, got "
                         f"{type(ds).__name__}")
    return ds


def _fn_args(fn) -> List[str]:
    return list(inspect.signature(fn).parameters)


class TFEstimator:
    """``TFEstimator(model_fn, optimizer=None, model_dir=None, config=None,
    params=None)`` — see ``estimator.py:86-148``. ``optimizer`` is anything
    the native ``compile`` accepts (an optax transformation or a name like
    ``"adam"``)."""

    def __init__(self, model_fn: Callable, optimizer=None,
                 model_dir: Optional[str] = None, config: Any = None,
                 params: Any = None, **optimizer_kwargs):
        self.model_fn = model_fn
        self.optimizer = optimizer
        self.optimizer_kwargs = optimizer_kwargs
        self.model_dir = model_dir
        self.config = config
        self.params = params
        self._train_model: Optional[Model] = None
        self._predict_model: Optional[Model] = None
        self._pred_def = None       # predictions structure treedef
        self._graph_ds_sig = None   # structure the graph was built for

    # -- graph construction -------------------------------------------------
    def _build_graph(self, ds: TFDataset, mode: str):
        """Call model_fn ONCE over Input variables shaped like ``ds``;
        construct the train and predict Models over the shared graph."""
        feat_metas, feat_def = _flatten(ds.tensor_structure)
        feat_inputs = [Input(shape=m.shape, name=m.name) for m in feat_metas]
        features = _pack(list(feat_inputs), feat_def)

        label_inputs: List[Variable] = []
        labels = None
        if ds.labels is not None:
            label_metas = [(a.dtype, a.shape[1:]) for a in ds.labels]
            label_inputs = [Input(shape=s, name=f"label_{i}")
                            for i, (d, s) in enumerate(label_metas)]
            packed = list(label_inputs)
            labels = (_pack(packed, ds._label_def)
                      if ds._label_def is not None else packed[0])

        fn_args = _fn_args(self.model_fn)
        kwargs: Dict[str, Any] = {}
        if "labels" in fn_args:
            kwargs["labels"] = labels
        elif labels is not None and mode == ModeKeys.TRAIN:
            raise ValueError("model_fn does not take labels, but input_fn "
                             "returns labels.")
        if "mode" in fn_args:
            kwargs["mode"] = mode
        if "params" in fn_args:
            kwargs["params"] = self.params
        if "config" in fn_args:
            kwargs["config"] = self.config
        spec = self.model_fn(features=features, **kwargs)
        if not isinstance(spec, TFEstimatorSpec):
            raise ValueError("model_fn must return a TFEstimatorSpec")

        # ORDER MATTERS: the first Model assigns the deterministic layer
        # names every later Model over the same nodes inherits.
        if spec.loss is not None:
            self._train_model = Model(feat_inputs + label_inputs, spec.loss)
        if spec.predictions is not None:
            pred_leaves, self._pred_def = _flatten(spec.predictions)
            self._predict_model = Model(feat_inputs, list(pred_leaves))
        self._graph_ds_sig = tuple((m.dtype, m.shape) for m in feat_metas)
        return spec

    def _ensure_graph(self, ds: TFDataset, mode: str):
        sig = tuple((np.dtype(a.dtype), a.shape[1:]) for a in ds.features)
        if (self._graph_ds_sig is None
                or (mode == ModeKeys.TRAIN and self._train_model is None)):
            # (re)build — the second case is predict-before-train, whose
            # label-less graph carries no loss output; nothing trained is
            # lost by rebuilding
            self._build_graph(ds, mode)
        elif sig != self._graph_ds_sig:
            raise ValueError(
                f"input_fn structure changed: graph was built for "
                f"{self._graph_ds_sig}, got {sig}")

    # -- checkpointing ------------------------------------------------------
    def _weights_path(self) -> Optional[str]:
        if self.model_dir is None:
            return None
        os.makedirs(self.model_dir, exist_ok=True)
        return os.path.join(self.model_dir, "estimator_weights.npz")

    def _save_weights(self):
        path = self._weights_path()
        if path is None or self._train_model is None:
            return
        leaves, _ = jax.tree_util.tree_flatten_with_path(
            self._train_model.params)
        np.savez(path, **{jax.tree_util.keystr(k): np.asarray(v)
                          for k, v in leaves})

    def _load_weights(self, model: Model, checkpoint_path: Optional[str]):
        path = checkpoint_path or self._weights_path()
        if path is None or not os.path.exists(path):
            return False
        data = np.load(path)
        leaves, treedef = jax.tree_util.tree_flatten_with_path(model.params)
        restored = []
        for k, v in leaves:
            key = jax.tree_util.keystr(k)
            if key not in data:
                raise ValueError(f"checkpoint {path} missing weight {key}")
            saved = data[key]
            if saved.shape != np.shape(v):
                raise ValueError(f"checkpoint {path} weight {key} shape "
                                 f"{saved.shape} != model {np.shape(v)}")
            restored.append(jnp.asarray(saved, np.asarray(v).dtype))  # zoolint: disable=ZL009 one-time checkpoint restore; leaf shapes differ
        model.params = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(model.params), restored)
        return True

    def _share_params_into_predict(self):
        """Copy trained params into the predict model by layer name (the
        models share layer objects, so keys line up exactly)."""
        if self._predict_model is None:
            return
        if self._predict_model.params is None:
            self._predict_model.init_weights()
        if self._train_model is None or self._train_model.params is None:
            return
        trained = self._train_model.params
        self._predict_model.params = {
            name: trained.get(name, p)
            for name, p in self._predict_model.params.items()}

    # -- the estimator contract --------------------------------------------
    def train(self, input_fn: Callable, steps: Optional[int] = None,
              batch_size: Optional[int] = None, nb_epoch: Optional[int] = None
              ) -> "TFEstimator":
        """``estimator.py:194`` — train until ``steps`` optimizer steps (the
        reference's MaxIteration), or ``nb_epoch`` epochs if given."""
        ds = _call_input_fn(input_fn, ModeKeys.TRAIN)
        if ds.labels is None:
            raise ValueError("training requires an input_fn with labels")
        self._ensure_graph(ds, ModeKeys.TRAIN)
        if self._train_model is None:
            raise ValueError("model_fn returned no loss; cannot train")
        m = self._train_model
        if m._compiled is None:
            if self.optimizer is None:
                raise ValueError(
                    "optimizer should be set when used for training. For "
                    "example: TFEstimator(model_fn, 'adam')")
            # the graph output IS the loss — identity objective (mean to
            # scalar), dummy zero labels feed the fit contract
            m.compile(optimizer=self.optimizer,
                      loss=lambda y_true, y_pred: jnp.mean(y_pred),
                      **self.optimizer_kwargs)
        bs = batch_size or ds.effective_batch()
        n = ds.n_examples
        steps_per_epoch = max(n // bs, 1)
        if nb_epoch is None:
            if steps is None:
                nb_epoch = 1
            else:
                nb_epoch = max(-(-steps // steps_per_epoch), 1)
        x = list(ds.features) + list(ds.labels)
        y = np.zeros((n,), np.float32)  # unused by the identity objective
        end = MaxIteration(steps) if steps is not None else None
        m.fit(x, y, batch_size=bs, nb_epoch=nb_epoch, end_trigger=end)
        self._share_params_into_predict()
        self._save_weights()
        return self

    def predict(self, input_fn: Callable, batch_size: Optional[int] = None,
                checkpoint_path: Optional[str] = None):
        """``estimator.py:315`` — run the PREDICT graph; returns ndarray(s)
        packed like the model_fn's ``predictions`` structure."""
        ds = _call_input_fn(input_fn, ModeKeys.PREDICT)
        self._ensure_graph(ds, ModeKeys.PREDICT)
        if self._predict_model is None:
            raise ValueError("model_fn returned no predictions")
        if self._predict_model.params is None:
            self._share_params_into_predict()
        if checkpoint_path or (self._train_model is None
                               or self._train_model.params is None):
            if self._predict_model.params is None:
                self._predict_model.init_weights()
            self._load_weights(self._predict_model, checkpoint_path)
        bs = batch_size or ds.effective_batch()
        outs = self._predict_model.predict(ds.feature_arrays(), batch_size=bs)
        if not isinstance(outs, list):
            outs = [outs]
        return _pack(outs, self._pred_def)

    def evaluate(self, input_fn: Callable, eval_methods: Sequence[str],
                 steps: Optional[int] = None, batch_size: Optional[int] = None,
                 checkpoint_path: Optional[str] = None) -> Dict[str, float]:
        """``estimator.py:253`` — named metrics over the EVAL dataset.
        Supported: accuracy/acc, top5accuracy/top5acc, mae, mse, loss (the
        graph-computed loss, exact batch weighting)."""
        ds = _call_input_fn(input_fn, ModeKeys.EVAL)
        if ds.labels is None:
            raise ValueError("evaluate requires an input_fn with labels")
        self._ensure_graph(ds, ModeKeys.EVAL)
        bs = batch_size or ds.effective_batch()
        n = ds.n_examples
        if steps is not None:
            n = min(n, steps * bs)
        out: Dict[str, float] = {}

        wants_loss = any(m.lower() == "loss" for m in eval_methods)
        other = [m for m in eval_methods if m.lower() != "loss"]
        if other:
            if self._predict_model is None:
                raise ValueError("model_fn returned no predictions — only "
                                 "the 'loss' eval_method is available")
            preds = self.predict(lambda: TFDataset(ds.features),
                                 batch_size=bs)
            flat_preds, _ = _flatten(preds)
            p = np.asarray(flat_preds[0])[:n]
            y = np.asarray(ds.labels[0])[:n]
            for mname in other:
                out[mname] = _host_metric(mname, y, p)
        if wants_loss:
            out["loss"] = self._exact_loss(ds, bs, n)
        return out

    def _exact_loss(self, ds: TFDataset, bs: int, n: int) -> float:
        """Graph loss with exact batch weighting (no pad bias): jit once per
        distinct tail shape — at most two compiles."""
        m = self._train_model
        if m is None:
            raise ValueError("model_fn returned no loss")
        if m.params is None:
            m.init_weights()
            self._load_weights(m, None)

        @jax.jit
        def batch_loss(params, state, xs):
            val, _ = m.apply(params, state, xs, training=False, rng=None)
            return jnp.mean(val)

        total, count = 0.0, 0
        for i in range(0, n, bs):
            # per-BATCH bulk transfers; the loop blocks on the scalar
            # loss each batch anyway, so prefetching buys nothing here
            xs = ([jnp.asarray(a[i:i + bs]) for a in ds.features]  # zoolint: disable=ZL009
                  + [jnp.asarray(a[i:i + bs]) for a in ds.labels])  # zoolint: disable=ZL009
            k = len(ds.features[0][i:i + bs])
            total += float(batch_loss(m.params, m.net_state or {}, xs)) * k
            count += k
        return total / max(count, 1)


def _host_metric(name: str, y: np.ndarray, p: np.ndarray) -> float:
    key = name.lower()
    if key in ("acc", "accuracy"):
        cls = p.argmax(-1) if p.ndim > 1 and p.shape[-1] > 1 else \
            (p.reshape(len(p), -1)[:, 0] > 0.5).astype(np.int64)
        return float((cls == y.reshape(len(y), -1)[:, 0]).mean())
    if key in ("top5acc", "top5accuracy"):
        top5 = np.argsort(p, axis=-1)[:, -5:]
        y1 = y.reshape(len(y), -1)[:, 0]
        return float((top5 == y1[:, None]).any(axis=1).mean())
    if key == "mae":
        return float(np.abs(p.reshape(len(p), -1)
                            - y.reshape(len(y), -1)).mean())
    if key == "mse":
        return float(((p.reshape(len(p), -1)
                       - y.reshape(len(y), -1)) ** 2).mean())
    raise ValueError(f"unsupported eval_method {name!r}; choose from "
                     f"accuracy, top5accuracy, mae, mse, loss")
