"""L7 — foreign-model import / TFPark equivalent (SURVEY §1, §2.2).

The reference's TFPark wraps TF sessions and estimators
(``pyzoo/zoo/tfpark/text/estimator/bert_classifier.py``,
``bert_estimator.py``); in the single-runtime redesign there is no second
framework to bridge — "import" means mapping a foreign checkpoint's weights
onto the native JAX layers. This package ships:

* ``BERTClassifier`` — the BERT fine-tune estimator (config #4 surface):
  native BERT encoder → pooled output → dropout → classifier head, trained
  with the ordinary compile/fit stack.
* ``BERTNER`` / ``BERTSQuAD`` — the prebuilt token-level estimators (both
  in this package's ``bert_ner.py``; reference
  ``tfpark/text/estimator/bert_ner.py`` + ``bert_squad.py``): per-token
  classification with ignore-label masking, and start/end span extraction.
* ``GANEstimator`` — alternating G/D training (``gan_estimator.py`` here;
  reference ``tfpark/gan/gan_estimator.py`` + ``GanOptimMethod.scala``) as
  two independently jitted donated steps.
* ``bert_params_from_torch`` — weight import from a HuggingFace/torch BERT
  ``state_dict`` (the analogue of TFPark's init_from_checkpoint path).
* ``TFEstimator`` / ``TFEstimatorSpec`` — the GENERIC model_fn estimator
  (``pyzoo/zoo/tfpark/estimator.py:84``): bring-your-own graph code over
  native layers, autograd ops, or imported ``Net.load_tf`` graphs.
* ``KerasModel`` — the compiled-model facade with the
  fit/evaluate/predict/weights surface (``pyzoo/zoo/tfpark/model.py:30``).
* ``TFDataset`` / ``TensorMeta`` — the feed contract (structure metas +
  batch_size-divides-the-mesh rule, ``tf_dataset.py:112-212``).
"""

from .bert_classifier import BERTClassifier, bert_params_from_torch  # noqa: F401
from .bert_ner import BERTNER, BERTSQuAD  # noqa: F401
from .gan_estimator import GANEstimator, gan_d_loss, gan_g_loss  # noqa: F401
from .tf_dataset import TFDataset, TensorMeta  # noqa: F401
from .estimator import TFEstimator, TFEstimatorSpec, ModeKeys  # noqa: F401
from .model import KerasModel  # noqa: F401
