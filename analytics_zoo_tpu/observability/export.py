"""Export sinks for the metrics registry.

Three ways out of the process, matching how the three audiences read:

* :func:`render_prometheus` / :class:`ScrapeServer` — text exposition for
  a scraper (or a human with ``curl``); the serving backend mounts this
  via ``ClusterServing.serve_metrics()``.
* :class:`JsonEventSink` — append-only JSON-lines event log (spans,
  per-batch serving events, error records); schema-stable under
  concurrent writers because each event is one ``json.dumps`` appended
  under a lock.
* :class:`TensorBoardSink` — adapter over the existing
  ``utils.tensorboard.EventFileWriter`` so registry snapshots can land in
  the same event files the training/serving scalars already use (the
  reference's only export channel keeps working unchanged).

:func:`parse_prometheus` is the deliberately minimal reader used by the
round-trip tests — names, types, labels, values, enough to reconcile a
scrape against ground truth without a client library.
"""

from __future__ import annotations

import http.server
import json
import math
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .metrics import Histogram, MetricsRegistry, Summary, default_registry

__all__ = ["render_prometheus", "parse_prometheus", "dump",
           "JsonEventSink", "read_events", "ScrapeServer", "TensorBoardSink"]


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _sanitize(name: str) -> str:
    if _NAME_OK.match(name):
        return name
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return out if _NAME_OK.match(out) else "_" + out


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_text(labels, extra: Optional[Dict[str, str]] = None) -> str:
    pairs = list(labels) + sorted((extra or {}).items())
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(str(v))}"'
                          for k, v in pairs) + "}"


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """The registry as Prometheus text exposition format (one ``# TYPE``
    per family; histograms as cumulative ``_bucket{le=...}`` + ``_sum`` /
    ``_count``; summaries as ``{quantile=...}`` series + ``_sum`` /
    ``_count``)."""
    reg = registry if registry is not None else default_registry()
    lines: List[str] = []
    typed = set()
    for m in reg.metrics():
        name = _sanitize(m.name)
        if name not in typed:
            typed.add(name)
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
        if isinstance(m, Histogram):
            # one locked snapshot: the +Inf bucket must equal _count even
            # when a producer observes mid-render
            buckets, count, total = m.stats()
            for le, c in buckets:
                lines.append(f"{name}_bucket"
                             f"{_labels_text(m.labels, {'le': _fmt(le)})}"
                             f" {c}")
            lines.append(f"{name}_sum{_labels_text(m.labels)} {_fmt(total)}")
            lines.append(f"{name}_count{_labels_text(m.labels)} {count}")
        elif isinstance(m, Summary):
            # one locked pass per summary: p99 >= p50 must hold in every
            # scrape even while producers observe concurrently
            qs, count, total = m.stats()
            for q, v in qs:
                lines.append(f"{name}"
                             f"{_labels_text(m.labels, {'quantile': repr(q)})}"
                             f" {_fmt(v)}")
            lines.append(f"{name}_sum{_labels_text(m.labels)} {_fmt(total)}")
            lines.append(f"{name}_count{_labels_text(m.labels)} {count}")
        else:
            lines.append(f"{name}{_labels_text(m.labels)} {_fmt(m.value)}")
    return "\n".join(lines) + "\n"


def dump(registry: Optional[MetricsRegistry] = None,
         compact: bool = False) -> Dict[str, Any]:
    """Plain-dict snapshot of the registry (see
    ``MetricsRegistry.snapshot``) — what ``bench.py`` embeds per round."""
    reg = registry if registry is not None else default_registry()
    return reg.snapshot(compact=compact)


# the label block matches quoted values char-by-char (escapes allowed), so
# a '}' INSIDE a label value does not terminate the block early
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(?:\{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"\s*,?\s*)*)\})?'
    r"\s+(?P<value>\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_value(s: str) -> float:
    return {"+Inf": math.inf, "-Inf": -math.inf}.get(s) or float(s)


def parse_prometheus(text: str) -> Dict[str, Dict[str, Any]]:
    """Minimal exposition-format reader: ``{family: {"type": ...,
    "samples": [(name, labels_dict, value), ...]}}``. Raises ValueError
    on lines that are neither comments nor well-formed samples — the
    round-trip tests lean on that strictness."""
    out: Dict[str, Dict[str, Any]] = {}
    last_family = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                last_family = parts[2]
                out[last_family] = {"type": parts[3].strip(), "samples": []}
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name = m.group("name")
        labels = {k: re.sub(r"\\(.)", lambda g: {"n": "\n"}.get(
            g.group(1), g.group(1)), v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")}
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and base in out and out[base]["type"] in ("histogram",
                                                              "summary"):
                family = base
                break
        if family not in out:
            # sample with no TYPE line: tolerated as untyped
            out[family] = {"type": "untyped", "samples": []}
        out[family]["samples"].append(
            (name, labels, _parse_value(m.group("value"))))
    return out


# ---------------------------------------------------------------------------
# JSON event sink
# ---------------------------------------------------------------------------

class JsonEventSink:
    """Append-only JSON-lines writer for structured event records.

    Every line is one complete JSON object with at least ``ts`` (epoch
    seconds) and ``kind``; producers add flat payload fields. Writes are
    serialized under a lock so concurrent writers (serving loop + span
    exits on producer threads) can never interleave bytes — the schema
    stability the exposition tests assert.

    ``max_bytes`` > 0 switches on size-based rotation: when the active
    file reaches the limit it is atomically renamed to ``path.1``
    (``os.replace``, the DLQ segments' crash-safe idiom — readers see
    either the old name or the new, never a torn file), older segments
    shift up, and at most ``keep`` rotated segments survive (oldest
    dropped). :func:`read_events` reads across the whole chain, oldest
    first."""

    def __init__(self, path: str, max_bytes: int = 0, keep: int = 3):
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self.path = path
        self.max_bytes = int(max_bytes)
        self.keep = max(int(keep), 1)
        # line-buffered: each event reaches the OS as it happens, so a
        # crash loses at most the in-flight line — the events nearest a
        # failure are exactly the ones diagnosis needs
        self._f = open(path, "a", encoding="utf-8", buffering=1)
        self._size = os.path.getsize(path) if os.path.exists(path) else 0
        self._lock = threading.Lock()

    def _rotate_locked(self) -> None:
        self._f.flush()
        self._f.close()
        oldest = f"{self.path}.{self.keep}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._f = open(self.path, "a", encoding="utf-8", buffering=1)
        self._size = 0

    def write(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, sort_keys=True, default=str)
        with self._lock:
            if self._f.closed:
                # a concurrent emitter may race close() (the registry's
                # sink snapshot is taken before removal); dropping the
                # event beats crashing the instrumented thread
                return
            self._f.write(line + "\n")
            self._size += len(line) + 1
            if self.max_bytes > 0 and self._size >= self.max_bytes:
                self._rotate_locked()

    def flush(self) -> None:
        with self._lock:
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


def read_events(path: str, kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Parse a JSON-lines event log back, optionally filtered by kind.
    Rotated segments (``path.N`` … ``path.1``, highest = oldest) are
    read before the active file, so the result is one chronological
    stream regardless of how many rotations happened."""
    chain: List[str] = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        chain.append(f"{path}.{i}")
        i += 1
    chain.reverse()                      # oldest segment first
    chain.append(path)
    out: List[Dict[str, Any]] = []
    for seg in chain:
        with open(seg, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                if kind is None or event.get("kind") == kind:
                    out.append(event)
    return out


# ---------------------------------------------------------------------------
# scrape endpoint
# ---------------------------------------------------------------------------

def _registry_value(reg: MetricsRegistry, name: str) -> float:
    """Sum of a counter/gauge family's values across its label series —
    the cheap way /statusz reads totals without a full exposition pass."""
    total = 0.0
    for m in reg.metrics():
        if m.name == name and not isinstance(m, (Histogram, Summary)):
            total += m.value
    return total


class _ScrapeHandler(http.server.BaseHTTPRequestHandler):
    registry: MetricsRegistry = None  # type: ignore[assignment]
    health_fn: Optional[Callable[[], Dict[str, Any]]] = None
    profiler: Optional[Any] = None     # a ProfilerTrigger, when mounted
    started_at: float = 0.0

    def _send(self, body: bytes, content_type: str, code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _health_payload(self) -> Dict[str, Any]:
        info: Dict[str, Any] = {"status": "ok",
                                "uptime_s": time.time() - self.started_at}
        fn = type(self).health_fn
        if fn is not None:
            try:
                info.update(fn())
            except Exception as e:     # a dead backend must not 500 /healthz
                info["status"] = "degraded"
                info["error"] = f"{type(e).__name__}: {e}"
        return info

    def _status_payload(self) -> Dict[str, Any]:
        info = self._health_payload()
        reg = self.registry
        info["jit"] = {
            "compile_total": _registry_value(reg, "zoo_jit_compile_total"),
            "retrace_total": _registry_value(reg, "zoo_jit_retrace_total"),
        }
        try:
            import jax
            info["device"] = {"platform": jax.default_backend(),
                              "device_count": jax.device_count()}
        except Exception as e:          # jax-free process: still report
            info["device"] = {"platform": "unavailable",
                              "error": f"{type(e).__name__}: {e}"}
        if self._device_memory_enabled():
            from .device import device_memory_stats
            mem = device_memory_stats()
            if mem:                     # off-TPU: absent beats lying zero
                info["device"]["memory"] = mem
        info["performance"] = self._performance_payload()
        return info

    def _performance_payload(self) -> Dict[str, Any]:
        """The goodput/attribution block: ratio + per-category badput
        read back off the registry (so it works whether the ledger
        lives in this process's fit loop or serve loop), plus the
        in-flight profiler capture when one is mounted."""
        from .goodput import registry_snapshot
        perf: Dict[str, Any] = registry_snapshot(self.registry)
        prof = type(self).profiler
        if prof is not None:
            perf["profiler"] = {"in_flight": prof.in_flight(),
                                "trace_dir": prof.trace_dir}
        return perf

    @staticmethod
    def _device_memory_enabled() -> bool:
        try:
            from ..common.context import get_zoo_context
            return bool(get_zoo_context().get(
                "zoo.telemetry.device_memory", True))
        except Exception:               # jax-free process: default on
            return True

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        path = self.path.split("?", 1)[0]
        if path in ("/", "/metrics"):
            self._send(render_prometheus(self.registry).encode("utf-8"),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            self._send(json.dumps(self._health_payload()).encode("utf-8"),
                       "application/json")
        elif path == "/statusz":
            self._send(json.dumps(self._status_payload(), indent=2,
                                  default=str).encode("utf-8"),
                       "application/json")
        else:
            self.send_error(404)

    def do_POST(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        path = self.path.split("?", 1)[0]
        if path != "/profilez":
            self.send_error(404)
            return
        prof = type(self).profiler
        if prof is None:
            self._send(json.dumps(
                {"armed": False, "error": "no profiler mounted"}
            ).encode("utf-8"), "application/json", code=404)
            return
        cap_dir = prof.arm(trigger="http", reason="POST /profilez")
        body = {"armed": cap_dir is not None, "dir": cap_dir,
                "in_flight": prof.in_flight()}
        self._send(json.dumps(body).encode("utf-8"), "application/json",
                   code=200 if cap_dir is not None else 409)

    def log_message(self, *args):  # scrapes must not spam stderr
        pass


class ScrapeServer:
    """A tiny HTTP endpoint over one registry: ``/metrics`` (Prometheus
    text exposition), ``/healthz`` (liveness: status + uptime + whatever
    ``health_fn`` reports), and ``/statusz`` (the operator page: health
    plus jit-compile totals and device/platform info). ``port=0`` picks a
    free port; the bound one is on ``self.port``.

    ``health_fn`` is an optional zero-arg callable returning a JSON-able
    dict merged into both payloads — ``ClusterServing.serve_metrics``
    passes its serve-loop introspection (stream depth, last-flush age)
    this way. It runs on the scrape thread, so it must be cheap and must
    not take locks the serve loop holds across dispatches.

    ``/statusz`` additionally carries a ``performance`` block (goodput
    ratio + per-category badput seconds read off the registry), and
    passing ``profiler=`` (a :class:`~.profiler.ProfilerTrigger`)
    mounts ``POST /profilez`` — arm a bounded trace capture over HTTP;
    200 with the capture dir on success, 409 when one is already in
    flight (or the start failed and degraded)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 profiler: Optional[Any] = None):
        attrs: Dict[str, Any] = {
            "registry": registry if registry is not None
            else default_registry(),
            "started_at": time.time(),
        }
        if health_fn is not None:
            attrs["health_fn"] = staticmethod(health_fn)
        if profiler is not None:
            attrs["profiler"] = profiler
        handler = type("Handler", (_ScrapeHandler,), attrs)
        self._httpd = http.server.ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="zoo-metrics-scrape",
                                        daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# TensorBoard sink (reuses the existing event-file writer)
# ---------------------------------------------------------------------------

class TensorBoardSink:
    """Export registry snapshots into TensorBoard event files through the
    in-repo ``EventFileWriter`` — counters/gauges as scalars, histograms
    as ``_count``/``_sum``/``_mean`` scalars (bucket shapes live in the
    Prometheus/JSON channels; TB scalars are for trend lines)."""

    def __init__(self, log_dir: str):
        from ..utils.tensorboard import EventFileWriter
        self.writer = EventFileWriter(log_dir)

    def export(self, registry: Optional[MetricsRegistry] = None,
               step: int = 0) -> None:
        reg = registry if registry is not None else default_registry()
        for m in reg.metrics():
            tag = m.name
            if m.labels:
                tag += "/" + "/".join(v for _, v in m.labels)
            if isinstance(m, Histogram):
                _, count, total = m.stats()   # one locked snapshot
                self.writer.add_scalar(tag + "_count", float(count), step)
                self.writer.add_scalar(tag + "_sum", float(total), step)
                if count:
                    self.writer.add_scalar(tag + "_mean",
                                           float(total / count), step)
            elif isinstance(m, Summary):
                qs, count, total = m.stats()
                self.writer.add_scalar(tag + "_count", float(count), step)
                self.writer.add_scalar(tag + "_sum", float(total), step)
                for q, v in qs:
                    if v == v:     # empty digests yield NaN — skip those
                        self.writer.add_scalar(
                            tag + f"_p{int(round(q * 100))}", float(v), step)
            else:
                self.writer.add_scalar(tag, float(m.value), step)
        self.writer.flush()

    def close(self) -> None:
        self.writer.close()
