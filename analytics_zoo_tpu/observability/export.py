"""Export sinks for the metrics registry.

Three ways out of the process, matching how the three audiences read:

* :func:`render_prometheus` / :class:`ScrapeServer` — text exposition for
  a scraper (or a human with ``curl``); the serving backend mounts this
  via ``ClusterServing.serve_metrics()``.
* :class:`JsonEventSink` — append-only JSON-lines event log (spans,
  per-batch serving events, error records); schema-stable under
  concurrent writers because each event is one ``json.dumps`` appended
  under a lock.
* :class:`TensorBoardSink` — adapter over the existing
  ``utils.tensorboard.EventFileWriter`` so registry snapshots can land in
  the same event files the training/serving scalars already use (the
  reference's only export channel keeps working unchanged).

:func:`parse_prometheus` is the deliberately minimal reader used by the
round-trip tests — names, types, labels, values, enough to reconcile a
scrape against ground truth without a client library.
"""

from __future__ import annotations

import http.server
import json
import math
import os
import re
import threading
from typing import Any, Dict, List, Optional

from .metrics import Histogram, MetricsRegistry, default_registry

__all__ = ["render_prometheus", "parse_prometheus", "dump",
           "JsonEventSink", "read_events", "ScrapeServer", "TensorBoardSink"]


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _sanitize(name: str) -> str:
    if _NAME_OK.match(name):
        return name
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return out if _NAME_OK.match(out) else "_" + out


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_text(labels, extra: Optional[Dict[str, str]] = None) -> str:
    pairs = list(labels) + sorted((extra or {}).items())
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(str(v))}"'
                          for k, v in pairs) + "}"


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """The registry as Prometheus text exposition format (one ``# TYPE``
    per family; histograms as cumulative ``_bucket{le=...}`` + ``_sum`` /
    ``_count``)."""
    reg = registry if registry is not None else default_registry()
    lines: List[str] = []
    typed = set()
    for m in reg.metrics():
        name = _sanitize(m.name)
        if name not in typed:
            typed.add(name)
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
        if isinstance(m, Histogram):
            # one locked snapshot: the +Inf bucket must equal _count even
            # when a producer observes mid-render
            buckets, count, total = m.stats()
            for le, c in buckets:
                lines.append(f"{name}_bucket"
                             f"{_labels_text(m.labels, {'le': _fmt(le)})}"
                             f" {c}")
            lines.append(f"{name}_sum{_labels_text(m.labels)} {_fmt(total)}")
            lines.append(f"{name}_count{_labels_text(m.labels)} {count}")
        else:
            lines.append(f"{name}{_labels_text(m.labels)} {_fmt(m.value)}")
    return "\n".join(lines) + "\n"


def dump(registry: Optional[MetricsRegistry] = None,
         compact: bool = False) -> Dict[str, Any]:
    """Plain-dict snapshot of the registry (see
    ``MetricsRegistry.snapshot``) — what ``bench.py`` embeds per round."""
    reg = registry if registry is not None else default_registry()
    return reg.snapshot(compact=compact)


# the label block matches quoted values char-by-char (escapes allowed), so
# a '}' INSIDE a label value does not terminate the block early
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(?:\{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"\s*,?\s*)*)\})?'
    r"\s+(?P<value>\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_value(s: str) -> float:
    return {"+Inf": math.inf, "-Inf": -math.inf}.get(s) or float(s)


def parse_prometheus(text: str) -> Dict[str, Dict[str, Any]]:
    """Minimal exposition-format reader: ``{family: {"type": ...,
    "samples": [(name, labels_dict, value), ...]}}``. Raises ValueError
    on lines that are neither comments nor well-formed samples — the
    round-trip tests lean on that strictness."""
    out: Dict[str, Dict[str, Any]] = {}
    last_family = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                last_family = parts[2]
                out[last_family] = {"type": parts[3].strip(), "samples": []}
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name = m.group("name")
        labels = {k: re.sub(r"\\(.)", lambda g: {"n": "\n"}.get(
            g.group(1), g.group(1)), v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")}
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and base in out and out[base]["type"] == "histogram":
                family = base
                break
        if family not in out:
            # sample with no TYPE line: tolerated as untyped
            out[family] = {"type": "untyped", "samples": []}
        out[family]["samples"].append(
            (name, labels, _parse_value(m.group("value"))))
    return out


# ---------------------------------------------------------------------------
# JSON event sink
# ---------------------------------------------------------------------------

class JsonEventSink:
    """Append-only JSON-lines writer for structured event records.

    Every line is one complete JSON object with at least ``ts`` (epoch
    seconds) and ``kind``; producers add flat payload fields. Writes are
    serialized under a lock so concurrent writers (serving loop + span
    exits on producer threads) can never interleave bytes — the schema
    stability the exposition tests assert."""

    def __init__(self, path: str):
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self.path = path
        # line-buffered: each event reaches the OS as it happens, so a
        # crash loses at most the in-flight line — the events nearest a
        # failure are exactly the ones diagnosis needs
        self._f = open(path, "a", encoding="utf-8", buffering=1)
        self._lock = threading.Lock()

    def write(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, sort_keys=True, default=str)
        with self._lock:
            if self._f.closed:
                # a concurrent emitter may race close() (the registry's
                # sink snapshot is taken before removal); dropping the
                # event beats crashing the instrumented thread
                return
            self._f.write(line + "\n")

    def flush(self) -> None:
        with self._lock:
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


def read_events(path: str, kind: Optional[str] = None) -> List[Dict[str, Any]]:
    """Parse a JSON-lines event log back, optionally filtered by kind."""
    out: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            event = json.loads(line)
            if kind is None or event.get("kind") == kind:
                out.append(event)
    return out


# ---------------------------------------------------------------------------
# scrape endpoint
# ---------------------------------------------------------------------------

class _ScrapeHandler(http.server.BaseHTTPRequestHandler):
    registry: MetricsRegistry = None  # type: ignore[assignment]

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        if self.path.split("?", 1)[0] not in ("/", "/metrics"):
            self.send_error(404)
            return
        body = render_prometheus(self.registry).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes must not spam stderr
        pass


class ScrapeServer:
    """A tiny ``/metrics`` HTTP endpoint over one registry — what a
    Prometheus scraper (or ``curl``) reads. ``port=0`` picks a free port;
    the bound one is on ``self.port``."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        handler = type("Handler", (_ScrapeHandler,),
                       {"registry": registry if registry is not None
                        else default_registry()})
        self._httpd = http.server.ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="zoo-metrics-scrape",
                                        daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# TensorBoard sink (reuses the existing event-file writer)
# ---------------------------------------------------------------------------

class TensorBoardSink:
    """Export registry snapshots into TensorBoard event files through the
    in-repo ``EventFileWriter`` — counters/gauges as scalars, histograms
    as ``_count``/``_sum``/``_mean`` scalars (bucket shapes live in the
    Prometheus/JSON channels; TB scalars are for trend lines)."""

    def __init__(self, log_dir: str):
        from ..utils.tensorboard import EventFileWriter
        self.writer = EventFileWriter(log_dir)

    def export(self, registry: Optional[MetricsRegistry] = None,
               step: int = 0) -> None:
        reg = registry if registry is not None else default_registry()
        for m in reg.metrics():
            tag = m.name
            if m.labels:
                tag += "/" + "/".join(v for _, v in m.labels)
            if isinstance(m, Histogram):
                _, count, total = m.stats()   # one locked snapshot
                self.writer.add_scalar(tag + "_count", float(count), step)
                self.writer.add_scalar(tag + "_sum", float(total), step)
                if count:
                    self.writer.add_scalar(tag + "_mean",
                                           float(total / count), step)
            else:
                self.writer.add_scalar(tag, float(m.value), step)
        self.writer.flush()

    def close(self) -> None:
        self.writer.close()
