"""Device memory telemetry — HBM headroom as first-class gauges.

``jax.Device.memory_stats()`` exposes per-device allocator state on
TPU (and CUDA) backends: bytes in use, peak bytes, and the bytes
limit. This module polls it into

    ``zoo_device_hbm_bytes{device=,kind=in_use|peak|limit}``

so the bench snapshot, ``/statusz``, and the fleet collector all see
HBM headroom the same way they see queue depth. Off-TPU (CPU jax, or
no jax importable at all) every entry point is a graceful no-op — the
gauges simply never appear, matching the catalog's off-device
behavior for the jit counters.

Entry points:

* :func:`device_memory_stats` — one poll, plain dicts (the
  ``/statusz`` block and the bench channel).
* :func:`sample_device_memory` — one poll **into a registry** (bench
  calls this right before embedding its snapshot).
* :class:`DeviceMemorySampler` — daemon thread sampling on the
  ``zoo.telemetry.sample_interval_s`` cadence for long-running
  servers.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from .metrics import MetricsRegistry, default_registry

log = logging.getLogger("analytics_zoo_tpu.observability")

__all__ = ["device_memory_stats", "sample_device_memory",
           "DeviceMemorySampler"]

#: memory_stats() key per exported ``kind=`` label value
_KIND_KEYS = (("in_use", "bytes_in_use"),
              ("peak", "peak_bytes_in_use"),
              ("limit", "bytes_limit"))


def device_memory_stats() -> List[Dict[str, float]]:
    """One poll of every local device's allocator stats:
    ``[{"device": "tpu:0", "in_use": ..., "peak": ..., "limit": ...},
    ...]``. Devices without ``memory_stats`` support (CPU backend)
    are skipped; no jax at all returns ``[]``."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return []
    out: List[Dict[str, float]] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        entry: Dict[str, float] = {
            "device": f"{d.platform}:{d.id}"}
        for kind, key in _KIND_KEYS:
            if key in stats:
                entry[kind] = float(stats[key])
        if len(entry) > 1:
            out.append(entry)
    return out


def sample_device_memory(
        registry: Optional[MetricsRegistry] = None
) -> List[Dict[str, float]]:
    """Poll once and set the ``zoo_device_hbm_bytes`` gauges; returns
    the polled stats (empty off-device, in which case no gauge is
    registered — absent beats lying zero)."""
    stats = device_memory_stats()
    if not stats:
        return stats
    reg = registry if registry is not None else default_registry()
    for entry in stats:
        device = entry["device"]
        for kind, _key in _KIND_KEYS:
            if kind not in entry:
                continue
            reg.gauge(   # zoolint: disable=ZL015 bounded label set —
                # device ids are fixed by the local topology and kind
                # ranges over the literal _KIND_KEYS enumeration
                "zoo_device_hbm_bytes",
                "device allocator bytes per local device "
                "(kind=in_use|peak|limit)",
                labels={"device": device, "kind": kind},
            ).set(entry[kind])
    return stats


class DeviceMemorySampler:
    """Daemon thread calling :func:`sample_device_memory` on a cadence
    (``zoo.telemetry.sample_interval_s`` by default). Safe to start
    off-device: each tick is a no-op."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 interval_s: Optional[float] = None):
        if interval_s is None:
            from .timeseries import _conf
            interval_s = _conf("zoo.telemetry.sample_interval_s", 1.0)
        self.registry = registry if registry is not None \
            else default_registry()
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "DeviceMemorySampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="zoo-device-memory-sampler",
            daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                sample_device_memory(self.registry)
            except Exception:       # telemetry must never kill a host
                log.exception("device memory sample failed")

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
