"""Goodput/badput accounting — where the wall clock of a run went.

The PR 17 telemetry plane answers "what is happening"; this module
answers "what it costs": a :class:`GoodputLedger` attributes **every
second** of a training run or a serving replica's lane loop to exactly
one category, so ``goodput + Σ badput == wall time`` holds by
construction (the acceptance tests reconcile it exactly under injected
fault plans).

Categories are role-scoped and exclusive:

* ``train`` — ``device_step`` (goodput) vs ``data_wait`` / ``compile``
  / ``ckpt_stall`` / ``rollback_replay`` / ``restart`` /
  ``anomaly_skip`` / ``idle``,
* ``serve`` — ``device_dispatch`` (goodput) vs ``host_decode`` /
  ``publish`` / ``shed`` / ``idle``.

The accounting model is **interval attribution**: the ledger keeps one
monotonic mark; ``note(category)`` attributes the interval since the
mark to that category and advances the mark. Because every interval is
attributed exactly once and intervals tile the open→last-note span,
exclusivity and the wall-time invariant cannot drift — there is no
"unaccounted" bucket to leak into. Instrumentation therefore only has
to call ``note`` at phase boundaries on the loop thread (training:
the prefetch stream wrapper, the checkpoint manager's synchronous
window, the retry/rollback handlers; serving: the lane loop's
read/shed/route/pump seams).

Exported metric families (docs/guides/OBSERVABILITY.md "Goodput &
performance attribution"): ``zoo_goodput_ratio``,
``zoo_goodput_seconds_total``, ``zoo_badput_seconds_total{category=}``.
The :class:`~.timeseries.RegistrySampler` picks the counters up like
any family, so windowed rates/slopes per category come for free in the
:class:`~.timeseries.TimeSeriesStore`; ``/statusz`` surfaces the same
numbers in its ``performance`` block.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from .metrics import MetricsRegistry, default_registry

__all__ = ["GoodputLedger", "TRAIN_CATEGORIES", "SERVE_CATEGORIES",
           "GOOD_CATEGORY", "goodput_enabled", "registry_snapshot"]

#: exclusive wall-time categories per role; the FIRST entry is goodput
TRAIN_CATEGORIES = ("device_step", "data_wait", "compile", "ckpt_stall",
                    "rollback_replay", "restart", "anomaly_skip", "idle")
SERVE_CATEGORIES = ("device_dispatch", "host_decode", "publish", "shed",
                    "idle")
GOOD_CATEGORY = {"train": "device_step", "serve": "device_dispatch"}


def _conf(key: str, default):
    """Config read through the zoo context when one is live; the default
    otherwise (context imports jax — keep this module importable
    without it)."""
    try:
        from ..common.context import get_zoo_context
        return get_zoo_context().get(key, default)
    except Exception:
        return default


def goodput_enabled() -> bool:
    """Whether the instrumented loops should keep a ledger
    (``zoo.goodput.enabled``, default on — the accounting is a handful
    of ``perf_counter`` reads per step)."""
    return bool(_conf("zoo.goodput.enabled", True))


class GoodputLedger:
    """Attributes wall-clock intervals to exclusive categories.

    ``note(category)`` charges everything since the previous note (or
    :meth:`open`) to ``category``. All notes must come from the loop
    thread being accounted; readers (``/statusz``, tests) may call the
    query methods from any thread. ``clock`` is injectable so tests
    drive the ledger tick by tick and reconcile exactly.
    """

    def __init__(self, role: str = "train",
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.perf_counter):
        if role not in GOOD_CATEGORY:
            raise ValueError(f"role must be 'train' or 'serve', got {role!r}")
        self.role = role
        self.categories = (TRAIN_CATEGORIES if role == "train"
                           else SERVE_CATEGORIES)
        self.good = GOOD_CATEGORY[role]
        self.registry = registry if registry is not None \
            else default_registry()
        self._clock = clock
        self._lock = threading.Lock()
        self._mark: Optional[float] = None
        self._opened: Optional[float] = None
        self._seconds: Dict[str, float] = {c: 0.0 for c in self.categories}
        self._m_ratio = self.registry.gauge(
            "zoo_goodput_ratio",
            "goodput seconds / attributed wall seconds of the accounted "
            "loop (train: device_step; serve: device_dispatch)")
        self._m_good = self.registry.counter(
            "zoo_goodput_seconds_total",
            "wall-clock seconds attributed to the goodput category "
            "(goodput + sum of zoo_badput_seconds_total == wall time)")
        self._m_badput: Dict[str, object] = {}
        for cat in ("data_wait", "compile", "ckpt_stall", "rollback_replay",
                    "restart", "anomaly_skip", "idle", "host_decode",
                    "publish", "shed"):
            if cat in self._seconds and cat != self.good:
                self._m_badput[cat] = self.registry.counter(
                    "zoo_badput_seconds_total",
                    "wall-clock seconds attributed to a non-goodput "
                    "category; exclusive — every accounted second lands "
                    "in exactly one category",
                    labels={"category": cat})

    # -- accounting ----------------------------------------------------------
    def open(self, now: Optional[float] = None) -> None:
        """(Re)start attribution at ``now`` — the next :meth:`note`
        charges from here. Accumulated seconds are kept (a retry
        attempt continues the same run's ledger)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._mark = now
            if self._opened is None:
                self._opened = now

    def note(self, category: str, now: Optional[float] = None) -> float:
        """Attribute ``[mark, now)`` to ``category``, advance the mark,
        and update the exported metrics. Returns the seconds attributed
        (0.0 on the first note of an unopened ledger, which just arms
        the mark)."""
        if category not in self._seconds:
            raise ValueError(
                f"unknown category {category!r} for role {self.role!r} "
                f"(one of {self.categories})")
        now = self._clock() if now is None else now
        with self._lock:
            if self._mark is None:
                self._mark = now
                if self._opened is None:
                    self._opened = now
                return 0.0
            dt = max(now - self._mark, 0.0)
            self._mark = now
            self._seconds[category] += dt
            if category == self.good:
                self._m_good.inc(dt)
            else:
                self._m_badput[category].inc(dt)
            wall = sum(self._seconds.values())
            if wall > 0:
                self._m_ratio.set(self._seconds[self.good] / wall)
            return dt

    # -- queries -------------------------------------------------------------
    def seconds(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._seconds)

    def wall(self) -> float:
        """Total attributed seconds — equals the open→last-note span."""
        with self._lock:
            return sum(self._seconds.values())

    def goodput_seconds(self) -> float:
        with self._lock:
            return self._seconds[self.good]

    def badput_seconds(self) -> Dict[str, float]:
        with self._lock:
            return {c: s for c, s in self._seconds.items()
                    if c != self.good}

    def ratio(self) -> float:
        with self._lock:
            wall = sum(self._seconds.values())
            return self._seconds[self.good] / wall if wall > 0 else 0.0

    def snapshot(self) -> Dict[str, object]:
        """The JSON-able block ``/statusz`` and ``bench.py`` embed."""
        with self._lock:
            wall = sum(self._seconds.values())
            return {
                "role": self.role,
                "ratio": (self._seconds[self.good] / wall
                          if wall > 0 else None),
                "wall_s": wall,
                "seconds": dict(self._seconds),
            }


def registry_snapshot(registry: Optional[MetricsRegistry] = None
                      ) -> Dict[str, object]:
    """Goodput families read back off a registry — for consumers that
    see only the metrics (``/statusz`` of another process, ``bench.py``
    rounds) rather than the ledger object. Returns ``{"ratio",
    "goodput_s", "badput_s": {category: seconds}}``; ratio is ``None``
    when no ledger ever exported. Several ledgers may export into one
    registry (a bench round runs a fit loop AND serving replicas), so
    the ratio is recomputed from the summed seconds — the per-ledger
    ``zoo_goodput_ratio`` gauge is last-writer-wins and would misstate
    the aggregate; it is used only before any seconds accumulate."""
    reg = registry if registry is not None else default_registry()
    ratio = None
    good = 0.0
    bad: Dict[str, float] = {}
    seen = False
    for m in reg.metrics():
        if m.name == "zoo_goodput_ratio":
            ratio = m.value
            seen = True
        elif m.name == "zoo_goodput_seconds_total":
            good += m.value
            seen = True
        elif m.name == "zoo_badput_seconds_total":
            cat = dict(m.labels).get("category", "")
            bad[cat] = bad.get(cat, 0.0) + m.value
            seen = True
    if not seen:
        return {"ratio": None, "goodput_s": 0.0, "badput_s": {}}
    wall = good + sum(bad.values())
    if wall > 0:
        ratio = good / wall
    return {"ratio": ratio, "goodput_s": good, "badput_s": bad}
