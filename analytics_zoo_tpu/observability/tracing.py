"""Span-based tracing over the metrics registry.

``with trace.span("serving.dispatch"): ...`` records the block's wall
duration into the ``zoo_span_seconds{span=...}`` histogram and — when the
registry has event sinks attached — emits one structured span event with
the parent span name, so the JSON log reconstructs nesting without a
separate trace-file format. Nesting is tracked per thread; a span opened
on one thread never becomes the parent of a span on another (the serving
loop, producers, and the training loop each own their stack).

Spans are phase-level ("which phase of the request spent the time" — the
reference's scoped ``timeIt`` role): per-thread nesting, no sampling, no
cross-process context. REQUEST-level tracing is the thin Dapper-style
layer on top: :func:`new_trace_id` mints the 64-bit hex id the serving
client stamps on each enqueued record, and the serve loop emits
parent-linked per-request phase events (enqueue → dequeue → dispatch →
publish) carrying that id into the JSON event log — the id is the join
key, the log is the trace store, and there is still no in-band context
to thread through the hot path.
"""

from __future__ import annotations

import contextlib
import secrets
import threading
import time
import weakref
from typing import Dict, Iterator, Optional

from .metrics import Histogram, MetricsRegistry, default_registry

__all__ = ["span", "current_span", "SpanHandle", "new_trace_id"]


def new_trace_id() -> str:
    """A fresh Dapper-style trace id: 16 lowercase hex chars (64 random
    bits — collision-free at any realistic request volume). This exact
    format is the serving wire contract (docs/guides/SERVING.md): the
    client stamps it into the stream record's ``trace`` field and every
    per-request event carries it verbatim."""
    return secrets.token_hex(8)

_state = threading.local()

# per-(registry, span-name) histogram cache: a span exit must not take the
# registry lock (which a concurrent scrape holds while rendering) — the
# lock is paid once per new span name, then exits are lock-free dict reads
_hist_cache: "weakref.WeakKeyDictionary[MetricsRegistry, Dict[str, Histogram]]" \
    = weakref.WeakKeyDictionary()


def _span_histogram(reg: MetricsRegistry, name: str) -> Histogram:
    per_reg = _hist_cache.get(reg)
    if per_reg is None:
        per_reg = _hist_cache.setdefault(reg, {})
    h = per_reg.get(name)
    if h is None:
        # span names are code-defined constants (obs.span("...")),
        # one series per instrumented phase
        h = per_reg[name] = reg.histogram(  # zoolint: disable=ZL015 bounded label set
            "zoo_span_seconds", "wall seconds per traced span",
            labels={"span": name})
    return h


def _stack() -> list:
    st = getattr(_state, "stack", None)
    if st is None:
        st = _state.stack = []
    return st


def current_span() -> Optional[str]:
    """Name of the innermost open span on this thread, or None."""
    st = _stack()
    return st[-1] if st else None


class SpanHandle:
    """Yielded by :func:`span`; :meth:`discard` cancels recording — for
    blocks that turn out to be no-ops (e.g. a refused non-blocking
    dispatch probe) whose ~zero durations would skew the distribution."""

    __slots__ = ("discarded",)

    def __init__(self):
        self.discarded = False

    def discard(self) -> None:
        self.discarded = True


@contextlib.contextmanager
def span(name: str, registry: Optional[MetricsRegistry] = None,
         **attrs) -> Iterator[SpanHandle]:
    """Time a block as a named span.

    * duration → ``zoo_span_seconds{span=name}`` histogram in ``registry``
      (default: the process-wide registry),
    * one ``{"kind": "span", "name", "parent", "dur_s", **attrs}`` event
      to the registry's sinks (no-op when none are attached),
    * ``attrs`` ride along on the event only — keep them small and
      JSON-serializable (batch sizes, record counts),
    * yields a :class:`SpanHandle`; ``handle.discard()`` suppresses the
      histogram observation and event for a block that did no real work.
    """
    reg = registry if registry is not None else default_registry()
    st = _stack()
    parent = st[-1] if st else None
    st.append(name)
    handle = SpanHandle()
    t0 = time.perf_counter()
    try:
        yield handle
    finally:
        dur = time.perf_counter() - t0
        st.pop()
        if not handle.discarded:
            _span_histogram(reg, name).observe(dur)
            reg.emit("span", name=name, parent=parent, dur_s=dur, **attrs)
