"""Compilation observability — the answer to "why did this step suddenly
take 40x longer?" (a silent retrace).

:func:`instrument_jit` is a drop-in ``jax.jit`` replacement used by every
hot-path entry point (the four training dispatch paths, the eval/predict
steps, ``InferenceModel``'s serving predict, ``Seq2seq.infer``'s
encode/decode closures). On every call it derives the ABSTRACT signature
of the arguments (pytree structure + per-leaf shape/dtype — the same
identity ``jax.jit`` keys its executable cache on, minus shardings) and,
when the signature is new:

* counts the compilation in ``zoo_jit_compile_total`` (process-wide) and
  times it into ``zoo_jit_compile_seconds{fn=...}`` — the wall time of
  the first dispatch, which trace+compile dominate,
* emits a ``jit.compile`` event, and — when the function had already
  compiled under a DIFFERENT signature — a ``jit.retrace`` event plus a
  ``zoo_jit_retrace_total{fn=...}`` increment. A retrace under load is
  almost always a shape-discipline bug (unpadded dynamic batch, a new
  sequence length); the event names the function so the operator can go
  straight to the offending caller.

Steady-state cost is two executable-cache size reads per call (~tens of
nanoseconds; the signature is only derived on the rare call that actually
compiled). On jax builds without ``_cache_size`` it degrades to one
pytree flatten per call — the same order of work ``jax.jit``'s own cache
lookup does. Retrace classification is deliberately sharding-blind: a
recompile triggered purely by a resharded input counts as a compile but
never as a retrace, so the retrace signal stays a pure shape-discipline
alarm.

``jax`` is imported lazily so the observability package stays importable
(and the scrape/status CLI stays fast) in jax-free processes.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional, Tuple

from .metrics import MetricsRegistry, default_registry

__all__ = ["instrument_jit", "InstrumentedJit"]

_HASHABLE = (int, float, bool, str, bytes, type(None))


class InstrumentedJit:
    """A jitted callable with compile/retrace accounting. Behaves like the
    underlying ``jax.jit`` result — extra attributes (``lower``,
    ``clear_cache``, ...) forward to it, so AOT cost-analysis callers
    (``TrainingLoop._maybe_compute_flops``) work unchanged."""

    def __init__(self, fn, *, name: str,
                 registry: Optional[MetricsRegistry] = None, **jit_kwargs):
        import jax
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._name = name
        # None = resolve default_registry() per compile event, so a test's
        # reset_default_registry() is honored (compiles are rare; the
        # lookup never lands on the steady-state path)
        self._registry = registry
        self._seen: set = set()
        self._lock = threading.Lock()

    @staticmethod
    def _signature(args, kwargs) -> Tuple[Any, ...]:
        import jax
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig: list = [treedef]
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is not None and dtype is not None:
                # metadata only — safe on buffers the call just donated
                sig.append((tuple(shape), str(dtype)))
            elif isinstance(leaf, (int, float, bool)):
                # jax traces Python numbers by dtype, not value — keying
                # by value would report a phantom retrace per distinct
                # value (and grow the seen-set without bound)
                sig.append((type(leaf).__name__,))
            elif isinstance(leaf, _HASHABLE):
                # str/bytes/None only pass jit as static args, where the
                # VALUE does key the executable cache
                sig.append((type(leaf).__name__, leaf))
            else:
                sig.append((type(leaf).__name__,))
        return tuple(sig)

    def _registry_now(self) -> MetricsRegistry:
        return (self._registry if self._registry is not None
                else default_registry())

    def __call__(self, *args, **kwargs):
        cache_size = getattr(self._jitted, "_cache_size", None)
        if cache_size is not None:
            # fast path: one executable-cache size read (~tens of ns)
            # before and after — the signature is only derived on the
            # rare call that actually compiled, so the steady state pays
            # no pytree flatten at all
            before = cache_size()
            t0 = time.perf_counter()
            out = self._jitted(*args, **kwargs)
            if cache_size() == before:
                return out
            dur = time.perf_counter() - t0
            self._record_compile(self._signature(args, kwargs), dur)
            return out
        # fallback (no _cache_size on this jax): signature-per-call —
        # a sharding-only recompile is invisible here, matching the
        # documented sharding-blind contract
        sig = self._signature(args, kwargs)
        with self._lock:
            known = sig in self._seen
        if known:
            return self._jitted(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        self._record_compile(sig, time.perf_counter() - t0)
        return out

    def _record_compile(self, sig, dur: float) -> None:
        with self._lock:
            fresh = sig not in self._seen
            if fresh:
                self._seen.add(sig)
            n_sigs = len(self._seen)
        reg = self._registry_now()
        reg.counter(
            "zoo_jit_compile_total",
            "XLA compilations across all instrumented entry points").inc()
        # fn = the instrument_jit(name=...) entry-point constant
        reg.histogram(  # zoolint: disable=ZL015 bounded label set
            "zoo_jit_compile_seconds",
            "first-dispatch wall time per compilation "
            "(trace+compile dominated)",
            labels={"fn": self._name}).observe(dur)
        reg.emit("jit.compile", fn=self._name, dur_s=dur, n_signatures=n_sigs)
        # retrace = a compile under a NEW abstract signature after the
        # first; a compile with a KNOWN signature (resharded inputs, a
        # concurrent first call racing this one) counts above but is not
        # a retrace — never report a phantom shape-discipline bug
        if fresh and n_sigs > 1:
            # fn = the instrument_jit(name=...) entry-point constant
            reg.counter(  # zoolint: disable=ZL015 bounded label set
                "zoo_jit_retrace_total",
                "recompilations of an already-compiled function under a "
                "new abstract signature",
                labels={"fn": self._name}).inc()
            reg.emit("jit.retrace", fn=self._name, dur_s=dur,
                     n_signatures=n_sigs)

    def __getattr__(self, attr):
        if attr == "_jitted":
            # only reachable when __init__ hasn't populated the instance
            # dict (e.g. unpickling); forwarding would infinitely recurse
            raise AttributeError(attr)
        return getattr(self._jitted, attr)

    def __repr__(self):
        return f"InstrumentedJit({self._name!r}, {self._jitted!r})"


def instrument_jit(fn, *, name: str,
                   registry: Optional[MetricsRegistry] = None,
                   **jit_kwargs) -> InstrumentedJit:
    """``jax.jit(fn, **jit_kwargs)`` with compile observability. ``name``
    labels the ``zoo_jit_compile_seconds``/``zoo_jit_retrace_total``
    series and the ``jit.compile``/``jit.retrace`` events; keep it a
    stable dotted identifier (``train.step``, ``inference.predict``)."""
    return InstrumentedJit(fn, name=name, registry=registry, **jit_kwargs)
