"""Fleet collector — the continuous half of fleet observability.

``scripts/cluster-serving-status`` answers "how is the fleet *right
now*" when an operator runs it; this daemon asks the same question on
a cadence and **remembers the answers**: it discovers replicas from
the fleet registry (each replica's heartbeat carries its scrape
``endpoint``; explicit endpoints work registry-less), scrapes every
``/metrics`` + ``/statusz`` under a per-target
:class:`~..common.reliability.RetryPolicy` +
:class:`~..common.reliability.CircuitBreaker`, and ingests into two
:class:`~.timeseries.TimeSeriesStore`\\ s — per-replica series (the
original series key with a ``replica=`` label) and fleet-aggregated
series.

Aggregation semantics per metric kind (the catalog contract,
docs/guides/OBSERVABILITY.md "Fleet telemetry & alerting"):

* **counters** — summed over every replica *ever* scraped, using each
  replica's last-known value: a replica dropping out of scrape must
  not make fleet totals dip (monotonicity is what ``rate()`` and the
  reconciliation tests key on).
* **gauges** — summed over currently-healthy replicas (depth, DLQ
  bytes: extensive quantities), except the enumerated-state gauges in
  :data:`GAUGE_MAX` which take the worst (max) across the fleet.
* **summaries** — merged count-weighted through
  :func:`~.timeseries.rehydrate_digest` +
  ``QuantileDigest.merge`` (the PR-5 fleet rollup, which lives in
  ``timeseries`` now; the CLI imports it back).
* **histograms** — counts and sums summed (mean-level trend).

Every scrape attempt passes the ``collector.scrape`` fault site, so
chaos plans can drop a replica mid-scrape and reconcile breaker/alert
behavior exactly.

The aggregated state serves over HTTP (:class:`FleetzServer`):

* ``/fleetz`` — the JSON fleet page: per-replica health, fleet
  totals, windowed rates, quantiles, alert states, and the
  ``saturation`` block — **the autoscaler's input surface** (stable,
  documented): per-replica utilization + trend, fleet saturation
  verdict, windowed depth slope.
* ``/metrics`` — the fleet-level Prometheus re-export (aggregated
  ``zoo_*`` families rendered straight from the fleet store).
* ``/healthz`` — collector liveness + replica counts.

The collector's own metrics ride the normal catalog:
``zoo_collector_scrapes_total{outcome=}`` and
``zoo_collector_replicas_live``, registered through the
:func:`collector_counter`/:func:`collector_gauge` helpers zoolint's
ZL017 extractor resolves to call sites.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..common import faults
from ..common.reliability import CircuitBreaker, RetryPolicy
from .alerts import AlertEngine, AlertRule, StoreSignals
from .export import _fmt, parse_prometheus
from .metrics import MetricsRegistry, default_registry
from .timeseries import (SummarySample, TimeSeriesStore, family_of,
                         rehydrate_digest)

log = logging.getLogger("analytics_zoo_tpu.observability")

__all__ = [
    "FleetCollector", "FleetSignals", "FleetzServer", "GAUGE_MAX",
    "base_url", "summary_points", "fleet_rows", "endpoint_rows",
    "collector_counter", "collector_gauge",
]

#: enumerated-state gauge families aggregated by max (worst state wins
#: fleet-wide); every other gauge family sums
GAUGE_MAX = frozenset({"zoo_breaker_state", "zoo_alert_state"})

#: counter families the ``/fleetz`` ``rates`` block reports
RATE_FAMILIES = ("zoo_serving_records_total", "zoo_serving_shed_total",
                 "zoo_serving_failure_errors_total",
                 "zoo_serving_dlq_spilled_total")


def collector_counter(registry: MetricsRegistry, name: str,
                      help: str = "",
                      labels: Optional[Dict[str, str]] = None):
    """Register/fetch a counter for the collector plane (ZL017
    resolves the caller's name/labels, not this shim)."""
    return registry.counter(name, help, labels=labels)


def collector_gauge(registry: MetricsRegistry, name: str,
                    help: str = "",
                    labels: Optional[Dict[str, str]] = None):
    """Register/fetch a gauge for the collector plane (see
    :func:`collector_counter`)."""
    return registry.gauge(name, help, labels=labels)


def base_url(arg: str) -> str:
    """``host:port`` / bare port / URL → a scrapable base URL."""
    if arg.startswith("http://") or arg.startswith("https://"):
        return arg.rstrip("/").rsplit("/metrics", 1)[0]
    if ":" not in arg:                      # bare port
        arg = f"127.0.0.1:{arg}"
    return f"http://{arg}"


# ---------------------------------------------------------------------------
# fleet rollup helpers (migrated from scripts/cluster-serving-status,
# which imports them back)
# ---------------------------------------------------------------------------

def summary_points(families: Dict[str, Any],
                   name: str) -> Tuple[Dict[str, float], float]:
    """``({quantile_str: value}, count)`` for one scraped summary
    family."""
    samples = families[name]["samples"]
    qs = {lab["quantile"]: v for s_name, lab, v in samples
          if s_name == name and "quantile" in lab}
    count = next((v for s_name, _, v in samples
                  if s_name == name + "_count"), 0)
    return qs, count


def fleet_rows(scraped: Sequence[Tuple[Any, ...]]):
    """Roll several replicas' scrapes into fleet-wide
    ``(quantile_rows, scalar_rows)``: summaries merge through
    ``QuantileDigest`` (count-weighted rehydration), counters/gauges
    sum per labeled series, histograms report the mean of the summed
    sums/counts. ``scraped`` rows are ``(base, health, status,
    families)`` — the CLI's scrape tuple."""
    merged: Dict[str, list] = {}    # family -> [digest, count]
    sums: Dict[str, float] = {}     # scalar series -> value
    hist: Dict[str, list] = {}      # family -> [count, sum]
    for _base, _health, _status, families in scraped:
        for name in families:
            fam = families[name]
            if fam["type"] == "summary":
                qs, count = summary_points(families, name)
                if not count:
                    continue
                d = rehydrate_digest(qs, count)
                if name in merged:
                    merged[name][0].merge(d)
                    merged[name][1] += count
                else:
                    merged[name] = [d, count]
            elif fam["type"] in ("counter", "gauge"):
                for s_name, lab, v in fam["samples"]:
                    suffix = ("{" + ",".join(
                        f"{k}={vv}" for k, vv in lab.items()) + "}") \
                        if lab else ""
                    key = s_name + suffix
                    sums[key] = sums.get(key, 0.0) + v
            elif fam["type"] == "histogram":
                count = next((v for s_name, _, v in fam["samples"]
                              if s_name == name + "_count"), 0)
                total = next((v for s_name, _, v in fam["samples"]
                              if s_name == name + "_sum"), 0.0)
                h = hist.setdefault(name, [0, 0.0])
                h[0] += count
                h[1] += total
    quantile_rows = [
        (name, count, *(d.quantile(q) * 1000.0 for q in (0.5, 0.95, 0.99)))
        for name, (d, count) in sorted(merged.items())]
    scalar_rows = sorted(sums.items())
    scalar_rows += [(name + " (mean)", h[1] / h[0])
                    for name, h in sorted(hist.items()) if h[0]]
    return quantile_rows, scalar_rows


def endpoint_rows(families: Dict[str, Any]):
    """One endpoint's ``(quantile_rows, scalar_rows)`` — exact
    quantile values straight off the scrape, no rehydration."""
    quantile_rows = []
    scalar_rows = []
    for name in sorted(families):
        fam = families[name]
        samples = fam["samples"]
        if fam["type"] == "summary":
            qs, count = summary_points(families, name)
            if count:
                quantile_rows.append(
                    (name, count, *(qs.get(k, float("nan")) * 1000.0
                                    for k in ("0.5", "0.95", "0.99"))))
        elif fam["type"] in ("counter", "gauge"):
            for s_name, lab, v in samples:
                suffix = ("{" + ",".join(f"{k}={vv}"
                                         for k, vv in lab.items())
                          + "}") if lab else ""
                scalar_rows.append((s_name + suffix, v))
        elif fam["type"] == "histogram":
            count = next((v for s_name, _, v in samples
                          if s_name == name + "_count"), 0)
            total = next((v for s_name, _, v in samples
                          if s_name == name + "_sum"), 0.0)
            if count:
                scalar_rows.append((name + " (mean)", total / count))
    return quantile_rows, scalar_rows


def _series_key(name: str, labels: Dict[str, str]) -> str:
    """The store key for one labeled sample — same format as
    ``MetricsRegistry.snapshot`` (labels sorted)."""
    if not labels:
        return name
    return name + "{" + ",".join(
        f'{k}="{v}"' for k, v in sorted(labels.items())) + "}"


class _Target:
    """Per-replica scrape state."""

    def __init__(self, endpoint: str, base: str,
                 breaker: CircuitBreaker):
        self.endpoint = endpoint
        self.base = base
        self.breaker = breaker
        self.healthy = False
        self.last_ok_ts: Optional[float] = None
        self.last_error: Optional[str] = None
        self.source = "static"          # or "registry"


class FleetSignals(StoreSignals):
    """The alert-rule signals view for fleet scope: the fleet store's
    derived signals plus replica health from the collector."""

    def __init__(self, collector: "FleetCollector"):
        super().__init__(collector.fleet_store, clock=collector._clock)
        self._collector = collector

    def replicas_down(self) -> Optional[float]:
        return float(self._collector.replicas_down())

    def replicas_live(self) -> Optional[float]:
        return float(self._collector.replicas_live())

    def saturated_fraction(self) -> Optional[float]:
        live = self._collector.replica_saturation()
        if not live:
            return None
        return sum(1.0 for sat in live.values() if sat) / len(live)


class FleetCollector:
    """The scrape→aggregate→alert loop. Construct, then either
    :meth:`start` the daemon thread or drive :meth:`poll` by hand
    (tests, the one-shot CLI)."""

    def __init__(self,
                 endpoints: Sequence[str] = (),
                 backend=None, stream: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: Optional[float] = None,
                 retention_s: Optional[float] = None,
                 timeout_s: float = 5.0,
                 retry: Optional[RetryPolicy] = None,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 5.0,
                 registry_ttl_s: Optional[float] = None,
                 rules: Optional[Sequence[AlertRule]] = None,
                 clock=time.time):
        from .timeseries import _conf
        self.registry = registry if registry is not None \
            else default_registry()
        self.interval_s = float(
            interval_s if interval_s is not None
            else _conf("zoo.telemetry.sample_interval_s", 1.0))
        self.timeout_s = float(timeout_s)
        self.backend = backend
        self.stream = stream
        self.registry_ttl_s = registry_ttl_s
        self._clock = clock
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=2, base_delay=0.05, max_delay=0.5)
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_reset_s = float(breaker_reset_s)
        store_kw = dict(retention_s=retention_s,
                        sample_interval_s=self.interval_s)
        #: per-replica series (``replica=`` label on every key)
        self.replica_store = TimeSeriesStore(**store_kw)
        #: fleet-aggregated series (original keys)
        self.fleet_store = TimeSeriesStore(**store_kw)
        self._lock = threading.Lock()
        self._targets: Dict[str, _Target] = {}
        for ep in endpoints:
            self._ensure_target(ep, source="static")
        #: last-known good scrape per endpoint:
        #: ep -> (ts, families, status)
        self._last: Dict[str, Tuple[float, dict, dict]] = {}
        self._fleet_latest: Dict[str, Tuple[str, Any]] = {}
        self.signals = FleetSignals(self)
        self.alerts: Optional[AlertEngine] = None
        if rules:
            self.alerts = AlertEngine(rules, registry=self.registry,
                                      clock=clock)
        #: recent alert transitions, oldest first (bounded)
        self.transitions_log: List[dict] = []
        self.polls = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- target management ---------------------------------------------------
    def _ensure_target(self, endpoint: str, source: str) -> _Target:
        t = self._targets.get(endpoint)
        if t is None:
            t = _Target(endpoint, base_url(endpoint), CircuitBreaker(
                name=f"collector.{endpoint}",
                failure_threshold=self._breaker_threshold,
                reset_timeout=self._breaker_reset_s,
                registry=self.registry))
            self._targets[endpoint] = t
        t.source = source
        return t

    def _discover(self) -> List[_Target]:
        """Current targets: static endpoints plus live fleet-registry
        members advertising a scrape ``endpoint`` in their
        heartbeat."""
        if self.backend is not None and self.stream is not None:
            try:
                from ..serving.fleet import DEFAULT_TTL_S, live_members
                ttl = self.registry_ttl_s if self.registry_ttl_s \
                    is not None else DEFAULT_TTL_S
                members = live_members(self.backend, self.stream,
                                       ttl_s=ttl)
                for member in members.values():
                    ep = member.get("endpoint")
                    if ep:
                        with self._lock:
                            self._ensure_target(str(ep),
                                                source="registry")
            except Exception:   # a dead registry must not stop the scrape
                log.exception("fleet-registry discovery failed")
        with self._lock:
            return [self._targets[ep] for ep in sorted(self._targets)]

    # -- scraping ------------------------------------------------------------
    def _get(self, url: str) -> str:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return r.read().decode("utf-8")

    def _fetch(self, t: _Target) -> Tuple[dict, dict]:
        """One scrape attempt: the fault gate, then /metrics +
        /statusz. Runs inside the retry policy, so every attempt
        passes ``collector.scrape``."""
        faults.inject("collector.scrape")
        families = parse_prometheus(self._get(t.base + "/metrics"))
        status = json.loads(self._get(t.base + "/statusz"))
        return families, status

    def _scrape_target(self, t: _Target, now: float) -> bool:
        if not t.breaker.allow():
            collector_counter(   # zoolint: disable=ZL015 bounded label set
                self.registry, "zoo_collector_scrapes_total",
                "fleet-collector scrape attempts per outcome",
                labels={"outcome": "breaker_open"}).inc()
            t.healthy = False
            return False
        try:
            families, status = self.retry.call(
                lambda: self._fetch(t), op="collector.scrape",
                registry=self.registry,
                classify=lambda e: isinstance(
                    e, (ConnectionError, OSError, ValueError)))
        except Exception as e:
            t.breaker.record_failure()
            t.healthy = False
            t.last_error = f"{type(e).__name__}: {e}"
            collector_counter(   # zoolint: disable=ZL015 bounded label set
                self.registry, "zoo_collector_scrapes_total",
                "fleet-collector scrape attempts per outcome",
                labels={"outcome": "error"}).inc()
            return False
        t.breaker.record_success()
        t.healthy = True
        t.last_ok_ts = now
        t.last_error = None
        collector_counter(   # zoolint: disable=ZL015 bounded label set
            self.registry, "zoo_collector_scrapes_total",
            "fleet-collector scrape attempts per outcome",
            labels={"outcome": "ok"}).inc()
        with self._lock:
            self._last[t.endpoint] = (now, families, status)
        self._ingest_replica(t.endpoint, families, status, now)
        return True

    def _ingest_replica(self, ep: str, families: dict, status: dict,
                        now: float) -> None:
        store = self.replica_store
        for name, fam in families.items():
            kind = fam["type"]
            if kind in ("counter", "gauge"):
                for s_name, lab, v in fam["samples"]:
                    labels = dict(lab)
                    labels["replica"] = ep
                    store.record(_series_key(s_name, labels), kind,
                                 now, v)
            elif kind == "summary":
                qs, count = summary_points(families, name)
                total = next((v for s_name, _, v in fam["samples"]
                              if s_name == name + "_sum"), 0.0)
                store.record(_series_key(name, {"replica": ep}),
                             "summary", now,
                             SummarySample(count, total, qs))
            elif kind == "histogram":
                count = next((v for s_name, _, v in fam["samples"]
                              if s_name == name + "_count"), 0)
                total = next((v for s_name, _, v in fam["samples"]
                              if s_name == name + "_sum"), 0.0)
                store.record(_series_key(name, {"replica": ep}),
                             "histogram", now, (count, total))
        # statusz-derived operational series (store-only: these are
        # /statusz facts, not catalog metric families, and the fleet
        # re-export page filters to zoo_* — see render_fleet_prometheus)
        sc = (status.get("serving") or {}).get("scaling") or {}
        for field, key in (("utilization", "statusz_utilization"),
                           ("stream_depth", "statusz_depth"),
                           ("pending_entries", "statusz_pending")):
            v = sc.get(field)
            if isinstance(v, (int, float)):
                store.record(_series_key(key, {"replica": ep}),
                             "gauge", now, float(v))

    # -- aggregation ---------------------------------------------------------
    def _aggregate(self, now: float) -> None:
        with self._lock:
            last = dict(self._last)
            healthy = {ep for ep, t in self._targets.items()
                       if t.healthy}
        sums: Dict[str, Tuple[str, float]] = {}
        maxes: Dict[str, float] = {}
        merged: Dict[str, list] = {}    # key -> [digest, count, sum]
        hist: Dict[str, list] = {}
        for ep, (_ts, families, status) in last.items():
            for name, fam in families.items():
                kind = fam["type"]
                if kind == "counter":
                    for s_name, lab, v in fam["samples"]:
                        key = _series_key(s_name, lab)
                        sums[key] = ("counter",
                                     sums.get(key, ("", 0.0))[1] + v)
                elif kind == "gauge":
                    if ep not in healthy:
                        continue        # stale gauges drop out
                    for s_name, lab, v in fam["samples"]:
                        key = _series_key(s_name, lab)
                        if family_of(key) in GAUGE_MAX:
                            maxes[key] = max(maxes.get(key, v), v)
                        else:
                            sums[key] = ("gauge",
                                         sums.get(key, ("", 0.0))[1] + v)
                elif kind == "summary":
                    qs, count = summary_points(families, name)
                    if not count:
                        continue
                    total = next((v for s_name, _, v in fam["samples"]
                                  if s_name == name + "_sum"), 0.0)
                    d = rehydrate_digest(qs, count)
                    if name in merged:
                        merged[name][0].merge(d)
                        merged[name][1] += count
                        merged[name][2] += total
                    else:
                        merged[name] = [d, count, total]
                elif kind == "histogram":
                    count = next((v for s_name, _, v in fam["samples"]
                                  if s_name == name + "_count"), 0)
                    total = next((v for s_name, _, v in fam["samples"]
                                  if s_name == name + "_sum"), 0.0)
                    h = hist.setdefault(name, [0, 0.0])
                    h[0] += count
                    h[1] += total
        latest: Dict[str, Tuple[str, Any]] = {}
        for key, (kind, v) in sums.items():
            latest[key] = (kind, v)
            self.fleet_store.record(key, kind, now, v)
        for key, v in maxes.items():
            latest[key] = ("gauge", v)
            self.fleet_store.record(key, "gauge", now, v)
        for name, (d, count, total) in merged.items():
            sample = SummarySample(count, total, {
                repr(q): d.quantile(q) for q in (0.5, 0.95, 0.99)
                if d.count})
            latest[name] = ("summary", sample)
            self.fleet_store.record(name, "summary", now, sample)
        for name, (count, total) in hist.items():
            latest[name] = ("histogram", (count, total))
            self.fleet_store.record(name, "histogram", now,
                                    (count, total))
        # fleet depth (statusz-derived, healthy replicas): the series
        # the saturation block's depth slope reads
        depth = self._healthy_scaling_sum("stream_depth")
        if depth is not None:
            latest["statusz_depth"] = ("gauge", depth)
            self.fleet_store.record("statusz_depth", "gauge", now,
                                    depth)
        with self._lock:
            self._fleet_latest = latest

    def _healthy_scaling_sum(self, field: str) -> Optional[float]:
        vals = []
        with self._lock:
            for ep, t in self._targets.items():
                if not t.healthy or ep not in self._last:
                    continue
                sc = (self._last[ep][2].get("serving") or {}) \
                    .get("scaling") or {}
                v = sc.get(field)
                if isinstance(v, (int, float)):
                    vals.append(float(v))
        return sum(vals) if vals else None

    # -- the loop ------------------------------------------------------------
    def poll(self, now: Optional[float] = None) -> int:
        """One synchronous discover→scrape→aggregate→alert pass;
        returns the number of healthy replicas."""
        now = self._clock() if now is None else now
        targets = self._discover()
        ok = 0
        for t in targets:
            if self._scrape_target(t, now):
                ok += 1
        collector_gauge(
            self.registry, "zoo_collector_replicas_live",
            "fleet replicas the collector scraped successfully on its "
            "latest pass").set(float(ok))
        self._aggregate(now)
        if self.alerts is not None:
            transitions = self.alerts.evaluate(self.signals, now=now)
            if transitions:
                self.transitions_log.extend(transitions)
                del self.transitions_log[:-256]     # bounded log
        self.polls += 1
        return ok

    def start(self) -> "FleetCollector":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="zoo-fleet-collector", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll()
            except Exception:       # the loop outlives any bad scrape
                log.exception("collector poll failed")

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # -- introspection -------------------------------------------------------
    def replicas_live(self) -> int:
        with self._lock:
            return sum(1 for t in self._targets.values() if t.healthy)

    def replicas_down(self) -> int:
        with self._lock:
            return sum(1 for t in self._targets.values()
                       if not t.healthy)

    def replica_saturation(self) -> Dict[str, bool]:
        """``{endpoint: saturated}`` for healthy replicas, derived
        from the ``/statusz`` overload block (backlog at or past the
        shed watermark)."""
        out: Dict[str, bool] = {}
        with self._lock:
            for ep, t in self._targets.items():
                if not t.healthy or ep not in self._last:
                    continue
                ov = (self._last[ep][2].get("serving") or {}) \
                    .get("overload") or {}
                wm = ov.get("shed_watermark") or 0
                depth = ov.get("stream_depth") or 0
                out[ep] = bool(wm) and depth >= wm
        return out

    def fleet_totals(self) -> Dict[str, float]:
        """Latest fleet-aggregated scalar series (counters + gauges):
        ``{series_key: value}``."""
        with self._lock:
            return {k: v for k, (kind, v) in self._fleet_latest.items()
                    if kind in ("counter", "gauge")}

    def fleetz(self, window_s: float = 60.0) -> Dict[str, Any]:
        """The aggregated fleet page — see the module docstring for
        the stable-surface contract."""
        now = self._clock()
        with self._lock:
            targets = dict(self._targets)
            last = dict(self._last)
            latest = dict(self._fleet_latest)
        replicas: Dict[str, Any] = {}
        for ep, t in sorted(targets.items()):
            entry: Dict[str, Any] = {
                "healthy": t.healthy,
                "breaker": t.breaker.state,
                "source": t.source,
                "age_s": (now - t.last_ok_ts)
                if t.last_ok_ts is not None else None,
            }
            if t.last_error:
                entry["error"] = t.last_error
            if ep in last:
                sc = (last[ep][2].get("serving") or {}) \
                    .get("scaling") or {}
                entry["scaling"] = {k: sc.get(k) for k in (
                    "consumer", "stream_depth", "pending_entries",
                    "utilization", "batch_size_target", "goodput")}
            replicas[ep] = entry
        quantiles = {
            fam: {"count": s.count,
                  "quantiles": dict(s.points)}
            for fam, (kind, s) in sorted(latest.items())
            if kind == "summary"}
        rates = {fam: self.signals.rate(fam, window_s)
                 for fam in RATE_FAMILIES}
        saturation = self._saturation_block(window_s)
        out: Dict[str, Any] = {
            "ts": now,
            "window_s": window_s,
            "replicas": replicas,
            "fleet": {
                "replicas_live": self.replicas_live(),
                "replicas_down": self.replicas_down(),
                "replicas_seen": len(last),
                "totals": self.fleet_totals(),
                "quantiles": quantiles,
            },
            "rates": rates,
            "saturation": saturation,
            "alerts": self.alerts.states()
            if self.alerts is not None else {},
        }
        return out

    def _saturation_block(self, window_s: float) -> Dict[str, Any]:
        """The autoscaler input: per-replica utilization level +
        trend, fleet depth + windowed slope, saturation verdict."""
        sat = self.replica_saturation()
        util: Dict[str, Optional[float]] = {}
        trend: Dict[str, Optional[float]] = {}
        with self._lock:
            healthy = [ep for ep, t in self._targets.items()
                       if t.healthy]
        for ep in healthy:
            key = _series_key("statusz_utilization", {"replica": ep})
            got = self.replica_store.latest(key)
            util[ep] = float(got[1]) if got is not None else None
            trend[ep] = self.replica_store.slope(key, window_s)
        known = [u for u in util.values() if u is not None]
        util_mean = sum(known) / len(known) if known else None
        depth_got = self.fleet_store.latest("statusz_depth")
        depth = float(depth_got[1]) if depth_got is not None else None
        depth_slope = self.fleet_store.slope("statusz_depth", window_s)
        live = len(healthy)
        saturated = live > 0 and sat and all(sat.values())
        if saturated or (util_mean is not None and util_mean > 0.8
                         and (depth_slope or 0.0) > 0):
            verdict = "scale_up"
        elif util_mean is not None and util_mean < 0.3 \
                and (depth or 0.0) <= 0 and (depth_slope or 0.0) <= 0:
            verdict = "scale_down"
        else:
            verdict = "steady"
        return {
            "verdict": verdict,
            "saturated": bool(saturated),
            "saturated_replicas": sum(1 for v in sat.values() if v),
            "replicas_live": live,
            "utilization": util,
            "utilization_mean": util_mean,
            "utilization_trend": trend,
            "depth": depth,
            "depth_slope": depth_slope,
        }

    # -- fleet re-export -----------------------------------------------------
    def render_fleet_prometheus(self) -> str:
        """The aggregated ``zoo_*`` families as Prometheus text
        exposition — the fleet-level twin of a replica's
        ``/metrics``."""
        with self._lock:
            latest = dict(self._fleet_latest)
        lines: List[str] = []
        typed = set()
        for key in sorted(latest):
            fam = family_of(key)
            if not fam.startswith("zoo_"):
                continue            # statusz-derived series stay internal
            kind, val = latest[key]
            if fam not in typed:
                typed.add(fam)
                lines.append(f"# TYPE {fam} {kind}")
            braces = key[len(fam):]
            if kind in ("counter", "gauge"):
                lines.append(f"{key} {_fmt(float(val))}")
            elif kind == "summary":
                for q in sorted(val.points, key=float):
                    inner = (braces[:-1] + "," if braces else "{") \
                        + f'quantile="{q}"' + "}"
                    lines.append(f"{fam}{inner} "
                                 f"{_fmt(float(val.points[q]))}")
                lines.append(f"{fam}_sum{braces} {_fmt(val.sum)}")
                lines.append(f"{fam}_count{braces} "
                             f"{_fmt(float(val.count))}")
            elif kind == "histogram":
                count, total = val
                lines.append(f"{fam}_sum{braces} {_fmt(float(total))}")
                lines.append(f"{fam}_count{braces} "
                             f"{_fmt(float(count))}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# the /fleetz HTTP endpoint
# ---------------------------------------------------------------------------

class _FleetzHandler(http.server.BaseHTTPRequestHandler):
    collector: FleetCollector = None    # type: ignore[assignment]

    def _send(self, body: bytes, content_type: str,
              code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        path = self.path.split("?", 1)[0]
        c = type(self).collector
        if path in ("/", "/fleetz"):
            self._send(json.dumps(c.fleetz(), indent=2,
                                  default=str).encode("utf-8"),
                       "application/json")
        elif path == "/metrics":
            self._send(c.render_fleet_prometheus().encode("utf-8"),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            self._send(json.dumps({
                "status": "ok",
                "replicas_live": c.replicas_live(),
                "replicas_down": c.replicas_down(),
                "polls": c.polls,
            }).encode("utf-8"), "application/json")
        else:
            self.send_error(404)

    def log_message(self, *args):   # scrapes must not spam stderr
        pass


class FleetzServer:
    """HTTP front for one :class:`FleetCollector`: ``/fleetz`` (JSON
    aggregate), ``/metrics`` (fleet Prometheus re-export), and
    ``/healthz``. ``port=0`` picks a free port."""

    def __init__(self, collector: FleetCollector, port: int = 0,
                 host: str = "127.0.0.1"):
        handler = type("Handler", (_FleetzHandler,),
                       {"collector": collector})
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="zoo-fleetz",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/fleetz"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
