"""Bounded in-process time series — the telemetry plane's storage.

Everything upstream of alerting needs history: a scrape is a point, but
``rate()``, burn rates, and saturation trends are functions of a window.
This module keeps that window in a **fixed-capacity ring buffer per
series** — O(1) append, retention = ``capacity × sample interval``, and
no unbounded growth no matter how long the process lives (the same
bounded-queue discipline zoolint's ZL011 enforces on work queues).

Three layers:

* :class:`RingBuffer` — the storage primitive: a preallocated circular
  array of ``(ts, value)`` points.
* :class:`TimeSeriesStore` — series keyed by the same
  ``name{k="v",...}`` strings :meth:`MetricsRegistry.snapshot` emits,
  each carrying its family kind, with the derived-signal queries:
  :meth:`~TimeSeriesStore.rate` (counter-reset aware),
  :meth:`~TimeSeriesStore.avg`/``max``/``min`` over a window for
  gauges, :meth:`~TimeSeriesStore.slope` (the depth-trend signal the
  autoscaler wants), and :meth:`~TimeSeriesStore.quantile` —
  quantile-over-window by rehydrating each scrape's quantile points
  into a :class:`QuantileDigest` weighted by its count **delta** and
  merging (so the window distribution weights each scrape by the
  traffic it actually saw).
* :class:`RegistrySampler` — a daemon thread snapshotting a local
  :class:`MetricsRegistry` into a store on a cadence
  (``zoo.telemetry.sample_interval_s``).

Samples are scalars for counters/gauges, ``(count, sum)`` pairs for
histograms, and :class:`SummarySample` (cumulative count/sum + the
scrape-time quantile points) for summaries.

``rehydrate_digest`` — the PR-5 fleet-rollup rehydration that turns
scraped ``(quantile, value)`` points back into a mergeable digest —
lives here now (it migrated from ``scripts/cluster-serving-status``,
which imports it back), because quantile-over-window is the same
operation as the fleet quantile merge: weight points by mass, merge.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .metrics import MetricsRegistry, QuantileDigest

log = logging.getLogger("analytics_zoo_tpu.observability")

__all__ = [
    "RingBuffer", "SummarySample", "TimeSeriesStore", "RegistrySampler",
    "rehydrate_digest", "family_of",
]


def _conf(key: str, default):
    """Config read through the zoo context when one is live; the default
    otherwise (context imports jax — keep this module importable
    without it)."""
    try:
        from ..common.context import get_zoo_context
        return get_zoo_context().get(key, default)
    except Exception:
        return default


def family_of(key: str) -> str:
    """The metric family of a series key: ``name{k="v"}`` → ``name``."""
    return key.split("{", 1)[0]


def rehydrate_digest(qs: Dict[str, float], count: float,
                     budget: int = 64) -> QuantileDigest:
    """An approximate :class:`QuantileDigest` from scraped quantile
    points ``{quantile_str: value}``: each (q, v) point carries the
    probability mass between the midpoints of its neighboring
    quantiles, scaled by ``count``. Merging these weights every
    source by its actual traffic — the property a naive percentile
    average lacks. (Migrated from ``scripts/cluster-serving-status``.)
    """
    d = QuantileDigest(budget)
    pts = sorted((float(q), v) for q, v in qs.items() if v == v)
    if not pts or count <= 0:
        return d
    mids = [(pts[i][0] + pts[i + 1][0]) / 2.0 for i in range(len(pts) - 1)]
    bounds = [0.0] + mids + [1.0]
    for (q, v), lo, hi in zip(pts, bounds, bounds[1:]):
        w = (hi - lo) * count
        if w > 0:
            d.add(v, w)
    return d


class RingBuffer:
    """Fixed-capacity circular buffer of ``(ts, value)`` points.

    Preallocated; :meth:`append` is O(1) and overwrites the oldest
    point once full. Not thread-safe on its own — the store serializes
    access under its lock.
    """

    __slots__ = ("_ts", "_vals", "_cap", "_head", "_len")

    def __init__(self, capacity: int):
        if capacity < 2:
            raise ValueError("RingBuffer capacity must be >= 2 "
                             "(rates need two points)")
        self._cap = int(capacity)
        self._ts: List[float] = [0.0] * self._cap
        self._vals: List[Any] = [None] * self._cap
        self._head = 0          # next write slot
        self._len = 0

    def __len__(self) -> int:
        return self._len

    @property
    def capacity(self) -> int:
        return self._cap

    def append(self, ts: float, value: Any) -> None:
        self._ts[self._head] = ts
        self._vals[self._head] = value
        self._head = (self._head + 1) % self._cap
        if self._len < self._cap:
            self._len += 1

    def last(self) -> Optional[Tuple[float, Any]]:
        if not self._len:
            return None
        i = (self._head - 1) % self._cap
        return self._ts[i], self._vals[i]

    def items(self) -> List[Tuple[float, Any]]:
        """Chronological ``[(ts, value), ...]`` (oldest first)."""
        if self._len < self._cap:
            idx = range(self._len)
        else:
            idx = ((self._head + i) % self._cap for i in range(self._cap))
        return [(self._ts[i], self._vals[i]) for i in idx]

    def since(self, t0: float) -> List[Tuple[float, Any]]:
        """Chronological points with ``ts >= t0``."""
        return [(t, v) for t, v in self.items() if t >= t0]


class SummarySample:
    """One scrape of a summary family: cumulative ``count``/``sum`` and
    the scrape-time quantile points ``{quantile_str: value}``."""

    __slots__ = ("count", "sum", "points")

    def __init__(self, count: float, sum: float,
                 points: Dict[str, float]):
        self.count = float(count)
        self.sum = float(sum)
        self.points = dict(points)

    def __repr__(self):
        return (f"SummarySample(count={self.count:g}, sum={self.sum:g}, "
                f"points={self.points})")


#: sentinel kinds a series can carry (mirrors the registry kinds)
_KINDS = ("counter", "gauge", "histogram", "summary")


class TimeSeriesStore:
    """Bounded per-series history plus the derived-signal queries.

    Series keys are the ``name`` / ``name{k="v",...}`` strings
    :meth:`MetricsRegistry.snapshot` emits, so a sampler can feed a
    snapshot straight in; the collector uses the same keys with a
    ``replica=`` label prepended for per-replica series.

    Retention is ``capacity`` points per series; with the default
    cadence (``zoo.telemetry.sample_interval_s``) the defaults hold
    ``zoo.telemetry.retention_s`` of history. The series *map* is
    bounded by the metric catalog (families × bounded label sets), the
    same cardinality discipline ZL015 enforces at registration sites.
    """

    def __init__(self, retention_s: Optional[float] = None,
                 sample_interval_s: Optional[float] = None,
                 capacity: Optional[int] = None):
        interval = float(sample_interval_s if sample_interval_s is not None
                         else _conf("zoo.telemetry.sample_interval_s", 1.0))
        retention = float(retention_s if retention_s is not None
                          else _conf("zoo.telemetry.retention_s", 900.0))
        if capacity is None:
            capacity = max(2, int(round(retention / max(interval, 1e-6))) + 1)
        self.capacity = int(capacity)
        self.sample_interval_s = interval
        self.retention_s = retention
        self._lock = threading.Lock()
        self._series: Dict[str, RingBuffer] = {}
        self._kinds: Dict[str, str] = {}

    # -- ingest --------------------------------------------------------------
    def record(self, key: str, kind: str, ts: float, value: Any) -> None:
        """O(1) append of one point to one series (created on first
        touch)."""
        if kind not in _KINDS:
            raise ValueError(f"unknown series kind {kind!r}")
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                ring = self._series[key] = RingBuffer(self.capacity)
                self._kinds[key] = kind
            ring.append(ts, value)

    def ingest_snapshot(self, snapshot: Dict[str, Any], ts: float) -> int:
        """Feed one :meth:`MetricsRegistry.snapshot` dict in; returns
        the number of series touched."""
        n = 0
        for key, entry in snapshot.items():
            kind = entry.get("type")
            if kind in ("counter", "gauge"):
                self.record(key, kind, ts, float(entry["value"]))
            elif kind == "histogram":
                self.record(key, kind, ts,
                            (float(entry.get("count", 0)),
                             float(entry.get("sum", 0.0))))
            elif kind == "summary":
                self.record(key, kind, ts, SummarySample(
                    entry.get("count", 0), entry.get("sum", 0.0),
                    entry.get("quantiles", {})))
            else:
                continue
            n += 1
        return n

    # -- introspection -------------------------------------------------------
    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def kind(self, key: str) -> Optional[str]:
        with self._lock:
            return self._kinds.get(key)

    def series_for(self, family: str) -> List[str]:
        """Every series key of one family (``name`` or
        ``name{...}``)."""
        with self._lock:
            return sorted(k for k in self._series
                          if family_of(k) == family)

    def latest(self, key: str) -> Optional[Tuple[float, Any]]:
        with self._lock:
            ring = self._series.get(key)
            return ring.last() if ring is not None else None

    def window(self, key: str, window_s: float,
               now: Optional[float] = None) -> List[Tuple[float, Any]]:
        """Chronological points of one series within the last
        ``window_s`` seconds (anchored at ``now`` or the newest
        point)."""
        with self._lock:
            ring = self._series.get(key)
            if ring is None or not len(ring):
                return []
            if now is None:
                now = ring.last()[0]
            return ring.since(now - window_s)

    # -- derived signals -----------------------------------------------------
    def rate(self, key: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second increase of a counter over the window,
        counter-reset aware: negative deltas (a restarted replica)
        contribute the post-reset value instead of going negative —
        the Prometheus ``rate()`` convention. Histogram series rate
        their count. ``None`` until two points span the window."""
        pts = self.window(key, window_s, now)
        if len(pts) < 2:
            return None
        vals = [p[1][0] if isinstance(p[1], tuple) else float(p[1])
                for p in pts]
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        inc = 0.0
        for a, b in zip(vals, vals[1:]):
            inc += (b - a) if b >= a else b     # reset: restart from 0
        return inc / span

    def _gauge_vals(self, key: str, window_s: float,
                    now: Optional[float]) -> List[float]:
        return [float(v) for _, v in self.window(key, window_s, now)
                if isinstance(v, (int, float))]

    def avg(self, key: str, window_s: float,
            now: Optional[float] = None) -> Optional[float]:
        vals = self._gauge_vals(key, window_s, now)
        return sum(vals) / len(vals) if vals else None

    def max(self, key: str, window_s: float,
            now: Optional[float] = None) -> Optional[float]:
        vals = self._gauge_vals(key, window_s, now)
        return max(vals) if vals else None

    def min(self, key: str, window_s: float,
            now: Optional[float] = None) -> Optional[float]:
        vals = self._gauge_vals(key, window_s, now)
        return min(vals) if vals else None

    def slope(self, key: str, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
        """Least-squares slope (units/second) of a gauge over the
        window — the depth/backlog *trend* an autoscaler acts on
        (a positive depth slope under full utilization means falling
        behind; the level alone cannot say that)."""
        pts = [(t, float(v)) for t, v in self.window(key, window_s, now)
               if isinstance(v, (int, float))]
        if len(pts) < 2:
            return None
        t0 = pts[0][0]
        xs = [t - t0 for t, _ in pts]
        ys = [v for _, v in pts]
        n = float(len(pts))
        sx, sy = sum(xs), sum(ys)
        sxx = sum(x * x for x in xs)
        sxy = sum(x * y for x, y in zip(xs, ys))
        denom = n * sxx - sx * sx
        if denom <= 0:
            return None
        return (n * sxy - sx * sy) / denom

    def quantile(self, key: str, q: float, window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
        """Quantile of a summary series **over the window**: each
        consecutive scrape pair contributes the newer scrape's quantile
        points weighted by the count delta between them (the traffic
        that arrived in that interval), rehydrated and merged. Falls
        back to the lifetime distribution of the newest scrape when the
        window saw no traffic. ``None`` with no data at all."""
        pts = [(t, v) for t, v in self.window(key, window_s, now)
               if isinstance(v, SummarySample)]
        if not pts:
            return None
        d = QuantileDigest(64)
        for (_, a), (_, b) in zip(pts, pts[1:]):
            delta = b.count - a.count
            if delta < 0:               # reset: the whole new count
                delta = b.count
            if delta > 0:
                d.merge(rehydrate_digest(b.points, delta))
        if not d.count:                 # no traffic in window: lifetime
            last = pts[-1][1]
            if not last.count:
                return None
            d = rehydrate_digest(last.points, last.count)
        if not d.count:
            return None
        return d.quantile(q)


class RegistrySampler:
    """Daemon thread snapshotting one :class:`MetricsRegistry` into a
    :class:`TimeSeriesStore` on a cadence — the local half of the
    telemetry plane (the collector is the fleet half).

    ``interval_s`` defaults to ``zoo.telemetry.sample_interval_s``;
    ``clock`` is injectable so tests drive deterministic timestamps via
    :meth:`sample_once`.
    """

    def __init__(self, registry: MetricsRegistry,
                 store: Optional[TimeSeriesStore] = None,
                 interval_s: Optional[float] = None,
                 clock=None):
        import time as _time
        self.registry = registry
        self.store = store if store is not None else TimeSeriesStore(
            sample_interval_s=interval_s)
        self.interval_s = float(
            interval_s if interval_s is not None
            else _conf("zoo.telemetry.sample_interval_s", 1.0))
        self._clock = clock if clock is not None else _time.time
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_taken = 0

    def sample_once(self, now: Optional[float] = None) -> int:
        """One synchronous snapshot→store pass; returns series
        touched."""
        ts = self._clock() if now is None else now
        n = self.store.ingest_snapshot(
            self.registry.snapshot(compact=True), ts)
        self.samples_taken += 1
        return n

    def start(self) -> "RegistrySampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="zoo-telemetry-sampler", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:           # never kill the sampler thread
                log.exception("registry sampler tick failed")

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
