"""Declarative alerting over derived telemetry signals.

An alert rule is ``(expr, threshold, for_s)``: ``expr`` is a callable
over a **signals view** (duck-typed — :class:`StoreSignals` for a
local :class:`~.timeseries.TimeSeriesStore`, the fleet collector for
fleet scope) returning the measured value or ``None`` for no-data;
``threshold``/``cmp`` decide breach; ``for_s`` is the hold the breach
must sustain before the alert fires (the Prometheus ``for:``
semantic).

The :class:`AlertEngine` runs a **deterministic state machine** per
rule — inactive → pending → firing → resolved — under an injectable
clock, so tests drive it tick by tick. Every state *entered* bumps
``zoo_alert_transitions_total{alert=,state=}`` (states ``pending``,
``firing``, ``resolved``; a pending that recovers before ``for_s``
goes quietly back to inactive — it never fired, so nothing
"resolves"), the current state is exported as
``zoo_alert_state{alert=}`` (0 inactive, 1 pending, 2 firing), and
firing/resolving emit ``alert.fire`` / ``alert.resolve`` events on the
engine's registry.

SLO burn-rate rules (:func:`burn_rate_rule`,
:func:`quantile_burn_rule`) are **multi-window**: the classic
fast-5m + slow-1h pair, alerting on the *minimum* of the two window
burns — the fast window gives reaction time, the slow window keeps a
brief blip from paging (both must breach). Burn rate is error-budget
consumption speed: ``(bad / total) / (1 - slo)``; burn 1.0 spends the
budget exactly at the SLO boundary, the default threshold 14.4 is the
"2% of a 30-day budget in one hour" page from the SRE workbook.

:func:`default_ruleset` covers the known failure modes: publish
breaker open, DLQ growth, shed rate, replica down, clock skew, fleet
saturation, plus the e2e failure burn rate.

Metric registration goes through the :func:`alert_gauge` /
:func:`alert_counter` helper constructors — zoolint's ZL017 extractor
resolves registrations made through ``*_gauge``/``*_counter`` helpers
to their call sites, so the per-alert families stay on the catalog
reconciliation with the rule name as the label value.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from .metrics import MetricsRegistry, default_registry
from .timeseries import TimeSeriesStore, family_of

__all__ = [
    "AlertRule", "AlertEngine", "StoreSignals",
    "alert_gauge", "alert_counter",
    "burn_rate_rule", "quantile_burn_rule", "default_ruleset",
    "INACTIVE", "PENDING", "FIRING",
]

INACTIVE, PENDING, FIRING = "inactive", "pending", "firing"

#: gauge encoding of the state machine
_STATE_VALUE = {INACTIVE: 0.0, PENDING: 1.0, FIRING: 2.0}

#: fast/slow window pair for the multi-window burn rules (seconds)
FAST_WINDOW_S, SLOW_WINDOW_S = 300.0, 3600.0


def alert_gauge(registry: MetricsRegistry, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None):
    """Register/fetch a gauge for the alert plane (ZL017 resolves the
    caller's name/labels, not this shim)."""
    return registry.gauge(name, help, labels=labels)


def alert_counter(registry: MetricsRegistry, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None):
    """Register/fetch a counter for the alert plane (see
    :func:`alert_gauge`)."""
    return registry.counter(name, help, labels=labels)


class AlertRule:
    """One declarative rule: ``expr(signals) -> Optional[float]``
    measured against ``threshold`` under ``cmp`` (``">"`` or ``"<"``),
    breaching for ``for_s`` seconds before firing. ``None`` from
    ``expr`` means no data — never a breach (rules for which *absence*
    is the failure encode it as a count, e.g. replicas down)."""

    def __init__(self, name: str,
                 expr: Callable[[object], Optional[float]],
                 threshold: float, for_s: float = 0.0,
                 cmp: str = ">", severity: str = "page",
                 summary: str = ""):
        if cmp not in (">", "<"):
            raise ValueError(f"cmp must be '>' or '<', got {cmp!r}")
        self.name = name
        self.expr = expr
        self.threshold = float(threshold)
        self.for_s = float(for_s)
        self.cmp = cmp
        self.severity = severity
        self.summary = summary

    def breached(self, value: Optional[float]) -> bool:
        if value is None or value != value:
            return False
        return value > self.threshold if self.cmp == ">" \
            else value < self.threshold


class AlertEngine:
    """The pending→firing→resolved state machine over a rule set.

    ``clock`` is injectable (defaults to ``time.time``); tests call
    :meth:`evaluate` with explicit ``now`` values for fully
    deterministic transitions. :meth:`evaluate` returns the transition
    records of that tick — ``{"alert", "state", "value", "ts"}`` — the
    same records the transition counter and events reflect, so a test
    can reconcile all three exactly.
    """

    def __init__(self, rules: Sequence[AlertRule],
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.time):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError("duplicate alert rule names")
        self.rules = list(rules)
        self.registry = registry if registry is not None \
            else default_registry()
        self._clock = clock
        self._lock = threading.Lock()
        self._state: Dict[str, str] = {}
        self._since: Dict[str, float] = {}       # pending start ts
        self._value: Dict[str, Optional[float]] = {}
        self._hooks: List[Callable[[dict], None]] = []
        for r in self.rules:
            self._state[r.name] = INACTIVE
            alert_gauge(   # zoolint: disable=ZL015 bounded label set —
                # alert names come from the declared ruleset
                self.registry, "zoo_alert_state",
                "alert state machine: 0 inactive, 1 pending, 2 firing",
                labels={"alert": r.name}).set(0.0)

    def add_transition_hook(self, fn: Callable[[dict], None]) -> None:
        """Register ``fn(transition)`` to run for every transition
        record :meth:`evaluate` produces (e.g.
        :meth:`~.profiler.ProfilerTrigger.on_alert` auto-captures a
        trace when a rule fires). Hooks run after the evaluation lock
        is released; a raising hook is logged-equivalent swallowed —
        it can never wedge the alert plane."""
        self._hooks.append(fn)

    # -- state machine -------------------------------------------------------
    def _enter(self, rule: AlertRule, state: str,
               value: Optional[float], now: float,
               transitions: List[dict]) -> None:
        self._state[rule.name] = state if state != "resolved" \
            else INACTIVE
        alert_gauge(   # zoolint: disable=ZL015 bounded label set —
            # alert names come from the declared ruleset
            self.registry, "zoo_alert_state",
            "alert state machine: 0 inactive, 1 pending, 2 firing",
            labels={"alert": rule.name}).set(
                _STATE_VALUE[self._state[rule.name]])
        alert_counter(   # zoolint: disable=ZL015 bounded label set —
            # alert names from the ruleset; state from a closed set
            self.registry, "zoo_alert_transitions_total",
            "alert state-machine transitions, by state entered",
            labels={"alert": rule.name, "state": state}).inc()
        transitions.append({"alert": rule.name, "state": state,
                            "value": value, "ts": now})
        if state == FIRING:
            self.registry.emit("alert.fire", alert=rule.name,
                               value=value, threshold=rule.threshold,
                               severity=rule.severity,
                               summary=rule.summary)
        elif state == "resolved":
            self.registry.emit("alert.resolve", alert=rule.name,
                               value=value)

    def evaluate(self, signals: object,
                 now: Optional[float] = None) -> List[dict]:
        """One tick: evaluate every rule against ``signals``, advance
        the state machines, return this tick's transition records."""
        now = self._clock() if now is None else now
        transitions: List[dict] = []
        with self._lock:
            for rule in self.rules:
                try:
                    value = rule.expr(signals)
                except Exception:
                    value = None        # a broken expr is no-data
                self._value[rule.name] = value
                breached = rule.breached(value)
                state = self._state[rule.name]
                if state == INACTIVE and breached:
                    if rule.for_s <= 0:
                        self._enter(rule, FIRING, value, now,
                                    transitions)
                    else:
                        self._since[rule.name] = now
                        self._enter(rule, PENDING, value, now,
                                    transitions)
                elif state == PENDING:
                    if not breached:
                        # never fired: back to inactive, no "resolved"
                        self._state[rule.name] = INACTIVE
                        alert_gauge(  # zoolint: disable=ZL015 bounded label set
                            self.registry, "zoo_alert_state",
                            "alert state machine: 0 inactive, "
                            "1 pending, 2 firing",
                            labels={"alert": rule.name}).set(0.0)
                    elif now - self._since[rule.name] >= rule.for_s:
                        self._enter(rule, FIRING, value, now,
                                    transitions)
                elif state == FIRING and not breached:
                    self._enter(rule, "resolved", value, now,
                                transitions)
        for tr in transitions:      # outside the lock: hooks may call
            for hook in self._hooks:            # back into the engine
                try:
                    hook(tr)
                except Exception:   # a hook failure never wedges alerts
                    logging.getLogger(
                        "analytics_zoo_tpu.observability").warning(
                        "alert transition hook failed for %r",
                        tr.get("alert"), exc_info=True)
        return transitions

    # -- introspection -------------------------------------------------------
    def state(self, name: str) -> str:
        with self._lock:
            return self._state[name]

    def value(self, name: str) -> Optional[float]:
        with self._lock:
            return self._value.get(name)

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(n for n, s in self._state.items()
                          if s == FIRING)

    def states(self) -> Dict[str, dict]:
        """``{alert: {"state", "value", "threshold", "severity",
        "summary"}}`` — the ``/fleetz`` alerts block and the CLI
        table."""
        with self._lock:
            return {r.name: {"state": self._state[r.name],
                             "value": self._value.get(r.name),
                             "threshold": r.threshold,
                             "for_s": r.for_s,
                             "severity": r.severity,
                             "summary": r.summary}
                    for r in self.rules}


class StoreSignals:
    """Signals view over one :class:`TimeSeriesStore` — family-level
    queries that sum/max across the family's labeled series. The fleet
    collector layers replica-health methods on top of this shape; any
    object with these methods satisfies a rule expr."""

    def __init__(self, store: TimeSeriesStore,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self._clock = clock

    def _keys(self, family: str,
              labels: Optional[Dict[str, str]] = None) -> List[str]:
        keys = self.store.series_for(family)
        if labels:
            need = [f'{k}="{v}"' for k, v in labels.items()]
            keys = [k for k in keys if all(n in k for n in need)]
        return keys

    def rate(self, family: str, window_s: float,
             labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Summed per-second rate across the family's series."""
        rates = [self.store.rate(k, window_s, now=self._clock())
                 for k in self._keys(family, labels)]
        rates = [r for r in rates if r is not None]
        return sum(rates) if rates else None

    def gauge_sum(self, family: str,
                  labels: Optional[Dict[str, str]] = None
                  ) -> Optional[float]:
        vals = [self.store.latest(k) for k in self._keys(family, labels)]
        vals = [v for _, v in filter(None, vals)
                if isinstance(v, (int, float))]
        return sum(vals) if vals else None

    def gauge_max(self, family: str,
                  labels: Optional[Dict[str, str]] = None
                  ) -> Optional[float]:
        vals = [self.store.latest(k) for k in self._keys(family, labels)]
        vals = [v for _, v in filter(None, vals)
                if isinstance(v, (int, float))]
        return max(vals) if vals else None

    def slope(self, family: str, window_s: float,
              labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Summed least-squares slope across the family's series."""
        slopes = [self.store.slope(k, window_s, now=self._clock())
                  for k in self._keys(family, labels)]
        slopes = [s for s in slopes if s is not None]
        return sum(slopes) if slopes else None

    def quantile(self, family: str, q: float, window_s: float,
                 labels: Optional[Dict[str, str]] = None
                 ) -> Optional[float]:
        """Worst (max) windowed quantile across the family's series —
        conservative for alerting."""
        qs = [self.store.quantile(k, q, window_s, now=self._clock())
              for k in self._keys(family, labels)]
        qs = [v for v in qs if v is not None]
        return max(qs) if qs else None

    # replica-health hooks the fleet collector overrides; a local store
    # has no fleet, so these read as no-data
    def replicas_down(self) -> Optional[float]:
        return None

    def replicas_live(self) -> Optional[float]:
        return None

    def saturated_fraction(self) -> Optional[float]:
        return None


# -- rule constructors -------------------------------------------------------

def burn_rate_rule(name: str, bad_family: str, good_family: str,
                   slo: float = 0.99, threshold: float = 14.4,
                   fast_s: float = FAST_WINDOW_S,
                   slow_s: float = SLOW_WINDOW_S,
                   for_s: float = 0.0,
                   severity: str = "page") -> AlertRule:
    """Multi-window failure-ratio burn rate: over each window the
    failure ratio is ``bad / (bad + good)`` (rates of the two counter
    families), burn is ``ratio / (1 - slo)``, and the rule's value is
    ``min(burn_fast, burn_slow)`` — both windows must breach."""
    budget = max(1.0 - float(slo), 1e-9)

    def expr(s) -> Optional[float]:
        burns = []
        for window in (fast_s, slow_s):
            bad = s.rate(bad_family, window)
            good = s.rate(good_family, window)
            if bad is None and good is None:
                return None
            bad = bad or 0.0
            good = good or 0.0
            total = bad + good
            ratio = (bad / total) if total > 0 else 0.0
            burns.append(ratio / budget)
        return min(burns)

    return AlertRule(
        name, expr, threshold=threshold, for_s=for_s,
        severity=severity,
        summary=f"error-budget burn (slo={slo:g}) over "
                f"{fast_s:g}s and {slow_s:g}s windows")


def quantile_burn_rule(name: str, family: str, q: float,
                       target_s: float,
                       fast_s: float = FAST_WINDOW_S,
                       slow_s: float = SLOW_WINDOW_S,
                       for_s: float = 0.0,
                       severity: str = "page") -> AlertRule:
    """Multi-window latency-SLO burn over a quantile summary family:
    value is ``min(q_fast, q_slow) / target_s`` — fires past 1.0 only
    when BOTH windows' quantile sits above the target."""

    def expr(s) -> Optional[float]:
        vals = []
        for window in (fast_s, slow_s):
            v = s.quantile(family, q, window)
            if v is None:
                return None
            vals.append(v)
        return min(vals) / float(target_s)

    return AlertRule(
        name, expr, threshold=1.0, for_s=for_s, severity=severity,
        summary=f"p{q * 100:g} of {family} vs {target_s:g}s target, "
                f"both windows")


def _hbm_in_use_fraction(s) -> Optional[float]:
    """in_use / limit over the ``zoo_device_hbm_bytes`` gauge family
    (PR 17's device-memory telemetry); no-data until both kinds have
    been sampled, and a zero limit (CPU hosts) reads as no-data too."""
    used = s.gauge_sum("zoo_device_hbm_bytes", labels={"kind": "in_use"})
    limit = s.gauge_sum("zoo_device_hbm_bytes", labels={"kind": "limit"})
    if used is None or not limit:
        return None
    return used / limit


def default_ruleset(for_s: float = 30.0,
                    shed_rate_threshold: float = 0.0,
                    replica_down_for_s: float = 10.0) -> List[AlertRule]:
    """The known-failure-mode rules (docs/guides/OBSERVABILITY.md
    "Default ruleset" table stays in lockstep with this list)."""
    return [
        AlertRule(
            "publish_breaker_open",
            lambda s: s.gauge_max("zoo_breaker_state",
                                  labels={"breaker": "serving.publish"}),
            threshold=0.5, for_s=0.0, severity="page",
            summary="result-publish circuit not closed on >=1 replica"),
        AlertRule(
            "dlq_growth",
            lambda s: s.rate("zoo_serving_dlq_spilled_total",
                             FAST_WINDOW_S),
            threshold=0.0, for_s=for_s, severity="warn",
            summary="records spilling to the dead-letter queue"),
        AlertRule(
            "shed_rate",
            lambda s: s.rate("zoo_serving_shed_total", FAST_WINDOW_S),
            threshold=shed_rate_threshold, for_s=for_s,
            severity="warn",
            summary="admission control shedding records"),
        AlertRule(
            "replica_down",
            lambda s: s.replicas_down(),
            threshold=0.5, for_s=replica_down_for_s, severity="page",
            summary="collector cannot scrape >=1 fleet replica"),
        AlertRule(
            "clock_skew",
            lambda s: s.rate("zoo_serving_clock_skew_total",
                             FAST_WINDOW_S),
            threshold=0.0, for_s=for_s, severity="warn",
            summary="client clocks running ahead of the server"),
        AlertRule(
            "fleet_saturated",
            lambda s: s.saturated_fraction(),
            threshold=0.99, for_s=for_s, severity="page",
            summary="every live replica reports saturated"),
        AlertRule(
            "hbm_high_watermark",
            _hbm_in_use_fraction,
            threshold=0.92, for_s=for_s, severity="page",
            summary="device HBM in_use above 92% of limit — next "
                    "compile or batch-size step likely OOMs"),
        burn_rate_rule(
            "e2e_burn_rate", "zoo_serving_failure_errors_total",
            "zoo_serving_records_total", slo=0.99, for_s=for_s),
    ]
