"""Metric primitives + the process-wide registry.

The reference stack's only runtime numbers are two TensorBoard scalars and
ad-hoc scoped timers (SURVEY §5); this module is the single place the
serving loop, ``InferenceModel``, and ``KerasNet.fit`` report what they are
doing. Three primitives, Prometheus-shaped:

* :class:`Counter` — monotonically increasing (records served, failures),
* :class:`Gauge`   — last-write-wins level (stream depth, records/sec),
* :class:`Histogram` — log-bucketed distribution (latencies, batch sizes),
* :class:`Summary` — accurate p50/p95/p99 from a mergeable fixed-budget
  quantile digest (per-request latencies, where the histogram's ~26%
  octave resolution is too coarse for an SLO).

Design constraints, in order:

1. **Hot-path cheap.** One ``Histogram.observe`` is a ``math.frexp`` plus
   three adds under a lock — no string formatting, no allocation, no
   timestamping. The serving loop calls a handful of these per *batch*
   (not per record), so instrumentation cost is noise even at queue rates.
2. **Process-wide.** :func:`default_registry` is the shared registry every
   instrumented layer writes to by default; components accept a
   ``registry=`` override so tests can reconcile counts in isolation.
3. **Exportable.** The registry renders to Prometheus text exposition and
   snapshots to plain dicts (``export.py``); event-style records (spans,
   per-batch serving events) fan out to attached sinks via :meth:`emit`.

Log bucketing: bucket upper bounds are powers of two spanning
``2**_EXP_LO .. 2**_EXP_HI`` (≈1e-8 s to ≈1.7e7), one bucket per octave —
~26% relative resolution over 15 decades for 51 buckets, enough to tell a
50 µs dispatch from a 5 ms one without per-metric bucket tuning.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

log = logging.getLogger("analytics_zoo_tpu.observability")

__all__ = ["Counter", "Gauge", "Histogram", "QuantileDigest", "Summary",
           "MetricsRegistry", "default_registry", "reset_default_registry"]

LabelsT = Tuple[Tuple[str, str], ...]


def _label_tuple(labels: Optional[Dict[str, str]]) -> LabelsT:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


class _Metric:
    """Common identity: a family ``name`` plus an optional fixed label set
    (labels are bound at creation — there is no per-observation label
    lookup on the hot path)."""

    kind = ""

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels: LabelsT = _label_tuple(labels)
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonic counter. ``inc`` only — a counter that can go down is a
    gauge, and Prometheus ``rate()`` depends on the distinction."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Metric):
    """Last-write-wins level."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


# bucket upper bounds are 2**e for e in [_EXP_LO, _EXP_HI]: 2**-27 ≈ 7.5e-9
# (sub-tick durations land in the first bucket) up to 2**24 ≈ 1.7e7
# (records/sec, byte counts); values outside clamp to the edge buckets
_EXP_LO, _EXP_HI = -27, 24


class Histogram(_Metric):
    """Log-bucketed histogram: fixed power-of-two bucket edges, cumulative
    exposition. ``observe(v, n=k)`` records ``k`` observations of ``v`` in
    one call — how the training loop reports a fused dispatch of ``k``
    identical-duration steps without ``k`` lock round-trips."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        super().__init__(name, help, labels)
        self._counts = [0] * (_EXP_HI - _EXP_LO + 1)
        self._count = 0
        self._sum = 0.0

    @staticmethod
    def _bucket_index(v: float) -> int:
        if v <= 0 or v != v:            # zeros/negatives/NaN: first bucket
            return 0
        m, e = math.frexp(v)            # v = m * 2**e, 0.5 <= m < 1
        if m == 0.5:                    # exact powers of two sit ON an edge
            e -= 1
        return min(max(e - _EXP_LO, 0), _EXP_HI - _EXP_LO)

    def observe(self, v: float, n: int = 1) -> None:
        i = self._bucket_index(v)
        with self._lock:
            self._counts[i] += n
            self._count += n
            self._sum += float(v) * n

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def stats(self) -> Tuple[List[Tuple[float, int]], int, float]:
        """``(cumulative_buckets, count, sum)`` from ONE locked snapshot —
        exporters must use this so a concurrent ``observe`` can never
        produce an exposition where the ``+Inf`` bucket != ``_count``
        (the Prometheus histogram invariant). Buckets are
        ``(upper_bound, cumulative_count)`` pairs ending with ``(inf,
        count)``; zero-count leading/trailing buckets are trimmed (the
        full 52-edge ladder would dominate the exposition)."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        nz = [i for i, c in enumerate(counts) if c]
        out: List[Tuple[float, int]] = []
        if nz:
            lo, hi = nz[0], nz[-1]
            acc = 0
            for i in range(lo, hi + 1):
                acc += counts[i]
                out.append((2.0 ** (i + _EXP_LO), acc))
        out.append((math.inf, total))
        return out, total, s

    def cumulative(self) -> List[Tuple[float, int]]:
        """The bucket series alone (see :meth:`stats`)."""
        return self.stats()[0]


class QuantileDigest:
    """Mergeable fixed-budget quantile sketch (merging t-digest style).

    Centroids are ``(mean, weight)`` pairs; incoming observations buffer
    and are folded in by a size-bounded merge pass whose per-centroid
    weight limit scales with ``q * (1 - q)`` — tails keep near-singleton
    centroids (accurate p99), the middle compresses aggressively. The
    whole structure stays ~``budget`` centroids regardless of how many
    observations it has absorbed, and two digests :meth:`merge` into one
    with the same bound — the property that lets per-replica digests roll
    up into a fleet-wide percentile without storing raw samples.

    NOT thread-safe on its own; :class:`Summary` wraps it under the
    metric lock. An ``observe`` between compressions is one list append.
    """

    __slots__ = ("budget", "_centroids", "_buf", "_count", "_sum")

    def __init__(self, budget: int = 128):
        if budget < 8:
            raise ValueError(f"digest budget too small ({budget}); "
                             "quantiles would be meaningless")
        self.budget = int(budget)
        self._centroids: List[Tuple[float, float]] = []   # sorted by mean
        self._buf: List[Tuple[float, float]] = []
        self._count = 0
        self._sum = 0.0

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def add(self, v: float, n: int = 1) -> None:
        v = float(v)
        if v != v:                       # NaN would poison every centroid
            return
        self._buf.append((v, float(n)))
        self._count += n
        self._sum += v * n
        if len(self._buf) >= self.budget:
            self._compress()

    def merge(self, other: "QuantileDigest") -> None:
        """Fold ``other``'s mass into this digest (other is unchanged)."""
        self._buf.extend(other._centroids)
        self._buf.extend(other._buf)
        self._count += other._count
        self._sum += other._sum
        self._compress()

    def _compress(self) -> None:
        pts = sorted(self._centroids + self._buf)
        self._buf = []
        if not pts:
            return
        total = sum(w for _, w in pts)
        out: List[Tuple[float, float]] = []
        cur_mean, cur_w = pts[0]
        cum = 0.0                        # weight fully merged before `cur`
        for mean, w in pts[1:]:
            q = (cum + cur_w + w / 2.0) / total
            # t-digest k1-style bound: centroid capacity peaks at the
            # median, pinches to ~1 at the tails
            limit = max(4.0 * total * q * (1.0 - q) / self.budget, 1.0)
            if cur_w + w <= limit:
                cur_mean += (mean - cur_mean) * (w / (cur_w + w))
                cur_w += w
            else:
                out.append((cur_mean, cur_w))
                cum += cur_w
                cur_mean, cur_w = mean, w
        out.append((cur_mean, cur_w))
        self._centroids = out

    def quantile(self, q: float) -> float:
        """Estimated value at quantile ``q`` in [0, 1]; NaN when empty.
        Monotone in ``q`` (centroid means are sorted), so p99 >= p50 by
        construction."""
        if self._buf:
            self._compress()
        cs = self._centroids
        if not cs:
            return float("nan")
        q = min(max(float(q), 0.0), 1.0)
        target = q * self._count
        cum = 0.0
        prev_mid: Optional[float] = None
        prev_mean = cs[0][0]
        for mean, w in cs:
            mid = cum + w / 2.0
            if target < mid:
                if prev_mid is None or mid == prev_mid:
                    return mean
                frac = (target - prev_mid) / (mid - prev_mid)
                return prev_mean + frac * (mean - prev_mean)
            prev_mid, prev_mean = mid, mean
            cum += w
        return cs[-1][0]


class Summary(_Metric):
    """Prometheus summary: accurate client-side quantiles over a
    :class:`QuantileDigest`, exposed as ``name{quantile="0.5"}`` series
    plus ``_sum``/``_count``. Complements :class:`Histogram` (which keeps
    the full shape but only ~26% relative resolution): the summary
    answers "what IS p99" exactly enough to hold an SLO against."""

    kind = "summary"

    DEFAULT_QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None,
                 quantiles: Iterable[float] = DEFAULT_QUANTILES,
                 budget: int = 128):
        super().__init__(name, help, labels)
        self.quantiles: Tuple[float, ...] = tuple(sorted(quantiles))
        self._digest = QuantileDigest(budget)

    def observe(self, v: float, n: int = 1) -> None:
        with self._lock:
            self._digest.add(v, n)

    @property
    def count(self) -> int:
        return self._digest.count

    @property
    def sum(self) -> float:
        return self._digest.sum

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._digest.quantile(q)

    def merge_from(self, other: "Summary") -> None:
        """Absorb another summary's digest (fleet roll-up)."""
        with other._lock:
            snap = QuantileDigest(other._digest.budget)
            snap.merge(other._digest)
        with self._lock:
            self._digest.merge(snap)

    def stats(self) -> Tuple[List[Tuple[float, float]], int, float]:
        """``([(q, value), ...], count, sum)`` from ONE locked pass, so a
        concurrent ``observe`` can never yield a scrape where p99 < p50."""
        with self._lock:
            return ([(q, self._digest.quantile(q)) for q in self.quantiles],
                    self._digest.count, self._digest.sum)


_METRIC_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram,
                 "summary": Summary}


class MetricsRegistry:
    """Name→metric map with get-or-create semantics and attached event
    sinks. All methods are thread-safe; metric objects are cached by the
    instrumented layers, so steady-state hot paths never touch the
    registry lock."""

    def __init__(self):
        self._metrics: Dict[Tuple[str, LabelsT], _Metric] = {}
        self._lock = threading.Lock()
        self._sinks: List[Any] = []
        self._broken_sinks: set = set()

    # -- get-or-create -------------------------------------------------------
    def _get(self, kind: str, name: str, help: str,
             labels: Optional[Dict[str, str]]) -> _Metric:
        key = (name, _label_tuple(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = _METRIC_TYPES[kind](name, help, labels)
                self._metrics[key] = m
            elif m.kind != kind:
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested as {kind}")
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        return self._get("histogram", name, help, labels)

    def summary(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Summary:
        """Quantile summary (p50/p95/p99 by default). Quantile set and
        digest budget are fixed at first creation — the family must
        expose one consistent series set."""
        return self._get("summary", name, help, labels)

    def metrics(self) -> List[_Metric]:
        """All metrics, sorted by (name, labels) — the exposition order."""
        with self._lock:
            return [m for _, m in sorted(self._metrics.items())]

    # -- snapshot ------------------------------------------------------------
    def snapshot(self, compact: bool = False) -> Dict[str, Any]:
        """Plain-dict view. Keys are ``name`` or ``name{k="v",...}``.
        ``compact=True`` drops histogram buckets (count/sum/mean only) —
        the form ``bench.py`` embeds in each BENCH record."""
        out: Dict[str, Any] = {}
        for m in self.metrics():
            key = m.name
            if m.labels:
                key += "{" + ",".join(f'{k}="{v}"' for k, v in m.labels) + "}"
            if isinstance(m, Histogram):
                buckets, count, total = m.stats()
                entry: Dict[str, Any] = {"type": m.kind, "count": count,
                                         "sum": total}
                if compact:
                    entry["mean"] = total / count if count else 0.0
                else:
                    entry["buckets"] = [[le, c] for le, c in buckets]
                out[key] = entry
            elif isinstance(m, Summary):
                # quantiles survive BOTH forms — the compact snapshot is
                # what bench.py embeds, and p50/p95/p99 are its point.
                # NaNs (empty digest) are dropped: json.dumps would emit
                # bare `NaN`, which strict JSON parsers reject
                qs, count, total = m.stats()
                out[key] = {"type": m.kind, "count": count, "sum": total,
                            "quantiles": {repr(q): v for q, v in qs
                                          if v == v}}
            else:
                out[key] = {"type": m.kind, "value": m.value}
        return out

    # -- event sinks ---------------------------------------------------------
    def add_event_sink(self, sink) -> None:
        """Attach a sink (anything with ``write(event: dict)``) that
        receives every :meth:`emit` — the JSON event log channel."""
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def remove_event_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def emit(self, kind: str, **fields) -> None:
        """Fan an event record out to the attached sinks. Near-free with
        no sinks attached (one attribute read + truth test). Sink write
        failures (disk full, closed file) are logged and swallowed — an
        event-log I/O error must never kill the instrumented thread
        (e.g. the serve loop) or fail the operation being measured."""
        sinks = self._sinks
        if not sinks:
            return
        event = {"ts": time.time(), "kind": kind, **fields}
        for sink in list(sinks):
            try:
                sink.write(event)
            except Exception:
                if id(sink) not in self._broken_sinks:   # warn once per sink
                    self._broken_sinks.add(id(sink))
                    log.exception("event sink %r failed; further errors "
                                  "from it are suppressed", sink)


_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide registry the instrumented layers share — one
    scrape endpoint sees serving, inference, and training together."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT


def reset_default_registry() -> None:
    """Drop the process-wide registry (tests: counter isolation between
    cases). Metric objects cached by live components keep working; they
    just stop being visible to new scrapes."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
