"""Unified metrics + tracing for the analytics_zoo_tpu stack.

One process-wide :class:`MetricsRegistry` (counters, gauges, log-bucketed
histograms, quantile summaries — cheap enough for the serving hot path)
plus span-based tracing, per-request trace ids, and jit compile
accounting, with three export sinks:

* Prometheus text exposition — ``render_prometheus()`` / the
  :class:`ScrapeServer` endpoint ``ClusterServing.serve_metrics()``
  mounts (``/metrics`` plus ``/healthz`` and ``/statusz``),
* structured JSON event records — :class:`JsonEventSink` (one JSON object
  per line; spans, per-batch serving events, per-request phase events,
  jit compile/retrace events, error records),
* TensorBoard event files — :class:`TensorBoardSink` over the existing
  ``utils.tensorboard.EventFileWriter`` (the reference's only channel
  keeps working unchanged).

On top of the point-in-time registry sits the **fleet telemetry
plane** (docs/guides/OBSERVABILITY.md "Fleet telemetry & alerting"):
bounded ring-buffer time series with windowed ``rate``/``avg``/
``slope``/quantile queries (``timeseries``), the continuous fleet
collector + ``/fleetz`` aggregate endpoint (``collector``), the
declarative burn-rate alert engine (``alerts``), and device HBM
telemetry (``device``). The **performance-attribution layer**
(docs/guides/OBSERVABILITY.md "Goodput & performance attribution")
closes the loop from "what is happening" to "what it costs":
goodput/badput wall-clock accounting per training run / serving
replica (``goodput``) and alert-triggered bounded ``jax.profiler``
captures (``profiler``).

Instrumented layers: ``serving/server.py`` (stream depth, batch size,
queue-wait/dispatch/e2e latency histograms + p50/p95/p99 summaries,
error + clock-skew counters, per-request enqueue→dequeue→dispatch→publish
trace events), ``pipeline/inference/inference_model.py`` (replica-permit
wait, per-batch device time), and ``pipeline/api/keras/training.py``
``fit``/``evaluate``/``predict`` (weighted step-time histograms,
records/sec, achieved MFU). Every hot-path jit entry point is staged
through :func:`instrument_jit`, which counts compilations and emits
``jit.retrace`` events on recompiles under new signatures. ``bench.py``
snapshots the registry into each BENCH record. Catalog + conventions:
``docs/guides/OBSERVABILITY.md``.

>>> from analytics_zoo_tpu import observability as obs
>>> with obs.span("my.phase"):
...     work()
>>> print(obs.render_prometheus())
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      QuantileDigest, Summary, default_registry,
                      reset_default_registry)
from .tracing import current_span, new_trace_id, span
from .compile import instrument_jit
from .export import (JsonEventSink, ScrapeServer, TensorBoardSink, dump,
                     parse_prometheus, read_events, render_prometheus)
from .timeseries import (RegistrySampler, RingBuffer, SummarySample,
                         TimeSeriesStore, rehydrate_digest)
from .device import (DeviceMemorySampler, device_memory_stats,
                     sample_device_memory)
from .alerts import (AlertEngine, AlertRule, StoreSignals,
                     burn_rate_rule, default_ruleset,
                     quantile_burn_rule)
from .collector import (FleetCollector, FleetSignals, FleetzServer,
                        base_url, endpoint_rows, fleet_rows,
                        summary_points)
from .goodput import (GOOD_CATEGORY, SERVE_CATEGORIES, TRAIN_CATEGORIES,
                      GoodputLedger, goodput_enabled)
from .goodput import registry_snapshot as goodput_snapshot
from .profiler import ProfilerTrigger

__all__ = [
    "Counter", "Gauge", "Histogram", "QuantileDigest", "Summary",
    "MetricsRegistry", "default_registry", "reset_default_registry",
    "span", "current_span", "new_trace_id", "instrument_jit",
    "JsonEventSink", "ScrapeServer", "TensorBoardSink",
    "dump", "parse_prometheus", "read_events", "render_prometheus",
    "RingBuffer", "SummarySample", "TimeSeriesStore", "RegistrySampler",
    "rehydrate_digest",
    "DeviceMemorySampler", "device_memory_stats", "sample_device_memory",
    "AlertEngine", "AlertRule", "StoreSignals", "burn_rate_rule",
    "quantile_burn_rule", "default_ruleset",
    "FleetCollector", "FleetSignals", "FleetzServer",
    "summary_points", "fleet_rows", "endpoint_rows", "base_url",
    "GoodputLedger", "GOOD_CATEGORY", "TRAIN_CATEGORIES",
    "SERVE_CATEGORIES", "goodput_enabled", "goodput_snapshot",
    "ProfilerTrigger",
]
