"""Unified metrics + tracing for the analytics_zoo_tpu stack.

One process-wide :class:`MetricsRegistry` (counters, gauges, log-bucketed
histograms — cheap enough for the serving hot path) plus span-based
tracing, with three export sinks:

* Prometheus text exposition — ``render_prometheus()`` / the
  :class:`ScrapeServer` endpoint ``ClusterServing.serve_metrics()`` mounts,
* structured JSON event records — :class:`JsonEventSink` (one JSON object
  per line; spans, per-batch serving events, error records),
* TensorBoard event files — :class:`TensorBoardSink` over the existing
  ``utils.tensorboard.EventFileWriter`` (the reference's only channel
  keeps working unchanged).

Instrumented layers: ``serving/server.py`` (stream depth, batch size,
queue-wait and dispatch latency, error counters), ``pipeline/inference/
inference_model.py`` (replica-permit wait, per-batch device time), and
``pipeline/api/keras/training.py`` ``fit`` (step-time histogram,
records/sec, achieved MFU). ``bench.py`` snapshots the registry into each
BENCH record. Catalog + conventions: ``docs/guides/OBSERVABILITY.md``.

>>> from analytics_zoo_tpu import observability as obs
>>> with obs.span("my.phase"):
...     work()
>>> print(obs.render_prometheus())
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      default_registry, reset_default_registry)
from .tracing import current_span, span
from .export import (JsonEventSink, ScrapeServer, TensorBoardSink, dump,
                     parse_prometheus, read_events, render_prometheus)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "default_registry", "reset_default_registry",
    "span", "current_span",
    "JsonEventSink", "ScrapeServer", "TensorBoardSink",
    "dump", "parse_prometheus", "read_events", "render_prometheus",
]
