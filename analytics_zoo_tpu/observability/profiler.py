"""Alert-triggered profiler capture — from "alert fired" to "trace in
hand" without a human re-running under ``set_profile``.

A :class:`ProfilerTrigger` arms a **bounded** ``jax.profiler`` capture:
at most one in flight, each capture stopped after ``duration_s``
seconds (a daemon timer) or ``steps`` step notifications (whichever
bound is configured), trace directories retention-capped to the newest
``keep``. Three ways to fire it:

* **alert** — register :meth:`on_alert` with
  :meth:`AlertEngine.add_transition_hook`; any rule entering ``firing``
  arms a capture, so the evidence for a step-time slope or e2e
  burn-rate page is on disk before anyone opens a terminal,
* **http** — ``POST /profilez`` on a :class:`~.export.ScrapeServer`
  built with ``profiler=``,
* **manual** — call :meth:`arm` from code or a debugger.

Failure is not an option we pass on: capture start runs under the
``profiler.capture`` fault site and every exception (missing
``jax.profiler``, unwritable trace dir, injected chaos) degrades to a
counter bump + ``profile.capture`` event — a profiler problem must
never kill the serve/fit loop that hosts it. Exported families:
``zoo_profile_captures_total{trigger=}``,
``zoo_profile_capture_failures_total``.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Callable, Dict, Optional

from ..common import faults
from .metrics import MetricsRegistry, default_registry

__all__ = ["ProfilerTrigger"]

log = logging.getLogger(__name__)

#: recognized arm() sources; anything else is folded into "manual"
TRIGGERS = ("alert", "http", "manual")


def _conf(key: str, default):
    """Config read through the zoo context when one is live; the default
    otherwise (keeps this module importable without jax)."""
    try:
        from ..common.context import get_zoo_context
        return get_zoo_context().get(key, default)
    except Exception:
        return default


def _default_start(trace_dir: str) -> None:
    from jax import profiler as jax_profiler
    jax_profiler.start_trace(trace_dir)


def _default_stop() -> None:
    from jax import profiler as jax_profiler
    jax_profiler.stop_trace()


class ProfilerTrigger:
    """Arms bounded, retention-capped ``jax.profiler`` captures.

    ``start_fn(trace_dir)`` / ``stop_fn()`` default to
    ``jax.profiler.start_trace`` / ``stop_trace`` and are injectable so
    tests (and non-jax hosts) run the full lifecycle without a real
    profiler. All public methods are safe to call from alert-evaluation
    or HTTP threads; the lock is never held across ``start_fn`` /
    ``stop_fn`` re-entry hazards because both are invoked with it held
    only briefly and are themselves non-reentrant by the in-flight
    guard.
    """

    def __init__(self, trace_dir: Optional[str] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 keep: Optional[int] = None,
                 duration_s: Optional[float] = None,
                 steps: Optional[int] = None,
                 start_fn: Optional[Callable[[str], None]] = None,
                 stop_fn: Optional[Callable[[], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.trace_dir = str(trace_dir if trace_dir is not None
                             else _conf("zoo.profiler.dir", "")) \
            or os.path.join(os.getcwd(), "zoo-profiles")
        self.keep = int(keep if keep is not None
                        else _conf("zoo.profiler.keep", 3))
        self.duration_s = float(duration_s if duration_s is not None
                                else _conf("zoo.profiler.duration_s", 10.0))
        self.steps = int(steps if steps is not None
                         else _conf("zoo.profiler.steps", 0))
        self.registry = registry if registry is not None \
            else default_registry()
        self._start_fn = start_fn or _default_start
        self._stop_fn = stop_fn or _default_stop
        self._clock = clock
        self._lock = threading.Lock()
        self._active: Optional[Dict[str, object]] = None
        self._timer: Optional[threading.Timer] = None
        self._seq = 0
        self._m_captures = {}
        for trig in ("alert", "http", "manual"):
            self._m_captures[trig] = self.registry.counter(
                "zoo_profile_captures_total",
                "profiler captures successfully started, by what armed "
                "them (ProfilerTrigger)",
                labels={"trigger": trig})
        self._m_failures = self.registry.counter(
            "zoo_profile_capture_failures_total",
            "capture starts that failed (profiler unavailable, trace dir "
            "unwritable, injected fault) — always degrades gracefully, "
            "never raises into the host loop")

    # -- lifecycle -----------------------------------------------------------
    def arm(self, trigger: str = "manual", reason: str = "") -> Optional[str]:
        """Start a bounded capture; returns its trace directory, or
        ``None`` when one is already in flight or the start failed.
        Never raises."""
        trig = trigger if trigger in TRIGGERS else "manual"
        with self._lock:
            if self._active is not None:
                self.registry.emit("profile.capture", phase="skipped",
                                   trigger=trig, reason="in_flight")
                return None
            self._seq += 1
            cap_dir = os.path.join(
                self.trace_dir, f"capture-{self._seq:04d}-{trig}")
            try:
                faults.inject("profiler.capture")
                os.makedirs(cap_dir, exist_ok=True)
                self._start_fn(cap_dir)
            except Exception as exc:
                self._m_failures.inc()
                self.registry.emit("profile.capture", phase="failed",
                                   trigger=trig, dir=cap_dir,
                                   error=f"{type(exc).__name__}: {exc}")
                log.warning("profiler capture start failed (%s): %s",
                            trig, exc)
                return None
            self._active = {"dir": cap_dir, "trigger": trig,
                            "t0": self._clock(), "steps_left": self.steps}
            self._m_captures[trig].inc()
            self.registry.emit("profile.capture", phase="start",
                               trigger=trig, dir=cap_dir, reason=reason,
                               duration_s=self.duration_s,
                               steps=self.steps)
            if self.steps <= 0 and self.duration_s > 0:
                self._timer = threading.Timer(self.duration_s, self.stop)
                self._timer.daemon = True
                self._timer.start()
        self._evict()
        return cap_dir

    def step(self) -> None:
        """Step notification from the hosting loop; stops a
        step-bounded capture once its budget is spent. No-op (one lock
        probe) otherwise."""
        with self._lock:
            act = self._active
            if act is None or act["steps_left"] <= 0:
                return
            act["steps_left"] -= 1
            if act["steps_left"] > 0:
                return
        self.stop()

    def stop(self) -> Optional[str]:
        """Stop the in-flight capture (idempotent); returns its trace
        directory, or ``None`` if nothing was running. Never raises."""
        with self._lock:
            act, self._active = self._active, None
            timer, self._timer = self._timer, None
        if act is None:
            return None
        if timer is not None:
            timer.cancel()
        try:
            self._stop_fn()
        except Exception as exc:
            log.warning("profiler capture stop failed: %s", exc)
        self.registry.emit("profile.capture", phase="stop",
                           trigger=act["trigger"], dir=act["dir"],
                           duration_s=round(self._clock() - act["t0"], 6))
        return act["dir"]

    def close(self) -> None:
        self.stop()

    # -- integration ---------------------------------------------------------
    def on_alert(self, transition: Dict[str, object]) -> None:
        """``AlertEngine.add_transition_hook`` target: a rule entering
        ``firing`` arms an alert-triggered capture."""
        if transition.get("state") == "firing":
            self.arm(trigger="alert",
                     reason=str(transition.get("alert", "")))

    def in_flight(self) -> Optional[Dict[str, object]]:
        """``{"dir", "trigger", "age_s"}`` of the active capture, else
        ``None`` — the ``/statusz`` ``performance`` block's view."""
        with self._lock:
            act = self._active
            if act is None:
                return None
            return {"dir": act["dir"], "trigger": act["trigger"],
                    "age_s": round(self._clock() - act["t0"], 6)}

    # -- retention -----------------------------------------------------------
    def _evict(self) -> None:
        """Keep only the newest ``keep`` capture dirs (by sequence name,
        which is creation order); never evicts the active capture."""
        if self.keep <= 0:
            return
        try:
            names = sorted(n for n in os.listdir(self.trace_dir)
                           if n.startswith("capture-"))
        except OSError:
            return
        with self._lock:
            active = self._active["dir"] if self._active else None
        for name in names[:-self.keep] if len(names) > self.keep else []:
            path = os.path.join(self.trace_dir, name)
            if path == active:
                continue
            shutil.rmtree(path, ignore_errors=True)
            self.registry.emit("profile.capture", phase="evicted",
                               dir=path)
