from .common import (ArrayToTensor, ChainedPreprocessing,  # noqa: F401
                     FeatureLabelPreprocessing, FnPreprocessing, Normalize,
                     Preprocessing, ScalarToTensor, SeqToTensor)
from .feature_set import (DiskFeatureSet, FeatureSet,  # noqa: F401
                          prefetch_to_device)
