from .common import (ArrayToTensor, ChainedPreprocessing,  # noqa: F401
                     FeatureLabelPreprocessing, FnPreprocessing, Normalize,
                     Preprocessing, ScalarToTensor, SeqToTensor)
from .feature_set import (BucketedFeatureSet, DiskFeatureSet,  # noqa: F401
                          FeatureSet, prefetch_to_device)
