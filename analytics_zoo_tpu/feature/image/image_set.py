"""ImageSet — parity with ``feature/image/ImageSet.scala:46-260``
(Local/Distributed image collections + ``ImageSet.read``), re-designed as a
host-side numpy collection feeding the device infeed.

The reference's ``DistributedImageSet`` is an RDD of ``ImageFeature``; here
one process holds its shard of images (multi-host: each host reads its own
file shard), and ``to_feature_set`` hands a dense batch to the training
``FeatureSet`` pipeline with its background prefetch.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..common import Preprocessing
from ..feature_set import FeatureSet
from .transforms import ImageSetToSample

__all__ = ["ImageSet", "LocalImageSet"]

_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


class ImageSet:
    """A collection of images (ragged list of HWC uint8 arrays or one dense
    NHWC array) with optional integer labels."""

    def __init__(self, images: Union[np.ndarray, List[np.ndarray]],
                 labels: Optional[np.ndarray] = None,
                 label_map: Optional[Dict[str, int]] = None,
                 paths: Optional[List[str]] = None):
        self.images = images
        self.labels = None if labels is None else np.asarray(labels)
        self.label_map = label_map
        self.paths = paths  # origin files, kept for NNImageReader tables

    # ---- factories (ImageSet.scala:236 read) ------------------------------
    @staticmethod
    def read(path: str, with_label: bool = False,
             resize_h: Optional[int] = None, resize_w: Optional[int] = None,
             ) -> "ImageSet":
        """Read a file, a directory of images, or (``with_label=True``) a
        directory of per-class subdirectories — the reference's folder
        convention for classification datasets. Labels are assigned by
        sorted class-name order."""
        from PIL import Image

        def load(p):
            im = Image.open(p).convert("RGB")
            if resize_h is not None and resize_w is not None:
                im = im.resize((resize_w, resize_h), Image.BILINEAR)
            return np.asarray(im, np.uint8)

        if os.path.isfile(path):
            if with_label:
                raise ValueError(
                    f"{path} is a single file; with_label=True needs a "
                    "directory of per-class subdirectories")
            return ImageSet([load(path)], paths=[path])
        if not os.path.isdir(path):
            raise FileNotFoundError(path)
        if with_label:
            classes = sorted(d for d in os.listdir(path)
                             if os.path.isdir(os.path.join(path, d)))
            if not classes:
                raise ValueError(f"{path}: with_label=True needs per-class "
                                 "subdirectories")
            label_map = {c: i for i, c in enumerate(classes)}
            images, labels, paths = [], [], []
            for c in classes:
                for f in sorted(os.listdir(os.path.join(path, c))):
                    if f.lower().endswith(_EXTS):
                        p = os.path.join(path, c, f)
                        images.append(load(p))
                        labels.append(label_map[c])
                        paths.append(p)
            if not images:
                raise ValueError(
                    f"no images under {path} (recognized extensions: "
                    f"{', '.join(_EXTS)})")
            return ImageSet(images, np.asarray(labels, np.int32), label_map,
                            paths=paths)
        files = [os.path.join(path, f) for f in sorted(os.listdir(path))
                 if f.lower().endswith(_EXTS)]
        images = [load(f) for f in files]
        if not images:
            raise ValueError(f"no images under {path}")
        return ImageSet(images, paths=files)

    @staticmethod
    def from_arrays(images, labels=None) -> "ImageSet":
        return ImageSet(images, labels)

    # ---- protocol ---------------------------------------------------------
    def __len__(self) -> int:
        return (self.images.shape[0] if isinstance(self.images, np.ndarray)
                else len(self.images))

    def transform(self, preprocessing: Preprocessing) -> "ImageSet":
        """Apply an image-transform chain (``ImageSet.transform``); labels
        ride along unchanged."""
        return ImageSet(preprocessing(self.images), self.labels,
                        self.label_map, paths=self.paths)

    def to_feature_set(self, shuffle: bool = True, seed: int = 0) -> FeatureSet:
        """Finalize into the training/inference ``FeatureSet``: stacks to a
        dense float NHWC batch (``ImageSetToSample`` role)."""
        x = ImageSetToSample()(self.images)
        return FeatureSet.array(x, self.labels, shuffle=shuffle, seed=seed)

    def to_array(self) -> np.ndarray:
        return ImageSetToSample()(self.images)


#: The reference distinguishes LocalImageSet/DistributedImageSet
#: (``ImageSet.scala:46,98``); one process = one host shard here.
LocalImageSet = ImageSet
