"""Image pipeline (``feature/image`` of the reference, L2)."""

from .image_set import ImageSet, LocalImageSet
from .transforms import (Brightness, CenterCrop, ChannelNormalize,
                         ChannelOrder, HFlip, ImageProcessing,
                         ImageSetToSample, MatToTensor, PixelNormalizer,
                         RandomCrop, Resize)

__all__ = [
    "ImageSet", "LocalImageSet", "ImageProcessing", "Resize", "CenterCrop",
    "RandomCrop", "HFlip", "Brightness", "ChannelNormalize", "ChannelOrder",
    "PixelNormalizer", "MatToTensor", "ImageSetToSample",
]
