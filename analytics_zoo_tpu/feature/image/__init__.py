"""Image pipeline (``feature/image`` of the reference, L2)."""

from .image_set import ImageSet, LocalImageSet
from .transforms import (AspectScale, Brightness, BytesToMat, CenterCrop,
                         ChannelNormalize, ChannelOrder,
                         ChannelScaledNormalizer, ColorJitter, Contrast,
                         Expand, Filler, FixedCrop, HFlip, Hue,
                         ImageProcessing, ImageSetToSample, MatToFloats,
                         MatToTensor, Mirror, PixelBytesToMat,
                         PixelNormalizer, RandomAspectScale, RandomCrop,
                         RandomPreprocessing, RandomResize, Resize,
                         Saturation)

__all__ = [
    "ImageSet", "LocalImageSet", "ImageProcessing", "Resize", "CenterCrop",
    "RandomCrop", "HFlip", "Brightness", "ChannelNormalize", "ChannelOrder",
    "PixelNormalizer", "MatToTensor", "ImageSetToSample",
    "Hue", "Saturation", "Contrast", "ColorJitter", "Expand", "Filler",
    "AspectScale", "RandomAspectScale", "ChannelScaledNormalizer", "Mirror",
    "FixedCrop", "RandomResize", "RandomPreprocessing", "BytesToMat",
    "PixelBytesToMat", "MatToFloats",
]
