"""Image preprocessing transformers — parity with the reference's
``feature/image/*.scala`` OpenCV-JNI transformer files (Resize, CenterCrop,
RandomCrop, Flip, Brightness, ChannelNormalize, ChannelOrder, MatToTensor...),
re-designed host-side for the TPU infeed:

* transforms are **vectorized numpy** wherever shapes allow (a batch
  ``(N, H, W, C)`` processes in one call — the role Spark's per-partition
  parallelism plays for the reference's per-record OpenCV ops), falling back
  to per-image application for ragged inputs;
* they compose with the same ``>>`` combinator as every other
  ``Preprocessing`` (``feature/common/Preprocessing.scala``);
* the output of a chain is a dense float32 NHWC batch ready for
  ``device_put`` (channels-last is the TPU-native layout; the reference's
  NCHW ``MatToTensor`` is an MKL layout choice).

Each class cites its reference counterpart file.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

from ..common import Preprocessing

__all__ = [
    "ImageProcessing", "Resize", "CenterCrop", "RandomCrop", "HFlip",
    "Brightness", "ChannelNormalize", "ChannelOrder", "PixelNormalizer",
    "MatToTensor", "ImageSetToSample",
    # augmentation family (ImageHue/Saturation/ColorJitter/Expand/... .scala)
    "Hue", "Saturation", "Contrast", "ColorJitter", "Expand", "Filler",
    "AspectScale", "RandomAspectScale", "ChannelScaledNormalizer", "Mirror",
    "FixedCrop", "RandomResize", "RandomPreprocessing", "BytesToMat",
    "PixelBytesToMat", "MatToFloats",
]


class ImageProcessing(Preprocessing):
    """Base: applies per-image (H, W, C) or batched (N, H, W, C).
    Counterpart of ``feature/image/ImageProcessing.scala``."""

    def apply(self, data):
        if isinstance(data, (list, tuple)):
            return [self.apply_one(np.asarray(im)) for im in data]
        data = np.asarray(data)
        if data.ndim == 4:
            return self.apply_batch(data)
        return self.apply_one(data)

    def apply_one(self, im: np.ndarray) -> np.ndarray:
        raise NotImplementedError(type(self).__name__)

    def apply_batch(self, batch: np.ndarray) -> np.ndarray:
        out = [self.apply_one(im) for im in batch]
        return np.stack(out) if out else batch


class Resize(ImageProcessing):
    """``Resize.scala`` — bilinear (triangle-filter) resize to
    (height, width). Fast path: the native batched C++ library
    (``native/zoo_image.cc``, the reference's OpenCV-JNI role); falls back
    to the per-image PIL loop when the library is unavailable."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = int(resize_h), int(resize_w)

    def apply_batch(self, batch):
        from analytics_zoo_tpu.native import image as native_image
        out = native_image.resize_bilinear(batch, self.h, self.w)
        if out is not None:
            return out
        return super().apply_batch(batch)

    def apply_one(self, im):
        from analytics_zoo_tpu.native import image as native_image
        if im.ndim == 3 and im.shape[-1] in (1, 3, 4):
            out = native_image.resize_bilinear(im, self.h, self.w)
            if out is not None:
                return out
        from PIL import Image
        arr = im
        squeeze = arr.ndim == 3 and arr.shape[-1] == 1
        if squeeze:
            arr = arr[..., 0]
        dtype = arr.dtype
        if dtype != np.uint8:
            # PIL resizes float per-channel via mode F; round-trip per channel
            chans = [np.asarray(Image.fromarray(
                arr[..., c].astype(np.float32), mode="F").resize(
                    (self.w, self.h), Image.BILINEAR))
                for c in range(arr.shape[-1])] if arr.ndim == 3 else [
                np.asarray(Image.fromarray(arr.astype(np.float32), mode="F")
                           .resize((self.w, self.h), Image.BILINEAR))]
            out = np.stack(chans, axis=-1) if arr.ndim == 3 else chans[0]
            out = out.astype(dtype)
        else:
            out = np.asarray(Image.fromarray(arr).resize((self.w, self.h),
                                                         Image.BILINEAR))
        if squeeze:
            out = out[..., None]
        return out


class CenterCrop(ImageProcessing):
    """``CenterCrop.scala``."""

    def __init__(self, crop_h: int, crop_w: int):
        self.h, self.w = int(crop_h), int(crop_w)

    def _box(self, H, W):
        if H < self.h or W < self.w:
            raise ValueError(f"image {H}x{W} smaller than crop "
                             f"{self.h}x{self.w}")
        y = (H - self.h) // 2
        x = (W - self.w) // 2
        return y, x

    def apply_one(self, im):
        y, x = self._box(im.shape[0], im.shape[1])
        return im[y:y + self.h, x:x + self.w]

    def apply_batch(self, batch):
        y, x = self._box(batch.shape[1], batch.shape[2])
        return batch[:, y:y + self.h, x:x + self.w]


class RandomCrop(ImageProcessing):
    """``RandomCrop.scala`` — train-time augmentation."""

    def __init__(self, crop_h: int, crop_w: int, seed: Optional[int] = None):
        self.h, self.w = int(crop_h), int(crop_w)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        H, W = im.shape[0], im.shape[1]
        if H < self.h or W < self.w:
            raise ValueError(f"image {H}x{W} smaller than crop "
                             f"{self.h}x{self.w}")
        y = int(self._rng.integers(0, H - self.h + 1))
        x = int(self._rng.integers(0, W - self.w + 1))
        return im[y:y + self.h, x:x + self.w]


class HFlip(ImageProcessing):
    """``Flip.scala`` (horizontal) with probability ``p``."""

    def __init__(self, p: float = 0.5, seed: Optional[int] = None):
        self.p = float(p)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        return im[:, ::-1] if self._rng.random() < self.p else im

    def apply_batch(self, batch):
        flip = self._rng.random(batch.shape[0]) < self.p
        out = batch.copy()
        out[flip] = out[flip, :, ::-1]
        return out


class Brightness(ImageProcessing):
    """``Brightness.scala`` — add a uniform delta in [delta_low, delta_high]
    (operates in float; clips uint8 range)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0,
                 seed: Optional[int] = None):
        self.lo, self.hi = float(delta_low), float(delta_high)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        delta = self._rng.uniform(self.lo, self.hi)
        out = im.astype(np.float32) + delta
        if im.dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out.astype(im.dtype)


class ChannelOrder(ImageProcessing):
    """``ChannelOrder.scala`` — swap RGB<->BGR."""

    def apply_one(self, im):
        return im[..., ::-1]

    def apply_batch(self, batch):
        return batch[..., ::-1]


class ChannelNormalize(ImageProcessing):
    """``ChannelNormalize.scala`` — per-channel (x - mean) / std, output
    float32. Batches take the fused native convert+normalize pass
    (``native/zoo_image.cc``) when available; numpy otherwise."""

    def __init__(self, mean: Sequence[float], std: Sequence[float] = (1., 1., 1.)):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply_one(self, im):
        return (im.astype(np.float32) - self.mean) / self.std

    def apply_batch(self, batch):
        if (batch.ndim == 4 and self.mean.shape == (batch.shape[-1],)
                and self.std.shape == self.mean.shape):
            from analytics_zoo_tpu.native import image as native_image
            out = native_image.normalize(batch, self.mean, self.std)
            if out is not None:
                return out
        return (batch.astype(np.float32) - self.mean) / self.std


class PixelNormalizer(ImageProcessing):
    """``PixelNormalizer.scala`` — subtract a full per-pixel mean image."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def apply_one(self, im):
        return im.astype(np.float32) - self.means

    def apply_batch(self, batch):
        return batch.astype(np.float32) - self.means


class MatToTensor(ImageProcessing):
    """``MatToTensor.scala`` — finalize to float32. The reference emits NCHW
    for MKL; TPU keeps NHWC (channels-last feeds conv kernels directly)."""

    def __init__(self, scale: float = 1.0):
        self.scale = float(scale)

    def apply_one(self, im):
        return im.astype(np.float32) * self.scale

    def apply_batch(self, batch):
        return batch.astype(np.float32) * self.scale


class ImageSetToSample(Preprocessing):
    """``ImageSetToSample.scala`` — stack a (possibly per-image) pipeline
    output into one dense NHWC float batch (all images must agree on shape
    by this point)."""

    def apply(self, data):
        if isinstance(data, np.ndarray) and data.ndim == 4:
            return data.astype(np.float32)
        ims = [np.asarray(im, np.float32) for im in data]
        shapes = {im.shape for im in ims}
        if len(shapes) != 1:
            raise ValueError(f"cannot stack ragged images {sorted(shapes)}; "
                             "Resize/Crop to a common size first")
        return np.stack(ims)


# ---------------------------------------------------------------------------
# color-space helpers (vectorized numpy HSV, matching colorsys per pixel)
# ---------------------------------------------------------------------------

def _rgb_to_hsv(rgb: np.ndarray) -> np.ndarray:
    """(..., 3) float in [0,1] → HSV in [0,1] (colorsys convention)."""
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = np.max(rgb, axis=-1)
    minc = np.min(rgb, axis=-1)
    v = maxc
    span = maxc - minc
    s = np.where(maxc > 0, span / np.where(maxc == 0, 1, maxc), 0.0)
    safe = np.where(span == 0, 1, span)
    rc = (maxc - r) / safe
    gc = (maxc - g) / safe
    bc = (maxc - b) / safe
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(span == 0, 0.0, (h / 6.0) % 1.0)
    return np.stack([h, s, v], axis=-1)


def _hsv_to_rgb(hsv: np.ndarray) -> np.ndarray:
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    r = np.choose(i, [v, q, p, p, t, v])
    g = np.choose(i, [t, v, v, q, p, p])
    b = np.choose(i, [p, p, t, v, v, q])
    return np.stack([r, g, b], axis=-1)


class _HSVTransform(ImageProcessing):
    """Base for HSV-space augmentations. Pixel-value convention follows the
    reference's OpenCV ops: uint8 OR float images both hold 0-255 values
    (``MatToFloats() >> Hue(...)`` works); rescale 0-1 float images to
    0-255 before color jitter."""

    def _hsv_op(self, hsv, delta):
        raise NotImplementedError

    def _delta(self):
        raise NotImplementedError

    def apply_one(self, im):
        delta = self._delta()
        if delta is None:      # no-op draw
            return im
        rgb = im.astype(np.float32) / 255.0
        out = _hsv_to_rgb(self._hsv_op(_rgb_to_hsv(rgb), delta))
        return (np.clip(out, 0.0, 1.0) * 255.0).astype(im.dtype)


class Hue(_HSVTransform):
    """``ImageHue.scala`` (BigDL ``augmentation.Hue``) — rotate the hue by a
    uniform delta in [delta_low, delta_high] DEGREES. (The reference's
    deltas are OpenCV H units = 2°; its conventional ``Hue(-18, 18)`` is
    ``Hue(-36, 36)`` here.)"""

    def __init__(self, delta_low: float = -36.0, delta_high: float = 36.0,
                 seed: Optional[int] = None):
        self.lo, self.hi = float(delta_low), float(delta_high)
        self._rng = np.random.default_rng(seed)

    def _delta(self):
        return self._rng.uniform(self.lo, self.hi)

    def _hsv_op(self, hsv, delta):
        hsv = hsv.copy()
        hsv[..., 0] = (hsv[..., 0] + delta / 360.0) % 1.0
        return hsv


class Saturation(_HSVTransform):
    """``ImageSaturation.scala`` — scale the saturation channel by a uniform
    factor in [delta_low, delta_high] (1.0 = unchanged)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: Optional[int] = None):
        self.lo, self.hi = float(delta_low), float(delta_high)
        self._rng = np.random.default_rng(seed)

    def _delta(self):
        d = self._rng.uniform(self.lo, self.hi)
        return None if d == 1.0 else d

    def _hsv_op(self, hsv, delta):
        hsv = hsv.copy()
        hsv[..., 1] = np.clip(hsv[..., 1] * delta, 0.0, 1.0)
        return hsv


class Contrast(ImageProcessing):
    """BigDL ``augmentation.Contrast`` (the zoo wraps it inside
    ``ImageColorJitter.scala``) — scale pixel values by a uniform factor in
    [delta_low, delta_high]."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: Optional[int] = None):
        self.lo, self.hi = float(delta_low), float(delta_high)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        delta = self._rng.uniform(self.lo, self.hi)
        out = im.astype(np.float32) * delta
        if im.dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out.astype(im.dtype)


class ColorJitter(ImageProcessing):
    """``ImageColorJitter.scala`` (BigDL ``augmentation.ColorJitter``) —
    randomly-ordered brightness/contrast/saturation/hue jitter, each applied
    with its own probability; the SSD training recipe's augmentation."""

    def __init__(self, brightness_prob: float = 0.5,
                 brightness_delta: float = 32.0,
                 contrast_prob: float = 0.5, contrast_lower: float = 0.5,
                 contrast_upper: float = 1.5,
                 hue_prob: float = 0.5, hue_delta: float = 36.0,
                 saturation_prob: float = 0.5,
                 saturation_lower: float = 0.5,
                 saturation_upper: float = 1.5,
                 random_order_prob: float = 0.0,
                 seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self.random_order_prob = float(random_order_prob)
        self.probs = dict(brightness=float(brightness_prob),
                          contrast=float(contrast_prob),
                          hue=float(hue_prob),
                          saturation=float(saturation_prob))
        self.ops = dict(
            brightness=Brightness(-brightness_delta, brightness_delta,
                                  seed=self._rng.integers(1 << 31)),
            contrast=Contrast(contrast_lower, contrast_upper,
                              seed=self._rng.integers(1 << 31)),
            hue=Hue(-hue_delta, hue_delta, seed=self._rng.integers(1 << 31)),
            saturation=Saturation(saturation_lower, saturation_upper,
                                  seed=self._rng.integers(1 << 31)),
        )

    def apply_one(self, im):
        order = list(self.ops)
        if self._rng.random() < self.random_order_prob:
            self._rng.shuffle(order)
        for name in order:
            if self._rng.random() < self.probs[name]:
                im = self.ops[name].apply_one(im)
        return im


class Expand(ImageProcessing):
    """``ImageExpand.scala`` — place the image at a random position inside a
    larger mean-filled canvas (ratio drawn from [min_expand_ratio,
    max_expand_ratio]); the SSD zoom-out augmentation."""

    def __init__(self, means_r: float = 123.0, means_g: float = 117.0,
                 means_b: float = 104.0, min_expand_ratio: float = 1.0,
                 max_expand_ratio: float = 4.0, seed: Optional[int] = None):
        self.means = (float(means_r), float(means_g), float(means_b))
        self.lo, self.hi = float(min_expand_ratio), float(max_expand_ratio)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        ratio = self._rng.uniform(self.lo, self.hi)
        H, W = im.shape[0], im.shape[1]
        nh, nw = int(H * ratio), int(W * ratio)
        y = int(self._rng.uniform(0, nh - H + 1))
        x = int(self._rng.uniform(0, nw - W + 1))
        fill = np.asarray(self.means[:im.shape[-1]] if im.ndim == 3 else
                          [self.means[0]], np.float32)
        canvas = np.broadcast_to(fill, (nh, nw) + fill.shape).astype(
            np.float32)
        canvas = canvas.copy()
        canvas[y:y + H, x:x + W] = im.astype(np.float32).reshape(
            H, W, -1)
        canvas = canvas if im.ndim == 3 else canvas[..., 0]
        if im.dtype == np.uint8:
            return np.clip(canvas, 0, 255).astype(np.uint8)
        return canvas.astype(im.dtype)

    def apply_batch(self, batch):
        # per-image random canvas sizes are ragged — return a list
        return [self.apply_one(im) for im in batch]


class Filler(ImageProcessing):
    """``ImageFiller.scala`` — fill a normalized-coordinate rectangle
    [start_x, end_x) x [start_y, end_y) with ``value`` (random-erasing
    style occlusion)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: float = 255.0):
        for v in (start_x, start_y, end_x, end_y):
            if not 0.0 <= v <= 1.0:
                raise ValueError("Filler coordinates are normalized to "
                                 "[0, 1]")
        if end_x <= start_x or end_y <= start_y:
            raise ValueError("Filler box must have positive area")
        self.box = (float(start_x), float(start_y), float(end_x),
                    float(end_y))
        self.value = value

    def apply_one(self, im):
        H, W = im.shape[0], im.shape[1]
        x1, y1, x2, y2 = self.box
        out = im.copy()
        out[int(y1 * H):int(y2 * H), int(x1 * W):int(x2 * W)] = self.value
        return out


class AspectScale(ImageProcessing):
    """``ImageAspectScale.scala`` — resize so the SHORT side is
    ``min_size`` keeping aspect ratio, long side capped at ``max_size``,
    both dims rounded down to a multiple of ``scale_multiple_of`` (the
    Faster-RCNN input convention)."""

    def __init__(self, min_size: int, scale_multiple_of: int = 1,
                 max_size: int = 1000):
        self.min_size = int(min_size)
        self.multiple = int(scale_multiple_of)
        self.max_size = int(max_size)

    def _target(self, H, W, min_size=None):
        short, long = min(H, W), max(H, W)
        scale = (min_size or self.min_size) / short
        if scale * long > self.max_size:
            scale = self.max_size / long
        nh, nw = int(round(H * scale)), int(round(W * scale))
        if self.multiple > 1:
            nh = max(self.multiple, nh // self.multiple * self.multiple)
            nw = max(self.multiple, nw // self.multiple * self.multiple)
        return nh, nw

    def apply_one(self, im):
        nh, nw = self._target(im.shape[0], im.shape[1])
        return Resize(nh, nw).apply_one(im)


class RandomAspectScale(AspectScale):
    """``ImageRandomAspectScale.scala`` — AspectScale with the short-side
    target drawn uniformly from ``scales`` (drawn per image, passed by
    value — the instance stays stateless/reentrant)."""

    def __init__(self, scales: Sequence[int], scale_multiple_of: int = 1,
                 max_size: int = 1000, seed: Optional[int] = None):
        super().__init__(int(scales[0]), scale_multiple_of, max_size)
        self.scales = [int(s) for s in scales]
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        draw = int(self._rng.choice(self.scales))
        nh, nw = self._target(im.shape[0], im.shape[1], draw)
        return Resize(nh, nw).apply_one(im)

    def apply_batch(self, batch):
        # per-image random sizes are ragged — return a list, not a stack
        return [self.apply_one(im) for im in batch]


class ChannelScaledNormalizer(ImageProcessing):
    """``ImageChannelScaledNormalizer.scala`` — (x - per-channel mean) *
    scale, output float32."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 scale: float = 1.0):
        self.mean = np.asarray([mean_r, mean_g, mean_b], np.float32)
        self.scale = float(scale)

    def apply_one(self, im):
        mean = self.mean[:im.shape[-1]] if im.ndim == 3 else self.mean[0]
        return (im.astype(np.float32) - mean) * self.scale

    apply_batch = apply_one


class Mirror(ImageProcessing):
    """``ImageMirror.scala`` — DETERMINISTIC horizontal flip (``HFlip`` is
    the probabilistic train-time variant)."""

    def apply_one(self, im):
        return im[:, ::-1]

    def apply_batch(self, batch):
        return batch[:, :, ::-1]


class FixedCrop(ImageProcessing):
    """``ImageFixedCrop.scala`` — crop a fixed box; coordinates are
    normalized to [0, 1] when ``normalized=True`` (the reference's wire
    form) else pixels."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = True):
        if x2 <= x1 or y2 <= y1:
            raise ValueError("FixedCrop box must have positive area")
        self.box = (x1, y1, x2, y2)
        self.normalized = bool(normalized)

    def apply_one(self, im):
        H, W = im.shape[0], im.shape[1]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * W, x2 * W
            y1, y2 = y1 * H, y2 * H
        xi1, yi1 = max(0, int(x1)), max(0, int(y1))
        xi2, yi2 = min(W, int(round(x2))), min(H, int(round(y2)))
        return im[yi1:yi2, xi1:xi2]


class RandomResize(ImageProcessing):
    """``ImageRandomResize.scala`` — square resize to a side drawn
    uniformly from [min_size, max_size]."""

    def __init__(self, min_size: int, max_size: int,
                 seed: Optional[int] = None):
        self.lo, self.hi = int(min_size), int(max_size)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        size = int(self._rng.integers(self.lo, self.hi + 1))
        return Resize(size, size).apply_one(im)

    def apply_batch(self, batch):
        # per-image random sizes are ragged — return a list
        return [self.apply_one(im) for im in batch]


class RandomPreprocessing(ImageProcessing):
    """``ImageRandomPreprocessing.scala`` — apply the wrapped transform
    with probability ``prob``, pass through otherwise."""

    def __init__(self, transform: ImageProcessing, prob: float,
                 seed: Optional[int] = None):
        self.transform = transform
        self.prob = float(prob)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        if self._rng.random() < self.prob:
            return self.transform.apply_one(im)
        return im


class BytesToMat(ImageProcessing):
    """``ImageBytesToMat.scala`` — decode encoded image bytes (JPEG/PNG)
    to an (H, W, C) uint8 array (PIL replaces the OpenCV imdecode JNI)."""

    def apply(self, data):
        if isinstance(data, (bytes, bytearray)):
            return self._decode(bytes(data))
        if isinstance(data, (list, tuple)):
            return [self.apply(d) for d in data]
        return super().apply(data)

    def apply_one(self, im):
        return im  # already decoded

    @staticmethod
    def _decode(raw: bytes) -> np.ndarray:
        import io

        from PIL import Image
        with Image.open(io.BytesIO(raw)) as img:
            return np.asarray(img.convert("RGB"))


class PixelBytesToMat(ImageProcessing):
    """``ImagePixelBytesToMat.scala`` — reinterpret RAW pixel bytes as an
    (H, W, C) uint8 array (the reference reads the shape from the
    ImageFeature; here it is explicit)."""

    def __init__(self, height: int, width: int, channels: int = 3):
        self.shape = (int(height), int(width), int(channels))

    def apply(self, data):
        if isinstance(data, (bytes, bytearray)):
            return np.frombuffer(bytes(data), np.uint8).reshape(self.shape)
        if isinstance(data, (list, tuple)):
            return [self.apply(d) for d in data]
        return super().apply(data)

    def apply_one(self, im):
        return np.asarray(im, np.uint8).reshape(self.shape)


class MatToFloats(ImageProcessing):
    """``ImageMatToFloats.scala`` — to float32, keeping HWC layout (the
    host-side form ``MatToTensor`` finalizes for the device)."""

    def apply_one(self, im):
        return im.astype(np.float32)

    apply_batch = apply_one
