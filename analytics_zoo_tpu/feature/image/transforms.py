"""Image preprocessing transformers — parity with the reference's
``feature/image/*.scala`` OpenCV-JNI transformer files (Resize, CenterCrop,
RandomCrop, Flip, Brightness, ChannelNormalize, ChannelOrder, MatToTensor...),
re-designed host-side for the TPU infeed:

* transforms are **vectorized numpy** wherever shapes allow (a batch
  ``(N, H, W, C)`` processes in one call — the role Spark's per-partition
  parallelism plays for the reference's per-record OpenCV ops), falling back
  to per-image application for ragged inputs;
* they compose with the same ``>>`` combinator as every other
  ``Preprocessing`` (``feature/common/Preprocessing.scala``);
* the output of a chain is a dense float32 NHWC batch ready for
  ``device_put`` (channels-last is the TPU-native layout; the reference's
  NCHW ``MatToTensor`` is an MKL layout choice).

Each class cites its reference counterpart file.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

from ..common import Preprocessing

__all__ = [
    "ImageProcessing", "Resize", "CenterCrop", "RandomCrop", "HFlip",
    "Brightness", "ChannelNormalize", "ChannelOrder", "PixelNormalizer",
    "MatToTensor", "ImageSetToSample",
]


class ImageProcessing(Preprocessing):
    """Base: applies per-image (H, W, C) or batched (N, H, W, C).
    Counterpart of ``feature/image/ImageProcessing.scala``."""

    def apply(self, data):
        if isinstance(data, (list, tuple)):
            return [self.apply_one(np.asarray(im)) for im in data]
        data = np.asarray(data)
        if data.ndim == 4:
            return self.apply_batch(data)
        return self.apply_one(data)

    def apply_one(self, im: np.ndarray) -> np.ndarray:
        raise NotImplementedError(type(self).__name__)

    def apply_batch(self, batch: np.ndarray) -> np.ndarray:
        out = [self.apply_one(im) for im in batch]
        return np.stack(out) if out else batch


class Resize(ImageProcessing):
    """``Resize.scala`` — bilinear resize to (height, width) via PIL."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = int(resize_h), int(resize_w)

    def apply_one(self, im):
        from PIL import Image
        arr = im
        squeeze = arr.ndim == 3 and arr.shape[-1] == 1
        if squeeze:
            arr = arr[..., 0]
        dtype = arr.dtype
        if dtype != np.uint8:
            # PIL resizes float per-channel via mode F; round-trip per channel
            chans = [np.asarray(Image.fromarray(
                arr[..., c].astype(np.float32), mode="F").resize(
                    (self.w, self.h), Image.BILINEAR))
                for c in range(arr.shape[-1])] if arr.ndim == 3 else [
                np.asarray(Image.fromarray(arr.astype(np.float32), mode="F")
                           .resize((self.w, self.h), Image.BILINEAR))]
            out = np.stack(chans, axis=-1) if arr.ndim == 3 else chans[0]
            out = out.astype(dtype)
        else:
            out = np.asarray(Image.fromarray(arr).resize((self.w, self.h),
                                                         Image.BILINEAR))
        if squeeze:
            out = out[..., None]
        return out


class CenterCrop(ImageProcessing):
    """``CenterCrop.scala``."""

    def __init__(self, crop_h: int, crop_w: int):
        self.h, self.w = int(crop_h), int(crop_w)

    def _box(self, H, W):
        if H < self.h or W < self.w:
            raise ValueError(f"image {H}x{W} smaller than crop "
                             f"{self.h}x{self.w}")
        y = (H - self.h) // 2
        x = (W - self.w) // 2
        return y, x

    def apply_one(self, im):
        y, x = self._box(im.shape[0], im.shape[1])
        return im[y:y + self.h, x:x + self.w]

    def apply_batch(self, batch):
        y, x = self._box(batch.shape[1], batch.shape[2])
        return batch[:, y:y + self.h, x:x + self.w]


class RandomCrop(ImageProcessing):
    """``RandomCrop.scala`` — train-time augmentation."""

    def __init__(self, crop_h: int, crop_w: int, seed: Optional[int] = None):
        self.h, self.w = int(crop_h), int(crop_w)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        H, W = im.shape[0], im.shape[1]
        if H < self.h or W < self.w:
            raise ValueError(f"image {H}x{W} smaller than crop "
                             f"{self.h}x{self.w}")
        y = int(self._rng.integers(0, H - self.h + 1))
        x = int(self._rng.integers(0, W - self.w + 1))
        return im[y:y + self.h, x:x + self.w]


class HFlip(ImageProcessing):
    """``Flip.scala`` (horizontal) with probability ``p``."""

    def __init__(self, p: float = 0.5, seed: Optional[int] = None):
        self.p = float(p)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        return im[:, ::-1] if self._rng.random() < self.p else im

    def apply_batch(self, batch):
        flip = self._rng.random(batch.shape[0]) < self.p
        out = batch.copy()
        out[flip] = out[flip, :, ::-1]
        return out


class Brightness(ImageProcessing):
    """``Brightness.scala`` — add a uniform delta in [delta_low, delta_high]
    (operates in float; clips uint8 range)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0,
                 seed: Optional[int] = None):
        self.lo, self.hi = float(delta_low), float(delta_high)
        self._rng = np.random.default_rng(seed)

    def apply_one(self, im):
        delta = self._rng.uniform(self.lo, self.hi)
        out = im.astype(np.float32) + delta
        if im.dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out.astype(im.dtype)


class ChannelOrder(ImageProcessing):
    """``ChannelOrder.scala`` — swap RGB<->BGR."""

    def apply_one(self, im):
        return im[..., ::-1]

    def apply_batch(self, batch):
        return batch[..., ::-1]


class ChannelNormalize(ImageProcessing):
    """``ChannelNormalize.scala`` — per-channel (x - mean) / std, output
    float32."""

    def __init__(self, mean: Sequence[float], std: Sequence[float] = (1., 1., 1.)):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply_one(self, im):
        return (im.astype(np.float32) - self.mean) / self.std

    def apply_batch(self, batch):
        return (batch.astype(np.float32) - self.mean) / self.std


class PixelNormalizer(ImageProcessing):
    """``PixelNormalizer.scala`` — subtract a full per-pixel mean image."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def apply_one(self, im):
        return im.astype(np.float32) - self.means

    def apply_batch(self, batch):
        return batch.astype(np.float32) - self.means


class MatToTensor(ImageProcessing):
    """``MatToTensor.scala`` — finalize to float32. The reference emits NCHW
    for MKL; TPU keeps NHWC (channels-last feeds conv kernels directly)."""

    def __init__(self, scale: float = 1.0):
        self.scale = float(scale)

    def apply_one(self, im):
        return im.astype(np.float32) * self.scale

    def apply_batch(self, batch):
        return batch.astype(np.float32) * self.scale


class ImageSetToSample(Preprocessing):
    """``ImageSetToSample.scala`` — stack a (possibly per-image) pipeline
    output into one dense NHWC float batch (all images must agree on shape
    by this point)."""

    def apply(self, data):
        if isinstance(data, np.ndarray) and data.ndim == 4:
            return data.astype(np.float32)
        ims = [np.asarray(im, np.float32) for im in data]
        shapes = {im.shape for im in ims}
        if len(shapes) != 1:
            raise ValueError(f"cannot stack ragged images {sorted(shapes)}; "
                             "Resize/Crop to a common size first")
        return np.stack(ims)
