from .transforms import (AffineTransform3D, CenterCrop3D, Crop3D,  # noqa: F401
                         ImageProcessing3D, RandomCrop3D, Rotate3D, Warp3D)
