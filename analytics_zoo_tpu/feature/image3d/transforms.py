"""3D image (volume) preprocessing — parity with ``feature/image3d/*.scala``
(``Cropper.scala``: Crop3D/CenterCrop3D/RandomCrop3D; ``Affine.scala``:
AffineTransform3D; ``Rotation.scala``: Rotate3D; ``Warp.scala``:
WarpTransformer), re-designed as vectorized numpy host ops composing with
the ``>>`` Preprocessing combinator like the 2D pipeline.

Geometry follows the reference exactly (1-based voxel coordinates,
center ``(n+1)/2``, source position ``center - mat·(center - idx) -
translation``, trilinear interpolation with corner clamping). One
deliberate divergence: the reference's ``WarpTransformer`` compares its
clamp-mode STRING against the int 2 (``Warp.scala:67``), so its
``"padding"`` mode silently degrades to clamping; here ``"padding"``
actually pads with ``pad_val`` as documented. Volumes are channels-last
``(D, H, W, C)``; unlike the reference's 1-channel limit
(``Affine.scala:52``), any C is supported.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np

from ..common import Preprocessing

__all__ = ["ImageProcessing3D", "Crop3D", "CenterCrop3D", "RandomCrop3D",
           "AffineTransform3D", "Rotate3D", "Warp3D"]


class ImageProcessing3D(Preprocessing):
    """Base: applies per-volume (D, H, W, C) or batched (N, D, H, W, C)
    (``ImageProcessing3D.scala``)."""

    def apply(self, data):
        if isinstance(data, (list, tuple)):
            # recurse so per-item ndim normalization (3D → C=1) applies
            return [self.apply(np.asarray(v)) for v in data]
        data = np.asarray(data)
        if data.ndim == 5:
            return np.stack([self.apply_one(v) for v in data])
        if data.ndim == 3:  # channel-less volume → add C=1
            return self.apply_one(data[..., None])[..., 0]
        return self.apply_one(data)

    def apply_one(self, vol: np.ndarray) -> np.ndarray:
        raise NotImplementedError(type(self).__name__)


class Crop3D(ImageProcessing3D):
    """``Crop3D(start, patchSize)`` (``Cropper.scala:49``) — start is
    0-based (z, y, x)."""

    def __init__(self, start: Sequence[int], patch_size: Sequence[int]):
        self.start = tuple(int(s) for s in start)
        self.patch = tuple(int(p) for p in patch_size)

    def apply_one(self, vol):
        (z, y, x), (d, h, w) = self.start, self.patch
        if min(z, y, x) < 0 or z + d > vol.shape[0] \
                or y + h > vol.shape[1] or x + w > vol.shape[2]:
            raise ValueError(f"crop {self.start}+{self.patch} exceeds "
                             f"volume {vol.shape[:3]}")
        return vol[z:z + d, y:y + h, x:x + w]


class CenterCrop3D(ImageProcessing3D):
    """``CenterCrop3D(cropDepth, cropHeight, cropWidth)``."""

    def __init__(self, depth: int, height: int, width: int):
        self.patch = (int(depth), int(height), int(width))

    def apply_one(self, vol):
        start = [(s - p) // 2 for s, p in zip(vol.shape[:3], self.patch)]
        return Crop3D(start, self.patch).apply_one(vol)


class RandomCrop3D(ImageProcessing3D):
    """``RandomCrop3D(cropDepth, cropHeight, cropWidth)``."""

    def __init__(self, depth: int, height: int, width: int,
                 seed: Optional[int] = None):
        self.patch = (int(depth), int(height), int(width))
        self._rng = np.random.default_rng(seed)

    def apply_one(self, vol):
        start = [int(self._rng.integers(0, s - p + 1))
                 for s, p in zip(vol.shape[:3], self.patch)]
        return Crop3D(start, self.patch).apply_one(vol)


def _check_clamp_mode(clamp_mode: str) -> str:
    if clamp_mode not in ("clamp", "padding"):
        raise ValueError(f"clamp_mode must be 'clamp' or 'padding', got "
                         f"{clamp_mode!r}")
    return clamp_mode


def _trilinear_warp(src: np.ndarray, iz, iy, ix, clamp_mode: str,
                    pad_val: float) -> np.ndarray:
    """Sample ``src`` (D, H, W, C) at 1-based fractional positions
    (iz, iy, ix), the vectorized ``Warp.scala`` kernel."""
    d, h, w = src.shape[:3]
    off = ((iz < 1) | (iz > d) | (iy < 1) | (iy > h)
           | (ix < 1) | (ix > w))
    iz = np.clip(iz, 1, d)
    iy = np.clip(iy, 1, h)
    ix = np.clip(ix, 1, w)
    iz0 = np.floor(iz).astype(np.int64)
    iy0 = np.floor(iy).astype(np.int64)
    ix0 = np.floor(ix).astype(np.int64)
    iz1 = np.minimum(iz0 + 1, d)
    iy1 = np.minimum(iy0 + 1, h)
    ix1 = np.minimum(ix0 + 1, w)
    wz = (iz - iz0)[..., None]
    wy = (iy - iy0)[..., None]
    wx = (ix - ix0)[..., None]

    def at(zi, yi, xi):
        return src[zi - 1, yi - 1, xi - 1]  # 1-based → 0-based gather

    val = ((1 - wy) * (1 - wx) * (1 - wz) * at(iz0, iy0, ix0)
           + (1 - wy) * (1 - wx) * wz * at(iz1, iy0, ix0)
           + (1 - wy) * wx * (1 - wz) * at(iz0, iy0, ix1)
           + (1 - wy) * wx * wz * at(iz1, iy0, ix1)
           + wy * (1 - wx) * (1 - wz) * at(iz0, iy1, ix0)
           + wy * (1 - wx) * wz * at(iz1, iy1, ix0)
           + wy * wx * (1 - wz) * at(iz0, iy1, ix1)
           + wy * wx * wz * at(iz1, iy1, ix1))
    if clamp_mode == "padding":
        val = np.where(off[..., None], pad_val, val)
    if np.issubdtype(src.dtype, np.integer):
        info = np.iinfo(src.dtype)
        val = np.clip(np.rint(val), info.min, info.max)
    return val.astype(src.dtype)


class AffineTransform3D(ImageProcessing3D):
    """``AffineTransform3D(mat, translation, clampMode, padVal)``
    (``Affine.scala:44``): source position =
    ``center - mat · (center - idx) - translation`` in 1-based (z, y, x)
    coordinates with center ``(n+1)/2``."""

    def __init__(self, mat: np.ndarray,
                 translation: Sequence[float] = (0.0, 0.0, 0.0),
                 clamp_mode: str = "clamp", pad_val: float = 0.0):
        self.mat = np.asarray(mat, np.float64).reshape(3, 3)
        self.translation = np.asarray(translation, np.float64).reshape(3)
        self.clamp_mode = _check_clamp_mode(clamp_mode)
        self.pad_val = float(pad_val)

    def apply_one(self, vol):
        d, h, w = vol.shape[:3]
        cz, cy, cx = (d + 1) / 2.0, (h + 1) / 2.0, (w + 1) / 2.0
        zz, yy, xx = np.meshgrid(np.arange(1, d + 1, dtype=np.float64),
                                 np.arange(1, h + 1, dtype=np.float64),
                                 np.arange(1, w + 1, dtype=np.float64),
                                 indexing="ij")
        grid = np.stack([cz - zz, cy - yy, cx - xx])          # (3, D, H, W)
        src_pos = (grid - np.tensordot(self.mat, grid, axes=1)
                   - self.translation[:, None, None, None])
        # warp runs in offset mode: sample at idx + flow
        iz = zz + src_pos[0]
        iy = yy + src_pos[1]
        ix = xx + src_pos[2]
        return _trilinear_warp(vol, iz, iy, ix, self.clamp_mode,
                               self.pad_val)


class Rotate3D(AffineTransform3D):
    """``Rotate3D([yaw, pitch, roll])`` (``Rotation.scala:36``) — intrinsic
    z/y/x-axis rotations composed as yaw · pitch · roll."""

    def __init__(self, rotation_angles: Sequence[float],
                 clamp_mode: str = "clamp", pad_val: float = 0.0):
        yaw, pitch, roll = (float(a) for a in rotation_angles)
        roll_m = np.array([[1, 0, 0],
                           [0, math.cos(roll), -math.sin(roll)],
                           [0, math.sin(roll), math.cos(roll)]])
        pitch_m = np.array([[math.cos(pitch), 0, math.sin(pitch)],
                            [0, 1, 0],
                            [-math.sin(pitch), 0, math.cos(pitch)]])
        yaw_m = np.array([[math.cos(yaw), -math.sin(yaw), 0],
                          [math.sin(yaw), math.cos(yaw), 0],
                          [0, 0, 1]])
        super().__init__(yaw_m @ pitch_m @ roll_m,
                         clamp_mode=clamp_mode, pad_val=pad_val)
        self.rotation_angles = (yaw, pitch, roll)


class Warp3D(ImageProcessing3D):
    """Raw flow-field warp (``Warp.scala``): ``flow`` is (3, D, H, W);
    ``offset=True`` samples at ``idx + flow``, else at ``flow``."""

    def __init__(self, flow: np.ndarray, offset: bool = True,
                 clamp_mode: str = "clamp", pad_val: float = 0.0):
        self.flow = np.asarray(flow, np.float64)
        self.offset = bool(offset)
        self.clamp_mode = _check_clamp_mode(clamp_mode)
        self.pad_val = float(pad_val)

    def apply_one(self, vol):
        d, h, w = vol.shape[:3]
        if self.flow.shape != (3, d, h, w):
            raise ValueError(f"flow shape {self.flow.shape} vs volume "
                             f"{(3, d, h, w)}")
        if self.offset:
            zz, yy, xx = np.meshgrid(np.arange(1, d + 1),
                                     np.arange(1, h + 1),
                                     np.arange(1, w + 1), indexing="ij")
            iz, iy, ix = (zz + self.flow[0], yy + self.flow[1],
                          xx + self.flow[2])
        else:
            iz, iy, ix = self.flow
        return _trilinear_warp(vol, iz, iy, ix, self.clamp_mode,
                               self.pad_val)
