"""FeatureSet — the TPU-native data-caching layer, replacing the reference's
``FeatureSet.scala`` family:

* ``CachedDistributedFeatureSet`` (``FeatureSet.scala:222-322``): per-partition
  in-memory cache + shuffled index + an *infinite looped iterator* for
  training → here an in-host-RAM numpy cache with a per-epoch reshuffled
  permutation and an infinite batch generator.
* ``DiskFeatureSet`` DRAM-slice semantics (``FeatureSet.scala:332-409``) →
  ``numpy.memmap``-backed arrays pass straight through: the OS page cache is
  the slice manager, so datasets larger than RAM stream from disk.
* factory ``FeatureSet.rdd(memoryType=...)`` (``FeatureSet.scala:423-466``) →
  ``FeatureSet.array(...)`` / ``FeatureSet.from_iterable(...)``.

TPU-critical difference from round 1's synchronous per-batch indexing: batches
are assembled on a background thread and transferred with double-buffered
``device_put`` (``prefetch_to_device``), so the chip never waits on the host —
the role Spark's per-partition parallelism plays for the reference.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel import mesh as mesh_lib
from .common import Preprocessing


def _as_list(x) -> List[np.ndarray]:
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class FeatureSet:
    """In-memory (host-RAM) cached dataset of ``x`` (array or list of arrays)
    and optional ``y``. One instance per host process; under multi-host each
    host holds its shard of the global dataset, mirroring the reference's
    per-partition caches."""

    def __init__(self, x, y=None, shuffle: bool = True, seed: int = 0):
        self.xs = [np.asarray(a) for a in _as_list(x)]
        if not self.xs:
            raise ValueError("FeatureSet needs at least one feature array")
        n = self.xs[0].shape[0]
        for a in self.xs:
            if a.shape[0] != n:
                raise ValueError("feature arrays disagree on leading dim")
        self.y = None if y is None else np.asarray(y)
        if self.y is not None and self.y.shape[0] != n:
            raise ValueError("labels disagree with features on leading dim")
        self.shuffle = shuffle
        self.seed = seed

    # ---- factories (FeatureSet.scala:423-466) -----------------------------
    @staticmethod
    def array(x, y=None, *, shuffle: bool = True, seed: int = 0) -> "FeatureSet":
        return FeatureSet(x, y, shuffle=shuffle, seed=seed)

    @staticmethod
    def from_iterable(records: Sequence[Tuple[Any, Any]], *, shuffle: bool = True,
                      seed: int = 0) -> "FeatureSet":
        """Build from an iterable of ``(x, y)`` records (the RDD-of-Samples
        role). Stacks everything into contiguous arrays once."""
        xs, ys = [], []
        for rec in records:
            if isinstance(rec, tuple) and len(rec) == 2:
                xs.append(rec[0])
                ys.append(rec[1])
            else:
                xs.append(rec)
        x = np.stack([np.asarray(a) for a in xs])
        y = np.stack([np.asarray(a) for a in ys]) if ys else None
        return FeatureSet(x, y, shuffle=shuffle, seed=seed)

    # ---- basic protocol ---------------------------------------------------
    def __len__(self) -> int:
        return self.xs[0].shape[0]

    @property
    def x(self):
        return self.xs if len(self.xs) > 1 else self.xs[0]

    def transform(self, fn: Union[Preprocessing, Callable]) -> "FeatureSet":
        """Apply a (vectorized) preprocessing to the cached arrays — the
        ``featureSet.transform(preprocessing)`` step of the reference
        (cache-after-transform, ``FeatureSet.scala:222-322``). ``fn`` receives
        ``(x, y)`` and returns ``(x', y')``."""
        out = fn((self.x, self.y))
        x2, y2 = out
        return FeatureSet(x2, y2, shuffle=self.shuffle, seed=self.seed)

    # ---- iterators --------------------------------------------------------
    def _order(self, epoch: int) -> np.ndarray:
        n = len(self)
        if not self.shuffle:
            return np.arange(n)
        return np.random.default_rng(self.seed + epoch).permutation(n)

    def _slice(self, idx) -> Tuple[Any, Any]:
        bx = [a[idx] for a in self.xs]
        bx = bx if len(bx) > 1 else bx[0]
        by = None if self.y is None else self.y[idx]
        return bx, by

    def iter_batches(self, batch_size: int, *, epoch: int = 0,
                     drop_last: bool = True) -> Iterator[Tuple[Any, Any]]:
        """One pass (one 'epoch'), reshuffled by ``epoch`` number."""
        order = self._order(epoch)
        n = len(self)
        end = n - (n % batch_size) if drop_last else n
        for i in range(0, end, batch_size):
            yield self._slice(order[i:i + batch_size])

    def infinite_batches(self, batch_size: int, *, start_epoch: int = 0,
                         ) -> Iterator[Tuple[Any, Any]]:
        """The training iterator: loops forever, reshuffling every pass —
        ``CachedDistributedFeatureSet``'s infinite looped iterator
        (``FeatureSet.scala:264-322``)."""
        epoch = start_epoch
        while True:
            yield from self.iter_batches(batch_size, epoch=epoch, drop_last=True)
            epoch += 1

    def steps_per_epoch(self, batch_size: int, drop_last: bool = True) -> int:
        n = len(self)
        return n // batch_size if drop_last else (n + batch_size - 1) // batch_size


# ---------------------------------------------------------------------------
# async host prefetch + double-buffered device transfer
# ---------------------------------------------------------------------------

class _ThreadedIterator:
    """Run a host iterator on a background thread with a bounded queue —
    overlaps numpy batch assembly with device compute (the reference gets
    this overlap from Spark's task threads; here it is explicit)."""

    _END = object()

    def __init__(self, it: Iterator, buffer_size: int = 4):
        self._q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()

        def run():
            try:
                for item in it:
                    if self._stop.is_set():
                        return
                    self._q.put(item)
            except BaseException as e:  # propagate to consumer
                self._err = e
            finally:
                self._q.put(self._END)

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._END:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self):
        self._stop.set()
        # drain so the producer can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


def prefetch_to_device(it: Iterator, mesh=None, *, buffer_size: int = 2,
                       threaded: bool = True, sharding=None) -> Iterator:
    """Double-buffered device transfer: keep ``buffer_size`` batches already
    dispatched to the devices while the current one computes. ``device_put``
    is async in JAX, so this pipeline hides both host batch assembly (via the
    background thread) and PCIe/DMA transfer behind the previous step.

    ``sharding`` overrides the default leading-dim data sharding — used by the
    multi-step scan path, whose chunks are ``(K, batch, ...)`` and shard the
    *second* axis."""
    if sharding is None:
        sharding = mesh_lib.batch_sharding(mesh)

    def put(item):
        return jax.tree.map(
            lambda a: jax.device_put(jnp.asarray(a), sharding) if a is not None else None,
            item, is_leaf=lambda a: a is None or not isinstance(a, (list, tuple, dict)))

    src = _ThreadedIterator(it, buffer_size=buffer_size + 2) if threaded else it
    buf: collections.deque = collections.deque()
    try:
        for item in src:
            buf.append(put(item))
            if len(buf) > buffer_size:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
    finally:
        if threaded:
            src.close()
