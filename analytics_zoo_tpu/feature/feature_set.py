"""FeatureSet — the TPU-native data-caching layer, replacing the reference's
``FeatureSet.scala`` family:

* ``CachedDistributedFeatureSet`` (``FeatureSet.scala:222-322``): per-partition
  in-memory cache + shuffled index + an *infinite looped iterator* for
  training → here an in-host-RAM numpy cache with a per-epoch reshuffled
  permutation and an infinite batch generator.
* ``DiskFeatureSet`` DRAM-slice semantics (``FeatureSet.scala:332-409``) →
  ``numpy.memmap``-backed arrays pass straight through: the OS page cache is
  the slice manager, so datasets larger than RAM stream from disk.
* factory ``FeatureSet.rdd(memoryType=...)`` (``FeatureSet.scala:423-466``) →
  ``FeatureSet.array(...)`` / ``FeatureSet.from_iterable(...)``.

TPU-critical difference from round 1's synchronous per-batch indexing: batches
are assembled on a background thread and transferred with double-buffered
``device_put`` (``prefetch_to_device``), so the chip never waits on the host —
the role Spark's per-partition parallelism plays for the reference.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel import mesh as mesh_lib
from .common import Preprocessing


def _as_list(x) -> List[np.ndarray]:
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _keep(a):
    """Host-or-device array normalization. Device-resident ``jax.Array``s
    stay on device — ``np.asarray`` would drag them back through the
    host (a full HBM→host readback on tunneled devices), which matters
    for extract→fit chains where one model's jitted output feeds another
    model's training (the reference's frozen-backbone transfer-learning
    flow keeps features in executor RAM the same way,
    ``FeatureSet.scala:222-322``). Host-side batch slicing converts ONCE
    via ``_host_xs`` below, never per batch."""
    if isinstance(a, jax.Array):
        return a
    return np.asarray(a)


class FeatureSet:
    """In-memory (host-RAM) cached dataset of ``x`` (array or list of arrays)
    and optional ``y``. One instance per host process; under multi-host each
    host holds its shard of the global dataset, mirroring the reference's
    per-partition caches."""

    def __init__(self, x, y=None, shuffle: bool = True, seed: int = 0):
        self.xs = [_keep(a) for a in _as_list(x)]
        if not self.xs:
            raise ValueError("FeatureSet needs at least one feature array")
        n = self.xs[0].shape[0]
        for a in self.xs:
            if a.shape[0] != n:
                raise ValueError("feature arrays disagree on leading dim")
        self.y = None if y is None else _keep(y)
        if self.y is not None and self.y.shape[0] != n:
            raise ValueError("labels disagree with features on leading dim")
        self.shuffle = shuffle
        self.seed = seed

    # ---- factories (FeatureSet.scala:423-466) -----------------------------
    @staticmethod
    def array(x, y=None, *, shuffle: bool = True, seed: int = 0) -> "FeatureSet":
        return FeatureSet(x, y, shuffle=shuffle, seed=seed)

    @staticmethod
    def from_iterable(records: Sequence[Tuple[Any, Any]], *, shuffle: bool = True,
                      seed: int = 0) -> "FeatureSet":
        """Build from an iterable of ``(x, y)`` records (the RDD-of-Samples
        role). Stacks everything into contiguous arrays once."""
        xs, ys = [], []
        for rec in records:
            if isinstance(rec, tuple) and len(rec) == 2:
                xs.append(rec[0])
                ys.append(rec[1])
            else:
                xs.append(rec)
        x = np.stack([np.asarray(a) for a in xs])
        y = np.stack([np.asarray(a) for a in ys]) if ys else None
        return FeatureSet(x, y, shuffle=shuffle, seed=seed)

    # ---- basic protocol ---------------------------------------------------
    def __len__(self) -> int:
        return self.xs[0].shape[0]

    @property
    def x(self):
        return self.xs if len(self.xs) > 1 else self.xs[0]

    def transform(self, fn: Union[Preprocessing, Callable]) -> "FeatureSet":
        """Apply a (vectorized) preprocessing to the cached arrays — the
        ``featureSet.transform(preprocessing)`` step of the reference
        (cache-after-transform, ``FeatureSet.scala:222-322``). ``fn`` receives
        ``(x, y)`` and returns ``(x', y')``."""
        xs, y = self._host_view()
        out = fn((xs if len(xs) > 1 else xs[0], y))
        x2, y2 = out
        return FeatureSet(x2, y2, shuffle=self.shuffle, seed=self.seed)

    # ---- iterators --------------------------------------------------------
    def _host_view(self):
        """Numpy copies of device-resident arrays, materialized ONCE and
        memoized — the host slicing below must not re-read HBM per batch.
        ``xs``/``y`` are read exactly once: subclasses make them properties
        backed by full-file disk gathers (DiskFeatureSet)."""
        xs, y = self.xs, self.y
        if not any(isinstance(a, jax.Array)
                   for a in xs + ([y] if y is not None else [])):
            return xs, y
        if getattr(self, "_host_xs", None) is None:
            self._host_xs = [np.asarray(a) for a in xs]
            self._host_y = None if y is None else np.asarray(y)
        return self._host_xs, self._host_y

    def _order(self, epoch: int) -> np.ndarray:
        n = len(self)
        if not self.shuffle:
            return np.arange(n)
        return np.random.default_rng(self.seed + epoch).permutation(n)

    def _slice(self, idx) -> Tuple[Any, Any]:
        xs, y = self._host_view()
        bx = [a[idx] for a in xs]
        bx = bx if len(bx) > 1 else bx[0]
        by = None if y is None else y[idx]
        return bx, by

    def iter_batches(self, batch_size: int, *, epoch: int = 0,
                     drop_last: bool = True) -> Iterator[Tuple[Any, Any]]:
        """One pass (one 'epoch'), reshuffled by ``epoch`` number."""
        order = self._order(epoch)
        n = len(self)
        end = n - (n % batch_size) if drop_last else n
        for i in range(0, end, batch_size):
            yield self._slice(order[i:i + batch_size])

    def infinite_batches(self, batch_size: int, *, start_epoch: int = 0,
                         ) -> Iterator[Tuple[Any, Any]]:
        """The training iterator: loops forever, reshuffling every pass —
        ``CachedDistributedFeatureSet``'s infinite looped iterator
        (``FeatureSet.scala:264-322``)."""
        epoch = start_epoch
        while True:
            yield from self.iter_batches(batch_size, epoch=epoch, drop_last=True)
            epoch += 1

    def steps_per_epoch(self, batch_size: int, drop_last: bool = True) -> int:
        n = len(self)
        return n // batch_size if drop_last else (n + batch_size - 1) // batch_size

    def sample(self, n: int):
        """First ``n`` records — shape/dtype probing (e.g. lazy weight
        init) without materializing more than ``n`` rows."""
        bx = [np.asarray(a[:n]) for a in self.xs]
        return bx if len(bx) > 1 else bx[0]


# ---------------------------------------------------------------------------
# async host prefetch + double-buffered device transfer
# ---------------------------------------------------------------------------

class _ThreadedIterator:
    """Run a host iterator on a background thread with a bounded queue —
    overlaps numpy batch assembly with device compute (the reference gets
    this overlap from Spark's task threads; here it is explicit)."""

    _END = object()

    def __init__(self, it: Iterator, buffer_size: int = 4):
        self._q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()

        def run():
            try:
                for item in it:
                    if not self._put(item):
                        return
            except BaseException as e:  # propagate to consumer
                self._err = e
            finally:
                # the sentinel is delivered UNCONDITIONALLY — a consumer
                # blocked in __next__ (or one that races close()) needs
                # the END to raise StopIteration/propagate _err rather
                # than hang. While live, wait for the consumer like any
                # item; once close() set the stop flag the stream is
                # abandoned, so freeing a slot (dropping one unread
                # item) to land the sentinel is correct and guarantees
                # termination.
                while True:
                    try:
                        self._q.put(self._END, timeout=0.1)
                        return
                    except queue.Full:
                        if self._stop.is_set():
                            try:
                                self._q.get_nowait()
                            except queue.Empty:
                                pass

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def _put(self, item) -> bool:
        """Bounded producer put: re-check the stop flag between timed
        attempts so a consumer that stopped consuming mid-buffer-full
        (close() racing a refill) releases this thread instead of
        parking it forever on a full queue (ZL011)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._END:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self):
        self._stop.set()
        # drain so the producer can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


def prefetch_to_device(it: Iterator, mesh=None, *, buffer_size: int = 2,
                       threaded: bool = True, sharding=None,
                       ledger=None) -> Iterator:
    """Double-buffered device transfer: keep ``buffer_size`` batches already
    dispatched to the devices while the current one computes. ``device_put``
    is async in JAX, so this pipeline hides both host batch assembly (via the
    background thread) and PCIe/DMA transfer behind the previous step.

    ``sharding`` overrides the default leading-dim data sharding — used by the
    multi-step scan path, whose chunks are ``(K, batch, ...)`` and shard the
    *second* axis.

    ``ledger`` (a :class:`~..observability.goodput.GoodputLedger`)
    attributes the step/data seam from inside the pipeline: time spent
    in here — the blocking source pull (prefetch starvation) plus batch
    assembly and transfer dispatch — is ``data_wait``; the consumer's
    time between a yielded batch and its next ``next()`` is the
    training step (``device_step``); spin-up before the first yield is
    ``idle``. The notes run on the consumer's thread (generators
    execute in their caller), which is exactly the thread the ledger
    accounts."""
    if sharding is None:
        sharding = mesh_lib.batch_sharding(mesh)

    def put(item):
        return jax.tree.map(
            lambda a: jax.device_put(jnp.asarray(a), sharding) if a is not None else None,
            item, is_leaf=lambda a: a is None or not isinstance(a, (list, tuple, dict)))

    def note(category):
        if ledger is not None:
            ledger.note(category)

    src = _ThreadedIterator(it, buffer_size=buffer_size + 2) if threaded else it
    buf: collections.deque = collections.deque()
    note("idle")                    # body first runs at the first next()
    try:
        for item in src:
            buf.append(put(item))
            if len(buf) > buffer_size:
                note("data_wait")
                yield buf.popleft()
                note("device_step")
        while buf:
            note("data_wait")
            yield buf.popleft()
            note("device_step")
    finally:
        note("data_wait")           # close the pipeline's own tail
        if threaded:
            src.close()


# ---------------------------------------------------------------------------
# disk tier (DiskFeatureSet, FeatureSet.scala:332-409)
# ---------------------------------------------------------------------------

class DiskFeatureSet(FeatureSet):
    """``DISK_AND_DRAM(numSlice)`` semantics (``FeatureSet.scala:332-409``):
    the dataset lives on disk (standard ``.npy`` files, memory-mapped by the
    native IO library); each training pass materializes a random
    ``1/num_slices`` DRAM slice, and the NEXT pass's pages stream in on a
    background thread while the current slice trains. ``EveryEpoch``-style
    triggers and ``nb_epoch`` count FULL passes (``num_slices`` slice
    passes), matching ``ZooTrigger.scala:44-66``.

    ``num_slices == 0`` declares an evaluation-only set (whole set readable,
    no training slices), mirroring the reference's contract.
    """

    def __init__(self, x_paths, y_path: Optional[str] = None,
                 num_slices: int = 2, shuffle: bool = True, seed: int = 0):
        from ..native import NativeArrayFile
        if num_slices == 1 or num_slices < 0:
            raise ValueError(
                "num_slices must be 0 (eval-only) or >= 2; for everything "
                "in DRAM use FeatureSet.array (the reference's DRAM type)")
        paths = [x_paths] if isinstance(x_paths, (str, bytes)) else list(x_paths)
        self.files_x = [NativeArrayFile(p) for p in paths]
        self.file_y = NativeArrayFile(y_path) if y_path is not None else None
        self.total = self.files_x[0].n
        for f in self.files_x:
            if f.n != self.total:
                raise ValueError("feature files disagree on record count")
        if self.file_y is not None and self.file_y.n != self.total:
            raise ValueError("label file disagrees with features on count")
        self.num_slices = int(num_slices)
        self.shuffle = shuffle
        self.seed = seed
        self.slice_size = (self.total // self.num_slices
                           if self.num_slices else self.total)
        self._cur: Optional[Tuple[int, List[np.ndarray], Any]] = None

    # -- factory ------------------------------------------------------------
    @staticmethod
    def disk(x_paths, y_path=None, *, num_slices: int = 2,
             shuffle: bool = True, seed: int = 0) -> "DiskFeatureSet":
        return DiskFeatureSet(x_paths, y_path, num_slices, shuffle, seed)

    # -- protocol -----------------------------------------------------------
    @property
    def num_of_slice(self) -> int:
        return self.num_slices

    def __len__(self) -> int:
        return self.slice_size

    def _slice_indices(self, pass_idx: int) -> np.ndarray:
        """Record indices of slice ``pass_idx``, SORTED for sequential disk
        reads (within-slice order is reshuffled by ``_order`` anyway)."""
        if self.shuffle:
            rng = np.random.default_rng(self.seed + 7919 * pass_idx)
            idx = rng.choice(self.total, size=self.slice_size, replace=False)
            idx.sort()
            return idx
        # modular rotation so a total that doesn't divide num_slices still
        # covers every record across passes (no permanently-dropped tail)
        lo = (pass_idx * self.slice_size) % self.total
        return (np.arange(lo, lo + self.slice_size) % self.total)

    def _materialize(self, pass_idx: int) -> None:
        if self._cur is not None and self._cur[0] == pass_idx:
            return
        idx = self._slice_indices(pass_idx)
        xs = [f.gather(idx) for f in self.files_x]
        y = self.file_y.gather(idx) if self.file_y is not None else None
        self._cur = (pass_idx, xs, y)
        # stream the NEXT slice's pages in while this one trains — only in
        # rotation mode, where the next slice is a dense range; a shuffled
        # slice's sorted sample spans ~the whole file, and prefetching all
        # of it would read num_slices× the IO the slicing exists to avoid
        if not self.shuffle:
            nxt = self._slice_indices(pass_idx + 1)
            lo, hi = int(nxt.min()), int(nxt.max()) + 1
            for f in self.files_x + ([self.file_y] if self.file_y else []):
                f.prefetch(lo, hi)

    def iter_batches(self, batch_size: int, *, epoch: int = 0,
                     drop_last: bool = True):
        if self.num_slices == 0:
            raise ValueError("num_slices=0 is an evaluation-only "
                             "DiskFeatureSet — it cannot train "
                             "(FeatureSet.scala:369-375)")
        self._materialize(epoch)
        _, xs, y = self._cur
        order = self._order(epoch)
        n = self.slice_size
        end = n - (n % batch_size) if drop_last else n
        for i in range(0, end, batch_size):
            sel = order[i:i + batch_size]
            bx = [a[sel] for a in xs]
            yield (bx if len(bx) > 1 else bx[0],
                   None if y is None else y[sel])

    def sample(self, n: int):
        """First ``n`` records straight from disk — no full-set gather."""
        idx = np.arange(min(n, self.total))
        bx = [f.gather(idx) for f in self.files_x]
        return bx if len(bx) > 1 else bx[0]

    # whole-set views (the reference's data(train=false) path)
    @property
    def xs(self):  # type: ignore[override]
        all_idx = np.arange(self.total)
        return [f.gather(all_idx) for f in self.files_x]

    @property
    def x(self):
        xs = self.xs
        return xs if len(xs) > 1 else xs[0]

    @property
    def y(self):
        if self.file_y is None:
            return None
        return self.file_y.gather(np.arange(self.total))

    def close(self):
        for f in self.files_x + ([self.file_y] if self.file_y else []):
            f.close()


class BucketedFeatureSet(FeatureSet):
    """Length-bucketed dataset for ragged sequences under XLA static
    shapes (SURVEY §7 "hard parts": the reference just pads everything to
    one length — bucketing compiles one program per bucket and wastes far
    less padding compute). Batches never mix buckets; batch order
    interleaves buckets, reshuffled per epoch.

    Note: multi-step scan fusing (``zoo.train.scan_steps > 1``) stacks K
    consecutive batches into one array and therefore cannot mix shapes —
    use the default ``scan_steps=1`` with bucketed data.
    """

    device_cacheable = False  # ragged across buckets: no one HBM array
    ragged = True             # evaluate/predict need a single dense array

    def __init__(self, buckets: Sequence[FeatureSet], shuffle: bool = True,
                 seed: int = 0):
        buckets = [b for b in buckets if len(b) > 0]
        if not buckets:
            raise ValueError("BucketedFeatureSet needs non-empty buckets")
        self.buckets = list(buckets)
        self.shuffle = shuffle
        self.seed = seed

    def __len__(self) -> int:
        return sum(len(b) for b in self.buckets)

    def steps_per_epoch(self, batch_size: int, drop_last: bool = True) -> int:
        return sum(b.steps_per_epoch(batch_size, drop_last)
                   for b in self.buckets)

    def iter_batches(self, batch_size: int, *, epoch: int = 0,
                     drop_last: bool = True):
        iters = [b.iter_batches(batch_size, epoch=epoch, drop_last=drop_last)
                 for b in self.buckets]
        order = [bi for bi, b in enumerate(self.buckets)
                 for _ in range(b.steps_per_epoch(batch_size, drop_last))]
        if self.shuffle:
            np.random.default_rng(self.seed + 31 * epoch).shuffle(order)
        for bi in order:
            yield next(iters[bi])

    def sample(self, n: int):
        return self.buckets[0].sample(n)

    @property
    def xs(self):  # type: ignore[override]
        raise ValueError("bucketed data is ragged across buckets; iterate "
                         "with iter_batches or use the per-bucket sets")

    @property
    def x(self):
        return self.xs

    @property
    def y(self):
        ys = [b.y for b in self.buckets]
        if any(v is None for v in ys):
            return None
        return np.concatenate(ys)
