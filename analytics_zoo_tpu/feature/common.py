"""Chainable preprocessing combinators — the TPU-native equivalent of the
reference's ``Preprocessing`` family (``feature/common/Preprocessing.scala``
and the adapters in ``feature/common/*.scala``: SeqToTensor, ArrayToTensor,
ScalarToTensor, TensorToSample, FeatureLabelPreprocessing, ...).

Design difference: the reference transforms records lazily, one at a time,
inside RDD iterators. Here a ``Preprocessing`` is a *vectorized* function over
a whole numpy batch (applied once when a FeatureSet caches, or per host batch
when streaming) — batch-at-a-time numpy is what keeps the host fast enough to
feed a TPU, and the chain composes with ``>>`` (the reference's ``->``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np


class Preprocessing:
    """A composable transformation. Subclasses override ``apply``; chaining
    uses ``a >> b`` (the reference's ``a -> b``,
    ``feature/common/Preprocessing.scala``)."""

    def apply(self, data: Any) -> Any:
        raise NotImplementedError(type(self).__name__)

    def __call__(self, data: Any) -> Any:
        return self.apply(data)

    def __rshift__(self, other: "Preprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])


class ChainedPreprocessing(Preprocessing):
    """``ChainedPreprocessing`` — function composition."""

    def __init__(self, stages: Sequence[Preprocessing]):
        flat = []
        for s in stages:
            if isinstance(s, ChainedPreprocessing):
                flat.extend(s.stages)
            else:
                flat.append(s)
        self.stages = flat

    def apply(self, data):
        for s in self.stages:
            data = s(data)
        return data

    def __rshift__(self, other: Preprocessing) -> "ChainedPreprocessing":
        return ChainedPreprocessing(self.stages + [other])


class FnPreprocessing(Preprocessing):
    """Wrap a plain function (the reference's ``BigDLAdapter`` role)."""

    def __init__(self, fn: Callable[[Any], Any], name: Optional[str] = None):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "fn")

    def apply(self, data):
        return self.fn(data)


class SeqToTensor(Preprocessing):
    """``SeqToTensor.scala`` — number sequence → float array, optionally
    reshaped to ``size`` (per example)."""

    def __init__(self, size: Optional[Tuple[int, ...]] = None,
                 dtype: Any = np.float32):
        self.size = tuple(size) if size is not None else None
        self.dtype = dtype

    def apply(self, data):
        a = np.asarray(data, self.dtype)
        if self.size is not None:
            a = a.reshape((a.shape[0],) + self.size)
        return a


class ArrayToTensor(Preprocessing):
    """``ArrayToTensor.scala`` — stack a list of per-example arrays."""

    def __init__(self, dtype: Any = np.float32):
        self.dtype = dtype

    def apply(self, data):
        return np.stack([np.asarray(d, self.dtype) for d in data])


class ScalarToTensor(Preprocessing):
    """``ScalarToTensor.scala`` — scalars → (N, 1) array."""

    def __init__(self, dtype: Any = np.float32):
        self.dtype = dtype

    def apply(self, data):
        return np.asarray(data, self.dtype).reshape(-1, 1)


class Normalize(Preprocessing):
    """Feature scaling: ``(x - mean) / std`` (vectorized; the image pipeline
    has its own channel-wise variant)."""

    def __init__(self, mean: Any, std: Any):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply(self, data):
        return (np.asarray(data, np.float32) - self.mean) / self.std


class FeatureLabelPreprocessing(Preprocessing):
    """``FeatureLabelPreprocessing.scala`` — apply one chain to features and
    another to labels of an ``(x, y)`` pair."""

    def __init__(self, feature: Preprocessing, label: Optional[Preprocessing] = None):
        self.feature = feature
        self.label = label

    def apply(self, data):
        x, y = data
        fx = self.feature(x)
        fy = self.label(y) if (self.label is not None and y is not None) else y
        return fx, fy
