"""TextSet — parity with ``feature/text/TextSet.scala`` (Local/Distributed
text collections) and its transformer chain:

* ``read`` (``TextSet.scala:290``): per-class-subdirectory corpus or
  in-memory (text, label) pairs; ``read_csv``/``read_parquet``
  (``TextSet.scala:345,372``) become ``from_csv``/``from_parquet``.
* ``tokenize`` (``TextSet.scala:97`` → ``Tokenizer.scala``) and
  ``normalize`` (``Normalizer.scala``): host-side string ops.
* ``word2idx`` (``TextSet.scala:147`` → ``WordIndexer.scala``): frequency
  vocabulary, 1-based indices (0 = padding / OOV), ``remove_topN`` and
  ``max_words_num`` semantics kept.
* ``shape_sequence`` (``SequenceShaper.scala``): fixed-length pad/truncate —
  the XLA static-shape requirement makes this mandatory rather than optional.
* ``generate_sample`` (``TextSet.scala:177`` → ``TextFeatureToSample.scala``):
  dense int32 arrays ready for the ``FeatureSet`` infeed.

One process holds one host shard (the reference's DistributedTextSet role).
"""

from __future__ import annotations

import collections
import csv
import os
import re
import string
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..feature_set import BucketedFeatureSet, FeatureSet

__all__ = ["TextFeature", "TextSet"]

_PUNCT_RE = re.compile(f"[{re.escape(string.punctuation)}]")


class TextFeature:
    """One text record (``TextFeature.scala``): raw text, optional label,
    optional ``uri`` identifier (the reference keys relation corpora by
    URI), accumulated pipeline fields (tokens, indices)."""

    def __init__(self, text: str, label: Optional[int] = None,
                 uri: Optional[str] = None):
        self.text = text
        self.label = label
        self.uri = uri
        self.tokens: Optional[List[str]] = None
        self.indices: Optional[np.ndarray] = None

    def __repr__(self):
        return f"TextFeature({self.text[:30]!r}, label={self.label})"


class TextSet:
    def __init__(self, features: List[TextFeature],
                 word_index: Optional[Dict[str, int]] = None,
                 label_map: Optional[Dict[str, int]] = None):
        self.features = features
        self.word_index = word_index
        self.label_map = label_map

    # ---- factories (TextSet.scala:290,345) --------------------------------
    @staticmethod
    def from_pairs(pairs: Sequence[Tuple[str, Optional[int]]]) -> "TextSet":
        return TextSet([TextFeature(t, l) for t, l in pairs])

    @staticmethod
    def from_texts(texts: Sequence[str],
                   labels: Optional[Sequence[int]] = None) -> "TextSet":
        labels = labels if labels is not None else [None] * len(texts)
        return TextSet([TextFeature(t, l) for t, l in zip(texts, labels)])

    @staticmethod
    def from_corpus(mapping: Dict[str, str]) -> "TextSet":
        """An id→text corpus (the reference's URI-keyed relation corpora,
        ``TextSet.scala:399-470``); deterministic id order."""
        return TextSet([TextFeature(t, uri=i)
                        for i, t in sorted(mapping.items())])

    def indices_by_id(self) -> Dict[str, np.ndarray]:
        """URI → fixed-length index vector; requires the tokenize →
        word2idx → shape_sequence chain to have run."""
        out: Dict[str, np.ndarray] = {}
        for f in self.features:
            if f.uri is None:
                raise RuntimeError("corpus features need uris; build via "
                                   "TextSet.from_corpus")
            if f.indices is None:
                raise RuntimeError("run tokenize/word2idx/shape_sequence "
                                   "before indices_by_id()")
            out[f.uri] = f.indices
        return out

    @staticmethod
    def read(path: str) -> "TextSet":
        """Per-class-subdirectory corpus of ``.txt`` files
        (``TextSet.scala:290`` folder convention); labels by sorted class
        name."""
        classes = sorted(d for d in os.listdir(path)
                         if os.path.isdir(os.path.join(path, d)))
        if not classes:
            raise ValueError(f"{path}: need per-class subdirectories")
        label_map = {c: i for i, c in enumerate(classes)}
        feats = []
        for c in classes:
            d = os.path.join(path, c)
            for f in sorted(os.listdir(d)):
                if f.endswith(".txt"):
                    with open(os.path.join(d, f), encoding="utf-8") as fh:
                        feats.append(TextFeature(fh.read(), label_map[c]))
        return TextSet(feats, label_map=label_map)

    @staticmethod
    def from_csv(path: str, text_col: str = "text", label_col: str = "label",
                 ) -> "TextSet":
        """``readCSV`` (``TextSet.scala:345``)."""
        feats = []
        with open(path, newline="", encoding="utf-8") as fh:
            for row in csv.DictReader(fh):
                label = row.get(label_col)
                feats.append(TextFeature(
                    row[text_col], int(label) if label not in (None, "") else None))
        return TextSet(feats)

    @staticmethod
    def from_parquet(path: str, text_col: str = "text",
                     label_col: str = "label") -> "TextSet":
        """``readParquet`` (``TextSet.scala:372``) — columnar corpora via
        pyarrow (present in this environment; a clear error otherwise)."""
        try:
            import pyarrow.parquet as pq
        except ImportError as e:  # pragma: no cover - env without pyarrow
            raise ImportError(
                "TextSet.from_parquet needs pyarrow; install it or convert "
                "the corpus to csv for TextSet.from_csv") from e
        cols = set(pq.read_schema(path).names)
        if text_col not in cols:
            raise ValueError(f"{path}: no column {text_col!r} "
                             f"(have {sorted(cols)})")
        wanted = [text_col] + ([label_col] if label_col in cols else [])
        table = pq.read_table(path, columns=wanted)  # skip unused columns
        texts = table.column(text_col).to_pylist()
        labels = (table.column(label_col).to_pylist()
                  if label_col in cols else [None] * len(texts))
        feats = []
        for i, (t, l) in enumerate(zip(texts, labels)):
            if t is None:
                raise ValueError(
                    f"{path}: null text at row {i} — clean the corpus or "
                    f"drop null rows before loading")
            feats.append(TextFeature(t, None if l is None else int(l)))
        return TextSet(feats)

    # ---- protocol ---------------------------------------------------------
    def __len__(self) -> int:
        return len(self.features)

    @property
    def labels(self) -> Optional[np.ndarray]:
        if any(f.label is None for f in self.features):
            return None
        return np.asarray([f.label for f in self.features], np.int32)

    # ---- transformers -----------------------------------------------------
    def tokenize(self) -> "TextSet":
        """``Tokenizer.scala``: lowercase, strip punctuation, whitespace
        split (the reference chains Normalizer the same way)."""
        for f in self.features:
            cleaned = _PUNCT_RE.sub(" ", f.text.lower())
            f.tokens = cleaned.split()
        return self

    def word2idx(self, remove_top_n: int = 0,
                 max_words_num: int = -1,
                 existing_map: Optional[Dict[str, int]] = None) -> "TextSet":
        """``WordIndexer`` (``TextSet.scala:147``): build (or reuse) the
        frequency vocabulary; 1-based indices, 0 = padding/OOV. The
        ``remove_top_n`` most frequent words are dropped (stop-word
        heuristic), capped at ``max_words_num`` words."""
        if any(f.tokens is None for f in self.features):
            raise RuntimeError("call tokenize() before word2idx()")
        if existing_map is not None:
            self.word_index = dict(existing_map)
        else:
            counts = collections.Counter()
            for f in self.features:
                counts.update(f.tokens)
            ranked = [w for w, _ in counts.most_common()]
            ranked = ranked[remove_top_n:]
            if max_words_num > 0:
                ranked = ranked[:max_words_num]
            self.word_index = {w: i + 1 for i, w in enumerate(ranked)}
        wi = self.word_index
        for f in self.features:
            f.indices = np.asarray([wi.get(t, 0) for t in f.tokens], np.int32)
        return self

    def shape_sequence(self, length: int, trunc_mode: str = "pre",
                       pad_element: int = 0) -> "TextSet":
        """``SequenceShaper.scala``: pad (post) / truncate to ``length``.
        ``trunc_mode='pre'`` keeps the LAST ``length`` tokens (the
        reference's default), 'post' keeps the first."""
        if trunc_mode not in ("pre", "post"):
            raise ValueError("trunc_mode must be 'pre' or 'post'")
        for f in self.features:
            if f.indices is None:
                raise RuntimeError("call word2idx() before shape_sequence()")
            idx = f.indices
            if len(idx) > length:
                idx = idx[-length:] if trunc_mode == "pre" else idx[:length]
            elif len(idx) < length:
                idx = np.concatenate(
                    [idx, np.full(length - len(idx), pad_element, np.int32)])
            f.indices = idx
        return self

    def generate_sample(self) -> FeatureSet:
        """``TextFeatureToSample`` (``TextSet.scala:177``): dense arrays into
        the training FeatureSet."""
        if any(f.indices is None for f in self.features):
            raise RuntimeError("run tokenize/word2idx/shape_sequence first")
        lens = {len(f.indices) for f in self.features}
        if len(lens) != 1:
            raise ValueError(f"ragged sequences {sorted(lens)}; call "
                             "shape_sequence(length) first")
        x = np.stack([f.indices for f in self.features])
        return FeatureSet.array(x, self.labels)

    def to_arrays(self) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        fs = self.generate_sample()
        return fs.x, fs.y

    def to_bucketed(self, lengths, trunc_mode: str = "pre",
                    shuffle: bool = True, seed: int = 0
                    ) -> BucketedFeatureSet:
        """Length-bucketed alternative to ``shape_sequence`` + one
        FeatureSet: each text pads to the SMALLEST bucket length that fits
        it (the longest bucket truncates), so short texts stop paying
        full-length padding compute. Returns a ``BucketedFeatureSet``."""
        if trunc_mode not in ("pre", "post"):
            raise ValueError("trunc_mode must be 'pre' or 'post'")
        lengths = sorted({int(ln) for ln in lengths})
        if not lengths:
            raise ValueError("need at least one bucket length")
        groups: Dict[int, Tuple[list, list]] = {ln: ([], [])
                                               for ln in lengths}
        for f in self.features:
            if f.indices is None:
                raise RuntimeError("call tokenize() and word2idx() first")
            idx = np.asarray(f.indices, np.int32)
            ln = next((b for b in lengths if len(idx) <= b), lengths[-1])
            if len(idx) > ln:
                idx = idx[-ln:] if trunc_mode == "pre" else idx[:ln]
            elif len(idx) < ln:
                idx = np.concatenate(
                    [idx, np.zeros(ln - len(idx), np.int32)])
            xs, ys = groups[ln]
            xs.append(idx)
            ys.append(f.label)
        buckets = []
        for ln in lengths:
            xs, ys = groups[ln]
            if not xs:
                continue
            y = (np.asarray(ys, np.int32)
                 if all(v is not None for v in ys) else None)
            buckets.append(FeatureSet(np.stack(xs), y, shuffle=shuffle,
                                      seed=seed))
        return BucketedFeatureSet(buckets, shuffle=shuffle, seed=seed)

    def get_word_index(self) -> Dict[str, int]:
        if self.word_index is None:
            raise RuntimeError("word2idx() has not run")
        return self.word_index

