"""Text pipeline (``feature/text`` of the reference, L2)."""

from .relations import (Relation, RelationPair, generate_relation_pairs,
                        read_relations, relation_lists_to_groups,
                        relation_pairs_to_arrays)
from .text_set import TextFeature, TextSet

__all__ = ["TextFeature", "TextSet", "Relation", "RelationPair",
           "read_relations", "generate_relation_pairs",
           "relation_pairs_to_arrays", "relation_lists_to_groups"]
