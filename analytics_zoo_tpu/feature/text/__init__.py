"""Text pipeline (``feature/text`` of the reference, L2)."""

from .text_set import TextFeature, TextSet

__all__ = ["TextFeature", "TextSet"]
