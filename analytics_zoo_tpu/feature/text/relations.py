"""Relations — the QA-ranking data path, parity with
``feature/common/Relations.scala:26-160`` and the relation-pair/list TextSet
factories (``feature/text/TextSet.scala:399-533``).

A ``Relation(id1, id2, label)`` links a query to a candidate document with a
relevance label. Training consumes *pairs* (each positive of a query crossed
with each of its negatives; rows interleaved pos/neg for the ``rank_hinge``
loss), evaluation consumes *lists* (every candidate of a query as one group
for NDCG/MAP/HitRate via ``RankerMixin``). The reference materializes these
through Spark joins on URI-keyed RDDs; here corpora are id→indices maps and
the joins are dict lookups — arrays come out dense and static-shaped for the
jitted step.
"""

from __future__ import annotations

import collections
from typing import Dict, List, NamedTuple, Sequence, Tuple, Union

import numpy as np

from .text_set import TextSet

__all__ = ["Relation", "RelationPair", "read_relations",
           "generate_relation_pairs", "relation_pairs_to_arrays",
           "relation_lists_to_groups"]


class Relation(NamedTuple):
    id1: str
    id2: str
    label: int


class RelationPair(NamedTuple):
    id1: str
    id2_positive: str
    id2_negative: str


def read_relations(path: str) -> List[Relation]:
    """``Relations.read`` (``Relations.scala:44-67``): csv/txt lines of
    ``id1,id2,label`` (no header)."""
    out: List[Relation] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            parts = line.split(",")
            if len(parts) < 3:
                raise ValueError(f"{path}: bad relation line {line!r}")
            out.append(Relation(parts[0], parts[1], int(parts[2])))
    return out


def generate_relation_pairs(
        relations: Sequence[Relation]) -> List[RelationPair]:
    """``Relations.generateRelationPairs`` (``Relations.scala:88+``): for
    each query, every positive (label > 0) crosses every negative
    (label == 0). Deterministic order (query, positive, negative)."""
    by_q: Dict[str, Tuple[List[str], List[str]]] = collections.OrderedDict()
    for r in relations:
        pos, neg = by_q.setdefault(r.id1, ([], []))
        (pos if r.label > 0 else neg).append(r.id2)
    pairs: List[RelationPair] = []
    for q, (pos, neg) in by_q.items():
        for p in pos:
            for n in neg:
                pairs.append(RelationPair(q, p, n))
    return pairs


def _corpus_map(corpus: Union[TextSet, Dict[str, np.ndarray]]
                ) -> Dict[str, np.ndarray]:
    if isinstance(corpus, TextSet):
        return corpus.indices_by_id()
    return {k: np.asarray(v, np.int32) for k, v in corpus.items()}


def _lookup(m: Dict[str, np.ndarray], key: str, side: str) -> np.ndarray:
    try:
        return m[key]
    except KeyError:
        raise KeyError(f"relation id {key!r} missing from {side}") from None


def relation_pairs_to_arrays(
        relations: Sequence[Relation],
        corpus1: Union[TextSet, Dict[str, np.ndarray]],
        corpus2: Union[TextSet, Dict[str, np.ndarray]],
) -> Tuple[np.ndarray, np.ndarray]:
    """``TextSet.fromRelationPairs`` (``TextSet.scala:399-470``): join pairs
    with both corpora and emit ``(x, y)`` where ``x`` is
    ``(2 * n_pairs, len1 + len2)`` int32 — row ``2i`` = [query ++ positive],
    row ``2i+1`` = [query ++ negative], exactly the consecutive-pair layout
    ``rank_hinge`` consumes (train UNSHUFFLED, keep batch sizes even). ``y``
    is the matching 1/0 labels (unused by rank_hinge; usable for AUC)."""
    c1, c2 = _corpus_map(corpus1), _corpus_map(corpus2)
    rows: List[np.ndarray] = []
    for pair in generate_relation_pairs(relations):
        q = _lookup(c1, pair.id1, "corpus1")
        rows.append(np.concatenate(
            [q, _lookup(c2, pair.id2_positive, "corpus2")]))
        rows.append(np.concatenate(
            [q, _lookup(c2, pair.id2_negative, "corpus2")]))
    if not rows:
        raise ValueError("no relation pairs (no query has both a positive "
                         "and a negative)")
    x = np.stack(rows).astype(np.int32)
    y = np.tile(np.asarray([1, 0], np.float32), len(rows) // 2)
    return x, y


def relation_lists_to_groups(
        relations: Sequence[Relation],
        corpus1: Union[TextSet, Dict[str, np.ndarray]],
        corpus2: Union[TextSet, Dict[str, np.ndarray]],
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """``TextSet.fromRelationLists`` (``TextSet.scala:503-533``): one
    ``(x, y)`` group per query over ALL its candidates — the input
    ``RankerMixin.evaluate_ndcg/evaluate_map/evaluate_hit_rate`` take."""
    c1, c2 = _corpus_map(corpus1), _corpus_map(corpus2)
    by_q: Dict[str, List[Relation]] = collections.OrderedDict()
    for r in relations:
        by_q.setdefault(r.id1, []).append(r)
    groups: List[Tuple[np.ndarray, np.ndarray]] = []
    for q, rels in by_q.items():
        qv = _lookup(c1, q, "corpus1")
        x = np.stack([np.concatenate([qv, _lookup(c2, r.id2, "corpus2")])
                      for r in rels]).astype(np.int32)
        y = np.asarray([r.label for r in rels], np.float32)
        groups.append((x, y))
    return groups
