from .context import ZooContext, init_zoo_context, get_zoo_context, reset_zoo_context  # noqa: F401
from .reliability import (CircuitBreaker, CircuitOpenError,  # noqa: F401
                          RetryPolicy)
from .triggers import (EveryEpoch, SeveralIteration, MaxEpoch, MaxIteration,  # noqa: F401
                       MinLoss, TrainLoopState, Trigger)
