"""Reliability primitives — retry/backoff and circuit breaking.

The reference stack runs Cluster Serving as a long-lived service on
Spark/Redis where transient backend failures are the norm (dropped Redis
connections, slow result stores, flaky device links); its recovery story
is Spark's task re-execution plus ``bigdl.failure.retryTimes``
(``Topology.scala:1172``). This module is the TPU-native equivalent,
following classic exponential-backoff / circuit-breaker practice and the
supervisor discipline of Ray's actor-restart model:

* :class:`RetryPolicy` — exponential backoff with **full jitter**
  (delay ~ U[0, min(max_delay, base*2^k)]), a deadline cap, bounded
  attempts, and retryable-error classification. Seeded policies produce
  the exact same backoff sequence every time — chaos tests reconcile
  against it deterministically.
* :class:`CircuitBreaker` — closed → open → half-open with single-probe
  admission. Consecutive failures trip it open; after ``reset_timeout``
  exactly one probe call is admitted; a probe success closes it, a probe
  failure re-opens it with a fresh window. State and transitions are
  exported as ``zoo_breaker_state{breaker=}`` /
  ``zoo_breaker_transitions_total{breaker=,state=}``.
* :class:`RetryBudget` — a GLOBAL deterministic token bucket shared by
  every caller of a resource: retries withdraw, successes deposit, an
  empty bucket refuses further retries
  (``zoo_retry_budget_exhausted_total{budget=}``) so a correlated outage
  cannot multiply load fleet-wide the way per-caller backoff alone
  allows.
* :class:`AIMDController` — bounded additive-increase /
  multiplicative-decrease control (the TCP congestion-avoidance shape):
  a healthy signal grows the value additively toward a ceiling, a breach
  backs it off multiplicatively toward a floor. Deterministic by
  construction (no RNG) — the serving loop's adaptive batch sizing
  reconciles its target sequence exactly under test.

Consumers: ``serving/resp.py`` (transparent reconnect), ``serving/
backend.py`` (bounded full-stream waits), ``serving/server.py``
(supervised loops, breaker-guarded reads, dispatch retries), and
``pipeline/inference/inference_model.py`` (chunk readback retries).
Policies/fault recipes are cataloged in ``docs/guides/RELIABILITY.md``.

Nothing here imports jax — the module is importable from any host-side
path (clients, scripts) without touching a device runtime.
"""

from __future__ import annotations

import itertools
import logging
import random
import threading
import time
from typing import Callable, Iterator, Optional, Tuple, Type

log = logging.getLogger("analytics_zoo_tpu.reliability")

__all__ = ["RetryPolicy", "RetryBudget", "CircuitBreaker",
           "CircuitOpenError", "AIMDController"]

#: default transient-transport classification: connection drops, socket
#: errors and timeouts retry; everything else (protocol errors, bugs)
#: propagates immediately
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    ConnectionError, OSError, TimeoutError)


class CircuitOpenError(RuntimeError):
    """The breaker is open: the protected resource failed repeatedly and
    the cool-down window has not elapsed — fail fast instead of adding
    load to a struggling backend. ``retry_in`` is the seconds until the
    next half-open probe is admitted."""

    def __init__(self, name: str, retry_in: float):
        super().__init__(f"circuit {name!r} is open; next probe in "
                         f"{retry_in:.3f}s")
        self.breaker = name
        self.retry_in = retry_in


class RetryBudget:
    """Global retry token bucket — the fleet-wide brake on correlated
    retries (the classic Finagle/SRE "retry budget": per-op backoff
    bounds ONE caller, but when a whole backend goes down every caller
    retries ``max_attempts`` times at once and the retry storm multiplies
    the outage load).

    Semantics (deterministic — no RNG, so chaos tests reconcile exactly):

    * each **retry** withdraws one token (``withdraw()`` → False once the
      bucket is empty; the caller must NOT retry, counting the refusal in
      ``zoo_retry_budget_exhausted_total{budget=...}``),
    * each **success** deposits ``deposit`` tokens (capped at
      ``capacity``), so the sustained retry rate is bounded at roughly
      ``deposit`` retries per success — a healthy system earns its retry
      allowance, a broken one drains the bucket once and then fails fast.

    One budget is meant to be SHARED across every caller of a protected
    resource (pass the same instance to each ``RetryPolicy.call`` /
    ``ClusterServing(retry_budget=...)``); all methods are thread-safe.
    """

    def __init__(self, capacity: float = 100.0, deposit: float = 0.1,
                 name: str = "default", registry=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1 ({capacity})")
        if deposit < 0:
            raise ValueError(f"deposit must be >= 0 ({deposit})")
        self.capacity = float(capacity)
        self.deposit = float(deposit)
        self.name = name
        self._tokens = float(capacity)
        self._lock = threading.Lock()
        self._m_exhausted = None
        if registry is not None:
            # budget names are operator-chosen code identifiers (a
            # handful per process), not request data
            self._m_exhausted = registry.counter(  # zoolint: disable=ZL015 bounded label set
                "zoo_retry_budget_exhausted_total",
                "retries refused because the shared retry budget was "
                "empty (a correlated outage draining the bucket)",
                labels={"budget": name})

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def withdraw(self) -> bool:
        """Take one token for a retry. False (and a count in the
        exhausted metric) when the bucket is empty — the caller must not
        retry."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
        if self._m_exhausted is not None:
            self._m_exhausted.inc()
        log.warning("retry budget %r exhausted; refusing retry", self.name)
        return False

    def on_success(self) -> None:
        """Deposit after a successful call (retried or not)."""
        with self._lock:
            self._tokens = min(self.capacity, self._tokens + self.deposit)


class RetryPolicy:
    """Exponential backoff with full jitter, bounded attempts, a deadline
    cap, and error classification.

    * ``max_attempts`` — total tries (1 = no retry). :meth:`delays`
      yields at most ``max_attempts - 1`` backoff values.
    * ``base_delay`` / ``max_delay`` — the k-th retry waits
      ``U[0, min(max_delay, base_delay * 2**k)]`` seconds (full jitter;
      ``jitter=False`` uses the envelope itself, for tests that need
      exact wall bounds).
    * ``deadline`` — optional RELATIVE seconds budget applied per
      :meth:`call`/:meth:`wait_for` invocation; a per-call ``timeout``
      overrides it. Delays are trimmed to the remaining budget and the
      sequence stops once it is exhausted — a retried operation can
      never overshoot its caller's deadline by more than one attempt.
    * ``retryable`` / per-call ``classify`` — which exceptions retry.
      Idempotent reads retry by default; non-idempotent writes must be
      classified per-op by the caller (cf. ``serving/resp.py``: XADD
      never retries, a duplicate stream entry is worse than an error).
    * ``seed`` — deterministic jitter: the same seed yields the same
      delay sequence on every call (chaos tests depend on this).

    Policies are immutable and thread-safe; generators returned by
    :meth:`delays` are single-use like any generator.
    """

    def __init__(self, max_attempts: int = 5, base_delay: float = 0.05,
                 max_delay: float = 2.0, deadline: Optional[float] = None,
                 retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE,
                 jitter: bool = True, seed: Optional[int] = None):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1 ({max_attempts})")
        if base_delay < 0 or max_delay < 0:
            raise ValueError("delays must be >= 0")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = deadline
        self.retryable = tuple(retryable)
        self.jitter = bool(jitter)
        self.seed = seed

    def __repr__(self) -> str:
        return (f"RetryPolicy(max_attempts={self.max_attempts}, "
                f"base_delay={self.base_delay}, max_delay={self.max_delay}, "
                f"deadline={self.deadline}, seed={self.seed})")

    # -- the backoff sequence ------------------------------------------------
    def _rng(self):
        # a FRESH generator per sequence: a seeded policy must produce the
        # same delays every time it is consulted, not a continuation
        return random.Random(self.seed) if self.seed is not None else random

    def _envelope(self, k: int) -> float:
        # cap the doubling exponent: 2.0**k overflows a float past ~1024
        # rounds, and the envelope saturates at max_delay long before —
        # a long-lived wait_for poll must not crash at poll 1025
        return min(self.max_delay, self.base_delay * (2.0 ** min(k, 64)))

    def delays(self, deadline: Optional[float] = None) -> Iterator[float]:
        """Yield the sleep before each retry (so ``max_attempts - 1``
        values at most). ``deadline`` is an ABSOLUTE ``time.monotonic()``
        stamp (defaults to now + ``self.deadline`` when the policy has
        one); each delay is trimmed to the remaining budget and the
        sequence ends once the budget is spent."""
        if deadline is None and self.deadline is not None:
            deadline = time.monotonic() + self.deadline
        rng = self._rng()
        start = time.monotonic()
        yielded = 0.0
        for k in range(self.max_attempts - 1):
            env = self._envelope(k)
            d = rng.uniform(0.0, env) if self.jitter else env
            if deadline is not None:
                # budget spent = real elapsed OR the delays already
                # handed out, whichever is larger — so the cap holds both
                # for real sleepers and for test consumers with a no-op
                # sleep (deterministic truncation)
                spent = max(time.monotonic() - start, yielded)
                remaining = (deadline - start) - spent
                if remaining <= 0:
                    return
                d = min(d, remaining)
            yield d
            yielded += d

    # -- classification ------------------------------------------------------
    def should_retry(self, exc: BaseException,
                     classify: Optional[Callable[[BaseException], bool]]
                     = None) -> bool:
        if classify is not None:
            return bool(classify(exc))
        return isinstance(exc, self.retryable)

    # -- wrappers ------------------------------------------------------------
    def call(self, fn: Callable, *, op: str = "op",
             classify: Optional[Callable[[BaseException], bool]] = None,
             sleep: Callable[[float], None] = time.sleep,
             timeout: Optional[float] = None, registry=None,
             budget: Optional["RetryBudget"] = None):
        """Run ``fn()`` with retries. Non-retryable errors propagate
        immediately; retryable ones back off and re-run until attempts or
        the deadline run out, then the LAST error propagates. Each retry
        increments ``zoo_retry_attempts_total{op=...}`` in ``registry``
        (when given) and logs at warning level — silent retries hide a
        dying backend until it is fully dead.

        ``budget`` (a shared :class:`RetryBudget`) additionally gates
        every retry on the GLOBAL token bucket — an exhausted budget
        raises the last error immediately instead of piling this caller's
        retries onto a correlated outage; successes deposit back."""
        deadline = None
        time_budget = self.deadline if timeout is None else timeout
        if time_budget is not None:
            deadline = time.monotonic() + time_budget
        last: Optional[BaseException] = None
        counter = None
        if registry is not None:
            # op names are call-site string constants (one per
            # retried operation), not request data
            counter = registry.counter(  # zoolint: disable=ZL015 bounded label set
                "zoo_retry_attempts_total",
                "retries performed by reliability.RetryPolicy, by operation",
                labels={"op": op})
        for d in itertools.chain((None,), self.delays(deadline)):
            if d is not None:
                if budget is not None and not budget.withdraw():
                    log.warning("%s: retry budget exhausted after (%s); "
                                "not retrying", op, last)
                    break
                if counter is not None:
                    counter.inc()
                log.warning("%s failed (%s); retry in %.3fs", op, last, d)
                if d > 0:
                    sleep(d)
            try:
                result = fn()
            except Exception as e:
                if not self.should_retry(e, classify):
                    raise
                last = e
            else:
                if budget is not None:
                    budget.on_success()
                return result
        assert last is not None
        raise last

    def wait_for(self, predicate: Callable[[], bool], *,
                 timeout: Optional[float] = None,
                 sleep: Callable[[float], None] = time.sleep) -> bool:
        """Poll ``predicate`` with backoff until it is truthy (→ True) or
        the deadline passes (→ False). Unlike :meth:`call`, attempts are
        unbounded — the deadline is the bound (``timeout`` falls back to
        the policy's ``deadline``; with neither, polls forever — give
        long-lived pollers a default timeout, cf. the serving backends).
        The first check is immediate; delays then follow the jittered
        envelope, trimmed so the final sleep lands on the deadline."""
        deadline = None
        budget = self.deadline if timeout is None else timeout
        if budget is not None:
            deadline = time.monotonic() + budget
        rng = self._rng()
        for k in itertools.count():
            if predicate():
                return True
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
            env = self._envelope(k)
            d = rng.uniform(0.0, env) if self.jitter else env
            if deadline is not None:
                d = min(d, max(deadline - time.monotonic(), 0.0))
            if d > 0:
                sleep(d)
        return False    # unreachable (itertools.count never ends)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

#: gauge encoding of breaker state — documented in OBSERVABILITY.md
_STATE_VALUE = {"closed": 0, "open": 1, "half_open": 2}


class CircuitBreaker:
    """Closed → open → half-open breaker with single-probe admission.

    * **closed** — calls flow; ``failure_threshold`` CONSECUTIVE
      failures (any success resets the count) trip it open.
    * **open** — :meth:`allow` refuses (callers fail fast / back off
      instead of hammering a down backend) until ``reset_timeout``
      seconds have passed.
    * **half-open** — exactly ONE probe call is admitted; its success
      closes the breaker, its failure re-opens it with a fresh window.
      Further :meth:`allow` calls while the probe is in flight refuse.

    Use either the low-level surface (``allow`` / ``record_success`` /
    ``record_failure`` — how the serve loop wraps its stream reads, so a
    refused read can *wait* instead of raising) or :meth:`call`, which
    raises :class:`CircuitOpenError` when refused.

    ``clock`` is injectable for deterministic tests. All methods are
    thread-safe. State is exported on every transition:
    ``zoo_breaker_state{breaker=name}`` (0 closed / 1 open / 2
    half-open) and ``zoo_breaker_transitions_total{breaker=,state=}``.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, name: str = "breaker", failure_threshold: int = 5,
                 reset_timeout: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if reset_timeout <= 0:
            raise ValueError("reset_timeout must be > 0")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probe_inflight = False
        self._registry = registry
        self._gauge = None
        if registry is not None:
            # breaker names are code-defined identifiers, one per
            # guarded dependency
            self._gauge = registry.gauge(  # zoolint: disable=ZL015 bounded label set
                "zoo_breaker_state",
                "circuit state: 0 closed, 1 open, 2 half-open",
                labels={"breaker": name})
            self._gauge.set(_STATE_VALUE[self.CLOSED])

    # -- state machine (call under self._lock) -------------------------------
    def _transition(self, new_state: str) -> None:
        if new_state == self._state:
            return
        self._state = new_state
        if self._gauge is not None:
            self._gauge.set(_STATE_VALUE[new_state])
        if self._registry is not None:
            # breaker = code identifier, state = the 3-value enum
            self._registry.counter(  # zoolint: disable=ZL015 bounded label set
                "zoo_breaker_transitions_total",
                "circuit state transitions, labeled by the state entered",
                labels={"breaker": self.name, "state": new_state}).inc()
            self._registry.emit("breaker.transition", breaker=self.name,
                                state=new_state)
        log.info("circuit %r -> %s", self.name, new_state)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def probe_in(self) -> float:
        """Seconds until the next probe would be admitted (0 when calls
        are currently allowed)."""
        with self._lock:
            if self._state == self.CLOSED:
                return 0.0
            if self._state == self.HALF_OPEN:
                return 0.0 if not self._probe_inflight else self.reset_timeout
            assert self._opened_at is not None
            return max(self._opened_at + self.reset_timeout - self._clock(),
                       0.0)

    def allow(self) -> bool:
        """Whether a call may proceed now. In half-open, admits exactly
        one probe — the caller MUST resolve it with ``record_success`` /
        ``record_failure`` (or further probes stay refused until the
        reset window elapses again)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                assert self._opened_at is not None
                if self._clock() - self._opened_at < self.reset_timeout:
                    return False
                self._transition(self.HALF_OPEN)
                self._probe_inflight = False
            # half-open: one probe only
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                # the probe failed: back to open with a fresh window
                self._probe_inflight = False
                self._opened_at = self._clock()
                self._transition(self.OPEN)
                return
            self._failures += 1
            if self._state == self.CLOSED \
                    and self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._transition(self.OPEN)

    # -- wrapper -------------------------------------------------------------
    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` under the breaker: raises :class:`CircuitOpenError`
        when refused; otherwise records the outcome and re-raises any
        failure."""
        if not self.allow():
            raise CircuitOpenError(self.name, self.probe_in())
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result


# ---------------------------------------------------------------------------
# AIMD controller
# ---------------------------------------------------------------------------

class AIMDController:
    """Bounded additive-increase / multiplicative-decrease controller.

    The control shape TCP congestion avoidance (and Clipper-style
    adaptive batching) uses: while the observed signal is healthy the
    value climbs ``add`` per update toward ``ceiling``; one breach backs
    it off by ``backoff`` (multiplicative), never below ``floor``. The
    asymmetry is the point — growth probes capacity slowly, a breach
    sheds it immediately, and the loop converges instead of oscillating
    wall to wall.

    Deterministic: no RNG, no clock — the value after N updates is a
    pure function of the breach sequence, so tests reconcile the target
    trajectory exactly. Thread-safe; ``value`` reads the current target
    without updating it."""

    def __init__(self, floor: int = 1, ceiling: int = 32,
                 initial: Optional[int] = None, add: float = 1.0,
                 backoff: float = 0.5):
        if floor < 1:
            raise ValueError(f"floor must be >= 1 ({floor})")
        if ceiling < floor:
            raise ValueError(f"ceiling {ceiling} < floor {floor}")
        if add <= 0:
            raise ValueError(f"add must be > 0 ({add})")
        if not 0.0 < backoff < 1.0:
            raise ValueError(f"backoff must be in (0, 1) ({backoff})")
        self.floor = int(floor)
        self.ceiling = int(ceiling)
        self.add = float(add)
        self.backoff = float(backoff)
        v = ceiling if initial is None else initial
        if not floor <= v <= ceiling:
            raise ValueError(f"initial {v} outside [{floor}, {ceiling}]")
        self._value = float(v)
        self._lock = threading.Lock()

    @property
    def value(self) -> int:
        with self._lock:
            return int(self._value)

    def update(self, overloaded: bool) -> int:
        """One control step: ``overloaded=True`` backs off
        multiplicatively, ``False`` grows additively. Returns the new
        integer target."""
        with self._lock:
            if overloaded:
                self._value = max(float(self.floor),
                                  self._value * self.backoff)
            else:
                self._value = min(float(self.ceiling), self._value + self.add)
            return int(self._value)
