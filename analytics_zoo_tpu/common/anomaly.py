"""Anomaly sentinels — on-device divergence detection for the training
step (docs/guides/TRAINING.md "Anomaly detection & recovery").

The reference platform survived *executor* failures through Spark's
lineage recompute (``Topology.scala:1171-1253``); the loop itself had no
defense against the most common production failure: **numerical
divergence**. One poison batch or an fp-overflow NaNs the params and
every subsequent step silently trains garbage until a human reads the
loss curve. This module is the training-side sibling of serving's
poison-record isolation (``serving/server.py`` solo re-dispatch +
dead-letter): detect the bad step on device, contain it (skip the
update), and escalate to rollback when skipping is not enough.

Design constraints (all enforced here, consumed by
``pipeline/api/keras/training.py``):

* **Cheap and fused.** The checks are a handful of scalar ops folded
  into the already-compiled train step: non-finite loss, non-finite
  global gradient norm, and a relative spike of the gradient norm
  against its own EWMA baseline. They ride the step's XLA program — no
  extra dispatch, no extra host sync.
* **One packed scalar.** All flags come back as ONE int32 bitmask per
  step (a ``(K,)`` vector per scan chunk), read by the host alongside
  the loss it already reads back — see :data:`NAN_LOSS` /
  :data:`NAN_GRAD` / :data:`SPIKE` / :data:`GRAD_CLIPPED`.
* **Deterministic.** No RNG, no clock: the EWMA baseline is a pure
  function of the observed gradient norms (anomalous steps never teach
  it), so chaos tests reconcile the flagged-step set exactly against an
  injected ``train.grads`` fault plan, and ``zoo.train.sentinel=off``
  builds the exact step of a sentinel-free build (bit-identical
  numerics — the sentinel ops are gated at build time, not runtime).

Knobs (``docs/guides/CONFIG.md``): ``zoo.train.sentinel``
(``off|warn|recover``), ``zoo.train.spike_factor``,
``zoo.train.grad_clip``, ``zoo.train.max_skips_per_epoch``,
``zoo.train.max_rollbacks``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import jax.numpy as jnp

from .context import get_zoo_context

__all__ = ["NAN_LOSS", "NAN_GRAD", "SPIKE", "ANOMALY_MASK", "GRAD_CLIPPED",
           "SentinelConfig", "resolve_config", "init_state", "check",
           "global_norm", "clip_by_global_norm", "inject_grads",
           "kinds_of", "FAULT_CODES", "EWMA_ALPHA", "WARMUP_STEPS",
           "EWMA_FLOOR"]

# -- the packed per-step flag word -------------------------------------------
#: loss came back non-finite (NaN/inf)
NAN_LOSS = 1
#: loss finite but the global gradient norm is non-finite
NAN_GRAD = 2
#: finite gradient norm spiked past spike_factor x its EWMA baseline
SPIKE = 4
#: any bit in here marks the step anomalous (recover mode discards it)
ANOMALY_MASK = NAN_LOSS | NAN_GRAD | SPIKE
#: informational: global-norm gradient clipping engaged this step
#: (zoo.train.grad_clip) — NOT an anomaly, never triggers a skip
GRAD_CLIPPED = 8

#: bit → metric label for zoo_train_anomaly_total{kind=}
KIND_BITS: Tuple[Tuple[int, str], ...] = (
    (NAN_LOSS, "nan_loss"), (NAN_GRAD, "nan_grad"), (SPIKE, "spike"))

#: ``train.grads`` fault-plan kind → the on-device poison code the host
#: feeds the step (0 = no fault); see :func:`inject_grads`
FAULT_CODES = {"nan_loss": 1, "nan_grad": 2, "spike": 3}

#: EWMA smoothing for the gradient-norm baseline: norm_t contributes
#: alpha, history (1-alpha). 0.1 tracks the slow decay of a healthy
#: norm while a 10x one-step spike still stands ~9x above the baseline.
EWMA_ALPHA = 0.1
#: observed (non-anomalous) steps before the spike check engages — the
#: first steps of a run legitimately swing the norm while the optimizer
#: finds scale, and an unprimed EWMA would flag them all
WARMUP_STEPS = 5
#: spike check additionally requires the baseline itself to stand above
#: this floor: a (near-)zero EWMA — fully-masked warmup window, frozen
#: phase, dead-ReLU start — makes the RELATIVE test meaningless (any
#: first real gradient would flag, recover mode would skip it, params
#: and baseline would never move, and the loop would livelock into
#: rollback escalation on a perfectly healthy run). Below the floor the
#: non-finite checks still guard; the spike check waits for a baseline.
EWMA_FLOOR = 1e-8


@dataclasses.dataclass(frozen=True)
class SentinelConfig:
    """Build-time resolution of the sentinel/clipping knobs — resolved
    ONCE per :class:`TrainingLoop` (like the fused-loss resolution) so
    every step builder of a loop agrees, and a ``sentinel=off`` loop
    builds steps with zero sentinel ops in them."""

    mode: str            # off | warn | recover
    spike_factor: float
    grad_clip: float     # 0 = off
    faults: bool         # step accepts per-step train.grads poison codes
    max_skips_per_epoch: int
    max_rollbacks: int

    @property
    def sentinel(self) -> bool:
        return self.mode != "off"

    @property
    def active(self) -> bool:
        """Whether the step builders must emit the extended signature
        (sentinel state carry and/or packed-flag output)."""
        return self.sentinel or self.grad_clip > 0


def resolve_config() -> SentinelConfig:
    """Read and validate the ``zoo.train.*`` sentinel knobs."""
    ctx = get_zoo_context()
    raw = ctx.get("zoo.train.sentinel", "off")
    mode = str(raw).strip().lower() if raw is not None else "off"
    from .context import FALSE_FLAG_SPELLINGS
    if mode in FALSE_FLAG_SPELLINGS:
        mode = "off"
    if mode not in ("off", "warn", "recover"):
        raise ValueError(f"zoo.train.sentinel must be off|warn|recover, "
                         f"got {raw!r}")
    spike_factor = float(ctx.get("zoo.train.spike_factor", 10.0))
    # the sentinel-only knobs are validated only when the sentinel is
    # on: a (mis-)configured value for a disabled feature must not
    # abort training that never reads it (grad_clip stands alone)
    if mode != "off" and spike_factor <= 1.0:
        raise ValueError(f"zoo.train.spike_factor must be > 1 "
                         f"({spike_factor})")
    grad_clip = float(ctx.get("zoo.train.grad_clip", 0.0) or 0.0)
    if grad_clip < 0:
        raise ValueError(f"zoo.train.grad_clip must be >= 0 ({grad_clip})")
    max_skips = int(ctx.get("zoo.train.max_skips_per_epoch", 8))
    if mode != "off" and max_skips < 0:
        raise ValueError(f"zoo.train.max_skips_per_epoch must be >= 0 "
                         f"({max_skips})")
    max_rollbacks = int(ctx.get("zoo.train.max_rollbacks", 3))
    if mode != "off" and max_rollbacks < 1:
        raise ValueError(f"zoo.train.max_rollbacks must be >= 1 "
                         f"({max_rollbacks})")
    faults = bool(ctx.get("zoo.faults.enabled", False))
    return SentinelConfig(mode=mode, spike_factor=spike_factor,
                          grad_clip=grad_clip,
                          faults=faults and mode != "off",
                          max_skips_per_epoch=max_skips,
                          max_rollbacks=max_rollbacks)


# ---------------------------------------------------------------------------
# on-device pieces (called from inside the jitted step builders)
# ---------------------------------------------------------------------------

def init_state() -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fresh EWMA carry ``(baseline_norm, observed_count)`` — two f32
    scalars threaded through the step/scan like the rest of the carry."""
    return (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))


def global_norm(grads) -> jnp.ndarray:
    """Global L2 norm of a gradient tree, accumulated in f32 regardless
    of the compute dtype (a bf16 partial sum would overflow exactly on
    the exploding gradients this exists to catch)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    return jnp.sqrt(sq)


def check(loss, gnorm, state, spike_factor: float):
    """Classify one step: returns ``(flags, new_state)``.

    The three kinds are mutually exclusive by construction (checked in
    severity order), so the host's per-kind counters partition the
    anomalies exactly. An anomalous step never updates the EWMA baseline
    — a spike folded into its own baseline would mask the next one."""
    ewma, count = state
    loss32 = loss.astype(jnp.float32)
    nan_loss = ~jnp.isfinite(loss32)
    nan_grad = jnp.isfinite(loss32) & ~jnp.isfinite(gnorm)
    warmed = (count >= WARMUP_STEPS) & (ewma >= EWMA_FLOOR)
    spike = (jnp.isfinite(loss32) & jnp.isfinite(gnorm) & warmed
             & (gnorm > spike_factor * ewma))
    flags = (jnp.where(nan_loss, NAN_LOSS, 0)
             | jnp.where(nan_grad, NAN_GRAD, 0)
             | jnp.where(spike, SPIKE, 0)).astype(jnp.int32)
    anomalous = flags > 0
    seeded = jnp.where(count > 0,
                       (1.0 - EWMA_ALPHA) * ewma + EWMA_ALPHA * gnorm,
                       gnorm)
    new_ewma = jnp.where(anomalous, ewma, seeded)
    new_count = jnp.where(anomalous, count, count + 1.0)
    return flags, (new_ewma, new_count)


def clip_by_global_norm(grads, gnorm, clip: float):
    """Scale the tree so its global norm is at most ``clip``; returns
    ``(clipped_grads, engaged)``. A NON-FINITE norm leaves the grads
    untouched and ``engaged`` false: ``clip/inf`` is 0, and silently
    zeroing every (finite) leaf would turn an overflowing step into an
    undetected no-op update — the divergence must stay visible (and,
    with the sentinels on, flagged) rather than be masked by the
    clipper."""
    finite = jnp.isfinite(gnorm)
    scale = jnp.where(finite,
                      jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-16)),
                      1.0)
    engaged = finite & (gnorm > clip)
    clipped = jax.tree.map(lambda g: g * scale.astype(g.dtype), grads)
    return clipped, engaged


def inject_grads(loss, grads, code, scale):
    """Apply a ``train.grads`` fault-plan entry on device (chaos only —
    compiled into the step only when ``zoo.faults.enabled`` was set at
    build time). ``code`` follows :data:`FAULT_CODES`; ``scale`` is the
    spike multiplier. ``code == 0`` is an exact no-op on the values."""
    nan = jnp.asarray(jnp.nan, jnp.float32)
    loss = jnp.where(code == FAULT_CODES["nan_loss"],
                     jnp.asarray(jnp.nan, loss.dtype), loss)

    def poison(g):
        f = jnp.where(code == FAULT_CODES["nan_grad"], nan, 1.0)
        f = jnp.where(code == FAULT_CODES["spike"], scale, f)
        return g * f.astype(g.dtype)

    return loss, jax.tree.map(poison, grads)


# ---------------------------------------------------------------------------
# host-side decode
# ---------------------------------------------------------------------------

def kinds_of(flags: int) -> List[str]:
    """Metric labels for a packed flag word (empty when healthy)."""
    return [kind for bit, kind in KIND_BITS if flags & bit]
