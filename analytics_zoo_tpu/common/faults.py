"""Deterministic fault injection — the chaos harness behind
``tests/test_chaos.py``.

Reliability code that is only exercised by real outages is reliability
code that does not work. This module lets a test (or a staging run)
declare a **seeded, deterministic plan** of faults to fire at named
sites in the serving/backend/dispatch paths, then reconcile the
recovery metrics *exactly* against what the plan actually fired:

>>> plan = FaultPlan(seed=7)
>>> plan.add("backend.xread", "disconnect", at=(1, 2))
>>> with faults.activate(plan):
...     serve_and_assert_recovery()
>>> assert len(plan.fired) == 2

Fault kinds:

* ``error``      — raise (``exc`` or :class:`FaultError`),
* ``disconnect`` — raise ``ConnectionError`` (what a dropped Redis/TCP
  connection surfaces as),
* ``latency``    — sleep ``delay_s`` then proceed,
* ``partial_write`` — no raise here; the SITE receives the spec back and
  applies its own partial-effect semantics (e.g.
  ``LocalBackend.set_results`` writes ``fraction`` of the batch, then
  raises ``ConnectionError`` — the mid-write crash shape),
* ``nan_loss`` / ``nan_grad`` / ``spike`` — site-applied like
  ``partial_write``: the ``train.grads`` site (the training loop, one
  call per dispatched optimizer step) feeds the returned spec to the
  compiled step as an on-device poison code — NaN the loss, NaN the
  gradients, or multiply them by ``scale`` — so the anomaly-sentinel
  chaos harness (``tests/test_training_chaos.py``) reconciles detected
  anomalies exactly against the plan.

Sites are plain strings; the current catalog (grep ``faults.inject`` for
ground truth): ``backend.xadd`` (``LocalBackend`` AND ``RedisBackend`` —
chaos against a live server) / ``backend.xread`` (fired by ``xread``
AND ``xreadgroup`` — one site per serve-loop read in either mode) /
``backend.xack`` (the post-settlement consumer-group ack; both
backends) / ``backend.xclaim`` (the reclaim sweep's ``xautoclaim``;
both backends) / ``backend.stream_len``
/ ``backend.set_result`` / ``backend.set_results`` (``LocalBackend``),
``serving.loop`` (top of each serve-loop iteration), ``serving.dispatch``
(before every model call, retries included), ``serving.publish`` (one
per published result batch, on the publisher thread — unlike
``backend.set_results`` it never collides with the shed/error-record
writes, so an outage plan hits exactly the publishes), ``resp.send`` /
``resp.recv`` (one fire per RESP command/pipeline attempt, around the
wire ops — exercises the reconnect/idempotency rules against a real
socket), the checkpoint writer's ``ckpt.write`` (per tree file) /
``ckpt.manifest`` / ``ckpt.rename`` (the manifest commit,
``utils/checkpoint.py``), the training loop's ``train.grads`` (one
per dispatched optimizer step when the anomaly sentinels are armed —
``pipeline/api/keras/training.py``), the fleet collector's
``collector.scrape`` (``observability/collector.py``: one fire per
scrape attempt per target, retry attempts included — a disconnect
plan drops a replica mid-scrape and the breaker/alert chaos tests
reconcile against it), and the profiler trigger's ``profiler.capture``
(``observability/profiler.py``: one fire per capture-arm attempt,
before the trace starts — a capture failure degrades to a counter
bump + event and must never kill the serve/fit loop hosting the
trigger), and the out-of-core embedding cache's ``embed.host_fetch``
(``ops/sharded_embedding.py``: one fire per batched host-RAM row fetch,
whichever thread runs it — injected latency surfaces as ``data_wait``
badput on the consuming step's ledger) / ``embed.prefetch`` (one fire
per background plan-staging attempt in ``stream`` — an error degrades
that batch to a synchronous fetch on the consumer thread, counted by
``zoo_embed_prefetch_errors_total``, and must never wedge the step).

Determinism: each site keeps a 0-based call counter; a spec fires when
its site's counter is in ``at`` (or, for rate-based specs, when the
plan's seeded RNG draws below ``p`` — same seed, same draws). Every
firing is appended to ``plan.fired`` as ``(site, kind, call_index)``, the
ground truth chaos tests reconcile metrics against.

Activation is deliberately explicit: :func:`activate` refuses unless the
``zoo.faults.enabled`` context flag is set (``init_zoo_context(
faults_enabled=True)`` / ``ZOO_TPU_FAULTS_ENABLED=1``) — a production
process can never be chaos-injected by an import side effect. With no
plan active, :func:`inject` is one global read and a None test.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import List, Optional, Tuple

__all__ = ["FaultError", "FaultSpec", "FaultPlan", "activate", "inject",
           "active_plan", "KINDS"]

KINDS = ("error", "disconnect", "latency", "partial_write",
         "nan_loss", "nan_grad", "spike")


class FaultError(RuntimeError):
    """The default injected exception for ``kind="error"``."""


class FaultSpec:
    """One fault recipe bound to a site.

    ``at`` — iterable of 0-based call indices that fire (exact,
    deterministic). ``p`` — alternative rate-based trigger drawn from the
    plan's seeded RNG (deterministic given the seed and call order).
    ``delay_s`` — sleep for ``latency``. ``exc`` — exception INSTANCE to
    raise for ``error`` (a fresh ``FaultError`` per firing otherwise).
    ``fraction`` — for ``partial_write``, how much of the batch the site
    applies before failing. ``scale`` — for ``spike``, the gradient
    multiplier the ``train.grads`` site applies on device."""

    __slots__ = ("site", "kind", "at", "p", "delay_s", "exc", "fraction",
                 "scale")

    def __init__(self, site: str, kind: str, at=(), p: float = 0.0,
                 delay_s: float = 0.0, exc: Optional[BaseException] = None,
                 fraction: float = 0.5, scale: float = 1e4):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; one of {KINDS}")
        if not at and not p:
            raise ValueError(f"spec for {site!r} fires never: give at= "
                             f"call indices or a p= rate")
        self.site = site
        self.kind = kind
        self.at = frozenset(int(i) for i in at)
        self.p = float(p)
        self.delay_s = float(delay_s)
        self.exc = exc
        self.fraction = float(fraction)
        self.scale = float(scale)

    def __repr__(self) -> str:
        trig = f"at={sorted(self.at)}" if self.at else f"p={self.p}"
        return f"FaultSpec({self.site!r}, {self.kind!r}, {trig})"


class FaultPlan:
    """A seeded set of :class:`FaultSpec`\\ s plus the per-site call
    counters and the ``fired`` log. Thread-safe — sites fire from the
    serve loop, the publisher, and producer threads concurrently."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._specs: List[FaultSpec] = []
        self._calls: dict = {}
        self._lock = threading.Lock()
        #: ground truth for reconciliation: (site, kind, call_index)
        self.fired: List[Tuple[str, str, int]] = []

    def add(self, site: str, kind: str, **kw) -> "FaultPlan":
        self._specs.append(FaultSpec(site, kind, **kw))
        return self

    def calls(self, site: str) -> int:
        """How many times ``site`` has been reached so far."""
        with self._lock:
            return self._calls.get(site, 0)

    def fired_at(self, site: str) -> List[Tuple[str, str, int]]:
        with self._lock:
            return [f for f in self.fired if f[0] == site]

    def on_call(self, site: str) -> Optional[FaultSpec]:
        """Advance ``site``'s call counter; return the spec that fires at
        this call, if any (first match wins), recording it in ``fired``."""
        with self._lock:
            idx = self._calls.get(site, 0)
            self._calls[site] = idx + 1
            for spec in self._specs:
                if spec.site != site:
                    continue
                hit = idx in spec.at
                if not hit and spec.p:
                    hit = self._rng.random() < spec.p
                if hit:
                    self.fired.append((site, spec.kind, idx))
                    return spec
            return None


_PLAN: Optional[FaultPlan] = None
_ACTIVATE_LOCK = threading.Lock()


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


@contextmanager
def activate(plan: FaultPlan):
    """Install ``plan`` as the process-wide active plan for the block.
    Requires the ``zoo.faults.enabled`` context flag — fault injection
    must be an explicit deployment decision, never ambient."""
    from .context import get_zoo_context
    if not get_zoo_context().get("zoo.faults.enabled"):
        raise RuntimeError(
            "fault injection is disabled: set the zoo.faults.enabled "
            "context flag first (init_zoo_context(faults_enabled=True) "
            "or ZOO_TPU_FAULTS_ENABLED=1)")
    global _PLAN
    with _ACTIVATE_LOCK:
        if _PLAN is not None:
            raise RuntimeError("a fault plan is already active")
        _PLAN = plan
    try:
        yield plan
    finally:
        _PLAN = None


def inject(site: str) -> Optional[FaultSpec]:
    """The hook instrumented sites call. No-op (None) without an active
    plan or when no spec fires at this call. ``error``/``disconnect``
    raise; ``latency`` sleeps then returns None; ``partial_write``
    returns the spec for the site to interpret."""
    plan = _PLAN
    if plan is None:
        return None
    spec = plan.on_call(site)
    if spec is None:
        return None
    if spec.kind == "latency":
        time.sleep(spec.delay_s)
        return None
    if spec.kind == "error":
        raise spec.exc if spec.exc is not None \
            else FaultError(f"injected error at {site}")
    if spec.kind == "disconnect":
        raise ConnectionError(f"injected disconnect at {site}")
    # partial_write / nan_loss / nan_grad / spike: site-applied semantics
    return spec
