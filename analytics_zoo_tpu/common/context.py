"""Runtime bring-up — the TPU-native equivalent of the reference's
``NNContext.initNNContext`` (``common/NNContext.scala:133-149``) and pyzoo's
``init_nncontext`` (``pyzoo/zoo/common/nncontext.py:104``).

Where the reference creates a tuned SparkContext (conf merge at
``NNContext.scala:188-200``, KMP/OMP env pinning at ``NNContext.scala:209-237``)
and calls BigDL ``Engine.init``, this module:

* discovers JAX devices and process topology (multi-host over DCN),
* builds the global device ``Mesh`` (data/seq/expert/model axes),
* loads layered configuration (defaults < yaml file < env < kwargs),
* seeds the global PRNG and sets matmul precision policy.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Dict, Mapping, Optional

import jax

from ..parallel import mesh as mesh_lib

log = logging.getLogger("analytics_zoo_tpu")

#: Bundled defaults — the analogue of ``spark-analytics-zoo.conf``
#: (``zoo/src/main/resources/spark-analytics-zoo.conf``, loaded at
#: ``NNContext.scala:188-200``).
DEFAULT_CONF: Dict[str, Any] = {
    "zoo.mesh.data": -1,        # -1 = all remaining devices
    "zoo.mesh.model": 1,
    "zoo.mesh.seq": 1,
    "zoo.mesh.expert": 1,
    "zoo.mesh.pipe": 1,
    "zoo.seed": 0,
    # multi-host (DCN) bring-up — the reference's Spark executor topology
    # becomes the JAX multi-process runtime; empty coordinator = single host
    "zoo.distributed.coordinator": "",   # "host:port" of process 0
    "zoo.distributed.num_processes": 1,
    "zoo.distributed.process_id": 0,
    "zoo.matmul.precision": "default",   # default | high | highest
    "zoo.pallas.attention": "auto",      # auto (TPU only) | true | false
    "zoo.pallas.cross_entropy": "auto",  # fused-CE forward kernel: auto (TPU) | true | false
    "zoo.pallas.block_sweep": False,     # one-shot on-device block sweep per kernel signature
    "zoo.pallas.vmem_budget_mb": 0,      # 0 = the per-core default (16 MiB) for block selection
    "zoo.pallas.embed_gather": "auto",   # one-hot MXU expand-gather: auto (TPU) | true | false
    "zoo.rng.impl": "auto",              # auto (rbg on TPU) | default | rbg
    "zoo.seq.mode": "ring",              # seq-parallel routing: ring | ulysses | auto
    "zoo.seq.strict": False,             # fail (not warn) when attention can't ride the seq mesh
    "zoo.compute.dtype": "float32",      # float32 | bfloat16
    "zoo.train.scan_steps": 1,           # optimizer steps fused per dispatch (lax.scan)
    "zoo.train.device_cache": False,     # HBM-resident dataset, 1 dispatch/epoch
    "zoo.train.fuse_epochs": 1,          # epochs fused per dispatch (device_cache only)
    "zoo.train.zero_sharding": False,    # ZeRO-1: optimizer state sharded over data axis
    "zoo.train.fused_ce": "auto",        # fused blockwise LM-head CE: auto (V>=1024) | true | false
    "zoo.train.fused_ce_chunk": 512,     # rows per streamed logits tile (O(chunk*V) memory)
    "zoo.train.remat": False,            # scan-body remat: false | true/dots | full
    "zoo.train.seq_attention": "off",    # force seq-parallel attention in the
    #   training step: off | ring | ulysses (needs a seq mesh axis; fallback
    #   to full attention becomes an error instead of a warning)
    "zoo.train.pipe_stages": 0,          # >0: cut the model's homogeneous block
    #   run into this many GPipe stages over the pipe mesh axis (0 = off)
    "zoo.train.pipe_microbatch": 0,      # GPipe microbatches per step (0 = the
    #   pipe-axis size; raise it to amortize the (n_micro + P - 1) bubble)
    # -- anomaly sentinels / self-healing training (docs/guides/TRAINING.md)
    "zoo.train.sentinel": "off",         # off | warn | recover: on-device
    #   nan-loss / nan-grad / grad-norm-spike checks folded into the step
    "zoo.train.spike_factor": 10.0,      # grad-norm spike = factor x its EWMA
    "zoo.train.grad_clip": 0.0,          # >0: global-norm gradient clipping in
    #   the step builders (zoo_train_grad_clip_engaged_total)
    "zoo.train.max_skips_per_epoch": 8,  # recover mode: skips past this in one
    #   epoch escalate to rollback-to-last-good-checkpoint
    "zoo.train.max_rollbacks": 3,        # rollbacks per fit before the loop
    #   fails loudly with TrainingDiverged (RetryBudget-backed)
    # -- out-of-core sharded embeddings (docs/guides/TRAINING.md)
    "zoo.embed.sharded": "auto",         # row-partitioned dedup'd lookup for plain
    #   Embedding layers: auto (model>1 and rows divide) | true | false
    "zoo.embed.dedup": True,             # per-step unique-id dedup in the lookup
    "zoo.embed.hot_rows_budget_mb": 64,  # device budget for the oocore hot tier
    "zoo.embed.prefetch_depth": 2,       # staged plans ahead of the consuming step
    "zoo.metrics.flops": False,          # fit(): cost-analysis pass feeding the MFU gauge
    "zoo.failure.retry_times": 5,        # ≅ bigdl.failure.retryTimes (Topology.scala:1172)
    "zoo.failure.retry_window_sec": 3600,
    "zoo.faults.enabled": False,         # gate for common.faults.activate (chaos tests)
    "zoo.checkpoint.keep": 3,
    "zoo.checkpoint.on_sigterm": False,  # SIGTERM during fit → final sync snapshot + clean exit
    "zoo.checkpoint.sigterm_grace_s": 0.0,  # >0: cut a MID-EPOCH snapshot from the
    #   SIGTERM handler when the estimated time to the next step boundary
    #   exceeds this budget (preemption deadline shorter than a dispatch)
    # -- serving overload / degradation (docs/guides/RELIABILITY.md) --------
    "zoo.serving.shed_watermark": 0,     # stream-depth watermark; >0 sheds the
    #   newest records in each admission window once the backlog exceeds it
    "zoo.serving.adaptive_batch": False,  # AIMD batch-size control from the
    #   live backlog/queue-wait signals (zoo_serving_batch_size_target)
    "zoo.serving.queue_wait_target_ms": 500,  # queue-wait breach target the
    #   AIMD controller backs off against
    # -- serving device path: bucketing + multiplexing (SERVING.md) ---------
    "zoo.serving.shape_buckets": "",     # compiled-shape dispatch buckets, a
    #   comma-joined list of batch row counts ("" = powers of two up to
    #   batch_size); ragged reads pad up to a bucket instead of retracing jit
    "zoo.serving.dtype": "float32",      # serving precision path for models
    #   the server wraps (KerasNet lane specs): float32 | bfloat16 | int8
    #   (int8 = weight-only quantized inference, fp32 results on the wire)
    "zoo.serving.lane_max_inflight": "",  # per-lane dispatch-window ceilings,
    #   "lane:n,lane:n" — a big model's lane caps its in-flight batches so it
    #   cannot starve the other lanes ("" = the server-wide max_inflight)
    "zoo.serving.lane_batch_size": "",   # per-lane batch-size ceilings,
    #   "lane:n,lane:n" — caps the lane's dispatch size, bucket ladder, AIMD
    #   ceiling and arena rows ("" = the server-wide batch_size)
    "zoo.serving.dlq_dir": "",           # non-empty: spill dead-lettered records
    #   to this append-only on-disk DLQ (scripts/zoo-dlq replays them)
    "zoo.serving.dlq_max_bytes": 64 << 20,  # DLQ disk bound; oldest sealed
    #   segment evicted first once exceeded
    # -- fleet serving: consumer groups + coordinated backpressure ----------
    "zoo.serving.consumer_group": "serving",  # stream consumer group each
    #   replica joins ("" = legacy single-consumer consume-on-read)
    "zoo.serving.claim_idle_ms": 30000,  # pending entries idle past this are
    #   reclaimable by a surviving replica (crash-safe entry reclaim)
    "zoo.serving.max_deliveries": 5,     # deliveries (read + reclaims) past
    #   this dead-letter the entry instead of reclaiming it forever
    "zoo.serving.fleet_backpressure": False,  # InputQueue.enqueue consults
    #   the fleet registry and refuses/slows producers when EVERY live
    #   replica reports itself saturated (FleetSaturatedError)
    # -- telemetry plane: ring-buffer TSDB + fleet collector ----------------
    "zoo.telemetry.sample_interval_s": 1.0,  # cadence of the local registry
    #   sampler, the device-memory sampler and the fleet collector's
    #   scrape loop
    "zoo.telemetry.retention_s": 900.0,  # per-series history window; ring
    #   capacity = retention / sample interval (bounded, oldest evicted)
    "zoo.telemetry.device_memory": True,  # poll jax.Device.memory_stats()
    #   into zoo_device_hbm_bytes and the /statusz device block
    #   (graceful no-op off-TPU)
    # -- performance attribution: goodput ledger + profiler trigger ---------
    "zoo.goodput.enabled": True,  # per-fit / per-replica GoodputLedger:
    #   attribute every wall-clock second to an exclusive category
    #   (zoo_goodput_ratio, zoo_badput_seconds_total{category=})
    "zoo.profiler.dir": "",       # ProfilerTrigger trace-dir root
    #   ("" = ./zoo-profiles); captures land in capture-NNNN-<trigger>/
    "zoo.profiler.keep": 3,       # newest capture dirs retained; older
    #   ones evicted after each arm (never the in-flight capture)
    "zoo.profiler.duration_s": 10.0,  # time bound per capture (daemon
    #   timer stops the trace); used when zoo.profiler.steps == 0
    "zoo.profiler.steps": 0,      # >0 bounds a capture by step()
    #   notifications from the hosting loop instead of wall time
    "zoo.log.level": "INFO",
}

_ENV_PREFIX = "ZOO_TPU_"

#: normalized ("zoo_failure_retry_times") → canonical ("zoo.failure.retry_times")
#: so env/kwargs spellings of multi-word leaf keys land on the right conf entry
_CANONICAL = {k.lower().replace(".", "_"): k for k in DEFAULT_CONF}


def _canonical_key(raw: str) -> str:
    """Map an underscore-separated key (env var / kwarg) to its canonical
    dotted form. Known keys resolve via DEFAULT_CONF regardless of whether an
    underscore is a namespace separator or part of a leaf name
    (``failure_retry_times`` → ``zoo.failure.retry_times``); unknown keys fall
    back to dots-for-underscores."""
    norm = raw.lower().replace(".", "_")
    if not norm.startswith("zoo_"):
        norm = "zoo_" + norm
    if norm in _CANONICAL:
        return _CANONICAL[norm]
    return norm.replace("_", ".")


def _env_overrides() -> Dict[str, Any]:
    """``ZOO_TPU_MESH_MODEL=2`` → ``{"zoo.mesh.model": 2}`` — the analogue of
    the reference's env-var config channel (``NNContext.scala:216-229``)."""
    out: Dict[str, Any] = {}
    for k, v in os.environ.items():
        if k.startswith(_ENV_PREFIX):
            out[_canonical_key(k[len(_ENV_PREFIX):])] = _parse_scalar(v)
    return out


def _parse_scalar(v: str) -> Any:
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            pass
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v


def _load_yaml(path: str) -> Dict[str, Any]:
    """Flat yaml config loader (``config.yaml`` channel of the reference,
    ``serving/utils/ClusterServingHelper.scala``). Minimal parser: only
    ``key: value`` and one level of nesting, so we don't depend on pyyaml."""
    try:
        import yaml  # type: ignore

        with open(path) as f:
            data = yaml.safe_load(f) or {}
        return _flatten(data)
    except ImportError:
        out: Dict[str, Any] = {}
        prefix = ""
        with open(path) as f:
            for raw in f:
                line = raw.rstrip()
                if not line or line.lstrip().startswith("#"):
                    continue
                indented = line.startswith((" ", "\t"))
                key, _, val = line.strip().partition(":")
                val = val.strip()
                if not val:
                    prefix = key + "."
                    continue
                out[(prefix if indented else "") + key] = _parse_scalar(val)
                if not indented:
                    prefix = ""
        return out


def _flatten(d: Mapping[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in d.items():
        if isinstance(v, Mapping):
            out.update(_flatten(v, prefix + k + "."))
        else:
            out[prefix + k] = v
    return out


@dataclasses.dataclass
class ZooContext:
    """Process-wide runtime handle — what ``NNContext``/BigDL ``Engine`` is in
    the reference. Holds the mesh, config, and root PRNG key."""

    conf: Dict[str, Any]
    mesh: Any  # jax.sharding.Mesh

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def data_parallel_size(self) -> int:
        return self.mesh.shape[mesh_lib.DATA_AXIS]

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def process_count(self) -> int:
        return jax.process_count()

    @property
    def seed(self) -> int:
        return int(self.conf["zoo.seed"])

    def rng(self) -> jax.Array:
        return jax.random.key(self.seed)

    def get(self, key: str, default: Any = None) -> Any:
        return self.conf.get(key, default)


_context: Optional[ZooContext] = None
#: jax_default_prng_impl before init_zoo_context first overrode it (None =
#: never overridden); reset_zoo_context restores it
_prng_impl_before_init: Optional[str] = None
_distributed_initialized = False


def _maybe_init_distributed(conf: Mapping[str, Any]) -> None:
    """Multi-host bring-up over DCN: ``jax.distributed.initialize`` when a
    coordinator is configured (``zoo.distributed.*`` conf /
    ``ZOO_TPU_DISTRIBUTED_COORDINATOR`` env). Single-process runs skip this
    entirely — the analogue of the reference running Spark ``local[N]``
    without a cluster manager (``DistriEstimatorSpec.scala:118``)."""
    global _distributed_initialized
    coordinator = str(conf.get("zoo.distributed.coordinator") or "").strip()
    if not coordinator or _distributed_initialized:
        return
    from jax._src import xla_bridge
    if getattr(xla_bridge, "_backends", {}):
        raise RuntimeError(
            "zoo.distributed.coordinator is set but JAX backends are already "
            "initialized — init_zoo_context(...) with the coordinator must "
            "run before any jax.devices()/computation in this process")
    num_processes = int(conf.get("zoo.distributed.num_processes", 1))
    process_id = int(conf.get("zoo.distributed.process_id", 0))
    log.info("initializing JAX multi-host runtime: coordinator=%s "
             "process %d/%d", coordinator, process_id, num_processes)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _distributed_initialized = True


def init_zoo_context(
    conf: Optional[Mapping[str, Any]] = None,
    conf_path: Optional[str] = None,
    **kwargs: Any,
) -> ZooContext:
    """Initialise (or fetch) the global context.

    Precedence (lowest → highest): bundled defaults, yaml ``conf_path``, env
    vars ``ZOO_TPU_*``, explicit ``conf`` dict, ``kwargs`` — mirroring the
    reference's conf-file < spark-conf < user-conf merge
    (``NNContext.scala:239-246``).

    Idempotent like ``SparkContext.getOrCreate``: a second call returns the
    existing context unless new settings are passed.
    """
    global _context
    if _context is not None and conf is None and conf_path is None and not kwargs:
        return _context

    merged: Dict[str, Any] = dict(DEFAULT_CONF)
    explicit: set = set()
    if conf_path:
        loaded = _load_yaml(conf_path)
        merged.update(loaded)
        explicit.update(loaded)
    env = _env_overrides()
    merged.update(env)
    explicit.update(env)
    if conf:
        merged.update(conf)
        explicit.update(conf)
    for k, v in kwargs.items():
        ck = _canonical_key(k)
        merged[ck] = v
        explicit.add(ck)

    # validate BEFORE any global side-effect (jax config, distributed
    # bring-up): a rejected call must not leave half-applied state.
    # jnp.dtype normalization accepts both "bfloat16" and jnp.bfloat16.
    import jax.numpy as jnp
    try:
        dtype = jnp.dtype(merged.get("zoo.compute.dtype", "float32")).name
    except TypeError:
        dtype = str(merged.get("zoo.compute.dtype"))
    if dtype not in ("float32", "bfloat16"):
        raise ValueError(f"zoo.compute.dtype must be float32|bfloat16, "
                         f"got {merged.get('zoo.compute.dtype')!r}")
    merged["zoo.compute.dtype"] = dtype

    logging.basicConfig(level=merged.get("zoo.log.level", "INFO"))

    _maybe_init_distributed(merged)

    precision = merged.get("zoo.matmul.precision", "default")
    if precision != "default":
        jax.config.update("jax_default_matmul_precision", precision)

    # PRNG implementation. "auto" picks the hardware RBG generator on TPU —
    # dropout-heavy training otherwise spends real step time producing
    # threefry bits on the VPU (measured ~25% of a BERT-base fine-tune
    # step); rbg trades threefry's sharding-invariant streams for
    # hardware-rate bits, the right default on TPU where dropout RNG rides
    # the critical path. CPU/test runs keep threefry determinism.
    impl = str(merged.get("zoo.rng.impl", "auto")).lower()
    if impl == "auto":
        impl = "rbg" if jax.default_backend() == "tpu" else ""
    elif impl in ("default", "threefry", "threefry2x32"):
        impl = "threefry2x32"
    elif impl not in ("rbg", "unsafe_rbg", ""):
        raise ValueError(f"zoo.rng.impl must be auto|default|rbg, got "
                         f"{merged.get('zoo.rng.impl')!r}")
    if impl:
        global _prng_impl_before_init
        if _prng_impl_before_init is None:
            _prng_impl_before_init = jax.config.jax_default_prng_impl
        jax.config.update("jax_default_prng_impl", impl)

    mesh = mesh_lib.create_mesh(
        data=int(merged["zoo.mesh.data"]),
        model=int(merged["zoo.mesh.model"]),
        seq=int(merged["zoo.mesh.seq"]),
        expert=int(merged["zoo.mesh.expert"]),
        pipe=int(merged["zoo.mesh.pipe"]),
    )
    mesh_lib.set_global_mesh(mesh)

    # mixed-precision policy: params stay float32, layer compute runs at
    # zoo.compute.dtype (bfloat16 = MXU native). Applied only AFTER the
    # mesh commits (a failed re-init must not leave a half-applied
    # context). Ownership semantics (the flag lives in engine, the module
    # that owns the policy): an explicit zoo.compute.dtype makes the
    # CONTEXT own the policy; a later re-init without one resets a
    # context-owned policy back to the conf default (re-inits restart from
    # defaults like every other key); a policy set directly via
    # ``engine.set_policy(...)`` is never touched by inits that don't
    # name a dtype.
    from ..pipeline.api.keras import engine as _engine
    if "zoo.compute.dtype" in explicit or _engine.policy_owner() == "context":
        _engine._set_policy_from_context(dtype)

    _context = ZooContext(conf=merged, mesh=mesh)
    log.info(
        "ZooContext: %d device(s), mesh %s, %d process(es)",
        _context.num_devices,
        dict(mesh.shape),
        jax.process_count(),
    )
    return _context


def get_zoo_context() -> ZooContext:
    """Fetch the context, initialising with defaults if needed."""
    return init_zoo_context()


#: accepted spellings for boolean context flags — every tri-state
#: (auto|true|false) parser shares these so the flags can never drift
TRUE_FLAG_SPELLINGS = ("1", "true", "yes", "on")
FALSE_FLAG_SPELLINGS = ("0", "false", "no", "off", "")


def tri_state_conf(key: str, default: str = "auto"):
    """Parse an ``auto|true|false`` context flag to ``"auto"``, ``True``,
    or ``False`` — the call site decides what ``auto`` resolves to. Falls
    back to ``default`` when no context is constructible (odd device
    counts); raises ``ValueError`` on an unrecognized spelling."""
    try:
        flag = get_zoo_context().get(key, default)
    except Exception:  # zoolint: disable=ZL007 context not constructible
        flag = default
    if isinstance(flag, str):
        low = flag.strip().lower()
        if low == "auto":
            return "auto"
        if low in TRUE_FLAG_SPELLINGS:
            return True
        if low in FALSE_FLAG_SPELLINGS:
            return False
        raise ValueError(f"{key} must be auto|true|false, got {flag!r}")
    return bool(flag)


def reset_zoo_context() -> None:
    """Tear down the global context (mainly for tests)."""
    global _context, _prng_impl_before_init
    _context = None
    mesh_lib.reset_global_mesh()
    if _prng_impl_before_init is not None:
        # restore the PRE-init value: a user's own jax.config choice made
        # outside the zoo context is not ours to clobber
        jax.config.update("jax_default_prng_impl", _prng_impl_before_init)
        _prng_impl_before_init = None
    from ..pipeline.api.keras import engine as _engine
    _engine._reset_policy()
