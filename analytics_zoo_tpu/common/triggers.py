"""Training triggers — equivalent of the reference's ``ZooTrigger`` family
(``common/ZooTrigger.scala:30-82``) and BigDL's ``Trigger``.

A trigger is a predicate over the training loop state deciding when to stop,
checkpoint, or validate. The reference's triggers are "aware of data slicing"
(DiskFeatureSet epochs, ``FeatureSet.scala:332-409``); here ``TrainState``
carries both the global step and the (possibly fractional) epoch so the same
semantics hold for sliced datasets.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class TrainLoopState:
    """Mutable loop bookkeeping passed to triggers."""

    iteration: int = 0           # global optimizer steps taken
    epoch: int = 1               # 1-based, like BigDL's Trigger.everyEpoch
    epoch_finished: bool = False # True exactly at an epoch boundary


class Trigger:
    def __call__(self, state: TrainLoopState) -> bool:  # pragma: no cover
        raise NotImplementedError


class EveryEpoch(Trigger):
    """Fires at every epoch boundary (``ZooTrigger.scala:44``)."""

    def __call__(self, state: TrainLoopState) -> bool:
        return state.epoch_finished


class SeveralIteration(Trigger):
    """Fires every ``interval`` optimizer steps (``ZooTrigger.scala:66``)."""

    def __init__(self, interval: int):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self.interval = interval

    def __call__(self, state: TrainLoopState) -> bool:
        return state.iteration > 0 and state.iteration % self.interval == 0


class MaxEpoch(Trigger):
    """End-trigger: stop once ``max_epoch`` epochs finished."""

    def __init__(self, max_epoch: int):
        self.max_epoch = max_epoch

    def __call__(self, state: TrainLoopState) -> bool:
        # at a boundary the finished count IS state.epoch; mid-epoch the
        # current epoch has not finished yet
        if state.epoch_finished:
            return state.epoch >= self.max_epoch
        return state.epoch > self.max_epoch


class MaxIteration(Trigger):
    """End-trigger: stop after ``max_iteration`` steps."""

    def __init__(self, max_iteration: int):
        self.max_iteration = max_iteration

    def __call__(self, state: TrainLoopState) -> bool:
        return state.iteration >= self.max_iteration


class MinLoss(Trigger):
    """End-trigger: stop once the running loss drops below ``min_loss``."""

    def __init__(self, min_loss: float):
        self.min_loss = min_loss
        self.last_loss = float("inf")

    def record(self, loss: float) -> None:
        self.last_loss = loss

    def __call__(self, state: TrainLoopState) -> bool:
        return self.last_loss < self.min_loss


class And(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    def __call__(self, state: TrainLoopState) -> bool:
        return all(t(state) for t in self.triggers)


class Or(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    def __call__(self, state: TrainLoopState) -> bool:
        return any(t(state) for t in self.triggers)
