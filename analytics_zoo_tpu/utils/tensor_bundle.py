"""TensorFlow tensor-bundle (checkpoint) reader — the variables half of
SavedModel import (reference role: ``TFNetForInference.scala:412`` loads
SavedModels *with* their variables via a TF session; here the bundle is
parsed directly with the in-repo codecs, no TF runtime).

A bundle is ``prefix.index`` + ``prefix.data-NNNNN-of-MMMMM`` shards. The
index is a leveldb-format table file: prefix-compressed key blocks, a
block-handle index block, and a fixed 48-byte footer ending in the table
magic. Values are protos: the empty key maps to BundleHeaderProto
(num_shards/endianness/version), every other key is a tensor name mapping
to BundleEntryProto (dtype, shape, shard, offset, size, crc32c)
(``tensorflow/core/protobuf/tensor_bundle.proto``). Tensor bytes are raw
little-endian at [offset, offset+size) of the named shard.

Only what checkpoints actually contain is implemented: uncompressed index
blocks (the bundle writer never compresses them), full tensors (no
partitioned-variable slices), little-endian hosts.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Tuple

import numpy as np

from .proto import parse_fields, parse_varint


def _vint(payload) -> int:
    """parse_fields re-encodes varints as bytes; decode to int."""
    if isinstance(payload, int):
        return payload
    v, _ = parse_varint(payload, 0)
    return v

__all__ = ["read_tensor_bundle", "bundle_tensor_entries"]

_TABLE_MAGIC = 0xdb4775248b80fb57

# tensorflow DataType enum → numpy (the subset bundles carry)
_DTYPES = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
           5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
           14: np.uint16,  # DT_BFLOAT16: raw bits, widened by the caller
           19: np.float16, 22: np.uint32, 23: np.uint64}


def _parse_handle(buf: bytes, i: int) -> Tuple[Tuple[int, int], int]:
    off, i = parse_varint(buf, i)
    size, i = parse_varint(buf, i)
    return (off, size), i


def _read_block(raw: bytes, handle: Tuple[int, int]) -> List[Tuple[bytes, bytes]]:
    """One leveldb block → [(key, value)] via prefix-decompression."""
    off, size = handle
    block = raw[off:off + size]
    ctype = raw[off + size]  # 1-byte compression tag after the block
    if ctype != 0:
        raise NotImplementedError(
            f"compressed index block (type {ctype}); bundle index blocks "
            f"are written uncompressed")
    n_restarts = struct.unpack("<I", block[-4:])[0]
    data_end = len(block) - 4 * (n_restarts + 1)
    entries: List[Tuple[bytes, bytes]] = []
    i, key = 0, b""
    while i < data_end:
        shared, i = parse_varint(block, i)
        unshared, i = parse_varint(block, i)
        vlen, i = parse_varint(block, i)
        key = key[:shared] + block[i:i + unshared]
        i += unshared
        entries.append((key, block[i:i + vlen]))
        i += vlen
    return entries


def _decode_shape(payload: bytes) -> Tuple[int, ...]:
    dims = []
    for f, wt, p in parse_fields(payload):
        if f == 2:  # Dim
            size = 0
            for ff, _, pp in parse_fields(p):
                if ff == 1:
                    size = _vint(pp)
            dims.append(size)
    return tuple(dims)


def bundle_tensor_entries(prefix: str) -> Dict[str, Dict]:
    """Parse ``prefix.index`` → {tensor_name: {dtype, shape, shard, offset,
    size}} plus the header's shard count under the ``""`` key."""
    index_path = prefix + ".index"
    with open(index_path, "rb") as f:
        raw = f.read()
    if len(raw) < 48:
        raise ValueError(f"{index_path}: too short to be a bundle index")
    footer = raw[-48:]
    magic = struct.unpack("<Q", footer[-8:])[0]
    if magic != _TABLE_MAGIC:
        raise ValueError(f"{index_path}: bad table magic "
                         f"{magic:#x} (not a tensor-bundle index)")
    i = 0
    _meta, i = _parse_handle(footer, i)
    index_handle, i = _parse_handle(footer, i)

    entries: Dict[str, Dict] = {}
    num_shards = 1
    for _ikey, ival in _read_block(raw, index_handle):
        data_handle, _ = _parse_handle(ival, 0)
        for key, val in _read_block(raw, data_handle):
            if key == b"":
                for f, wt, p in parse_fields(val):
                    if f == 1:
                        num_shards = _vint(p)
                continue
            ent = {"dtype": 1, "shape": (), "shard": 0, "offset": 0,
                   "size": 0}
            for f, wt, p in parse_fields(val):
                if f == 1:
                    ent["dtype"] = _vint(p)
                elif f == 2 and isinstance(p, (bytes, bytearray)):
                    ent["shape"] = _decode_shape(p)
                elif f == 3:
                    ent["shard"] = _vint(p)
                elif f == 4:
                    ent["offset"] = _vint(p)
                elif f == 5:
                    ent["size"] = _vint(p)
                elif f == 7:
                    raise NotImplementedError(
                        f"tensor {key.decode()!r} is a partitioned-variable "
                        f"slice; merge the checkpoint first")
            entries[key.decode("utf-8")] = ent
    entries[""] = {"num_shards": num_shards}
    return entries


def read_tensor_bundle(prefix: str) -> Dict[str, np.ndarray]:
    """Read every tensor of the bundle at ``prefix`` (e.g.
    ``.../variables/variables``). DT_BFLOAT16 widens to float32."""
    entries = bundle_tensor_entries(prefix)
    header = entries.pop("")
    num_shards = header["num_shards"]
    shard_bytes: Dict[int, bytes] = {}
    out: Dict[str, np.ndarray] = {}
    for name, ent in entries.items():
        shard = ent["shard"]
        if shard not in shard_bytes:
            path = f"{prefix}.data-{shard:05d}-of-{num_shards:05d}"
            if not os.path.exists(path):
                raise FileNotFoundError(f"bundle shard missing: {path}")
            with open(path, "rb") as f:
                shard_bytes[shard] = f.read()
        code = ent["dtype"]
        if code not in _DTYPES:
            raise NotImplementedError(
                f"tensor {name!r}: unsupported dtype enum {code}")
        dt = np.dtype(_DTYPES[code]).newbyteorder("<")
        buf = shard_bytes[shard][ent["offset"]:ent["offset"] + ent["size"]]
        arr = np.frombuffer(buf, dtype=dt).reshape(ent["shape"])
        if code == 14:  # bf16 bits → f32
            arr = (arr.astype(np.uint32) << 16).view(np.float32)
        out[name] = np.ascontiguousarray(arr)
    return out
