"""Shared prediction post-processing (the ``predictClass`` decode rule,
``Predictor.scala:210``) — one implementation for every facade."""

from __future__ import annotations

import numpy as np

__all__ = ["probs_to_classes"]


def probs_to_classes(probs: np.ndarray, zero_based: bool = True,
                     threshold: float = 0.5) -> np.ndarray:
    """Multi-class: argmax over the last axis. Binary (single column or 1-D):
    threshold at ``threshold``."""
    probs = np.asarray(probs)
    if probs.ndim > 1 and probs.shape[-1] > 1:
        cls = np.argmax(probs, axis=-1).astype(np.int32)
    else:
        cls = (probs.reshape(-1) > threshold).astype(np.int32)
    return cls if zero_based else cls + 1
