"""Host-side utilities. Nothing imported at this package's top level may
pull in jax — the operator CLIs (`scripts/zoo-ckpt`, `scripts/zoo-dlq`,
`scripts/cluster-serving-status`) import from here on hosts with no
device runtime."""


def human_bytes(n: float) -> str:
    """``1536 -> "1.5KiB"`` — the operator CLIs' shared size formatter."""
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
