"""Minimal protobuf wire-format codec — shared by the TensorBoard event
writer/reader and the ONNX loader (no protobuf-runtime dependency; the wire
format is 4 primitives: varint, 64-bit, length-delimited, 32-bit)."""

from __future__ import annotations

import struct
from typing import Iterator, Tuple

__all__ = ["varint", "field_bytes", "field_varint", "field_double",
           "field_float", "parse_varint", "parse_fields"]


def varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_bytes(num: int, payload: bytes) -> bytes:
    return varint((num << 3) | 2) + varint(len(payload)) + payload


def field_varint(num: int, value: int) -> bytes:
    return varint(num << 3) + varint(value & 0xFFFFFFFFFFFFFFFF)


def field_double(num: int, value: float) -> bytes:
    return varint((num << 3) | 1) + struct.pack("<d", value)


def field_float(num: int, value: float) -> bytes:
    return varint((num << 3) | 5) + struct.pack("<f", value)


def parse_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def parse_fields(buf: bytes) -> Iterator[Tuple[int, int, bytes]]:
    """Yield (field_num, wire_type, payload) — varints re-encoded so callers
    can parse them uniformly."""
    i = 0
    while i < len(buf):
        key, i = parse_varint(buf, i)
        num, wt = key >> 3, key & 7
        if wt == 0:
            v, i = parse_varint(buf, i)
            yield num, wt, varint(v)
        elif wt == 1:
            yield num, wt, buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = parse_varint(buf, i)
            yield num, wt, buf[i:i + ln]
            i += ln
        elif wt == 5:
            yield num, wt, buf[i:i + 4]
            i += 4
        else:
            raise IOError(f"unsupported wire type {wt}")
