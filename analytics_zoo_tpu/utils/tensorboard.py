"""TensorBoard event-file writer/reader — parity with the reference's
``zoo/common/tensorboard/FileWriter.scala`` + ``EventWriter.scala`` (which
wrap TF's Java proto classes) and the ``setTensorBoard`` / ``getTrainSummary``
/ ``getValidationSummary`` surface of ``keras/engine/Topology.scala:204-236``.

Re-designed dependency-free: TensorBoard's on-disk format is just a TFRecord
stream of serialized ``tensorflow.Event`` protos, and the two messages we need
(Event{wall_time, step, file_version | summary{value{tag, simple_value}}})
are small enough to encode by hand — so this module writes bytes directly:

* TFRecord framing: ``uint64 len | masked_crc32c(len) | data |
  masked_crc32c(data)`` with the Castagnoli CRC and TF's mask rotation.
* Proto wire format: field tags ``(num << 3) | wire_type`` with varint (0),
  64-bit (1), length-delimited (2), 32-bit (5) payloads.

The reader side parses the same framing back (verifying both CRCs), which is
what ``get_train_summary`` uses — and doubles as proof the files are
TensorBoard-readable.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .proto import (field_bytes as _field_bytes,
                    field_double as _field_double,
                    field_float as _field_float,
                    field_varint as _field_varint,
                    parse_fields as _parse_fields,
                    parse_varint as _parse_varint)

__all__ = ["EventFileWriter", "TrainSummary", "ValidationSummary",
           "read_scalars", "read_histograms"]


# ---------------------------------------------------------------------------
# crc32c (Castagnoli, table-driven) + TF's masking
# ---------------------------------------------------------------------------

def _make_crc32c_table() -> List[int]:
    poly = 0x82F63B78  # reversed Castagnoli polynomial
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (poly if crc & 1 else 0)
        table.append(crc)
    return table


_CRC_TABLE = _make_crc32c_table()


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# minimal proto encoding (event.proto / summary.proto subset)
# ---------------------------------------------------------------------------

def _scalar_event(wall_time: float, step: int, tag: str,
                  value: float) -> bytes:
    # Summary.Value{ tag=1, simple_value=2 } inside Summary{ value=1 }
    sv = _field_bytes(1, tag.encode("utf-8")) + _field_float(2, float(value))
    summary = _field_bytes(1, sv)
    # Event{ wall_time=1, step=2, summary=5 }
    return (_field_double(1, wall_time) + _field_varint(2, int(step))
            + _field_bytes(5, summary))


def _version_event(wall_time: float) -> bytes:
    # Event{ wall_time=1, file_version=3 }
    return _field_double(1, wall_time) + _field_bytes(3, b"brain.Event:2")


def _packed_doubles(xs) -> bytes:
    return b"".join(struct.pack("<d", float(x)) for x in xs)


def _histogram_event(wall_time: float, step: int, tag: str,
                     values: np.ndarray) -> bytes:
    """Event carrying a HistogramProto (the reference writes these for
    weight/gradient distributions — ``Summary.scala`` histogram path,
    enabled via ``setSummaryTrigger("Parameters", ...)``)."""
    raw = np.asarray(values, np.float64).ravel()
    # stats cover FINITE values only: np.histogram raises on NaN/inf, and
    # a diverged run is exactly when the user needs the diagnostics — so
    # non-finite weights degrade to a degenerate histogram rather than
    # crash fit() from the logging path
    v = raw[np.isfinite(raw)]
    if v.size == 0:
        v = np.zeros(1)
    vmin, vmax = float(v.min()), float(v.max())
    if vmin == vmax:
        limits, counts = [vmax], [float(v.size)]
    else:
        c, edges = np.histogram(v, bins=30)
        limits, counts = edges[1:].tolist(), c.astype(np.float64).tolist()
    # HistogramProto{ min=1 max=2 num=3 sum=4 sum_squares=5
    #                 bucket_limit=6 packed, bucket=7 packed }
    histo = (_field_double(1, vmin) + _field_double(2, vmax)
             + _field_double(3, float(v.size))
             + _field_double(4, float(v.sum()))
             + _field_double(5, float((v * v).sum()))
             + _field_bytes(6, _packed_doubles(limits))
             + _field_bytes(7, _packed_doubles(counts)))
    # Summary.Value{ tag=1, histo=5 }
    sv = _field_bytes(1, tag.encode("utf-8")) + _field_bytes(5, histo)
    summary = _field_bytes(1, sv)
    return (_field_double(1, wall_time) + _field_varint(2, int(step))
            + _field_bytes(5, summary))


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

class EventFileWriter:
    """Appends TFRecord-framed Event protos to one
    ``events.out.tfevents.<ts>.<host>`` file (``EventWriter.scala``
    equivalent; thread-safe, explicit ``flush``)."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._lock = threading.Lock()
        self._write(_version_event(time.time()))

    def _write(self, event: bytes) -> None:
        header = struct.pack("<Q", len(event))
        rec = (header + struct.pack("<I", _masked_crc(header))
               + event + struct.pack("<I", _masked_crc(event)))
        with self._lock:
            self._f.write(rec)

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: Optional[float] = None) -> None:
        self._write(_scalar_event(wall_time if wall_time is not None
                                  else time.time(), step, tag, value))

    def add_histogram(self, tag: str, values, step: int,
                      wall_time: Optional[float] = None) -> None:
        self._write(_histogram_event(wall_time if wall_time is not None
                                     else time.time(), step, tag,
                                     np.asarray(values)))

    def flush(self) -> None:
        with self._lock:
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.flush()
            self._f.close()


# ---------------------------------------------------------------------------
# reader (used by get_train_summary / get_validation_summary)
# ---------------------------------------------------------------------------

def _read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != _masked_crc(header):
                raise IOError(f"corrupt record header in {path}")
            data = f.read(length)
            (dcrc,) = struct.unpack("<I", f.read(4))
            if dcrc != _masked_crc(data):
                raise IOError(f"corrupt record payload in {path}")
            yield data


def _iter_summary_values(log_dir: str):
    """Yield ``(step, wall_time, value_payload)`` for every Summary.Value
    in every event file under ``log_dir`` — the Event-envelope decoding
    shared by :func:`read_scalars` and :func:`read_histograms` (one place
    owns the TFRecord/Event framing rules)."""
    for fname in sorted(os.listdir(log_dir)):
        if "tfevents" not in fname:
            continue
        for rec in _read_records(os.path.join(log_dir, fname)):
            wall, step, summary = 0.0, 0, None
            for num, wt, payload in _parse_fields(rec):
                if num == 1 and wt == 1:
                    (wall,) = struct.unpack("<d", payload)
                elif num == 2 and wt == 0:
                    step, _ = _parse_varint(payload, 0)
                elif num == 5 and wt == 2:
                    summary = payload
            if summary is None:
                continue
            for num, wt, val in _parse_fields(summary):
                if num == 1 and wt == 2:
                    yield step, wall, val


def read_scalars(log_dir: str, tag: Optional[str] = None
                 ) -> List[Tuple[int, float, float, str]]:
    """All scalar points under ``log_dir`` as ``(step, value, wall_time,
    tag)``, sorted by step — the ``readScalar`` analogue."""
    points = []
    for step, wall, val in _iter_summary_values(log_dir):
        vtag, simple = "", None
        for n2, w2, p2 in _parse_fields(val):
            if n2 == 1 and w2 == 2:
                vtag = p2.decode("utf-8")
            elif n2 == 2 and w2 == 5:
                (simple,) = struct.unpack("<f", p2)
        if simple is not None and (tag is None or vtag == tag):
            points.append((step, simple, wall, vtag))
    points.sort(key=lambda p: (p[0], p[2]))
    return points


def _unpack_doubles(payload: bytes) -> List[float]:
    return [x[0] for x in struct.iter_unpack("<d", payload)]


def read_histograms(log_dir: str, tag: Optional[str] = None
                    ) -> List[Tuple[int, dict, float, str]]:
    """All histogram points under ``log_dir`` as ``(step, stats, wall_time,
    tag)`` where ``stats`` has min/max/num/sum/sum_squares/bucket_limit/
    bucket — the histogram-side ``readScalar`` analogue."""
    points = []
    for step, wall, val in _iter_summary_values(log_dir):
        vtag, histo = "", None
        for n2, w2, p2 in _parse_fields(val):
            if n2 == 1 and w2 == 2:
                vtag = p2.decode("utf-8")
            elif n2 == 5 and w2 == 2:
                histo = p2
        if histo is None or (tag is not None and vtag != tag):
            continue
        stats = {"min": 0.0, "max": 0.0, "num": 0.0, "sum": 0.0,
                 "sum_squares": 0.0, "bucket_limit": [], "bucket": []}
        keys = {1: "min", 2: "max", 3: "num", 4: "sum", 5: "sum_squares"}
        for n3, w3, p3 in _parse_fields(histo):
            if n3 in keys and w3 == 1:
                (stats[keys[n3]],) = struct.unpack("<d", p3)
            elif n3 == 6 and w3 == 2:
                stats["bucket_limit"] = _unpack_doubles(p3)
            elif n3 == 7 and w3 == 2:
                stats["bucket"] = _unpack_doubles(p3)
        points.append((step, stats, wall, vtag))
    points.sort(key=lambda p: (p[0], p[2]))
    return points


# ---------------------------------------------------------------------------
# TrainSummary / ValidationSummary (Topology.scala:204-236 surface)
# ---------------------------------------------------------------------------

class _Summary:
    sub_dir = ""

    def __init__(self, log_dir: str, app_name: str):
        self.log_dir = os.path.join(log_dir, app_name, self.sub_dir)
        self.writer = EventFileWriter(self.log_dir)

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self.writer.add_scalar(tag, value, step)

    def add_histogram(self, tag: str, values, step: int) -> None:
        self.writer.add_histogram(tag, values, step)

    def read_scalar(self, tag: str) -> np.ndarray:
        """(n, 3) array of ``[step, value, wall_time]`` rows for ``tag``."""
        self.writer.flush()
        pts = read_scalars(self.log_dir, tag)
        if not pts:
            return np.zeros((0, 3), np.float64)
        return np.asarray([[s, v, w] for s, v, w, _ in pts], np.float64)

    def read_histogram(self, tag: str):
        """``(step, stats)`` pairs for ``tag`` (see :func:`read_histograms`)."""
        self.writer.flush()
        return [(s, st) for s, st, _, t in read_histograms(self.log_dir, tag)]

    def close(self) -> None:
        self.writer.close()


class TrainSummary(_Summary):
    """Per-iteration Loss/Throughput (+ LearningRate when known) scalars,
    written by ``fit`` when ``set_tensorboard`` is configured. Weight
    histograms opt in via :meth:`set_summary_trigger` — the reference's
    ``TrainSummary.setSummaryTrigger("Parameters", ...)`` surface."""
    sub_dir = "train"
    parameters_every_epochs: Optional[int] = None
    parameters_trigger = None   # Trigger-like alternative to the int form

    # families the reference's ``setSummaryTrigger`` also accepts
    # (``TrainSummary.scala``); Loss/Throughput/LearningRate are written
    # unconditionally per iteration here, so their triggers are a no-op —
    # accepted for reference-API portability instead of raising
    _ALWAYS_ON_FAMILIES = ("Loss", "Throughput", "LearningRate")

    def set_summary_trigger(self, name: str, trigger=None, *,
                            every_epochs=None) -> "TrainSummary":
        """Enable an optional summary family, reference-style.

        ``"Parameters"`` — per-layer weight histograms. ``trigger`` is
        either the ``every_epochs`` int shorthand (also accepted under
        its pre-Trigger keyword spelling ``every_epochs=``) or a
        Trigger-like callable (``common.triggers``: ``EveryEpoch()``,
        ``SeveralIteration(n)``, ...) evaluated at epoch boundaries, where
        the params are host-visible; under fused-epoch dispatch that is
        the final epoch of each fused block. The reference's always-on
        scalar families (``Loss``/``Throughput``/``LearningRate``) accept
        any trigger as a no-op."""
        if every_epochs is not None:
            if trigger is not None:
                raise TypeError(
                    "pass either trigger or every_epochs, not both")
            trigger = every_epochs
        if trigger is None:
            raise TypeError("a trigger (or every_epochs=) is required")
        if name != "Parameters" and name not in self._ALWAYS_ON_FAMILIES:
            raise ValueError(
                f"unknown summary family {name!r}; supported: 'Parameters' "
                f"(+ no-op {'/'.join(self._ALWAYS_ON_FAMILIES)})")
        # validate BEFORE the always-on no-op return: a malformed trigger
        # must raise identically for every accepted family, or the typo
        # only surfaces when the call is later copied onto "Parameters"
        if callable(trigger) and not isinstance(trigger, type):
            every = None
        else:
            # the every-N-epochs shorthand: any real number (incl.
            # np.int64 / a float epoch count, as the pre-Trigger
            # signature coerced)
            if isinstance(trigger, bool) or isinstance(trigger, str):
                raise TypeError(
                    f"trigger must be an int (every N epochs) or a "
                    f"Trigger-like callable, got {trigger!r}")
            try:
                every = int(trigger)
            except (TypeError, ValueError):
                raise TypeError(
                    f"trigger must be an int (every N epochs) or a "
                    f"Trigger-like callable, got {type(trigger).__name__}")
            if every < 1:
                raise ValueError("every_epochs must be >= 1")
        if name in self._ALWAYS_ON_FAMILIES:
            return self
        if every is None:
            self.parameters_trigger = trigger
            self.parameters_every_epochs = None
        else:
            self.parameters_every_epochs = every
            self.parameters_trigger = None
        return self


class ValidationSummary(_Summary):
    """Per-epoch validation metrics, tagged by metric name."""
    sub_dir = "validation"
