"""TensorBoard event-file writer/reader — parity with the reference's
``zoo/common/tensorboard/FileWriter.scala`` + ``EventWriter.scala`` (which
wrap TF's Java proto classes) and the ``setTensorBoard`` / ``getTrainSummary``
/ ``getValidationSummary`` surface of ``keras/engine/Topology.scala:204-236``.

Re-designed dependency-free: TensorBoard's on-disk format is just a TFRecord
stream of serialized ``tensorflow.Event`` protos, and the two messages we need
(Event{wall_time, step, file_version | summary{value{tag, simple_value}}})
are small enough to encode by hand — so this module writes bytes directly:

* TFRecord framing: ``uint64 len | masked_crc32c(len) | data |
  masked_crc32c(data)`` with the Castagnoli CRC and TF's mask rotation.
* Proto wire format: field tags ``(num << 3) | wire_type`` with varint (0),
  64-bit (1), length-delimited (2), 32-bit (5) payloads.

The reader side parses the same framing back (verifying both CRCs), which is
what ``get_train_summary`` uses — and doubles as proof the files are
TensorBoard-readable.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .proto import (field_bytes as _field_bytes,
                    field_double as _field_double,
                    field_float as _field_float,
                    field_varint as _field_varint,
                    parse_fields as _parse_fields,
                    parse_varint as _parse_varint)

__all__ = ["EventFileWriter", "TrainSummary", "ValidationSummary",
           "read_scalars"]


# ---------------------------------------------------------------------------
# crc32c (Castagnoli, table-driven) + TF's masking
# ---------------------------------------------------------------------------

def _make_crc32c_table() -> List[int]:
    poly = 0x82F63B78  # reversed Castagnoli polynomial
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (poly if crc & 1 else 0)
        table.append(crc)
    return table


_CRC_TABLE = _make_crc32c_table()


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# minimal proto encoding (event.proto / summary.proto subset)
# ---------------------------------------------------------------------------

def _scalar_event(wall_time: float, step: int, tag: str,
                  value: float) -> bytes:
    # Summary.Value{ tag=1, simple_value=2 } inside Summary{ value=1 }
    sv = _field_bytes(1, tag.encode("utf-8")) + _field_float(2, float(value))
    summary = _field_bytes(1, sv)
    # Event{ wall_time=1, step=2, summary=5 }
    return (_field_double(1, wall_time) + _field_varint(2, int(step))
            + _field_bytes(5, summary))


def _version_event(wall_time: float) -> bytes:
    # Event{ wall_time=1, file_version=3 }
    return _field_double(1, wall_time) + _field_bytes(3, b"brain.Event:2")


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

class EventFileWriter:
    """Appends TFRecord-framed Event protos to one
    ``events.out.tfevents.<ts>.<host>`` file (``EventWriter.scala``
    equivalent; thread-safe, explicit ``flush``)."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._lock = threading.Lock()
        self._write(_version_event(time.time()))

    def _write(self, event: bytes) -> None:
        header = struct.pack("<Q", len(event))
        rec = (header + struct.pack("<I", _masked_crc(header))
               + event + struct.pack("<I", _masked_crc(event)))
        with self._lock:
            self._f.write(rec)

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: Optional[float] = None) -> None:
        self._write(_scalar_event(wall_time if wall_time is not None
                                  else time.time(), step, tag, value))

    def flush(self) -> None:
        with self._lock:
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.flush()
            self._f.close()


# ---------------------------------------------------------------------------
# reader (used by get_train_summary / get_validation_summary)
# ---------------------------------------------------------------------------

def _read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != _masked_crc(header):
                raise IOError(f"corrupt record header in {path}")
            data = f.read(length)
            (dcrc,) = struct.unpack("<I", f.read(4))
            if dcrc != _masked_crc(data):
                raise IOError(f"corrupt record payload in {path}")
            yield data


def read_scalars(log_dir: str, tag: Optional[str] = None
                 ) -> List[Tuple[int, float, float, str]]:
    """All scalar points under ``log_dir`` as ``(step, value, wall_time,
    tag)``, sorted by step — the ``readScalar`` analogue."""
    points = []
    for fname in sorted(os.listdir(log_dir)):
        if "tfevents" not in fname:
            continue
        for rec in _read_records(os.path.join(log_dir, fname)):
            wall, step, summary = 0.0, 0, None
            for num, wt, payload in _parse_fields(rec):
                if num == 1 and wt == 1:
                    (wall,) = struct.unpack("<d", payload)
                elif num == 2 and wt == 0:
                    step, _ = _parse_varint(payload, 0)
                elif num == 5 and wt == 2:
                    summary = payload
            if summary is None:
                continue
            for num, wt, val in _parse_fields(summary):
                if num != 1 or wt != 2:
                    continue
                vtag, simple = "", None
                for n2, w2, p2 in _parse_fields(val):
                    if n2 == 1 and w2 == 2:
                        vtag = p2.decode("utf-8")
                    elif n2 == 2 and w2 == 5:
                        (simple,) = struct.unpack("<f", p2)
                if simple is not None and (tag is None or vtag == tag):
                    points.append((step, simple, wall, vtag))
    points.sort(key=lambda p: (p[0], p[2]))
    return points


# ---------------------------------------------------------------------------
# TrainSummary / ValidationSummary (Topology.scala:204-236 surface)
# ---------------------------------------------------------------------------

class _Summary:
    sub_dir = ""

    def __init__(self, log_dir: str, app_name: str):
        self.log_dir = os.path.join(log_dir, app_name, self.sub_dir)
        self.writer = EventFileWriter(self.log_dir)

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self.writer.add_scalar(tag, value, step)

    def read_scalar(self, tag: str) -> np.ndarray:
        """(n, 3) array of ``[step, value, wall_time]`` rows for ``tag``."""
        self.writer.flush()
        pts = read_scalars(self.log_dir, tag)
        if not pts:
            return np.zeros((0, 3), np.float64)
        return np.asarray([[s, v, w] for s, v, w, _ in pts], np.float64)

    def close(self) -> None:
        self.writer.close()


class TrainSummary(_Summary):
    """Per-iteration Loss/Throughput (+ LearningRate when known) scalars,
    written by ``fit`` when ``set_tensorboard`` is configured."""
    sub_dir = "train"


class ValidationSummary(_Summary):
    """Per-epoch validation metrics, tagged by metric name."""
    sub_dir = "validation"
