"""Profiling & performance accounting — the TPU-native replacement for the
reference's ad-hoc scoped timers (``Utils.timeIt`` at
``pipeline/api/net/TFNet.scala:176``, ``EstimateSupportive.throughputing*`` at
``pipeline/estimator/EstimateSupportive.scala``) and BigDL's per-phase
``metrics`` table (driven at ``Topology.scala:1184``).

Adds what the reference never had (SURVEY §5 "no sampling profiler, no trace
files"): ``jax.profiler`` trace capture and achieved-MFU accounting from XLA's
compiled cost analysis.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Any, Dict, Optional

import jax

log = logging.getLogger("analytics_zoo_tpu.profiling")

#: Peak dense-matmul FLOP/s per chip by ``jax.Device.device_kind`` substring.
#: bf16 peaks (the MXU native precision); fp32 runs at a fraction of these.
#: Sources: public TPU spec sheets (v2 45T, v3 123T, v4 275T, v5e 197T,
#: v5p 459T, v6e 918T bf16 per chip).
PEAK_FLOPS_BF16: Dict[str, float] = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,      # plain "TPU v5" reported by some runtimes
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}


def device_peak_flops(device: Optional[jax.Device] = None) -> Optional[float]:
    """Best-effort per-chip peak FLOP/s for MFU accounting; None if unknown
    (e.g. the CPU test mesh)."""
    d = device if device is not None else jax.devices()[0]
    kind = getattr(d, "device_kind", "") or ""
    # longest match wins so "TPU v5 lite" beats "TPU v5"
    best = None
    for k, v in PEAK_FLOPS_BF16.items():
        if k.lower() in kind.lower() and (best is None or len(k) > best[0]):
            best = (len(k), v)
    return best[1] if best else None


def compiled_flops(compiled) -> Optional[float]:
    """Total FLOPs of one invocation of a compiled (lowered) jax function,
    from XLA's cost analysis. Returns None when the backend doesn't report."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # pragma: no cover - backend-dependent
        return None
    if ca is None:
        return None
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    flops = ca.get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


def jit_flops(fn, *args, **kwargs) -> Optional[float]:
    """FLOPs for one call of ``jax.jit(fn)`` on these concrete args."""
    try:
        return compiled_flops(jax.jit(fn).lower(*args, **kwargs).compile())
    except Exception:  # pragma: no cover
        return None


def mfu(flops_per_sec: float, n_devices: Optional[int] = None) -> Optional[float]:
    """Achieved model-FLOPs-utilization given sustained FLOP/s across the
    mesh. None when the chip peak is unknown."""
    peak = device_peak_flops()
    if peak is None:
        return None
    n = n_devices if n_devices is not None else len(jax.devices())
    return flops_per_sec / (peak * n)


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """``jax.profiler`` trace capture scoped to a with-block; no-op when
    ``log_dir`` is None. View with TensorBoard's profile plugin / xprof."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", log_dir)


class Timer:
    """Scoped wall-clock timer with named laps — the ``timeIt`` role."""

    def __init__(self):
        self.laps: Dict[str, float] = {}

    @contextlib.contextmanager
    def lap(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.laps[name] = self.laps.get(name, 0.0) + time.perf_counter() - t0
