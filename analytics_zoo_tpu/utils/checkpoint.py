"""Durable checkpoint/resume — the TPU-native equivalent of the reference's
epoch-triggered snapshots (``Topology.scala:109-114,1161-1168``), the
``setCheckpoint`` API (``Topology.scala:245-255``) and the latest-file
resume logic (``Topology.scala:1220-1246``, ``getLatestFile`` ``:1511-1528``)
— hardened for a preemptible fleet where the snapshot you resume from is
the one thing that must never lie.

Format: one directory per snapshot (``ckpt-<iteration>/``) holding one
``.npz`` per pytree (params / opt_state / net_state — leaves in
deterministic ``tree_flatten`` order, restored against a same-structure
template) plus a ``manifest.json`` carrying per-tree CRC32 checksums, leaf
counts/shapes/dtypes, the resume metadata, and (``mesh=``) the
mesh/topology the snapshot was cut under — host leaves are
topology-free, and the metadata is what lets an elastic restore onto a
different device count/mesh shape say so instead of guessing
(``pipeline/api/keras/training.py::_try_resume`` re-places the trees
under the live mesh; malformed mesh metadata classifies as corruption). The manifest is written
LAST (tmp file + ``os.replace``) and is the **commit marker**: a directory
without one was never committed — a process killed mid-write can never
produce a snapshot that a resume will trust. (This replaces the old
whole-directory tmp+rename commit, which is only atomic on filesystems
with atomic directory rename — object-store and NFS mounts are not.)

Durability contract (``docs/guides/TRAINING.md``):

* **Async save.** :meth:`CheckpointManager.save` snapshots device arrays
  to host (one batched ``jax.device_get``) and returns; serialization,
  checksumming, the manifest commit, and pruning run on a background
  writer thread, off the training step path. At most ONE save is in
  flight: the next ``save()`` (or ``close()``) joins it first. A
  background failure counts in ``zoo_ckpt_save_failures_total`` and
  surfaces as :class:`CheckpointSaveError` on that next call — never
  silently.
* **Verified restore with fallback.** :meth:`restore` verifies the
  manifest and checksums; :meth:`restore_latest` walks snapshots newest
  → oldest, **quarantines** a corrupt/uncommitted one to
  ``ckpt-<n>.corrupt`` (counted in ``zoo_ckpt_corrupt_total``, never
  silently deleted) and falls back to the newest snapshot that verifies,
  so resume always lands on good weights. Legacy snapshots (pre-manifest:
  ``meta.json`` only) restore with a logged warning — there is nothing to
  verify them against.
* **Chaos-provable.** The writer carries named fault sites
  (``ckpt.write`` per tree file, ``ckpt.manifest``, ``ckpt.rename`` for
  the commit) through ``common.faults``;
  ``tests/test_checkpoint_chaos.py`` reconciles kill-mid-write /
  truncation / bit-flip / missing-manifest recovery exactly.

Single-writer discipline (unchanged from the start): one process owns a
checkpoint directory at a time — concurrent writers were never supported.
Quarantining is an OWNER action: a reader of someone else's live
directory (serving loading a training run's weights) must restore with
``restore_latest(..., quarantine=False)``, which skips bad snapshots
instead of renaming them — an "uncommitted" directory seen from outside
may be the owner's save in flight.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..common import faults

log = logging.getLogger("analytics_zoo_tpu.checkpoint")

__all__ = ["CheckpointManager", "CheckpointError", "CheckpointSaveError",
           "CheckpointCorruptError", "CheckpointArchitectureError"]

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")
MANIFEST = "manifest.json"
_MANIFEST_VERSION = 1
_UNCOMMITTED = "no manifest.json — the save never committed"


class CheckpointError(RuntimeError):
    """Base class for checkpoint failures."""


class CheckpointSaveError(CheckpointError):
    """A background (async) save failed; raised on the NEXT checkpoint
    call so the failure is never silent. The original error is chained."""


class CheckpointCorruptError(CheckpointError):
    """A snapshot failed verification (bad checksum, torn write, missing
    commit marker). The snapshot has been quarantined to
    ``ckpt-<n>.corrupt``."""

    def __init__(self, step: int, reason: str):
        super().__init__(f"checkpoint ckpt-{step} is corrupt: {reason}")
        self.step = step
        self.reason = reason


class CheckpointArchitectureError(ValueError):
    """The snapshot does not match the restore template (leaf count or
    shape) — a configuration error, NOT corruption: it must never trigger
    quarantine or fallback (every snapshot of the run would be
    quarantined against a wrong template)."""


# ---------------------------------------------------------------------------
# leaf-level helpers
# ---------------------------------------------------------------------------

def _snapshot_leaves(tree: Any) -> List[np.ndarray]:
    """Host-side copies of every leaf — the only work that stays on the
    caller's (step) path. Device leaves come back in ONE batched
    ``jax.device_get``; host leaves are copied so the background writer
    never races a caller mutating its own arrays."""
    leaves = jax.tree_util.tree_leaves(tree)
    fetched = jax.device_get(leaves)
    out = []
    for orig, got in zip(leaves, fetched):
        a = np.asarray(got)
        if a is orig:
            a = np.array(a, copy=True)
        out.append(a)
    return out


def _write_tree(path: str, leaves: List[np.ndarray]) -> Tuple[int, int]:
    """Serialize ``leaves`` to ``path`` (.npz), fsync, and return
    ``(crc32, bytes)`` of the file as written."""
    faults.inject("ckpt.write")
    np.savez(path, **{f"leaf_{i}": a for i, a in enumerate(leaves)})
    crc = 0
    size = 0
    with open(path, "rb+") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
        os.fsync(f.fileno())
    return crc & 0xFFFFFFFF, size


def _file_crc(path: str) -> Tuple[int, int]:
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return crc & 0xFFFFFFFF, size


def _rebuild_tree(template: Any, loaded: List[np.ndarray], path: str) -> Any:
    """Rebuild a pytree from loaded leaves using ``template``'s structure
    (the template supplies the treedef — no pickled treedefs on disk).
    Preserves template leaf dtypes for non-array leaves (e.g. optax
    counts) and fails loudly on any shape mismatch — silently installing
    permuted leaves would train on scrambled weights."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if len(loaded) != len(leaves):
        raise CheckpointArchitectureError(
            f"{path}: checkpoint has {len(loaded)} leaves, "
            f"template has {len(leaves)} — architecture mismatch")
    out = []
    for i, (tmpl, arr) in enumerate(zip(leaves, loaded)):
        if tuple(np.shape(tmpl)) != tuple(arr.shape):
            raise CheckpointArchitectureError(
                f"{path}: leaf {i} shape {arr.shape} != template "
                f"{np.shape(tmpl)} — architecture mismatch")
        if np.ndim(tmpl) == 0 and not isinstance(tmpl, (np.ndarray, jax.Array)):
            out.append(type(tmpl)(arr.item()) if not isinstance(tmpl, jax.Array) else arr)
        else:
            out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


def _load_leaves(path: str) -> List[np.ndarray]:
    with np.load(path) as data:
        return [data[f"leaf_{i}"] for i in range(len(data.files))]


class CheckpointManager:
    """Directory of snapshots with asynchronous verified save,
    checksum-verified restore with corruption fallback, and pruning."""

    def __init__(self, directory: str, keep: int = 3, registry=None,
                 ledger=None):
        if keep < 0:
            raise ValueError(
                f"keep must be >= 0 (0 = keep every snapshot), got {keep}")
        self.directory = directory
        self.keep = keep
        # goodput attribution: when the owning loop passes its
        # GoodputLedger, save() charges the SYNCHRONOUS window (join of
        # the previous in-flight write + host snapshot + any sync write)
        # to ckpt_stall; the async background write stays hidden — by
        # construction only the stall the step path actually felt counts
        self._ledger = ledger
        os.makedirs(directory, exist_ok=True)
        # -- async writer state (at most one save in flight) ---------------
        self._lock = threading.Lock()
        self._pending: Optional[Tuple[threading.Thread, dict]] = None
        # -- observability (docs/guides/OBSERVABILITY.md zoo_ckpt_*) -------
        if registry is None:
            from ..observability import default_registry
            registry = default_registry()
        self._registry = registry
        self._m_save_s = registry.histogram(
            "zoo_ckpt_save_seconds",
            "background checkpoint write wall time per committed save")
        self._m_bytes = registry.histogram(
            "zoo_ckpt_bytes", "bytes written per committed save")
        self._m_save_fail = registry.counter(
            "zoo_ckpt_save_failures_total",
            "checkpoint saves that failed (surfaced on the next "
            "checkpoint call)")
        self._m_corrupt = registry.counter(
            "zoo_ckpt_corrupt_total",
            "snapshots quarantined to ckpt-<n>.corrupt (bad checksum, "
            "torn write, or missing commit marker)")
        self._m_fallback = registry.counter(
            "zoo_ckpt_restore_fallback_total",
            "restores that could not use the newest snapshot and fell "
            "back past quarantined one(s)")

    # ---- paths ------------------------------------------------------------
    def _dir(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step}")

    # ---- async plumbing ---------------------------------------------------
    def save_in_flight(self) -> bool:
        """Whether a background save is currently writing."""
        with self._lock:
            return (self._pending is not None
                    and self._pending[0].is_alive())

    def join(self) -> None:
        """Wait for the in-flight save (if any); surface its failure as
        :class:`CheckpointSaveError` exactly once."""
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is None:
            return
        thread, box = pending
        thread.join()
        err = box.get("error")
        if err is not None:
            raise CheckpointSaveError(
                f"background save of ckpt-{box['step']} failed: "
                f"{err}") from err

    def close(self, raise_pending: bool = True) -> None:
        """Join the in-flight save. ``raise_pending=False`` logs a
        pending failure instead of raising (exception-path cleanup — the
        failure was already counted when it happened)."""
        try:
            self.join()
        except CheckpointSaveError:
            if raise_pending:
                raise
            log.exception("in-flight checkpoint save failed during close")

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(raise_pending=exc_type is None)

    # ---- save -------------------------------------------------------------
    def save(self, step: int, trees: Dict[str, Any],
             meta: Optional[Dict[str, Any]] = None,
             sync: bool = False, mesh: Optional[Dict[str, Any]] = None,
             ) -> str:
        """Snapshot ``trees`` as ``ckpt-<step>``.

        Device arrays are fetched to host NOW (the step path pays one
        batched transfer); serialization + commit happen on a background
        writer unless ``sync=True``. Joins any previous in-flight save
        first — surfacing ITS failure — so at most one save is ever in
        flight and failures are never silent. ``mesh`` (a
        ``parallel.mesh.mesh_metadata`` dict) records the topology the
        snapshot was cut under, enabling elastic cross-topology restore
        — leaves are host-side and topology-free; the metadata lets a
        restore under a different mesh say so instead of guessing.
        Returns the final snapshot path (committed only once the
        manifest lands)."""
        led = self._ledger
        if led is not None:
            # close the caller's interval first, then charge everything
            # this call blocks on (join/snapshot/sync write) to
            # ckpt_stall via the finally below
            led.note(led.good)
        try:
            return self._save_blocking(step, trees, meta, sync, mesh)
        finally:
            if led is not None:
                led.note("ckpt_stall")

    def _save_blocking(self, step, trees, meta, sync, mesh) -> str:
        self.join()
        host = {name: _snapshot_leaves(tree) for name, tree in trees.items()}
        meta = {"step": step, **(meta or {})}
        final = self._dir(step)
        if sync:
            try:
                self._write(step, host, meta, final, mesh)
            except Exception as e:
                # Exception only: a KeyboardInterrupt/SystemExit mid-write
                # must stay a BaseException (wrapping it would feed the
                # user's Ctrl-C into the fit retry loop as a step failure)
                raise CheckpointSaveError(
                    f"save of ckpt-{step} failed: {e}") from e
            return final
        box: dict = {"step": step}
        thread = threading.Thread(
            target=self._write_guarded,
            args=(step, host, meta, final, mesh, box),
            name=f"ckpt-writer-{step}", daemon=True)
        with self._lock:
            self._pending = (thread, box)
        thread.start()
        return final

    def _write_guarded(self, step, host, meta, final, mesh, box) -> None:
        try:
            self._write(step, host, meta, final, mesh)
        except BaseException as e:   # surfaced via join(); never silent
            box["error"] = e

    def _write(self, step, host, meta, final, mesh=None) -> None:
        t0 = time.perf_counter()
        try:
            total = self._commit(step, host, meta, final, mesh)
        except BaseException as e:
            self._m_save_fail.inc()
            self._registry.emit("ckpt.save_failure", step=step,
                                error=f"{type(e).__name__}: {e}")
            log.error("checkpoint save of ckpt-%d failed: %s", step, e)
            raise
        dur = time.perf_counter() - t0
        self._m_save_s.observe(dur)
        self._m_bytes.observe(total)
        self._registry.emit("ckpt.save", step=step, bytes=total, dur_s=dur)
        self._prune()

    def _commit(self, step, host, meta, final, mesh=None) -> int:
        """Write tree files, then the manifest (the commit marker) LAST.
        A crash at any earlier point leaves an uncommitted directory no
        restore will trust."""
        if os.path.isdir(final):
            # leftovers of an uncommitted attempt at the same step (or an
            # explicit re-save): drop the commit marker FIRST so a crash
            # mid-overwrite cannot leave old-manifest/new-files mixtures
            marker = os.path.join(final, MANIFEST)
            if os.path.exists(marker):
                os.remove(marker)
            shutil.rmtree(final)
        os.makedirs(final)
        total = 0
        tree_entries: Dict[str, dict] = {}
        for name, leaves in host.items():
            fname = name + ".npz"
            crc, size = _write_tree(os.path.join(final, fname), leaves)
            total += size
            tree_entries[name] = {
                "file": fname, "crc32": crc, "bytes": size,
                "leaves": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                           for a in leaves]}
        manifest = {"version": _MANIFEST_VERSION, "step": step,
                    "meta": meta, "trees": tree_entries}
        if mesh is not None:
            manifest["mesh"] = mesh
        faults.inject("ckpt.manifest")
        tmp = os.path.join(final, MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        faults.inject("ckpt.rename")
        os.replace(tmp, os.path.join(final, MANIFEST))
        return total

    def _prune(self) -> None:
        steps = self._scan()
        for s in steps[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(self._dir(s), ignore_errors=True)

    # ---- lookup -----------------------------------------------------------
    def _scan(self) -> List[int]:
        """Committed-looking steps: a manifest (new format) or a
        ``meta.json`` (legacy, pre-manifest) marks a committed snapshot.
        No checksum verification here — that is :meth:`verify` /
        :meth:`restore_latest`'s job."""
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if not m:
                continue
            d = os.path.join(self.directory, name)
            if (os.path.exists(os.path.join(d, MANIFEST))
                    or os.path.exists(os.path.join(d, "meta.json"))):
                out.append(int(m.group(1)))
        return sorted(out)

    def _scan_all(self) -> List[int]:
        """Every ``ckpt-<n>`` directory, committed or not — the restore
        fallback walk must SEE uncommitted snapshots to quarantine them."""
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def steps(self) -> list:
        """Committed snapshot steps, ascending (joins an in-flight save
        first so a just-requested snapshot is visible once committed)."""
        self.join()
        return self._scan()

    def latest(self) -> Optional[int]:
        """Newest COMMITTED step (commit-marker check only; full checksum
        verification happens in :meth:`restore_latest`/:meth:`verify`)."""
        steps = self.steps()
        return steps[-1] if steps else None

    # ---- verification -----------------------------------------------------
    def _commit_status(self, step: int) -> str:
        """Cheap commit-marker classification, no checksums:
        ``committed`` / ``legacy`` / ``uncommitted`` / ``missing``."""
        d = self._dir(step)
        if not os.path.isdir(d):
            return "missing"
        if os.path.exists(os.path.join(d, MANIFEST)):
            return "committed"
        if os.path.exists(os.path.join(d, "meta.json")):
            return "legacy"
        return "uncommitted"

    def _read_manifest(self, step: int) -> dict:
        """Parse AND schema-check the manifest; raises
        :class:`CheckpointCorruptError` on anything unreadable or
        malformed — a manifest that parses as JSON but lost its schema
        (version skew, hand edit, torn rewrite) is corruption, not a
        crash."""
        try:
            with open(os.path.join(self._dir(step), MANIFEST)) as f:
                manifest = json.load(f)
            for name, entry in manifest["trees"].items():
                if (not isinstance(entry.get("file"), str)
                        or not isinstance(entry.get("bytes"), int)
                        or not isinstance(entry.get("crc32"), int)
                        or not isinstance(entry.get("leaves"), list)):
                    raise CheckpointCorruptError(
                        step, f"manifest entry for tree {name!r} is "
                              f"malformed")
            mesh = manifest.get("mesh")
            if mesh is not None:
                # elastic restore decides placement from this — torn or
                # hand-edited mesh metadata is corruption like any other
                # (it must never silently mis-shard a restore)
                if (not isinstance(mesh, dict)
                        or not isinstance(mesh.get("axes"), dict)
                        or not all(isinstance(v, int)
                                   for v in mesh["axes"].values())):
                    raise CheckpointCorruptError(
                        step, "manifest mesh metadata is malformed")
            manifest["meta"]
            return manifest
        except CheckpointCorruptError:
            raise
        except (OSError, ValueError, KeyError, AttributeError,
                TypeError) as e:
            raise CheckpointCorruptError(step, f"unreadable manifest: {e}")

    @staticmethod
    def _check_entry(step: int, entry: dict, crc: int, size: int) -> None:
        if size != entry["bytes"]:
            raise CheckpointCorruptError(
                step, f"{entry['file']}: {size} bytes on disk, manifest "
                      f"says {entry['bytes']} (truncated?)")
        if crc != entry["crc32"]:
            raise CheckpointCorruptError(
                step, f"{entry['file']}: CRC32 {crc:#010x} != manifest "
                      f"{entry['crc32']:#010x}")

    def verify(self, step: int) -> Tuple[str, Optional[str]]:
        """Classify snapshot ``step`` without touching it:
        ``("ok", None)`` — manifest present, every tree file matches its
        CRC32 and byte count; ``("legacy", None)`` — pre-manifest layout,
        nothing to verify against; ``("uncommitted", reason)`` — no
        commit marker; ``("corrupt", reason)`` — failed verification."""
        status = self._commit_status(step)
        if status == "missing":
            return "corrupt", "snapshot directory missing"
        if status == "legacy":
            return "legacy", None
        if status == "uncommitted":
            return "uncommitted", _UNCOMMITTED
        try:
            manifest = self._read_manifest(step)
            for entry in manifest["trees"].values():
                path = os.path.join(self._dir(step), entry["file"])
                if not os.path.exists(path):
                    raise CheckpointCorruptError(
                        step, f"tree file {entry['file']} missing")
                crc, size = _file_crc(path)
                self._check_entry(step, entry, crc, size)
        except CheckpointCorruptError as e:
            return "corrupt", e.reason
        return "ok", None

    def survey(self, verify: bool = False) -> List[dict]:
        """Operator inventory of the directory (``scripts/zoo-ckpt``):
        one dict per snapshot/quarantine directory with ``name``,
        ``step``, ``status`` (``committed``/``ok``/``corrupt``/
        ``legacy``/``uncommitted``/``quarantined``), ``reason``, and
        ``bytes``. ``verify=True`` upgrades the commit-marker check to a
        full checksum pass (``committed`` → ``ok``/``corrupt``)."""
        out = []
        for name in sorted(os.listdir(self.directory)):
            full = os.path.join(self.directory, name)
            if not os.path.isdir(full):
                continue
            m = _CKPT_RE.match(name)
            quarantined = re.match(r"^ckpt-(\d+)\.corrupt", name)
            if m:
                step: Optional[int] = int(m.group(1))
                if verify:
                    status, reason = self.verify(step)
                else:
                    # cheap pass: commit markers only, no checksums
                    status = self._commit_status(step)
                    reason = _UNCOMMITTED if status == "uncommitted" \
                        else None
            elif quarantined:
                step = int(quarantined.group(1))
                status, reason = "quarantined", None
            else:
                continue
            size = 0
            for f in os.listdir(full):
                try:
                    size += os.path.getsize(os.path.join(full, f))
                except OSError:
                    pass
            out.append({"name": name, "step": step, "status": status,
                        "reason": reason, "bytes": size})
        return out

    # ---- quarantine -------------------------------------------------------
    def _quarantine(self, step: int, reason: str) -> str:
        """Move a bad snapshot aside as ``ckpt-<n>.corrupt`` — out of the
        resume path but NEVER silently deleted (an operator may want the
        evidence; ``zoo-ckpt list`` shows it)."""
        src = self._dir(step)
        dst = src + ".corrupt"
        k = 1
        while os.path.exists(dst):
            dst = f"{src}.corrupt.{k}"
            k += 1
        os.rename(src, dst)
        self._m_corrupt.inc()
        self._registry.emit("ckpt.corrupt", step=step, reason=reason,
                            quarantined_to=os.path.basename(dst))
        log.error("checkpoint ckpt-%d failed verification (%s); "
                  "quarantined to %s", step, reason, os.path.basename(dst))
        return dst

    # ---- restore ----------------------------------------------------------
    def _load_verified(self, step: int, templates: Dict[str, Any],
                       ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Verify-and-load in ONE read per file: requested trees are read
        into memory, CRC32-checked against the manifest, then parsed from
        the same buffer; non-requested trees are stream-checked — restore
        never pays the read-twice cost a separate verify pass would."""
        import io

        d = self._dir(step)
        manifest = self._read_manifest(step)
        for name in templates:
            if name not in manifest["trees"]:
                raise CheckpointArchitectureError(
                    f"{d}: manifest has no tree {name!r} — "
                    f"architecture mismatch")
        trees = {}
        for name, entry in manifest["trees"].items():
            path = os.path.join(d, entry["file"])
            try:
                if name in templates:
                    with open(path, "rb") as f:
                        data = f.read()
                    crc, size = zlib.crc32(data) & 0xFFFFFFFF, len(data)
                else:
                    data = None
                    crc, size = _file_crc(path)
            except OSError as e:
                raise CheckpointCorruptError(step, f"{entry['file']}: {e}")
            self._check_entry(step, entry, crc, size)
            if data is None:
                continue
            with np.load(io.BytesIO(data)) as z:
                loaded = [z[f"leaf_{i}"] for i in range(len(z.files))]
            if len(loaded) != len(entry["leaves"]):
                raise CheckpointCorruptError(
                    step, f"{entry['file']}: {len(loaded)} leaves on disk, "
                          f"manifest says {len(entry['leaves'])}")
            trees[name] = _rebuild_tree(templates[name], loaded, path)
        meta = dict(manifest["meta"])
        if "mesh" in manifest:
            # surfaced through restore meta so callers (the training
            # loop's elastic _try_resume) can compare against the live
            # mesh and report a topology change
            meta["mesh"] = manifest["mesh"]
        return trees, meta

    def _load_legacy(self, step: int, templates: Dict[str, Any],
                     ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        d = self._dir(step)
        log.warning("snapshot ckpt-%d predates manifests; restoring "
                    "WITHOUT checksum verification", step)
        trees = {}
        for name, tmpl in templates.items():
            path = os.path.join(d, name + ".npz")
            trees[name] = _rebuild_tree(tmpl, _load_leaves(path), path)
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        return trees, meta

    def restore(self, step: int, templates: Dict[str, Any],
                ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Load snapshot ``step`` after verification; each named tree is
        rebuilt against the same-structure template (fresh
        ``optimizer.init`` output, fresh ``build`` params).

        A snapshot that fails verification is quarantined and raises
        :class:`CheckpointCorruptError`; a template that does not match
        raises :class:`CheckpointArchitectureError` (a ``ValueError`` —
        config bug, nothing is quarantined). Use :meth:`restore_latest`
        for the newest-valid-with-fallback semantics."""
        self.join()
        status = self._commit_status(step)
        if status == "missing":
            raise FileNotFoundError(f"no snapshot ckpt-{step} in "
                                    f"{self.directory}")
        if status == "legacy":
            return self._load_legacy(step, templates)
        if status == "uncommitted":
            self._quarantine(step, _UNCOMMITTED)
            raise CheckpointCorruptError(step, _UNCOMMITTED)
        try:
            return self._load_verified(step, templates)
        except CheckpointCorruptError as e:
            self._quarantine(step, e.reason)
            raise

    def _discard(self, step: int, reason: str, quarantine: bool) -> None:
        """A bad snapshot encountered during a fallback walk: the OWNING
        process quarantines it; a read-only observer (another process's
        directory — e.g. serving loading a live training dir) must only
        SKIP it, because what looks uncommitted from outside may be a
        concurrent writer's save in flight."""
        if quarantine:
            self._quarantine(step, reason)
        else:
            log.warning("skipping snapshot ckpt-%d (%s) — read-only "
                        "restore, not quarantining", step, reason)

    def restore_latest(self, templates: Dict[str, Any],
                       min_step: Optional[int] = None,
                       quarantine: bool = True,
                       ) -> Optional[Tuple[int, Dict[str, Any],
                                           Dict[str, Any]]]:
        """Restore the newest snapshot that VERIFIES, walking newest →
        oldest: a corrupt or uncommitted snapshot is quarantined (counted
        in ``zoo_ckpt_corrupt_total``) and the walk falls back to the
        next one (``zoo_ckpt_restore_fallback_total`` counts restores
        that could not use the newest snapshot). Returns ``(step, trees,
        meta)``, or ``None`` when no snapshot at or past ``min_step``
        verifies.

        ``quarantine=False`` makes the walk READ-ONLY (skip instead of
        rename): required for any process that does not own the
        directory — against a live training run, an "uncommitted"
        snapshot may simply be the writer's save in flight, and renaming
        it from outside would destroy a healthy save mid-commit."""
        self.join()
        skipped = 0
        result = None
        for step in reversed(self._scan_all()):
            status = self._commit_status(step)
            if status == "uncommitted":
                self._discard(step, _UNCOMMITTED, quarantine)
                skipped += 1
                continue
            if min_step is not None and step < min_step:
                break   # older than the caller's in-memory progress
            try:
                if status == "legacy":
                    trees, meta = self._load_legacy(step, templates)
                else:
                    trees, meta = self._load_verified(step, templates)
            except CheckpointArchitectureError:
                raise   # wrong template, not corruption — fail loudly
            except CheckpointCorruptError as e:
                self._discard(step, e.reason, quarantine)
                skipped += 1
                continue
            except (OSError, KeyError, ValueError, EOFError) as e:
                # a legacy (unverifiable) snapshot torn on disk, or an
                # unreadable file: discard and keep walking
                self._discard(step, f"{type(e).__name__}: {e}", quarantine)
                skipped += 1
                continue
            result = (step, trees, meta)
            break
        if skipped:
            self._m_fallback.inc()
            self._registry.emit(
                "ckpt.fallback", skipped=skipped,
                restored_step=result[0] if result else None)
        return result
