"""Checkpoint/resume — the TPU-native equivalent of the reference's
epoch-triggered snapshots (``Topology.scala:109-114,1161-1168``), the
``setCheckpoint`` API (``Topology.scala:245-255``) and the latest-file
resume logic (``Topology.scala:1220-1246``, ``getLatestFile`` ``:1511-1528``).

Format: one directory per snapshot (``ckpt-<iteration>/``) holding one ``.npz``
per pytree (params / opt_state / net_state — leaves in deterministic
``tree_flatten`` order, restored against a same-structure template) plus a
``meta.json``. Writes are atomic (tmp dir + rename) so a crash mid-save never
corrupts the latest snapshot; old snapshots are pruned to ``keep``.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")


def _save_tree(path: str, tree: Any) -> None:
    leaves = jax.tree_util.tree_leaves(tree)
    arrays = {f"leaf_{i}": np.asarray(jax.device_get(l)) for i, l in enumerate(leaves)}
    np.savez(path, **arrays)


def _restore_tree(path: str, template: Any) -> Any:
    """Rebuild a pytree from saved leaves using ``template``'s structure.
    The template supplies the treedef (avoids pickling treedefs to disk)."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    with np.load(path) as data:
        if len(data.files) != len(leaves):
            raise ValueError(
                f"{path}: checkpoint has {len(data.files)} leaves, "
                f"template has {len(leaves)} — architecture mismatch")
        loaded = [data[f"leaf_{i}"] for i in range(len(leaves))]
    # preserve template leaf dtypes for non-array leaves (e.g. optax counts),
    # and fail loudly on any shape mismatch — silently installing permuted
    # leaves would train on scrambled weights
    out = []
    for i, (tmpl, arr) in enumerate(zip(leaves, loaded)):
        if tuple(np.shape(tmpl)) != tuple(arr.shape):
            raise ValueError(
                f"{path}: leaf {i} shape {arr.shape} != template "
                f"{np.shape(tmpl)} — architecture mismatch")
        if np.ndim(tmpl) == 0 and not isinstance(tmpl, (np.ndarray, jax.Array)):
            out.append(type(tmpl)(arr.item()) if not isinstance(tmpl, jax.Array) else arr)
        else:
            out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


class CheckpointManager:
    """Directory of snapshots with atomic save, latest-lookup, and pruning."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    # ---- save -------------------------------------------------------------
    def save(self, step: int, trees: Dict[str, Any],
             meta: Optional[Dict[str, Any]] = None) -> str:
        final = os.path.join(self.directory, f"ckpt-{step}")
        tmp = tempfile.mkdtemp(prefix=".tmp-ckpt-", dir=self.directory)
        try:
            for name, tree in trees.items():
                _save_tree(os.path.join(tmp, name + ".npz"), tree)
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump({"step": step, **(meta or {})}, f)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._prune()
        return final

    def _prune(self) -> None:
        steps = self.steps()
        for s in steps[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(os.path.join(self.directory, f"ckpt-{s}"),
                          ignore_errors=True)

    # ---- lookup -----------------------------------------------------------
    def steps(self) -> list:
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m and os.path.exists(os.path.join(self.directory, name, "meta.json")):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    # ---- restore ----------------------------------------------------------
    def restore(self, step: int, templates: Dict[str, Any],
                ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Load snapshot ``step``; each named tree is rebuilt against the
        same-structure template (fresh ``optimizer.init`` output, fresh
        ``build`` params)."""
        d = os.path.join(self.directory, f"ckpt-{step}")
        trees = {name: _restore_tree(os.path.join(d, name + ".npz"), tmpl)
                 for name, tmpl in templates.items()}
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        return trees, meta
